"""Benchmark: Faster R-CNN train-step throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference never published throughput (BASELINE.md: Speedometer logs
only), so vs_baseline is measured against a fixed reference point of
5.0 img/s/GPU — a generous estimate of the classic implementation's
ResNet-101 COCO training speed on a 2017 P100 (README-era hardware), used
solely to make the ratio meaningful across rounds.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

REFERENCE_IMG_S = 5.0  # estimated reference img/s/GPU (see module docstring)


def main():
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.models.faster_rcnn import build_model, init_params
    from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
    from mx_rcnn_tpu.train.optimizer import build_optimizer
    from mx_rcnn_tpu.train.step import create_train_state, make_train_step

    # Flagship config: ResNet-101, COCO class count, (600,1000)-scale padded
    # canvas, full proposal counts — the reference's headline training shape.
    cfg = generate_config(
        "resnet101", "coco",
        **{"image.pad_shape": (640, 1024), "train.batch_images": 1},
    )
    b = cfg.train.batch_images
    h, w = cfg.image.pad_shape
    g = cfg.train.max_gt_boxes

    rs = np.random.RandomState(0)
    n_boxes = 8
    boxes = np.zeros((b, g, 4), np.float32)
    for i in range(b):
        x1 = rs.uniform(0, w - 200, n_boxes)
        y1 = rs.uniform(0, h - 200, n_boxes)
        boxes[i, :n_boxes] = np.stack(
            [x1, y1, x1 + rs.uniform(50, 199, n_boxes),
             y1 + rs.uniform(50, 199, n_boxes)], axis=1)
    valid = np.zeros((b, g), bool)
    valid[:, :n_boxes] = True
    classes = np.zeros((b, g), np.int32)
    classes[:, :n_boxes] = rs.randint(1, 81, (b, n_boxes))
    batch = {
        "image": rs.randn(b, h, w, 3).astype(np.float32),
        "im_info": np.asarray([[600, 1000, 1.0]] * b, np.float32),
        "gt_boxes": boxes,
        "gt_classes": classes,
        "gt_valid": valid,
    }

    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=1000)
    state = create_train_state(params, tx)
    mesh = create_mesh(str(jax.device_count()))
    step_fn = make_train_step(model, cfg, mesh=mesh)
    batch = shard_batch(batch, mesh)

    rng = jax.random.PRNGKey(1)
    # Warmup: TWO steps — the first compiles against host-committed inputs,
    # the second recompiles against the donated/device-layout state that
    # every subsequent step sees (verified: timing from step 1 includes a
    # full second compile otherwise).
    for _ in range(2):
        rng, k = jax.random.split(rng)
        state, metrics = step_fn(state, batch, k)
        jax.block_until_ready(metrics["TotalLoss"])

    iters = 20
    t0 = time.perf_counter()
    for i in range(iters):
        rng, k = jax.random.split(rng)
        state, metrics = step_fn(state, batch, k)
    jax.block_until_ready(metrics["TotalLoss"])
    dt = time.perf_counter() - t0
    img_s = iters * b / dt
    per_chip = img_s / jax.device_count()
    print(json.dumps({
        "metric": "faster_rcnn_r101_coco_train_img_per_sec_per_chip",
        "value": round(per_chip, 3),
        "unit": "img/s/chip",
        "vs_baseline": round(per_chip / REFERENCE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
