"""Benchmark: train-step throughput + MFU on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.
The headline metric stays the C4 R-101 img/s/chip figure (comparable
across rounds r01→); "detail" carries per-config {img_s, step_ms, mfu}
for BOTH the C4 and the flagship R101-FPN configs (BASELINE config 3),
each the MEDIAN of 5 timed repetitions (the axon relay adds run-to-run
host noise — see PERF.md).

MFU: analytic FLOPs from XLA's own cost model for the whole compiled train
step (fwd+bwd+update), divided by the v5e bf16 peak (197 TFLOP/s/chip).

The reference never published throughput (BASELINE.md: Speedometer logs
only), so vs_baseline is measured against a fixed reference point of
5.0 img/s/GPU — a generous estimate of the classic implementation's
ResNet-101 COCO training speed on a 2017 P100 (README-era hardware), used
solely to make the ratio meaningful across rounds.
"""

from __future__ import annotations

import json
import statistics
import time

import jax
import numpy as np

REFERENCE_IMG_S = 5.0  # estimated reference img/s/GPU (see module docstring)
V5E_PEAK_FLOPS = 197e12  # bf16 peak per chip


def make_batch(cfg):
    b = cfg.train.batch_images
    h, w = cfg.image.pad_shape
    g = cfg.train.max_gt_boxes
    rs = np.random.RandomState(0)
    n_boxes = 8
    boxes = np.zeros((b, g, 4), np.float32)
    for i in range(b):
        x1 = rs.uniform(0, w - 200, n_boxes)
        y1 = rs.uniform(0, h - 200, n_boxes)
        boxes[i, :n_boxes] = np.stack(
            [x1, y1, x1 + rs.uniform(50, 199, n_boxes),
             y1 + rs.uniform(50, 199, n_boxes)], axis=1)
    valid = np.zeros((b, g), bool)
    valid[:, :n_boxes] = True
    classes = np.zeros((b, g), np.int32)
    classes[:, :n_boxes] = rs.randint(1, 81, (b, n_boxes))
    return {
        "image": rs.randn(b, h, w, 3).astype(np.float32),
        "im_info": np.asarray([[600, 1000, 1.0]] * b, np.float32),
        "gt_boxes": boxes,
        "gt_classes": classes,
        "gt_valid": valid,
    }


def step_flops(compiled) -> float:
    """XLA's analytic FLOP count from an already-compiled train step."""
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):  # older jax: one dict per device
            analysis = analysis[0]
        return float(analysis.get("flops", 0.0))
    except Exception:
        return 0.0


def bench_config(cfg, reps: int = 5, iters: int = 10):
    from mx_rcnn_tpu.models.zoo import build_model, forward_train, init_params
    from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
    from mx_rcnn_tpu.train.optimizer import build_optimizer
    from mx_rcnn_tpu.train.step import create_train_state, make_train_step

    b = cfg.train.batch_images
    batch = make_batch(cfg)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    tx = build_optimizer(cfg, params, steps_per_epoch=1000)
    state = create_train_state(params, tx)
    mesh = create_mesh(str(jax.device_count()))
    step_fn = make_train_step(model, cfg, mesh=mesh, forward_fn=forward_train)
    batch = shard_batch(batch, mesh)

    rng = jax.random.PRNGKey(1)
    # AOT-compile ONCE and time the compiled executable directly: this
    # pins the donated/device layouts up front (no second trace on the
    # first donated call) and gives cost_analysis() for free — no second
    # compile just for FLOPs.
    rng, k0 = jax.random.split(rng)
    compiled = step_fn.lower(state, batch, k0).compile()
    flops = step_flops(compiled)

    # Warmup: two steps through the compiled executable.
    for _ in range(2):
        rng, k = jax.random.split(rng)
        state, metrics = compiled(state, batch, k)
        jax.block_until_ready(metrics["TotalLoss"])

    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            rng, k = jax.random.split(rng)
            state, metrics = compiled(state, batch, k)
        jax.block_until_ready(metrics["TotalLoss"])
        rates.append(iters * b / (time.perf_counter() - t0))
    img_s = statistics.median(rates)
    per_chip = img_s / jax.device_count()
    step_ms = 1000.0 * b / img_s

    # cost_analysis() counts the PER-DEVICE (SPMD-partitioned) program, so
    # per-device flops × global steps/sec ÷ per-chip peak is already the
    # per-chip MFU — no extra device_count factor.
    mfu = (flops * img_s / b) / V5E_PEAK_FLOPS if flops else None
    return {
        "img_s_per_chip": round(per_chip, 3),
        "step_ms": round(step_ms, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "reps_img_s": [round(r, 2) for r in rates],
    }


def main():
    from mx_rcnn_tpu.config import generate_config

    # Flagship shapes: (600,1000)-scale COCO canvas padded to 640x1024,
    # full train proposal path — the reference's headline training
    # configuration (C4) and BASELINE config 3 (FPN), each at per-chip
    # batch 1 (reference recipe, r01-r02 comparison point) and batch 2
    # (the Detectron-lineage recipe; amortizes fixed per-step overhead —
    # measured +40% through the axon relay, ~flat co-located, PERF.md).
    def cfg_for(net, b):
        return generate_config(net, "coco", **{
            "image.pad_shape": (640, 1024), "train.batch_images": b})

    configs = {
        "c4_r101": cfg_for("resnet101", 1),
        "c4_r101_b2": cfg_for("resnet101", 2),
        "fpn_r101": cfg_for("resnet101_fpn", 1),
        "fpn_r101_b2": cfg_for("resnet101_fpn", 2),
    }
    detail = {name: bench_config(cfg) for name, cfg in configs.items()}

    # Headline: best C4 recipe (batch 1 vs 2) — same model, same shapes.
    headline = max(detail["c4_r101"]["img_s_per_chip"],
                   detail["c4_r101_b2"]["img_s_per_chip"])
    print(json.dumps({
        "metric": "faster_rcnn_r101_coco_train_img_per_sec_per_chip",
        "value": headline,
        "unit": "img/s/chip",
        "vs_baseline": round(headline / REFERENCE_IMG_S, 3),
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
