"""Benchmark: train-step throughput + MFU on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"headline_config", "detail"}. The headline metric stays the C4 R-101
img/s/chip figure (comparable across rounds r01->); "headline_config"
names the recipe that produced it (ADVICE r3: keep round-over-round deltas
interpretable). "detail" carries per-config {img_s, step_ms, mfu} for ALL
FIVE BASELINE families — C4 (configs 1-2), FPN (config 3), Mask R-CNN
(config 4), ViTDet and DETR (config 5) — each the MEDIAN of 5 timed
repetitions (the axon relay adds run-to-run host noise; see PERF.md).

Timing discipline: every repetition ends by MATERIALIZING the loss value
on the host (float(...)), not jax.block_until_ready — through the axon
relay, block_until_ready can acknowledge enqueue before execution
finishes when the whole repetition fits in the relay pipeline (measured:
a 4-dispatch loop "finished" 14x faster than the chip's peak FLOP rate
allows; PERF.md r4). Fetching the scalar's bytes cannot be faked.

The `*_msd8` recipes drive 8 optimizer steps per host dispatch
(train.multi_step_dispatch — one lax.scan-ed program), eliminating the
fixed per-dispatch relay overhead instead of amortizing it with batch 2.
The `*_flat` recipes run the flatcore storage mode (train.flat_params —
fused flat-buffer optimizer update, train/flatcore.py), and
`update_r101`/`update_detr` isolate the optimizer update itself (tree vs
flat at full model size) so the ~6 ms many-buffer floor (PERF.md r4) is
a tracked number.

Crash-durability: every completed config's row is flushed to
<obs_dir>/partial.json (MX_RCNN_BENCH_PARTIAL overrides) the moment it
lands — an rc=124 mid-sweep keeps its finished measurements (the
BENCH_r05 lesson).

MFU: analytic FLOPs from XLA's own cost model for the whole compiled
program (fwd+bwd+update, x8 for msd8), divided by the v5e peak OF THE
RECIPE'S COMPUTE DTYPE (graftcast: 197 TFLOP/s bf16, ~98.5 f32 —
obs/costs.py::peak_flops_for); every row carries a `compute_dtype`
field and `ledger check` only grades rows against prior rows of the
SAME dtype.

The `*_bf16` recipes run graftcast's flatcore-native mixed precision
(train.compute_dtype=bf16 + train.flat_params: f32 master buffers, ONE
cast kernel per dtype buffer feeding the forward — train/precision.py);
`update_r101_bf16` isolates the update+shadow-cast program so the
cast's marginal cost over the plain flat update (`update_r101`, pinned
f32 so its trend line keeps measuring the same program) is a tracked
number.

graftscope: every run also writes an event stream + folded summary to
MX_RCNN_BENCH_OBS (default ./bench_obs) — per-config `bench` events plus
every XLA compile the run triggered, folded by obs/report.py into
bench_obs/report.json (the printed line carries its path).

The reference never published throughput (BASELINE.md: Speedometer logs
only), so vs_baseline is measured against a fixed reference point of
5.0 img/s/GPU — a generous estimate of the classic implementation's
ResNet-101 COCO training speed on a 2017 P100 (README-era hardware), used
solely to make the ratio meaningful across rounds.
"""

from __future__ import annotations

import json
import os
import statistics
import time
from typing import Optional

import jax
import numpy as np

# Persistent compile cache (shared with the suite/CLIs; the bench is
# compile-dominated cold, warm runs pay tracing only).
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from mx_rcnn_tpu.obs import compile_track
from mx_rcnn_tpu.obs import costs as obs_costs
from mx_rcnn_tpu.obs.events import _json_default
from mx_rcnn_tpu.utils.compile_cache import enable_persistent_cache

enable_persistent_cache()

REFERENCE_IMG_S = 5.0  # estimated reference img/s/GPU (see module docstring)
V5E_PEAK_FLOPS = obs_costs.V5E_PEAK_FLOPS  # bf16 peak per chip


def make_batch(cfg):
    b = cfg.train.batch_images
    h, w = cfg.image.pad_shape
    g = cfg.train.max_gt_boxes
    rs = np.random.RandomState(0)
    n_boxes = min(8, g)  # tiny tier-1 configs cap max_gt_boxes below 8
    # Box span and im_info content size scale with the canvas so tiny
    # tier-1 configs stay well-formed; at the flagship 640x1024 canvas
    # these reduce EXACTLY to the historical constants (span 200, boxes
    # uniform(50,199), content 600x1000 — rounds stay comparable). The
    # content-vs-canvas gap is also the measured pad_waste baseline.
    span = max(8, min(200, h // 2, w // 2))
    content_h, content_w = h * 600 // 640, w * 1000 // 1024
    boxes = np.zeros((b, g, 4), np.float32)
    for i in range(b):
        x1 = rs.uniform(0, w - span, n_boxes)
        y1 = rs.uniform(0, h - span, n_boxes)
        boxes[i, :n_boxes] = np.stack(
            [x1, y1, x1 + rs.uniform(span // 4, span - 1, n_boxes),
             y1 + rs.uniform(span // 4, span - 1, n_boxes)], axis=1)
    valid = np.zeros((b, g), bool)
    valid[:, :n_boxes] = True
    classes = np.zeros((b, g), np.int32)
    classes[:, :n_boxes] = rs.randint(1, cfg.dataset.num_classes,
                                      (b, n_boxes))
    batch = {
        "image": rs.randn(b, h, w, 3).astype(np.float32),
        "im_info": np.asarray([[content_h, content_w, 1.0]] * b,
                              np.float32),
        "gt_boxes": boxes,
        "gt_classes": classes,
        "gt_valid": valid,
    }
    if cfg.network.use_mask:
        m = cfg.train.mask_gt_resolution
        gm = np.zeros((b, g, m, m), np.uint8)
        gm[:, :n_boxes, 2:-2, 2:-2] = 1
        batch["gt_masks"] = gm
    return batch


def make_packed_batch(cfg):
    """Synthetic PACKED batch (graftcanvas — the ops/canvas.py contract):
    orientation-PURE landscape content at the first training scale (the
    aspect-grouped common case — mixed-orientation packing is covered by
    unit tests, not benched), shelf-packed into the config's fixed
    canvas by the real planner, random pixels in the placements and
    zeros in the gaps. The reported ``pad_waste`` is then genuine canvas
    utilization for the recipe's geometry."""
    from mx_rcnn_tpu.data.canvas import (content_size, plan_batch,
                                         validate_canvas_pack)

    spec = validate_canvas_pack(cfg)
    b = cfg.train.batch_images
    g = cfg.train.max_gt_boxes
    target, max_size = cfg.image.scales[0]
    rs = np.random.RandomState(0)
    # COCO-ish landscape source dims — aspect grouping keeps real
    # batches orientation-pure, so the bench times the common case (the
    # rare mixed seam batch pays scale-to-fit, covered by unit tests).
    # At the (600,1000) C4 scale these resize to the historical 600x1000
    # content, so canvas rows stay comparable to the bucketed recipes.
    srcs = [(480, 800) for _ in range(b)]

    def sizes_at(fit):
        t = max(1, int(round(target * fit)))
        mx = max(1, int(round(max_size * fit)))
        return [content_size(h0, w0, t, mx)[:2] for h0, w0 in srcs]

    placements, fit, sizes = plan_batch(sizes_at, b, spec)
    planes = b // spec.images
    ch, cw = spec.shape
    image = np.zeros((planes, ch, cw, 3), np.float32)
    info = np.zeros((planes, spec.images, 5), np.float32)
    boxes = np.zeros((planes, spec.images, g, 4), np.float32)
    classes = np.zeros((planes, spec.images, g), np.int32)
    valid = np.zeros((planes, spec.images, g), bool)
    n_boxes = min(8, g)
    t_f = max(1, int(round(target * fit)))
    m_f = max(1, int(round(max_size * fit)))
    for k, ((pl, y0, x0), (h, w)) in enumerate(zip(placements, sizes)):
        slot = k % spec.images
        image[pl, y0:y0 + h, x0:x0 + w] = rs.randn(h, w, 3)
        scale = content_size(*srcs[k], t_f, m_f)[2]
        info[pl, slot] = (h, w, scale, y0, x0)
        span = max(8, min(200, h // 2, w // 2))
        x1 = x0 + rs.uniform(0, w - span, n_boxes)
        y1 = y0 + rs.uniform(0, h - span, n_boxes)
        boxes[pl, slot, :n_boxes] = np.stack(
            [x1, y1, x1 + rs.uniform(span // 4, span - 1, n_boxes),
             y1 + rs.uniform(span // 4, span - 1, n_boxes)], axis=1)
        classes[pl, slot, :n_boxes] = rs.randint(
            1, cfg.dataset.num_classes, n_boxes)
        valid[pl, slot, :n_boxes] = True
    batch = {"image": image, "im_info": info, "gt_boxes": boxes,
             "gt_classes": classes, "gt_valid": valid}
    if cfg.network.use_mask:
        m = cfg.train.mask_gt_resolution
        gm = np.zeros((planes, spec.images, g, m, m), np.uint8)
        gm[:, :, :n_boxes, 2:-2, 2:-2] = 1
        batch["gt_masks"] = gm
    return batch


def step_flops(compiled) -> float:
    """XLA's analytic FLOP count from an already-compiled train step
    (graftprof: obs/costs.py owns the full cost/memory extraction)."""
    return obs_costs.executable_costs(compiled).get("flops", 0.0)


def bench_config(cfg, reps: int = 5, iters: int = 20):
    from mx_rcnn_tpu.models.zoo import build_model, forward_train, init_params
    from mx_rcnn_tpu.parallel.mesh import create_mesh, shard_batch
    from mx_rcnn_tpu.train import flatcore, precision
    from mx_rcnn_tpu.train.optimizer import build_optimizer
    from mx_rcnn_tpu.train.step import create_train_state, make_train_step

    policy = precision.policy_of(cfg)

    b = cfg.train.batch_images
    multi = max(1, cfg.train.multi_step_dispatch)
    batch = (make_packed_batch(cfg) if cfg.image.canvas_pack
             else make_batch(cfg))
    if multi > 1:
        batch = {k: np.stack([v] * multi) for k, v in batch.items()}
        iters = max(1, iters // multi)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    # flatcore recipes (train.flat_params): flat-buffer state, fused
    # update. Built directly (init_state) — flattening a fresh tree state
    # would round-trip every zero opt slot through the host.
    core = None
    if flatcore.flat_mode_for(cfg):
        core = flatcore.FlatCore(cfg, params, steps_per_epoch=1000)
        state = core.init_state(params)
    else:
        tx = build_optimizer(cfg, params, steps_per_epoch=1000)
        state = create_train_state(params, tx)
    mesh = create_mesh(str(jax.device_count()))
    step_fn = make_train_step(model, cfg, mesh=mesh, forward_fn=forward_train,
                              flat_core=core)
    batch = shard_batch(batch, mesh, stacked=multi > 1)

    rng = jax.random.PRNGKey(1)
    # AOT-compile ONCE and time the compiled executable directly: this
    # pins the donated/device layouts up front (no second trace on the
    # first donated call) and gives cost_analysis() for free — no second
    # compile just for FLOPs. The compile counter (graftprof) tallies
    # the real XLA compiles this row triggered — incl. the warmups, so
    # a donation-layout recompile shows up in compile_s too; a warm
    # persistent-cache run honestly reports 0.
    with compile_track.count() as cc:
        rng, k0 = jax.random.split(rng)
        compiled = step_fn.lower(state, batch, k0).compile()
        # XLA cost analysis counts a lax.scan BODY once, not per trip
        # (verified: the msd8 program reports the same flops as one
        # step), so this is per-OPTIMIZER-STEP flops for every recipe.
        costs = obs_costs.executable_costs(compiled)
        flops = costs.get("flops", 0.0)

        # Warmup dispatches through the compiled executable.
        for _ in range(4):
            rng, k = jax.random.split(rng)
            state, metrics = compiled(state, batch, k)
            float(np.asarray(metrics["TotalLoss"]))

    imgs_per_dispatch = b * multi
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            rng, k = jax.random.split(rng)
            state, metrics = compiled(state, batch, k)
        # Hard barrier: fetch the scalar VALUE (see module docstring).
        float(np.asarray(metrics["TotalLoss"]))
        rates.append(iters * imgs_per_dispatch
                     / (time.perf_counter() - t0))
    img_s = statistics.median(rates)
    per_chip = img_s / jax.device_count()
    step_ms = 1000.0 * b / img_s  # per optimizer step

    # cost_analysis() counts the PER-DEVICE (SPMD-partitioned) program, so
    # per-device flops x steps/sec / per-chip peak is already the
    # per-chip MFU — no extra device_count factor (obs_costs.mfu_from).
    # The peak is the COMPUTE DTYPE's (graftcast): a bf16 row graded
    # against the f32 peak would read ~2x inflated.
    mfu = obs_costs.mfu_from(flops, img_s / b,
                             obs_costs.peak_flops_for(policy.compute))
    pad = obs_costs.batch_pad_waste(batch)
    return {
        "img_s_per_chip": round(per_chip, 3),
        "step_ms": round(step_ms, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "compute_dtype": policy.short,
        # graftprof: the executable's HBM footprint (args+temps+output
        # −alias from memory_analysis) and this batch's padding waste —
        # the HBM headroom and canvas-packing numbers the ledger tracks.
        "hbm_bytes": costs.get("hbm_bytes"),
        "pad_waste": pad.get("pad_waste"),
        "compile_s": round(cc.seconds, 3),
        "n_executables": cc.n,
        "reps_img_s": [round(r, 2) for r in rates],
    }


def bench_update_config(cfg, reps: int = 5, iters: int = 50):
    """Isolated optimizer-update microbench: tree vs flat over the SAME
    synthetic gradients at full model size — the ~6 ms many-buffer floor
    (PERF.md r4 item 3) as a TRACKED number instead of a probe anecdote.
    No forward/backward: the jitted program is exactly `apply_gradients`,
    donated state, barrier = materializing the step counter's bytes."""
    from mx_rcnn_tpu.models.zoo import build_model, init_params
    from mx_rcnn_tpu.train import flatcore, precision
    from mx_rcnn_tpu.train.optimizer import build_optimizer
    from mx_rcnn_tpu.train.step import create_train_state

    policy = precision.policy_of(cfg)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(7)
    grads = jax.tree_util.tree_map(
        lambda p: (jax.random.normal(jax.random.fold_in(key, p.size),
                                     p.shape) * 1e-3).astype(p.dtype),
        params)
    tx = build_optimizer(cfg, params, steps_per_epoch=1000)
    core = flatcore.FlatCore(cfg, params, steps_per_epoch=1000)
    n_leaves = len(core.table.segments)

    def timed(state, gr):
        fn = jax.jit(lambda s, g: s.apply_gradients(g), donate_argnums=(0,))
        state = fn(state, gr)  # compile + donated-layout warmup
        for _ in range(3):
            state = fn(state, gr)
        float(np.asarray(state.step))
        rates = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                state = fn(state, gr)
            float(np.asarray(state.step))  # hard barrier (module docstring)
            rates.append(1000.0 * (time.perf_counter() - t0) / iters)
        return statistics.median(rates)

    # Flat state/grads are built BEFORE the tree timing: timed() donates
    # its state, whose param leaves alias `params` — flattening afterwards
    # would device_get deleted arrays.
    flat_state = core.init_state(params)
    fgrads = {d: jax.numpy.asarray(b)
              for d, b in core.table.flatten(grads).items()}
    with compile_track.count() as cc:  # graftprof compile accounting
        tree_ms = timed(create_train_state(params, tx), grads)
        flat_ms = timed(flat_state, fgrads)
    return {
        "tree_ms": round(tree_ms, 3),
        # under compute_dtype=bf16 flat_ms INCLUDES the graftcast shadow
        # cast (FlatCore.apply re-materializes the bf16 view buffer —
        # one convert per dtype buffer); vs the f32-pinned update_r101
        # row this isolates the cast's marginal per-step cost.
        "flat_ms": round(flat_ms, 3),
        "speedup": round(tree_ms / flat_ms, 3) if flat_ms else None,
        "param_leaves": n_leaves,
        "optimizer": cfg.train.optimizer,
        "compute_dtype": policy.short,
        "compile_s": round(cc.seconds, 3),
        "n_executables": cc.n,
    }


def bench_eval_config(cfg, batch_size: int = 4, reps: int = 5,
                      iters: int = 10):
    """Inference-path throughput: the Predictor's fused detect program
    (backbone → proposals → box head → decode → per-class NMS → packed
    (B, M, 7) output) at the test-time proposal budget (6000→300). The
    packed output read IS the barrier — eval always fetches its bytes.
    """
    from mx_rcnn_tpu.models.zoo import build_model, init_params
    from mx_rcnn_tpu.evaluation.tester import Predictor
    from mx_rcnn_tpu.train import precision

    policy = precision.policy_of(cfg)
    h, w = cfg.image.pad_shape
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    predictor = Predictor(model, params, cfg)
    rs = np.random.RandomState(0)
    images = rs.randn(batch_size, h, w, 3).astype(np.float32)
    im_info = np.asarray([[600, 1000, 1.0]] * batch_size, np.float32)

    with compile_track.count() as cc:
        compiled = predictor._detect.lower(params, images, im_info).compile()
        costs = obs_costs.executable_costs(compiled)
        flops = costs.get("flops", 0.0)
        for _ in range(3):
            np.asarray(compiled(params, images, im_info))  # warmup + barrier
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = compiled(params, images, im_info)
        np.asarray(out)
        rates.append(iters * batch_size / (time.perf_counter() - t0))
    img_s = statistics.median(rates)
    # The detect program is a plain jit on ONE device (no mesh), so the
    # measured rate already IS the per-chip rate — no device_count division
    # (unlike bench_config, whose step shards over all devices).
    mfu = obs_costs.mfu_from(flops, img_s / batch_size,
                             obs_costs.peak_flops_for(policy.compute))
    return {
        "img_s_per_chip": round(img_s, 3),
        "batch_size": batch_size,
        "ms_per_img": round(1000.0 / img_s, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "compute_dtype": policy.short,
        "hbm_bytes": costs.get("hbm_bytes"),
        "compile_s": round(cc.seconds, 3),
        "n_executables": cc.n,
        "reps_img_s": [round(r, 2) for r in rates],
    }


def flush_partial(path: str, payload: dict):
    """Atomically (tmp + rename) persist the sweep's completed rows.

    BENCH_r05 lost every completed config to an rc=124 timeout because the
    detail dict only hit disk in the final print; now each config's result
    lands here the moment it completes, so a killed run leaves its rows."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        # obs' last-resort coercion: an np/jnp scalar a recipe forgot to
        # round() must degrade in place, not kill the remaining sweep
        json.dump(payload, fh, indent=2, sort_keys=True,
                  default=_json_default)
        fh.write("\n")
    os.replace(tmp, path)


def run_sweep(configs: dict, runner, detail=None, elog=None,
              flush_path=None, attempts: int = 2,
              timeout_s: Optional[float] = None, on_row=None):
    """Measure each config, recording errors per-row (a relay drop must
    not lose the sweep) and flushing the accumulated detail dict to
    `flush_path` after EVERY config.

    timeout_s arms graftguard deadline isolation (resilience/isolate.py):
    each config runs in a spawn child with a per-config deadline, so a
    hung compile forfeits ONE row (a structured timeout row) instead of
    the whole sweep — the BENCH_r05 rc=124 failure mode. A timeout is
    never retried (a hung compile would just hang again); child error
    rows get the same `attempts` retry as in-process exceptions. The
    children share the persistent compile cache, but their XLA compiles
    are no longer visible to the parent's compile_track. timeout_s=None
    keeps the in-process path (unit tests; trusted local runs)."""
    detail = {} if detail is None else detail
    for name, cfg in configs.items():
        for _ in range(max(1, attempts)):  # the relay occasionally drops a
            if timeout_s is not None:      # remote_compile mid-flight
                from mx_rcnn_tpu.resilience.isolate import run_with_deadline

                detail[name] = run_with_deadline(runner, cfg, timeout_s,
                                                 label=name)
                if "timeout_s" in detail[name] or "error" not in detail[name]:
                    break
            else:
                try:
                    detail[name] = runner(cfg)
                    break
                except Exception as e:  # noqa: BLE001  # graftlint: disable=broad-except — record, don't lose the whole run
                    detail[name] = {"error": f"{type(e).__name__}: {e}"}
        if elog is not None:
            elog.emit("bench", config=name, **detail[name])
        if flush_path:
            flush_partial(flush_path, detail)
        if on_row is not None:
            # graftprof perf ledger: each completed row is appended the
            # moment it lands (same crash-durability contract as
            # flush_partial — a killed sweep keeps its ledger history).
            on_row(name, detail[name])
    return detail


def main():
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.obs import compile_track, open_event_log, run_meta_fields
    from mx_rcnn_tpu.obs import report as obs_report

    # graftscope: the bench emits its measurements (and every XLA compile
    # it triggers) as events, then folds them into <obs_dir>/report.json —
    # the machine-readable artifact alongside the printed JSON line
    # (PERF.md). Override the directory with MX_RCNN_BENCH_OBS.
    obs_dir = os.environ.get("MX_RCNN_BENCH_OBS", "bench_obs")
    elog = open_event_log(obs_dir, fresh=True)  # per-run artifact

    # graftguard: ride out a transient relay outage (classified retry
    # with backoff under a deadline) BEFORE the first device touch —
    # run_meta below reads jax.default_backend(), so acquisition must
    # come first or a silent CPU fallback gets cached unguarded. Leaves
    # backend_retry events in the report (OUTAGES.md).
    # MX_RCNN_BENCH_BACKEND_DEADLINE_S overrides the 12 h default — a CI
    # bench should give up in minutes, not burn its wall clock; 0 skips
    # acquisition entirely (raw first-touch jax behavior).
    # MX_RCNN_BENCH_BACKEND_PLATFORM=tpu arms the silent-CPU-fallback
    # guard: without it a relay-less box would record CPU rows as 'TPU'
    # numbers (resilience/backend.py::_check_platform).
    from mx_rcnn_tpu.config import ResilienceConfig
    from mx_rcnn_tpu.resilience import acquire_backend

    rkw = {}
    backend_deadline = os.environ.get("MX_RCNN_BENCH_BACKEND_DEADLINE_S")
    if backend_deadline is not None:
        rkw["backend_deadline_s"] = float(backend_deadline)
    platform = os.environ.get("MX_RCNN_BENCH_BACKEND_PLATFORM")
    if platform:
        rkw["backend_platform"] = platform
    rcfg = ResilienceConfig(**rkw)
    if rcfg.backend_deadline_s > 0:
        acquire_backend(rcfg, elog=elog)
    elog.emit("run_meta", **run_meta_fields(None, tool="bench"))
    compile_track.activate(elog)

    # Per-config deadline (graftguard isolation, resilience/isolate.py):
    # each config runs in a killable spawn child, so a hung compile
    # (BENCH_r05: one rc=124 ate the whole sweep) forfeits one row.
    # MX_RCNN_BENCH_DEADLINE_S overrides; 0 disables isolation (runs
    # in-process — compile events then land in this run's report).
    deadline_s = float(os.environ.get("MX_RCNN_BENCH_DEADLINE_S", "1800"))
    timeout_s = deadline_s if deadline_s > 0 else None

    # Flagship shapes: (600,1000)-scale COCO canvas padded to 640x1024,
    # full train proposal path. All five BASELINE families; C4 and FPN at
    # batch 1 (reference recipe, r01-r03 comparison point), batch 2 (the
    # Detectron-lineage recipe; amortizes fixed per-dispatch overhead) and
    # multi-step dispatch (8 steps per host call; eliminates it).
    def cfg_for(net, b, multi=1):
        return generate_config(net, "coco", **{
            "image.pad_shape": (640, 1024), "train.batch_images": b,
            "train.multi_step_dispatch": multi})

    configs = {
        # BASELINE configs 1-2 (C4 lineage; headline family).
        "c4_r101": cfg_for("resnet101", 1),
        "c4_r101_b2": cfg_for("resnet101", 2),
        "c4_r101_msd8": cfg_for("resnet101", 1, multi=8),
        # BASELINE config 3 (acceptance config).
        "fpn_r101": cfg_for("resnet101_fpn", 1),
        "fpn_r101_b2": cfg_for("resnet101_fpn", 2),
        # The acceptance recipe (script/resnet101_fpn_coco.sh) pins
        # exact top-k; the preset default is approx. Bench both so the
        # recorded number matches what the recipe would run.
        "fpn_r101_b2_exact": generate_config("resnet101_fpn", "coco", **{
            "image.pad_shape": (640, 1024), "train.batch_images": 2,
            "network.proposal_topk": "exact"}),
        "fpn_r101_msd8": cfg_for("resnet101_fpn", 1, multi=8),
        # BASELINE config 4 (+ b2: amortizes per-dispatch overhead and the
        # HBM-bound optimizer floor; PERF.md "batch>1 lever").
        "mask_r101_fpn": cfg_for("resnet101_fpn_mask", 1),
        "mask_r101_fpn_b2": cfg_for("resnet101_fpn_mask", 2),
        # BASELINE config 5 (stretch families) + batch-scaling recipes:
        # both are bounded at b1 by small-batch conv/matmul efficiency
        # plus the fixed ~6-7 ms AdamW update (PERF.md r4 decompositions).
        "vitdet_b": cfg_for("vitdet_b", 1),
        "vitdet_b_b2": cfg_for("vitdet_b", 2),
        "detr_r50": cfg_for("detr_r50", 1),
        "detr_r50_b4": cfg_for("detr_r50", 4),
        # BASELINE config 1 family (VGG-16; SURVEY §3 symbol_vgg.py) at
        # the VOC 600x1000 canvas. fc6 (25088x4096) dominates its head.
        "vgg16_voc": generate_config("vgg", "PascalVOC", **{
            "image.pad_shape": (608, 1024), "train.batch_images": 1}),
        "vgg16_voc_b2": generate_config("vgg", "PascalVOC", **{
            "image.pad_shape": (608, 1024), "train.batch_images": 2}),
        # flatcore (train/flatcore.py): full-step A/B against the plain
        # recipes above — the fused flat update vs the per-leaf chain.
        "c4_r101_flat": generate_config("resnet101", "coco", **{
            "image.pad_shape": (640, 1024), "train.batch_images": 1,
            "train.flat_params": True}),
        "detr_r50_flat": generate_config("detr_r50", "coco", **{
            "image.pad_shape": (640, 1024), "train.batch_images": 1,
            "train.flat_params": True}),
        # graftcanvas (image.canvas_pack): whole-batch canvas packing
        # A/B against the bucketed b2 recipes above — ONE compiled
        # train-step shape regardless of scale/orientation mix, content
        # pixels instead of bucket pixels; rows land in PERF_LEDGER via
        # on_row like every other recipe, and pad_waste in the row is
        # genuine canvas utilization (make_packed_batch). The C4 canvas
        # packs 2 × (600,1000)-scale landscapes with the 16px-aligned
        # gap; the FPN recipe keeps the multi-scale preset and the
        # derived never-overflow canvas (data/canvas.py) so its row
        # grades the compile-zoo collapse at the flagship recipe.
        "c4_r101_canvas": generate_config("resnet101", "coco", **{
            "train.batch_images": 2, "image.canvas_pack": True,
            "image.canvas_shape": (1248, 1024)}),
        "fpn_r101_canvas": generate_config("resnet101_fpn", "coco", **{
            "train.batch_images": 2, "image.canvas_pack": True}),
        # graftcast (train/precision.py): flatcore-native mixed
        # precision — f32 flat master weights, ONE bf16 cast kernel per
        # dtype buffer feeding the forward, f32 islands/grads/update.
        # NOTE on A/B reading: every flat recipe inherits the bf16
        # DEFAULT, so from round 8 on c4_r101_flat runs this same
        # one-cast program at b1 — the per-leaf-cast flat baseline
        # ENDED at round 7, and the one-cast win is read as the flat
        # recipes' round-7→8 trend (same recipe, same bf16 dtype
        # bucket). These b2 rows exist to grade the flagship batch
        # geometry; rows carry compute_dtype so `ledger check` never
        # grades them against a different dtype.
        "c4_r101_bf16": generate_config("resnet101", "coco", **{
            "image.pad_shape": (640, 1024), "train.batch_images": 2,
            "train.flat_params": True, "train.compute_dtype": "bf16"}),
        "fpn_r101_bf16": generate_config("resnet101_fpn", "coco", **{
            "image.pad_shape": (640, 1024), "train.batch_images": 2,
            "train.flat_params": True, "train.compute_dtype": "bf16"}),
    }
    # Partial-results flush: every completed row lands on disk immediately
    # (rc=124-proof; see flush_partial). The final report supersedes it.
    flush_path = os.environ.get("MX_RCNN_BENCH_PARTIAL",
                                os.path.join(obs_dir, "partial.json"))

    # graftprof perf ledger (obs/ledger.py): every completed row is also
    # appended to the cross-run history (PERF_LEDGER.jsonl at the repo
    # root; MX_RCNN_PERF_LEDGER overrides, empty disables). The round
    # tag comes from MX_RCNN_BENCH_ROUND when the driver exports it.
    from mx_rcnn_tpu.obs import ledger as perf_ledger

    ledger_path = os.environ.get("MX_RCNN_PERF_LEDGER",
                                 perf_ledger.default_path())
    bench_round = os.environ.get("MX_RCNN_BENCH_ROUND")
    if bench_round:
        bench_round = int(bench_round)
    elif ledger_path:
        # No explicit round: key this sweep as the next one after the
        # ledger's latest, so `ledger check` (which grades the latest
        # round against everything before) always sees these rows.
        prior = perf_ledger.latest_round(perf_ledger.load_rows(ledger_path))
        bench_round = (prior + 1) if prior is not None else None
    ledger_sha = perf_ledger._git_sha()
    # graftpulse: every live row carries the env fingerprint (jax/jaxlib
    # versions, git_dirty) so `ledger check` regressions are attributable
    # to environment drift, not just the sha (obs/events.py).
    from mx_rcnn_tpu.obs import env_fingerprint

    env_fields = env_fingerprint()

    def ledger_row(name, row):
        if not ledger_path:
            return
        perf_ledger.append_rows(ledger_path, [perf_ledger.normalize_row(
            name, dict(env_fields, **row), round_=bench_round,
            sha=ledger_sha, source="bench")])

    detail = run_sweep(configs, bench_config, elog=elog,
                       flush_path=flush_path, timeout_s=timeout_s,
                       on_row=ledger_row)

    # Isolated optimizer-update microbench (tree vs flat) at full model
    # size: the ~6 ms many-buffer floor, tracked per round in the JSON
    # and PERF.md instead of probe anecdotes.
    # update_r101/update_detr are PINNED f32 so their trend lines keep
    # measuring the exact pre-graftcast program (the pure flat update);
    # update_r101_bf16 adds the shadow cast — the delta vs update_r101
    # is the cast's marginal per-step cost.
    update_configs = {
        "update_r101": generate_config("resnet101", "coco", **{
            "image.pad_shape": (640, 1024),
            "train.compute_dtype": "f32"}),
        "update_detr": generate_config("detr_r50", "coco", **{
            "image.pad_shape": (640, 1024),
            "train.compute_dtype": "f32"}),
        "update_r101_bf16": generate_config("resnet101", "coco", **{
            "image.pad_shape": (640, 1024),
            "train.compute_dtype": "bf16"}),
    }
    run_sweep(update_configs, bench_update_config, detail=detail,
              elog=elog, flush_path=flush_path, timeout_s=timeout_s,
              on_row=ledger_row)

    # Inference path (SURVEY §4.2 call stack: test.py → Predictor →
    # pred_eval): the jitted detect program at the test proposal budget.
    eval_configs = {
        "eval_c4_r101": generate_config("resnet101", "coco", **{
            "image.pad_shape": (640, 1024)}),
        "eval_fpn_r101": generate_config("resnet101_fpn", "coco", **{
            "image.pad_shape": (640, 1024)}),
    }
    run_sweep(eval_configs, bench_eval_config, detail=detail,
              elog=elog, flush_path=flush_path, timeout_s=timeout_s,
              on_row=ledger_row)

    # Headline: best C4 recipe — same model, same shapes, same work per
    # optimizer step across recipes.
    c4 = {k: v for k, v in detail.items()
          if k.startswith("c4") and "img_s_per_chip" in v}
    if c4:
        headline_config = max(c4, key=lambda k: c4[k]["img_s_per_chip"])
        headline = c4[headline_config]["img_s_per_chip"]
        headline_mfu = c4[headline_config].get("mfu")
    else:  # every C4 attempt hit a relay error — still emit the line
        headline_config, headline, headline_mfu = "error", 0.0, None
    if c4:
        # Ledger continuity: rounds r01/r02 predate per-config detail and
        # exist only as headline rows — keep appending one per sweep.
        ledger_row("headline", {"img_s_per_chip": headline,
                                "mfu": headline_mfu})

    compile_track.deactivate()
    elog.close()
    # Fold the run DIR, not just this process's stream: a multi-host
    # bench leaves one events_p<k>.jsonl per host, and the blob should
    # summarize all of them (grafttower fleet_* aggregates ride in via
    # bench_blob when a --fleet fold adds them).
    summary = obs_report.summarize(obs_report.load_events(obs_dir))
    report_path = os.path.join(obs_dir, "report.json")
    with open(report_path, "w", encoding="utf-8") as fh:
        # the BENCH-compatible blob (top-level value/compile_count/...,
        # full summary under "detail") — what regression gates diff.
        json.dump(obs_report.bench_blob(summary), fh, indent=2,
                  sort_keys=True)
        fh.write("\n")

    print(json.dumps({
        "metric": "faster_rcnn_r101_coco_train_img_per_sec_per_chip",
        "value": headline,
        "unit": "img/s/chip",
        # MFU is the PRIMARY efficiency number (measured against the v5e
        # bf16 peak); vs_baseline is a reconstructed convenience ratio.
        "mfu": headline_mfu,
        "vs_baseline": round(headline / REFERENCE_IMG_S, 3),
        "baseline_provenance": ("reconstructed (5.0 img/s assumed; the "
                                "reference publishes no throughput — "
                                "BASELINE.md). MFU is the measured number."),
        "headline_config": headline_config,
        # graftscope artifact: the same run folded by obs/report.py
        # (compile count/time for the whole bench, per-config rows).
        "obs_report": report_path,
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
