/* Fused image normalize+pad kernels for the host input pipeline.
 *
 * The loader's numpy normalize ((img - mean) / std) and zero-pad stages
 * hold the GIL and walk the image twice; at flagship shapes they are the
 * measured bottleneck of the packed-shard path and the reason worker
 * threads scale INVERSELY (PERF.md r4). These kernels do both in one
 * pass, called through ctypes (which releases the GIL for the duration),
 * so decode/normalize workers actually run in parallel.
 *
 * Reference lineage: rcnn/io/image.py::transform + tensor_vstack padding
 * (pure numpy there; the reference's native layer was the CUDA ops, not
 * IO — this is TPU-era surface, where the host must keep up with a chip
 * that consumes 40-55 img/s).
 *
 * Layout: HWC, C=3, RGB. dst is (ph, pw, 3) float32, fully written
 * (image region normalized, remainder zeroed). src strides are
 * contiguous rows of w*3 elements.
 */

#include <stddef.h>
#include <string.h>

void normalize_pad_u8(const unsigned char *src, long h, long w,
                      float *dst, long ph, long pw,
                      const float *mean, const float *inv_std) {
  const float m0 = mean[0], m1 = mean[1], m2 = mean[2];
  const float s0 = inv_std[0], s1 = inv_std[1], s2 = inv_std[2];
  for (long y = 0; y < ph; ++y) {
    float *drow = dst + (size_t)y * pw * 3;
    if (y < h) {
      const unsigned char *srow = src + (size_t)y * w * 3;
      for (long x = 0; x < w; ++x) {
        drow[3 * x + 0] = ((float)srow[3 * x + 0] - m0) * s0;
        drow[3 * x + 1] = ((float)srow[3 * x + 1] - m1) * s1;
        drow[3 * x + 2] = ((float)srow[3 * x + 2] - m2) * s2;
      }
      if (pw > w)
        memset(drow + 3 * w, 0, sizeof(float) * 3 * (size_t)(pw - w));
    } else {
      memset(drow, 0, sizeof(float) * 3 * (size_t)pw);
    }
  }
}

void normalize_pad_f32(const float *src, long h, long w,
                       float *dst, long ph, long pw,
                       const float *mean, const float *inv_std) {
  const float m0 = mean[0], m1 = mean[1], m2 = mean[2];
  const float s0 = inv_std[0], s1 = inv_std[1], s2 = inv_std[2];
  for (long y = 0; y < ph; ++y) {
    float *drow = dst + (size_t)y * pw * 3;
    if (y < h) {
      const float *srow = src + (size_t)y * w * 3;
      for (long x = 0; x < w; ++x) {
        drow[3 * x + 0] = (srow[3 * x + 0] - m0) * s0;
        drow[3 * x + 1] = (srow[3 * x + 1] - m1) * s1;
        drow[3 * x + 2] = (srow[3 * x + 2] - m2) * s2;
      }
      if (pw > w)
        memset(drow + 3 * w, 0, sizeof(float) * 3 * (size_t)(pw - w));
    } else {
      memset(drow, 0, sizeof(float) * 3 * (size_t)pw);
    }
  }
}

/* Horizontally mirrored variant (the loader's flip path): writes the
 * image region x-reversed, so flip + normalize + pad is ONE pass too. */
void normalize_pad_u8_flip(const unsigned char *src, long h, long w,
                           float *dst, long ph, long pw,
                           const float *mean, const float *inv_std) {
  const float m0 = mean[0], m1 = mean[1], m2 = mean[2];
  const float s0 = inv_std[0], s1 = inv_std[1], s2 = inv_std[2];
  for (long y = 0; y < ph; ++y) {
    float *drow = dst + (size_t)y * pw * 3;
    if (y < h) {
      const unsigned char *srow = src + (size_t)y * w * 3;
      for (long x = 0; x < w; ++x) {
        const unsigned char *sp = srow + 3 * (w - 1 - x);
        drow[3 * x + 0] = ((float)sp[0] - m0) * s0;
        drow[3 * x + 1] = ((float)sp[1] - m1) * s1;
        drow[3 * x + 2] = ((float)sp[2] - m2) * s2;
      }
      if (pw > w)
        memset(drow + 3 * w, 0, sizeof(float) * 3 * (size_t)(pw - w));
    } else {
      memset(drow, 0, sizeof(float) * 3 * (size_t)pw);
    }
  }
}
