/* RLE mask kernels — the native core of the mask toolkit.
 *
 * Reference analog: rcnn/pycocotools/maskApi.c (the C RLE engine under the
 * vendored pycocotools). Original implementation for the TPU build: the
 * Python layer (mx_rcnn_tpu/masks/rle.py) is the semantic reference; this
 * file provides the hot dense-mask paths via ctypes
 * (mx_rcnn_tpu/masks/_native.py), operating directly on run lists so merge
 * and IoU never materialize dense masks.
 *
 * Conventions (identical to the Python layer):
 *   - masks are column-major (Fortran) flattened H*W uint8 arrays;
 *   - counts alternate 0-run/1-run lengths starting with a (possibly
 *     empty) 0-run;
 *   - crowd IoU = intersection / detection area.
 *
 * Build: gcc -O2 -shared -fPIC -o libmaskapi.so maskapi.c
 */

#include <stddef.h>
#include <stdint.h>

/* mask (h*w, column-major flat) -> counts; returns n_counts (<= h*w + 1). */
long rle_encode(const uint8_t *mask, long n, uint32_t *counts) {
    long m = 0;
    uint8_t cur = 0; /* counts start with the 0-run */
    uint32_t run = 0;
    for (long i = 0; i < n; i++) {
        uint8_t v = mask[i] ? 1 : 0;
        if (v != cur) {
            counts[m++] = run;
            run = 0;
            cur = v;
        }
        run++;
    }
    counts[m++] = run;
    return m;
}

/* counts -> mask (caller-allocated n bytes). Returns 0 ok, -1 length err. */
long rle_decode(const uint32_t *counts, long m, uint8_t *mask, long n) {
    long pos = 0;
    uint8_t val = 0;
    for (long i = 0; i < m; i++) {
        uint32_t c = counts[i];
        if (pos + (long)c > n) return -1;
        for (uint32_t j = 0; j < c; j++) mask[pos++] = val;
        val ^= 1;
    }
    return pos == n ? 0 : -1;
}

long rle_area(const uint32_t *counts, long m) {
    long a = 0;
    for (long i = 1; i < m; i += 2) a += counts[i];
    return a;
}

/* Run-walking iterator over one RLE. it.i's parity is the pixel value. */
typedef struct {
    const uint32_t *c;
    long m;        /* number of counts */
    long i;        /* current run index */
    uint32_t left; /* remaining pixels in current run */
} rle_iter;

static void it_skip_empty(rle_iter *it) {
    while (it->left == 0 && it->i + 1 < it->m) {
        it->i++;
        it->left = it->c[it->i];
    }
}

static void it_init(rle_iter *it, const uint32_t *c, long m) {
    it->c = c;
    it->m = m;
    it->i = 0;
    it->left = (m > 0) ? c[0] : 0;
    it_skip_empty(it);
}

static uint8_t it_val(const rle_iter *it) { return (uint8_t)(it->i & 1); }

static void it_advance(rle_iter *it, uint32_t step) {
    it->left -= step;
    it_skip_empty(it);
}

/* Merge two RLEs of EQUAL total length by walking runs in lockstep.
 * intersect=0 -> union, 1 -> intersection. Returns n_counts_out
 * (out must hold ma + mb entries). */
long rle_merge(const uint32_t *ca, long ma, const uint32_t *cb, long mb,
               uint32_t *out, int intersect) {
    rle_iter a, b;
    it_init(&a, ca, ma);
    it_init(&b, cb, mb);
    long mo = 0;
    uint8_t cur = 0;
    uint32_t run = 0;
    while (a.left > 0 && b.left > 0) {
        uint32_t step = a.left < b.left ? a.left : b.left;
        uint8_t v = intersect ? (it_val(&a) & it_val(&b))
                              : (it_val(&a) | it_val(&b));
        if (v != cur) {
            out[mo++] = run;
            run = 0;
            cur = v;
        }
        run += step;
        it_advance(&a, step);
        it_advance(&b, step);
    }
    out[mo++] = run;
    return mo;
}

/* Intersection area of two RLEs (no dense mask). */
static long rle_inter_area(const uint32_t *ca, long ma,
                           const uint32_t *cb, long mb) {
    rle_iter a, b;
    it_init(&a, ca, ma);
    it_init(&b, cb, mb);
    long inter = 0;
    while (a.left > 0 && b.left > 0) {
        uint32_t step = a.left < b.left ? a.left : b.left;
        if (it_val(&a) && it_val(&b)) inter += step;
        it_advance(&a, step);
        it_advance(&b, step);
    }
    return inter;
}

/* Pairwise IoU matrix: dts (D RLEs) x gts (G RLEs) -> out[D*G] row-major.
 * Counts are packed back-to-back; offsets/lengths index into them.
 * iscrowd[g] != 0 -> intersection / det area. */
void rle_iou(const uint32_t *dt_counts, const long *dt_off, const long *dt_len,
             long n_dt,
             const uint32_t *gt_counts, const long *gt_off, const long *gt_len,
             long n_gt,
             const uint8_t *iscrowd, double *out) {
    for (long d = 0; d < n_dt; d++) {
        const uint32_t *cd = dt_counts + dt_off[d];
        long md = dt_len[d];
        long ad = rle_area(cd, md);
        for (long g = 0; g < n_gt; g++) {
            const uint32_t *cg = gt_counts + gt_off[g];
            long mg = gt_len[g];
            long inter = rle_inter_area(cd, md, cg, mg);
            double denom;
            if (iscrowd[g]) {
                denom = (double)ad;
            } else {
                denom = (double)(ad + rle_area(cg, mg) - inter);
            }
            out[d * n_gt + g] = denom > 0 ? (double)inter / denom : 0.0;
        }
    }
}
