"""Single-image inference demo (reference entry point: demo.py).

    python demo.py --network resnet101 --dataset coco --prefix model/e2e \
        --epoch 10 --image street.jpg --out vis.jpg

With no --image, runs on a generated synthetic scene (offline smoke test).
"""

from __future__ import annotations

import argparse

import jax
import numpy as np

from mx_rcnn_tpu.utils.compile_cache import enable_persistent_cache
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data.image import (
    load_image, pad_image, resize_image, transform_image)
from mx_rcnn_tpu.evaluation.tester import Predictor, im_detect
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models.zoo import build_model, init_params
from mx_rcnn_tpu.train.checkpoint import load_checkpoint
from mx_rcnn_tpu.utils.vis import draw_detections


def parse_args():
    p = argparse.ArgumentParser(description="Faster R-CNN demo")
    p.add_argument("--network", default="resnet101")
    p.add_argument("--dataset", default="coco")
    p.add_argument("--prefix", default=None,
                   help="checkpoint prefix; random weights if omitted")
    p.add_argument("--epoch", type=int, default=10)
    p.add_argument("--image", default=None)
    p.add_argument("--out", default="demo_out.jpg")
    p.add_argument("--thresh", type=float, default=0.5)
    p.add_argument("--from-scratch", dest="from_scratch", action="store_true",
                   help="match a train_end2end.py --from-scratch checkpoint "
                        "(GroupNorm backbone)")
    return p.parse_args()


def main():
    enable_persistent_cache()
    args = parse_args()
    overrides = {}
    if args.from_scratch:
        overrides["network.norm"] = "group"
        overrides["network.freeze_at"] = 0
    cfg = generate_config(args.network, args.dataset, **overrides)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    if args.prefix:
        params, _ = load_checkpoint(
            args.prefix, args.epoch, template={"params": params},
            means=cfg.train.bbox_means, stds=cfg.train.bbox_stds,
            num_classes=cfg.dataset.num_classes)

    if args.image:
        raw = load_image(args.image)
    else:
        from mx_rcnn_tpu.data.datasets import SyntheticDataset
        raw = SyntheticDataset("demo", num_images=1)._gen(0)[0]
        logger.info("no --image given; using a synthetic scene")

    target, max_size = cfg.image.scales[0]
    img, scale = resize_image(raw, target, max_size)
    h, w = img.shape[:2]
    img_t = pad_image(
        transform_image(img, cfg.image.pixel_means, cfg.image.pixel_stds),
        cfg.image.pad_shape)
    im_info = np.asarray([[h, w, scale]], np.float32)

    predictor = Predictor(model, params, cfg)
    dets = im_detect(predictor, img_t[None], im_info, scale)[0]
    dets = dets[dets[:, 1] >= args.thresh]
    logger.info("%d detections above %.2f", len(dets), args.thresh)

    class_names = cfg.dataset.class_names or tuple(
        str(i) for i in range(cfg.dataset.num_classes))
    vis = draw_detections(raw.astype(np.uint8), dets, class_names)
    try:
        from PIL import Image
        Image.fromarray(vis).save(args.out)
        logger.info("wrote %s", args.out)
    except Exception as exc:  # pragma: no cover
        logger.warning("could not save visualization: %s", exc)


if __name__ == "__main__":
    main()
