"""mx_rcnn_tpu — a TPU-native region-based object-detection framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of the classic MXNet
Faster R-CNN framework (reference: acaridor/mx-rcnn, see SURVEY.md):

- Faster R-CNN training (end-to-end and 4-stage alternate optimization) and
  Fast R-CNN, with VGG-16 and ResNet-50/101 (C4) backbones, extended with
  FPN / Mask R-CNN heads.
- PASCAL VOC and COCO datasets with in-repo evaluation (VOC AP and COCO
  mAP@[.5:.95] including an RLE mask API — pycocotools is not a dependency).
- All detection ops (Proposal, NMS, ROIAlign/ROIPool, box/anchor math,
  anchor/roi target assignment) are static-shape, jit-traceable JAX
  functions / Pallas kernels that run *inside* the compiled train step —
  no host round-trips (the reference runs these as Python CustomOps /
  Cython / CUDA: rcnn/symbol/proposal.py, rcnn/cython/*, rcnn/processing/*).
- Data parallelism is a `jax.sharding.Mesh` + jit-with-shardings train step
  with XLA `psum` gradient allreduce over ICI/DCN (the reference uses MXNet
  Module/KVStore: rcnn/core/module.py).

Design rules (TPU-first):
- Static shapes everywhere: fixed max counts + validity masks replace every
  data-dependent filter in the reference.
- bfloat16 matmul path, float32 parameters and losses.
- No data-dependent Python control flow inside jit; `lax` control flow only.
"""

__version__ = "0.1.0"
