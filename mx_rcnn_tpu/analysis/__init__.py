"""graftlint — this repo's AST-based static-analysis pass.

Machine-enforces the trace-safety and config conventions the codebase
already follows by hand (see the package docstring's "Design rules" and
train/step.py's donation convention), before multi-chip debugging makes
violations expensive:

- host-sync-in-jit      host round-trips inside traced code
- data-dependent-shape  dynamic result shapes (TPU recompile bombs)
- missing-donation      jitted state steps without buffer donation
- prng-key-reuse        a key consumed twice without a split
- cfg-contract          cfg.section.field chains resolved against config.py
- broad-except          `except Exception` outside import probes

Run ``python -m mx_rcnn_tpu.analysis`` (configured via
``[tool.graftlint]`` in pyproject.toml); the API surface for tests is
``lint_source`` / ``run``. Stdlib-only — importing this package never
imports jax.
"""

from mx_rcnn_tpu.analysis.engine import (  # noqa: F401
    Finding,
    LintResult,
    lint_file,
    lint_source,
    lint_sources,
    run,
)
from mx_rcnn_tpu.analysis.settings import Settings, find_repo_root  # noqa: F401

__all__ = ["Finding", "LintResult", "lint_file", "lint_source",
           "lint_sources", "run", "Settings", "find_repo_root"]
