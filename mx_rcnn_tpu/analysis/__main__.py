"""``python -m mx_rcnn_tpu.analysis`` — see cli.py."""

import sys

from mx_rcnn_tpu.analysis.cli import main

sys.exit(main())
