"""On-disk parsed-AST cache — pre-commit stops re-parsing 155 files.

One pickle file (``.graftlint-cache/ast.pkl`` under the repo root) maps
repo-relative path -> (mtime_ns, size, pickled tree). A hit returns the
unpickled tree without calling ``ast.parse``; any miss (changed file, new
file, unreadable blob, interpreter change) silently re-parses — the cache
is a pure accelerator and every failure path degrades to correctness.
Writes are atomic (temp file + rename) so concurrent lint runs can share
one cache without corrupting it.
"""

from __future__ import annotations

import ast
import os
import pickle
import sys
import tempfile
from typing import Dict, Optional, Tuple

#: bump to invalidate every entry (AST pickles are not stable across
#: interpreter minor versions — the version key guards that too)
_FORMAT = 1
_DIR_NAME = ".graftlint-cache"
_FILE_NAME = "ast.pkl"


class AstCache:
    def __init__(self, path: Optional[str]):
        self._path = path
        self._entries: Dict[str, Tuple[int, int, bytes]] = {}
        self._dirty = False
        self.hits = 0
        self.misses = 0
        if path is None or not os.path.isfile(path):
            return
        try:
            with open(path, "rb") as fh:
                payload = pickle.load(fh)
            if (payload.get("format") == _FORMAT
                    and payload.get("python") == sys.version_info[:2]):
                self._entries = dict(payload.get("entries", {}))
        except Exception:  # noqa: BLE001  # graftlint: disable=broad-except — a corrupt/foreign cache must never break the lint run; it is rebuilt below
            self._entries = {}

    @staticmethod
    def open(root: str, enabled: bool = True) -> "AstCache":
        """Cache under ``<root>/.graftlint-cache``; a disabled cache is a
        no-op object (every parse is a miss, nothing is written)."""
        if not enabled:
            return AstCache(None)
        return AstCache(os.path.join(root, _DIR_NAME, _FILE_NAME))

    def parse(self, abs_path: str, rel_path: str,
              source: str) -> Optional[ast.AST]:
        """Parse ``source`` (already read from ``abs_path``), consulting
        the cache keyed by (path, mtime, size). Returns None on
        SyntaxError (callers report it; nothing is cached)."""
        key_stat = self._stat(abs_path)
        if key_stat is not None:
            entry = self._entries.get(rel_path)
            if entry is not None and entry[:2] == key_stat:
                try:
                    tree = pickle.loads(entry[2])
                    self.hits += 1
                    return tree
                except Exception:  # noqa: BLE001  # graftlint: disable=broad-except — an unreadable blob is a miss, not an error
                    pass
        self.misses += 1
        try:
            tree = ast.parse(source, filename=rel_path)
        except SyntaxError:
            self._entries.pop(rel_path, None)
            return None
        if key_stat is not None and self._path is not None:
            try:
                blob = pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL)
            except Exception:  # noqa: BLE001  # graftlint: disable=broad-except — an unpicklable tree just stays uncached
                return tree
            self._entries[rel_path] = (key_stat[0], key_stat[1], blob)
            self._dirty = True
        return tree

    @staticmethod
    def _stat(path: str) -> Optional[Tuple[int, int]]:
        try:
            st = os.stat(path)
        except OSError:
            return None
        return (st.st_mtime_ns, st.st_size)

    def save(self):
        """Atomic write-back; errors (read-only tree, full disk) are
        swallowed — the cache is an accelerator, not a product."""
        if not self._dirty or self._path is None:
            return
        payload = {"format": _FORMAT, "python": sys.version_info[:2],
                   "entries": self._entries}
        try:
            os.makedirs(os.path.dirname(self._path), exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=os.path.dirname(self._path), suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as fh:
                    pickle.dump(payload, fh,
                                protocol=pickle.HIGHEST_PROTOCOL)
                os.replace(tmp, self._path)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        except OSError:
            pass
        self._dirty = False
