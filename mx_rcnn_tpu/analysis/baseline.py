"""Baseline suppression: adopt-now, ratchet-later.

The baseline file records pre-existing findings as (path, rule, text)
triples — ``text`` is the stripped source line, so entries survive line
drift from unrelated edits but die the moment the flagged line itself is
touched (at which point the author must fix it or re-baseline
deliberately with ``--write-baseline``). Counts make duplicate identical
lines in one file behave sanely: a baseline with count 2 absorbs at most
two matching findings.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, Iterable, List, Tuple

from mx_rcnn_tpu.analysis.engine import Finding

Key = Tuple[str, str, str]  # (path, rule, stripped line text)


def _key(f: Finding) -> Key:
    return (f.path, f.rule, f.text)


def load(path: str) -> List[dict]:
    if not os.path.isfile(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return list(data.get("suppressions", []))


def write(path: str, findings: Iterable[Finding],
          keep: Iterable[dict] = ()) -> int:
    """Adopt ``findings``; ``keep`` carries forward entries for files
    outside the linted scope so a subset --write-baseline cannot silently
    drop another file's suppressions."""
    counts: Counter = Counter(_key(f) for f in findings)
    for e in keep:
        counts[(e["path"], e["rule"], e.get("text", ""))] += int(
            e.get("count", 1))
    entries = [
        {"path": p, "rule": r, "text": t, "count": n}
        for (p, r, t), n in sorted(counts.items())
    ]
    payload = {
        "comment": ("graftlint baseline — pre-existing findings adopted "
                    "when the gate landed. Entries match on (path, rule, "
                    "source-line text); editing a flagged line invalidates "
                    "its entry. Regenerate deliberately with "
                    "`python -m mx_rcnn_tpu.analysis --write-baseline`."),
        "suppressions": entries,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return len(entries)


class Matcher:
    """Mutable view over the baseline: each entry absorbs up to ``count``
    findings; leftovers report as stale via ``unused()``."""

    def __init__(self, entries: Iterable[dict]):
        self._budget: Dict[Key, int] = {}
        for e in entries:
            k = (e["path"], e["rule"], e.get("text", ""))
            self._budget[k] = self._budget.get(k, 0) + int(e.get("count", 1))

    def consume(self, f: Finding) -> bool:
        k = _key(f)
        if self._budget.get(k, 0) > 0:
            self._budget[k] -= 1
            return True
        return False

    def unused(self) -> List[Key]:
        return [k for k, n in self._budget.items() if n > 0]
