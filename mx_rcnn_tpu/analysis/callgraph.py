"""graftsight: whole-program call graph and project-wide jit reachability.

tracing.py answers "which functions run under a JAX trace?" for ONE file;
this module answers it for the whole tree. A ``Program`` indexes every
module's imports, module-level defs, classes and methods, discovers the
jit/pjit/pallas/shard_map roots (including ``jax.jit(imported_fn)``
cross-module roots), and closes the traced set transitively over a
module-qualified call graph:

- bare-name calls, resolved lexically (enclosing scopes outward), then
  through the function-local value environment (parameter defaults and
  local assignments that bind function references — the
  ``make_train_step(forward_fn=forward_train)`` idiom), then against the
  module's top level, then through imports;
- attribute calls on imported modules (``checkpoint.load_checkpoint``),
  plain and aliased ``from``-imports, and relative imports;
- method calls resolved through class defs: ``self.m()`` walks the
  enclosing class and its resolvable bases, ``obj.m()`` uses ``obj``'s
  inferred type (constructor assignment or parameter/variable
  annotation), ``obj(...)`` resolves to ``__call__``, and
  ``self.attr.m()`` goes through the class's attribute types
  (``self.model = FasterRCNN(...)`` in ``__init__``).

Anything dynamic — ``getattr``-dispatch, registry lookups, values
returned from calls — resolves to nothing and therefore propagates
nothing: the closure stays an under-approximation, never crashing and
never over-flagging host code (the same contract tracing.py documents
for its file-local pass).

The engine builds one Program per run over the SAME parsed trees it
lints, then seeds each file's TraceAnalysis with the program's traced
nodes for that file, so every reachability-consuming rule becomes
interprocedural with no rule changes.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from mx_rcnn_tpu.analysis.tracing import (
    FuncNode, FuncOrLambda, _ScopeIndex, dotted_name, jit_expr_name,
)


def module_name_for(rel_path: str) -> str:
    """'mx_rcnn_tpu/train/step.py' -> 'mx_rcnn_tpu.train.step';
    a package's __init__.py maps to the package itself."""
    name = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    parts = [p for p in name.split("/") if p]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class _ClassInfo:
    __slots__ = ("name", "node", "bases", "methods", "attr_types")

    def __init__(self, node: ast.ClassDef):
        self.name = node.name
        self.node = node
        #: base-class expressions, resolved lazily against the module
        self.bases: List[ast.AST] = list(node.bases)
        self.methods: Dict[str, ast.AST] = {}
        #: self.<attr> -> type expression (from ``self.x = Cls(...)``
        #: assignments and class-level annotations)
        self.attr_types: Dict[str, ast.AST] = {}
        for item in node.body:
            if isinstance(item, FuncNode):
                self.methods.setdefault(item.name, item)
            elif (isinstance(item, ast.AnnAssign)
                  and isinstance(item.target, ast.Name)):
                self.attr_types.setdefault(item.target.id, item.annotation)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and isinstance(sub.value, ast.Call)):
                        self.attr_types.setdefault(tgt.attr, sub.value.func)


class _ModuleInfo:
    __slots__ = ("name", "rel_path", "tree", "parents", "scope",
                 "imports", "defs", "classes", "class_of", "_own_cache")

    def __init__(self, name: str, rel_path: str, tree: ast.AST):
        self.name = name
        self.rel_path = rel_path
        self.tree = tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.scope = _ScopeIndex()
        self.scope.visit(tree)
        #: local name -> dotted target ('pkg.mod' or 'pkg.mod.symbol')
        self.imports: Dict[str, str] = {}
        #: module-level function defs
        self.defs: Dict[str, ast.AST] = {}
        #: module-level classes
        self.classes: Dict[str, _ClassInfo] = {}
        #: method/function node -> enclosing _ClassInfo (methods only)
        self.class_of: Dict[ast.AST, _ClassInfo] = {}
        self._own_cache: Dict[ast.AST, Dict[str, ast.AST]] = {}
        for item in tree.body if hasattr(tree, "body") else []:
            if isinstance(item, FuncNode):
                self.defs.setdefault(item.name, item)
            elif isinstance(item, ast.ClassDef):
                info = _ClassInfo(item)
                self.classes.setdefault(item.name, info)
                for m in info.methods.values():
                    self.class_of[m] = info
        self._index_imports(tree)

    def _index_imports(self, tree: ast.AST):
        pkg_parts = self.name.split(".") if self.name else []
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.imports[alias.asname] = alias.name
                    else:
                        root = alias.name.split(".")[0]
                        self.imports.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    # relative: level 1 strips the module's own leaf
                    base_parts = pkg_parts[:len(pkg_parts) - node.level]
                    base = ".".join(base_parts + (
                        [node.module] if node.module else []))
                else:
                    base = node.module or ""
                if not base:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    self.imports[local] = f"{base}.{alias.name}"

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, FuncOrLambda):
            cur = self.parents.get(cur)
        return cur

    def enclosing_class(self, node: ast.AST) -> Optional[_ClassInfo]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return self.classes.get(cur.name)
            cur = self.parents.get(cur)
        return None

    def resolve_def(self, name: str, at_node: ast.AST) -> Optional[ast.AST]:
        """Lexical def resolution, innermost scope outward (the same walk
        tracing.TraceAnalysis does file-locally, own scope included)."""
        fn = self.enclosing_function(at_node)
        while fn is not None:
            chain = self.scope.chain_of.get(fn, ())
            for scope in (self._own_scope(fn),) + tuple(reversed(chain)):
                if scope and name in scope:
                    return scope[name]
            fn = self.enclosing_function(fn)
        return self.scope.module_scope.get(name)

    def _own_scope(self, fn: ast.AST) -> Dict[str, ast.AST]:
        cached = self._own_cache.get(fn)
        if cached is not None:
            return cached
        out: Dict[str, ast.AST] = {}
        for child in ast.walk(fn):
            if child is fn or not isinstance(child, FuncNode):
                continue
            if self.enclosing_function(child) is fn:
                out.setdefault(child.name, child)
        self._own_cache[fn] = out
        return out


#: abstract values the lightweight env tracks
_FUNC, _CLASS, _INSTANCE = "func", "class", "instance"
Value = Tuple[str, ast.AST, "_ModuleInfo"]  # (kind, node-or-classinfo, mod)


class Program:
    """Whole-program index + traced-set closure over all added modules."""

    def __init__(self):
        self.modules: Dict[str, _ModuleInfo] = {}
        self._by_rel: Dict[str, _ModuleInfo] = {}
        #: traced function nodes, per module name
        self._traced: Dict[str, Set[ast.AST]] = {}
        self._env_cache: Dict[ast.AST, Dict[str, List[Value]]] = {}
        self._finalized = False

    # -- construction ------------------------------------------------------

    def add_module(self, rel_path: str, tree: ast.AST):
        mi = _ModuleInfo(module_name_for(rel_path), rel_path, tree)
        self.modules[mi.name] = mi
        self._by_rel[rel_path] = mi

    def finalize(self):
        """Discover roots in every module, then close transitively."""
        work: List[Tuple[_ModuleInfo, ast.AST]] = []

        def mark(mi: _ModuleInfo, node: ast.AST):
            traced = self._traced.setdefault(mi.name, set())
            if node not in traced:
                traced.add(node)
                work.append((mi, node))

        for mi in self.modules.values():
            for mi2, node in self._find_roots(mi):
                mark(mi2, node)
        while work:
            mi, fn = work.pop()
            for call in (n for n in ast.walk(fn)
                         if isinstance(n, ast.Call)):
                for kind, target, tmod in self._callee_values(mi, call):
                    if kind == _FUNC and isinstance(target, FuncOrLambda):
                        mark(tmod, target)
                    elif kind == _CLASS:
                        init = self._find_method(target, tmod, "__init__")
                        if init is not None:
                            mark(init[1], init[0])
        self._finalized = True

    # -- queries -----------------------------------------------------------

    def traced_nodes(self, rel_path: str) -> Set[ast.AST]:
        """Function nodes in ``rel_path`` that the whole-program closure
        marks as jit-reachable (seed for the file's TraceAnalysis)."""
        mi = self._by_rel.get(rel_path)
        if mi is None:
            return set()
        return self._traced.get(mi.name, set())

    def module_for(self, rel_path: str) -> Optional[_ModuleInfo]:
        return self._by_rel.get(rel_path)

    def resolve_symbol(self, rel_path: str, name: str,
                       at_node: ast.AST) -> Optional[ast.AST]:
        """Resolve a (possibly dotted) name used in ``rel_path`` to a
        function def anywhere in the program — rules use this to chase
        imported factories (e.g. donation-hazard's step builders)."""
        mi = self._by_rel.get(rel_path)
        if mi is None:
            return None
        expr = ast.parse(name, mode="eval").body if "." in name else None
        if expr is not None:
            for kind, node, _ in self._resolve_dotted(mi, name, at_node):
                if kind == _FUNC:
                    return node
            return None
        local = mi.resolve_def(name, at_node)
        if local is not None:
            return local
        for kind, node, _ in self._lookup_module_level(mi, name):
            if kind == _FUNC:
                return node
        return None

    def function_defs_of(self, rel_path: str, expr: ast.AST,
                         at_node: ast.AST) -> List[ast.AST]:
        """Function defs an expression used in ``rel_path`` may refer to
        (Name, Attribute, call-of-constructor...). Rules use this to
        chase imported factories; unresolvable -> []."""
        mi = self._by_rel.get(rel_path)
        if mi is None:
            return []
        return [n for k, n, _ in self._value_of(mi, expr, at_node)
                if k == _FUNC and isinstance(n, FuncNode)]

    # -- root discovery ----------------------------------------------------

    def _find_roots(self, mi: _ModuleInfo
                    ) -> Iterable[Tuple[_ModuleInfo, ast.AST]]:
        for node in ast.walk(mi.tree):
            if isinstance(node, FuncNode):
                for deco in node.decorator_list:
                    if jit_expr_name(deco):
                        yield mi, node
            elif isinstance(node, ast.Call) and jit_expr_name(node.func):
                if not node.args:
                    continue
                target = node.args[0]
                if (isinstance(target, ast.Call)
                        and dotted_name(target.func)
                        in ("partial", "functools.partial")
                        and target.args):
                    target = target.args[0]
                if isinstance(target, ast.Lambda):
                    yield mi, target
                    continue
                for kind, tnode, tmod in self._value_of(mi, target, node):
                    if kind == _FUNC and isinstance(tnode, FuncOrLambda):
                        yield tmod, tnode

    # -- resolution --------------------------------------------------------

    def _callee_values(self, mi: _ModuleInfo,
                       call: ast.Call) -> List[Value]:
        out = list(self._value_of(mi, call.func, call))
        resolved: List[Value] = []
        for kind, node, tmod in out:
            if kind == _INSTANCE:  # obj(...) -> __call__
                m = self._find_method(node, tmod, "__call__")
                if m is not None:
                    resolved.append((_FUNC, m[0], m[1]))
            else:
                resolved.append((kind, node, tmod))
        return resolved

    def _value_of(self, mi: _ModuleInfo, expr: ast.AST,
                  at_node: ast.AST) -> List[Value]:
        """Abstract value(s) of an expression: function refs, classes,
        instances. Unresolvable -> []."""
        if isinstance(expr, ast.BoolOp):
            out: List[Value] = []
            for v in expr.values:
                out.extend(self._value_of(mi, v, at_node))
            return out
        if isinstance(expr, ast.Call):
            # only constructor calls produce a value we track
            inner = self._value_of(mi, expr.func, at_node)
            return [(_INSTANCE, n, m) for k, n, m in inner if k == _CLASS]
        if isinstance(expr, ast.Name):
            return self._resolve_name(mi, expr.id, at_node)
        if isinstance(expr, ast.Attribute):
            return self._resolve_attribute(mi, expr, at_node)
        return []

    def _resolve_name(self, mi: _ModuleInfo, name: str,
                      at_node: ast.AST) -> List[Value]:
        local = mi.resolve_def(name, at_node)
        if local is not None:
            return [(_FUNC, local, mi)]
        # function-local env (params with defaults / annotations, local
        # assignments binding function or class references)
        fn = mi.enclosing_function(at_node)
        while fn is not None:
            env = self._env_for(mi, fn)
            if name in env:
                return env[name]
            fn = mi.enclosing_function(fn)
        return self._lookup_module_level(mi, name)

    def _lookup_module_level(self, mi: _ModuleInfo,
                             name: str) -> List[Value]:
        if name in mi.defs:
            return [(_FUNC, mi.defs[name], mi)]
        if name in mi.classes:
            return [(_CLASS, mi.classes[name], mi)]
        if name in mi.imports:
            return self._resolve_dotted(mi, mi.imports[name], None)
        return []

    def _resolve_attribute(self, mi: _ModuleInfo, expr: ast.Attribute,
                           at_node: ast.AST) -> List[Value]:
        parts: List[str] = []
        cur: ast.AST = expr
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        parts.reverse()
        if not isinstance(cur, ast.Name):
            return []
        root = cur.id
        # self.m() / self.attr.m() through the enclosing class
        if root == "self":
            cls = mi.enclosing_class(at_node)
            if cls is None:
                return []
            return self._walk_members(cls, mi, parts)
        # a local value with a known type: obj.m()
        for kind, node, tmod in self._resolve_name(mi, root, at_node):
            if kind == _INSTANCE and parts:
                m = self._find_method(node, tmod, parts[0])
                if m is not None and len(parts) == 1:
                    return [(_FUNC, m[0], m[1])]
            elif kind == _CLASS and parts:
                return self._walk_class_members(node, tmod, parts)
        # dotted path through imports: mod.sub.fn(...)
        if root in mi.imports:
            dotted = ".".join([mi.imports[root]] + parts)
            return self._resolve_dotted(mi, dotted, at_node)
        return []

    def _walk_members(self, cls: _ClassInfo, mi: _ModuleInfo,
                      parts: Sequence[str]) -> List[Value]:
        """Resolve self.<a>.<b>... : methods directly, or through the
        class's attribute types (self.model = FasterRCNN(...))."""
        if not parts:
            return []
        if len(parts) == 1:
            m = self._find_method(cls, mi, parts[0])
            return [(_FUNC, m[0], m[1])] if m is not None else []
        ann = cls.attr_types.get(parts[0])
        if ann is None:
            return []
        for kind, node, tmod in self._type_of_expr(mi, ann):
            if kind == _CLASS:
                return self._walk_class_members(node, tmod, parts[1:],
                                                as_instance=True)
        return []

    def _walk_class_members(self, cls: _ClassInfo, mi: _ModuleInfo,
                            parts: Sequence[str],
                            as_instance: bool = False) -> List[Value]:
        if len(parts) == 1:
            m = self._find_method(cls, mi, parts[0])
            return [(_FUNC, m[0], m[1])] if m is not None else []
        return []

    def _type_of_expr(self, mi: _ModuleInfo,
                      expr: ast.AST) -> List[Value]:
        """Resolve a type-ish expression (annotation or constructor
        callee) to a class. String annotations are accepted."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            try:
                expr = ast.parse(expr.value, mode="eval").body
            except SyntaxError:
                return []
        name = dotted_name(expr)
        if name is None:
            return []
        if "." not in name:
            if name in mi.classes:
                return [(_CLASS, mi.classes[name], mi)]
            if name in mi.imports:
                return [v for v in self._resolve_dotted(
                    mi, mi.imports[name], None) if v[0] == _CLASS]
            return []
        root, rest = name.split(".", 1)
        if root in mi.imports:
            return [v for v in self._resolve_dotted(
                mi, f"{mi.imports[root]}.{rest}", None) if v[0] == _CLASS]
        return []

    def _resolve_dotted(self, mi: _ModuleInfo, dotted: str,
                        at_node: Optional[ast.AST]) -> List[Value]:
        """Resolve a fully-dotted path: longest module prefix in the
        program, then symbols through that module's top level (one import
        indirection — re-exports — is followed)."""
        parts = dotted.split(".")
        for cut in range(len(parts), 0, -1):
            mod_name = ".".join(parts[:cut])
            target = self.modules.get(mod_name)
            if target is None:
                continue
            rest = parts[cut:]
            if not rest:
                return []  # a module itself is not a callable value
            head = rest[0]
            if head in target.defs:
                return ([(_FUNC, target.defs[head], target)]
                        if len(rest) == 1 else [])
            if head in target.classes:
                cls = target.classes[head]
                if len(rest) == 1:
                    return [(_CLASS, cls, target)]
                return self._walk_class_members(cls, target, rest[1:])
            if head in target.imports:  # re-export indirection
                return self._resolve_dotted(
                    target, ".".join([target.imports[head]] + rest[1:]),
                    None)
            return []
        return []

    def _find_method(self, cls: _ClassInfo, mi: _ModuleInfo, name: str,
                     _seen: Optional[Set[int]] = None
                     ) -> Optional[Tuple[ast.AST, _ModuleInfo]]:
        """Method lookup through the class and its resolvable bases."""
        if _seen is None:
            _seen = set()
        if id(cls) in _seen:
            return None
        _seen.add(id(cls))
        if name in cls.methods:
            return cls.methods[name], mi
        for base in cls.bases:
            for kind, node, tmod in self._type_of_expr(mi, base):
                if kind == _CLASS:
                    found = self._find_method(node, tmod, name, _seen)
                    if found is not None:
                        return found
        return None

    # -- function-local value environments ---------------------------------

    def _env_for(self, mi: _ModuleInfo,
                 fn: ast.AST) -> Dict[str, List[Value]]:
        env = self._env_cache.get(fn)
        if env is not None:
            return env
        env = {}
        self._env_cache[fn] = env  # placed first: guards self-recursion
        if isinstance(fn, FuncNode):
            a = fn.args
            params = list(a.posonlyargs) + list(a.args)
            defaults = list(a.defaults)
            # defaults align with the TAIL of the positional params
            for param, default in zip(params[len(params)
                                             - len(defaults):], defaults):
                vals = self._value_of(mi, default, fn)
                if vals:
                    env[param.arg] = vals
            for param in params + list(a.kwonlyargs):
                if param.annotation is not None:
                    types = self._type_of_expr(mi, param.annotation)
                    if types:
                        env.setdefault(param.arg, [
                            (_INSTANCE, n, m) for _, n, m in types])
            for param, default in zip(a.kwonlyargs, a.kw_defaults):
                if default is not None:
                    vals = self._value_of(mi, default, fn)
                    if vals:
                        env.setdefault(param.arg, vals)
        # local assignments binding function/class references or
        # constructor results — only defs whose nearest scope is fn
        for node in ast.walk(fn):
            if mi.enclosing_function(node) is not fn:
                continue
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                vals = self._value_of(mi, node.value, node)
                if vals:
                    env.setdefault(node.targets[0].id, vals)
            elif (isinstance(node, ast.AnnAssign)
                  and isinstance(node.target, ast.Name)):
                types = self._type_of_expr(mi, node.annotation)
                if types:
                    env.setdefault(node.target.id, [
                        (_INSTANCE, n, m) for _, n, m in types])
        return env


def build_program(sources: Dict[str, ast.AST]) -> Program:
    """Program over {rel_path: parsed tree} — the engine's entry point."""
    program = Program()
    for rel_path, tree in sources.items():
        if tree is not None:
            program.add_module(rel_path, tree)
    program.finalize()
    return program
