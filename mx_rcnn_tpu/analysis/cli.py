"""graftlint CLI: ``python -m mx_rcnn_tpu.analysis [paths...]``.

Exit codes: 0 clean (baselined findings don't fail the gate), 1 live
findings or stale baseline entries, 2 bad invocation. ``--write-baseline``
adopts the current findings as the suppression file — a deliberate,
diff-reviewed act, which is why there is no "auto-append" mode.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from mx_rcnn_tpu.analysis import baseline as baseline_mod
from mx_rcnn_tpu.analysis import engine
from mx_rcnn_tpu.analysis.settings import Settings, find_repo_root


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mx_rcnn_tpu.analysis",
        description=("graftlint — AST-based trace-safety and config-"
                     "contract checks for this repo's JAX/TPU conventions"),
    )
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: "
                        "[tool.graftlint] paths)")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help="baseline suppression file (default: "
                        "[tool.graftlint] baseline)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file entirely")
    p.add_argument("--write-baseline", action="store_true",
                   help="adopt all current findings into the baseline "
                        "file and exit 0")
    p.add_argument("--disable", metavar="RULES", default=None,
                   help="comma-separated rule names to skip for this run")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--changed-only", action="store_true",
                   help="lint only files changed vs `git merge-base HEAD "
                        "main` (plus untracked); the call graph still "
                        "covers the whole tree, so cross-module "
                        "reachability stays exact. Falls back to a full "
                        "run when git is unavailable")
    p.add_argument("--stats", action="store_true",
                   help="print per-rule finding counts and wall time")
    p.add_argument("--no-cache", action="store_true",
                   help="skip the on-disk parsed-AST cache")
    p.add_argument("--root", default=None, help=argparse.SUPPRESS)
    return p


def _git_changed_files(root: str) -> Optional[List[str]]:
    """Repo-relative paths changed vs merge-base with main, plus
    untracked files; None when git can't answer (not a repo, no main)."""
    import subprocess

    def git(*cmd: str) -> Optional[str]:
        try:
            proc = subprocess.run(
                ("git", "-C", root) + cmd, capture_output=True,
                text=True, timeout=30, check=False)
        except (OSError, subprocess.TimeoutExpired):
            return None
        return proc.stdout if proc.returncode == 0 else None

    base = git("merge-base", "HEAD", "main")
    if base is None:
        return None
    changed = git("diff", "--name-only", base.strip(), "HEAD")
    worktree = git("diff", "--name-only", "HEAD")
    untracked = git("ls-files", "--others", "--exclude-standard")
    if changed is None or worktree is None or untracked is None:
        return None
    out = set()
    for blob in (changed, worktree, untracked):
        out.update(ln.strip() for ln in blob.splitlines() if ln.strip())
    return sorted(out)


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        from mx_rcnn_tpu.analysis.rules import ALL_RULES

        for rule in ALL_RULES:
            print(f"{rule.NAME:24s} {rule.RATIONALE}")
        return 0

    root = os.path.abspath(args.root) if args.root else find_repo_root()
    settings = Settings.load(root)
    if args.disable:
        settings = Settings(**{
            **settings.__dict__,
            "disable": settings.disable + tuple(
                r.strip() for r in args.disable.split(",") if r.strip()),
        })
    paths = args.paths or list(settings.paths)
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(full):
            print(f"graftlint: path not found: {p}", file=sys.stderr)
            return 2

    baseline_path = os.path.join(
        root, args.baseline or settings.baseline)
    all_entries = baseline_mod.load(baseline_path)
    entries = ([] if (args.no_baseline or args.write_baseline)
               else all_entries)

    # Subset runs (explicit paths) must not judge — or clobber — baseline
    # entries for files that were never linted.
    scopes = [os.path.relpath(
        p if os.path.isabs(p) else os.path.join(root, p),
        root).replace(os.sep, "/") for p in paths]

    def in_scope(rel_path: str) -> bool:
        return any(s == "." or rel_path == s
                   or rel_path.startswith(s.rstrip("/") + "/")
                   for s in scopes)

    lint_only = None
    if args.changed_only:
        changed = _git_changed_files(root)
        if changed is None:
            print("graftlint: --changed-only: git unavailable, "
                  "linting everything", file=sys.stderr)
        else:
            lint_only = [p for p in changed if p.endswith(".py")]
            # Only linted files may be judged for baseline staleness.
            scopes = list(lint_only)
            if not lint_only:
                print("graftlint: --changed-only: no python files "
                      "changed, nothing to lint")
                return 0

    result = engine.run(paths, root, settings, entries,
                        lint_only=lint_only,
                        use_cache=not args.no_cache)

    if args.write_baseline:
        keep = [e for e in all_entries if not in_scope(e["path"])]
        n = baseline_mod.write(baseline_path, result.findings, keep)
        print(f"graftlint: wrote {n} baseline entr"
              f"{'y' if n == 1 else 'ies'} to "
              f"{os.path.relpath(baseline_path, root)}")
        return 0

    for f in result.findings:
        print(f.render())

    # Entries outside the linted scope, or for rules switched off this
    # run, are not judged stale — they simply weren't exercised.
    matcher = baseline_mod.Matcher(
        e for e in entries
        if in_scope(e["path"]) and e["rule"] not in settings.disable)
    for f in result.baselined + result.findings:
        matcher.consume(f)
    stale = matcher.unused()
    for path, rule, text in stale:
        print(f"{path}: [stale-baseline] entry no longer matches "
              f"anything: [{rule}] {text!r}")

    if args.stats:
        for name in sorted(result.rule_stats):
            count, secs = result.rule_stats[name]
            print(f"graftlint: rule {name:24s} {int(count):4d} finding"
                  f"{'s' if count != 1 else ' '}  {secs * 1000:7.1f} ms")
        print(f"graftlint: wall {result.wall_s:.2f}s  ast-cache "
              f"{result.cache_hits} hits / {result.cache_misses} misses")

    n, b = len(result.findings), len(result.baselined)
    summary = (f"graftlint: {result.files_checked} files, "
               f"{n} finding{'s' if n != 1 else ''}")
    if b:
        summary += f" ({b} baselined)"
    if stale:
        summary += f", {len(stale)} stale baseline entries"
    print(summary)
    return 1 if (result.findings or stale) else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
