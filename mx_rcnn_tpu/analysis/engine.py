"""graftlint engine: findings, rule registry, file walker, suppressions.

The linter is deliberately dependency-free (stdlib ``ast`` + ``tokenize``
only) and never imports jax or the package under analysis — the config
contract is recovered by parsing ``config.py``'s AST (rules/cfg_contract.py),
and trace-safety is a syntactic reachability analysis (tracing.py). That
keeps ``python -m mx_rcnn_tpu.analysis`` startup at milliseconds and makes
the pass runnable in any environment that can parse the sources.

A rule is a module in ``mx_rcnn_tpu/analysis/rules/`` exposing::

    NAME = "rule-name"          # kebab-case id used in reports/suppressions
    RATIONALE = "one line"      # shown by --list-rules and in the README
    def check(ctx) -> Iterator[Finding]: ...

``ctx`` is a FileContext: parsed AST + source lines + lazily computed
shared analyses (traced-function set). Findings can be silenced three ways,
in priority order: an inline ``# graftlint: disable=rule-a,rule-b`` (or
bare ``disable``) comment on the flagged line, a baseline entry
(baseline.py), or disabling the rule in ``[tool.graftlint]``.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence

_DISABLE_RE = re.compile(
    r"#\s*graftlint:\s*disable(?:=(?P<rules>[\w,\- ]+))?")


@dataclass(frozen=True)
class Finding:
    """One lint hit. ``text`` is the stripped source line — it doubles as
    the line-shift-tolerant baseline key (baseline.py matches on
    (path, rule, text), not on line numbers)."""

    path: str  # repo-relative posix path
    rule: str
    line: int
    col: int
    message: str
    text: str = ""

    def render(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        out = f"{loc}: [{self.rule}] {self.message}"
        if self.text:
            out += f"\n    {self.text}"
        return out


class FileContext:
    """Per-file parse result + lazy shared analyses handed to every rule."""

    def __init__(self, path: str, rel_path: str, source: str,
                 tree: ast.AST, settings):
        self.path = path
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.settings = settings
        self._traced = None
        self._comments = None
        # Parent links let rules walk outward (e.g. "is this node inside a
        # loop / a traced function"); computed once per file.
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

    @property
    def traced(self):
        """tracing.TraceAnalysis for this file (computed on first use)."""
        if self._traced is None:
            from mx_rcnn_tpu.analysis import tracing

            self._traced = tracing.TraceAnalysis(self.tree, self.parents)
        return self._traced

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            path=self.rel_path,
            rule=rule,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            text=self.line_text(getattr(node, "lineno", 0)),
        )

    def comment_on(self, lineno: int) -> str:
        """The real COMMENT token on a line (tokenize, not regex over the
        raw line — a string literal containing '# graftlint: disable'
        must not suppress anything)."""
        if self._comments is None:
            self._comments = {}
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(self.source).readline):
                    if tok.type == tokenize.COMMENT:
                        self._comments[tok.start[0]] = tok.string
            except (tokenize.TokenError, IndentationError):
                pass  # parsed fine but untokenizable — no suppressions
        return self._comments.get(lineno, "")

    def is_suppressed(self, finding: Finding) -> bool:
        m = _DISABLE_RE.search(self.comment_on(finding.line))
        if not m:
            return False
        rules = m.group("rules")
        if rules is None:
            return True
        return finding.rule in {r.strip() for r in rules.split(",")}


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    files_checked: int = 0


def iter_python_files(paths: Sequence[str], root: str,
                      exclude: Sequence[str] = ()) -> Iterator[str]:
    """Yield .py files under ``paths`` (files or directories), sorted,
    skipping any whose repo-relative path starts with an exclude prefix."""

    def excluded(p: str) -> bool:
        rel = os.path.relpath(p, root).replace(os.sep, "/")
        return any(rel == e or rel.startswith(e.rstrip("/") + "/")
                   for e in exclude)

    seen = set()  # overlapping path args must not lint a file twice

    def emit(p: str):
        key = os.path.abspath(p)
        if key not in seen:
            seen.add(key)
            yield p

    for path in paths:
        path = os.path.join(root, path) if not os.path.isabs(path) else path
        if os.path.isfile(path):
            if path.endswith(".py") and not excluded(path):
                yield from emit(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__" and not excluded(
                    os.path.join(dirpath, d)))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    fp = os.path.join(dirpath, fn)
                    if not excluded(fp):
                        yield from emit(fp)


def lint_file(path: str, root: str, settings, rules) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    return lint_source(source, rel, settings, rules, abs_path=path)


def lint_source(source: str, rel_path: str, settings, rules,
                abs_path: Optional[str] = None) -> List[Finding]:
    """Lint one source blob; the API tests drive this directly."""
    try:
        tree = ast.parse(source, filename=rel_path)
    except SyntaxError as exc:
        return [Finding(path=rel_path, rule="syntax",
                        line=exc.lineno or 0, col=(exc.offset or 0),
                        message=f"syntax error: {exc.msg}")]
    ctx = FileContext(abs_path or rel_path, rel_path, source, tree, settings)
    out: List[Finding] = []
    for rule in rules:
        if rule.NAME in settings.disable:
            continue
        for f in rule.check(ctx):
            if not ctx.is_suppressed(f):
                out.append(f)
    out.sort(key=lambda f: (f.line, f.col, f.rule))
    return out


def run(paths: Sequence[str], root: str, settings,
        baseline_entries=None) -> LintResult:
    """Lint ``paths``, splitting findings into live vs baselined."""
    from mx_rcnn_tpu.analysis import baseline as baseline_mod
    from mx_rcnn_tpu.analysis.rules import ALL_RULES

    result = LintResult()
    matcher = baseline_mod.Matcher(baseline_entries or [])
    for path in iter_python_files(paths, root, settings.exclude):
        findings = lint_file(path, root, settings, ALL_RULES)
        result.files_checked += 1
        for f in findings:
            (result.baselined if matcher.consume(f)
             else result.findings).append(f)
    return result
