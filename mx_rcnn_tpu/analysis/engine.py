"""graftlint engine: findings, rule registry, file walker, suppressions.

The linter is deliberately dependency-free (stdlib ``ast`` + ``tokenize``
only) and never imports jax or the package under analysis — the config
contract is recovered by parsing ``config.py``'s AST (rules/cfg_contract.py),
and trace-safety is a syntactic reachability analysis (tracing.py). That
keeps ``python -m mx_rcnn_tpu.analysis`` startup at milliseconds and makes
the pass runnable in any environment that can parse the sources.

A rule is a module in ``mx_rcnn_tpu/analysis/rules/`` exposing::

    NAME = "rule-name"          # kebab-case id used in reports/suppressions
    RATIONALE = "one line"      # shown by --list-rules and in the README
    def check(ctx) -> Iterator[Finding]: ...

``ctx`` is a FileContext: parsed AST + source lines + lazily computed
shared analyses (traced-function set). Findings can be silenced three ways,
in priority order: an inline ``# graftlint: disable=rule-a,rule-b`` (or
bare ``disable``) comment on the flagged line, a baseline entry
(baseline.py), or disabling the rule in ``[tool.graftlint]``.
"""

from __future__ import annotations

import ast
import io
import os
import re
import time
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence

_DISABLE_RE = re.compile(
    r"#\s*graftlint:\s*disable(?:=(?P<rules>[\w,\- ]+))?")


@dataclass(frozen=True)
class Finding:
    """One lint hit. ``text`` is the stripped source line — it doubles as
    the line-shift-tolerant baseline key (baseline.py matches on
    (path, rule, text), not on line numbers)."""

    path: str  # repo-relative posix path
    rule: str
    line: int
    col: int
    message: str
    text: str = ""

    def render(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        out = f"{loc}: [{self.rule}] {self.message}"
        if self.text:
            out += f"\n    {self.text}"
        return out


class FileContext:
    """Per-file parse result + lazy shared analyses handed to every rule."""

    def __init__(self, path: str, rel_path: str, source: str,
                 tree: ast.AST, settings, program=None):
        self.path = path
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.settings = settings
        #: callgraph.Program for whole-tree runs (None for single-snippet
        #: lint_source calls) — rules use it to chase imported symbols,
        #: and `traced` seeds itself from its cross-module closure.
        self.program = program
        self._traced = None
        self._comments = None
        # Parent links let rules walk outward (e.g. "is this node inside a
        # loop / a traced function"); computed once per file.
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

    @property
    def traced(self):
        """tracing.TraceAnalysis for this file (computed on first use),
        seeded with the whole-program closure when a Program is live —
        jit rules are interprocedural exactly when the run is."""
        if self._traced is None:
            from mx_rcnn_tpu.analysis import tracing

            extra = (self.program.traced_nodes(self.rel_path)
                     if self.program is not None else ())
            self._traced = tracing.TraceAnalysis(
                self.tree, self.parents, extra_traced=extra)
        return self._traced

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            path=self.rel_path,
            rule=rule,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            text=self.line_text(getattr(node, "lineno", 0)),
        )

    def comment_on(self, lineno: int) -> str:
        """The real COMMENT token on a line (tokenize, not regex over the
        raw line — a string literal containing '# graftlint: disable'
        must not suppress anything)."""
        if self._comments is None:
            self._comments = {}
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(self.source).readline):
                    if tok.type == tokenize.COMMENT:
                        self._comments[tok.start[0]] = tok.string
            except (tokenize.TokenError, IndentationError):
                pass  # parsed fine but untokenizable — no suppressions
        return self._comments.get(lineno, "")

    def is_suppressed(self, finding: Finding) -> bool:
        m = _DISABLE_RE.search(self.comment_on(finding.line))
        if not m:
            return False
        rules = m.group("rules")
        if rules is None:
            return True
        return finding.rule in {r.strip() for r in rules.split(",")}


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    files_checked: int = 0
    #: rule name -> [finding count (live+baselined), seconds in check()]
    rule_stats: Dict[str, List[float]] = field(default_factory=dict)
    wall_s: float = 0.0
    cache_hits: int = 0
    cache_misses: int = 0


def iter_python_files(paths: Sequence[str], root: str,
                      exclude: Sequence[str] = ()) -> Iterator[str]:
    """Yield .py files under ``paths`` (files or directories), sorted,
    skipping any whose repo-relative path starts with an exclude prefix."""

    def excluded(p: str) -> bool:
        rel = os.path.relpath(p, root).replace(os.sep, "/")
        return any(rel == e or rel.startswith(e.rstrip("/") + "/")
                   for e in exclude)

    seen = set()  # overlapping path args must not lint a file twice

    def emit(p: str):
        key = os.path.abspath(p)
        if key not in seen:
            seen.add(key)
            yield p

    for path in paths:
        path = os.path.join(root, path) if not os.path.isabs(path) else path
        if os.path.isfile(path):
            if path.endswith(".py") and not excluded(path):
                yield from emit(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__" and not excluded(
                    os.path.join(dirpath, d)))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    fp = os.path.join(dirpath, fn)
                    if not excluded(fp):
                        yield from emit(fp)


def lint_file(path: str, root: str, settings, rules) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    return lint_source(source, rel, settings, rules, abs_path=path)


def lint_source(source: str, rel_path: str, settings, rules,
                abs_path: Optional[str] = None,
                program=None) -> List[Finding]:
    """Lint one source blob; the API tests drive this directly."""
    try:
        tree = ast.parse(source, filename=rel_path)
    except SyntaxError as exc:
        return [Finding(path=rel_path, rule="syntax",
                        line=exc.lineno or 0, col=(exc.offset or 0),
                        message=f"syntax error: {exc.msg}")]
    return _lint_tree(source, rel_path, tree, settings, rules,
                      abs_path=abs_path, program=program)


def _lint_tree(source: str, rel_path: str, tree: ast.AST, settings, rules,
               abs_path: Optional[str] = None, program=None,
               rule_stats: Optional[Dict] = None) -> List[Finding]:
    ctx = FileContext(abs_path or rel_path, rel_path, source, tree,
                      settings, program=program)
    out: List[Finding] = []
    for rule in rules:
        if rule.NAME in settings.disable:
            continue
        t0 = time.perf_counter() if rule_stats is not None else 0.0
        n = 0
        for f in rule.check(ctx):
            if not ctx.is_suppressed(f):
                out.append(f)
                n += 1
        if rule_stats is not None:
            stat = rule_stats.setdefault(rule.NAME, [0, 0.0])
            stat[0] += n
            stat[1] += time.perf_counter() - t0
    out.sort(key=lambda f: (f.line, f.col, f.rule))
    return out


def lint_sources(files: Dict[str, str], settings=None,
                 rules=None) -> List[Finding]:
    """Lint a multi-file mini-program given as {rel_path: source} — the
    graftsight cross-module fixtures drive this: reachability closes over
    ALL the given files before any rule runs."""
    from mx_rcnn_tpu.analysis import callgraph
    from mx_rcnn_tpu.analysis.settings import Settings

    if settings is None:
        settings = Settings()
    if rules is None:
        from mx_rcnn_tpu.analysis.rules import ALL_RULES as rules

    trees: Dict[str, Optional[ast.AST]] = {}
    out: List[Finding] = []
    for rel_path, source in files.items():
        try:
            trees[rel_path] = ast.parse(source, filename=rel_path)
        except SyntaxError as exc:
            trees[rel_path] = None
            out.append(Finding(path=rel_path, rule="syntax",
                               line=exc.lineno or 0, col=(exc.offset or 0),
                               message=f"syntax error: {exc.msg}"))
    program = callgraph.build_program(trees)
    for rel_path, source in files.items():
        tree = trees[rel_path]
        if tree is not None:
            out.extend(_lint_tree(source, rel_path, tree, settings, rules,
                                  program=program))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def run(paths: Sequence[str], root: str, settings,
        baseline_entries=None, *, lint_only: Optional[Sequence[str]] = None,
        use_cache: bool = True) -> LintResult:
    """Lint ``paths``, splitting findings into live vs baselined.

    Two phases: every file under ``paths`` is parsed (through the on-disk
    AST cache) and indexed into one callgraph.Program — reachability is
    always whole-program — then rules run per file. ``lint_only``
    restricts phase two to a subset of repo-relative paths (the CLI's
    ``--changed-only``) without narrowing the program.
    """
    from mx_rcnn_tpu.analysis import baseline as baseline_mod
    from mx_rcnn_tpu.analysis import callgraph
    from mx_rcnn_tpu.analysis.astcache import AstCache
    from mx_rcnn_tpu.analysis.rules import ALL_RULES

    t_start = time.perf_counter()
    result = LintResult()
    matcher = baseline_mod.Matcher(baseline_entries or [])
    cache = AstCache.open(root, enabled=use_cache)

    parsed: List[tuple] = []  # (abs, rel, source, tree-or-None)
    program = callgraph.Program()
    for path in iter_python_files(paths, root, settings.exclude):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            continue
        tree = cache.parse(path, rel, source)
        parsed.append((path, rel, source, tree))
        if tree is not None:
            program.add_module(rel, tree)
    program.finalize()
    cache.save()
    result.cache_hits, result.cache_misses = cache.hits, cache.misses

    only = (None if lint_only is None
            else {p.replace(os.sep, "/") for p in lint_only})
    for path, rel, source, tree in parsed:
        if only is not None and rel not in only:
            continue
        if tree is None:  # syntax error — re-derive the finding
            findings = lint_source(source, rel, settings, ALL_RULES,
                                   abs_path=path)
        else:
            findings = _lint_tree(source, rel, tree, settings, ALL_RULES,
                                  abs_path=path, program=program,
                                  rule_stats=result.rule_stats)
        result.files_checked += 1
        for f in findings:
            (result.baselined if matcher.consume(f)
             else result.findings).append(f)
    result.wall_s = time.perf_counter() - t_start
    return result
