"""graftlint rule catalog.

Order is report order. Each module exposes NAME / RATIONALE / check(ctx);
adding a rule = adding a module here and appending it to ALL_RULES.
"""

from mx_rcnn_tpu.analysis.rules import (
    cfg_contract,
    chaos_site,
    donation,
    donation_hazard,
    dtype_cast,
    excepts,
    flat_state,
    health_pull,
    host_sync,
    obs_schema,
    prng,
    queue_timeout,
    retry,
    shapes,
    thread_race,
    time_in_jit,
    unbarriered_publish,
    wall_time_duration,
)

ALL_RULES = (
    host_sync,
    time_in_jit,
    shapes,
    donation,
    donation_hazard,
    prng,
    cfg_contract,
    excepts,
    obs_schema,
    flat_state,
    retry,
    chaos_site,
    dtype_cast,
    health_pull,
    thread_race,
    queue_timeout,
    unbarriered_publish,
    wall_time_duration,
)

__all__ = ["ALL_RULES"]
