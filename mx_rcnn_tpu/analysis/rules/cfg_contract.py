"""cfg-contract: every `cfg.<section>.<field>` chain must exist in config.py.

The frozen dataclass tree in ``mx_rcnn_tpu/config.py`` is the single
config contract — but attribute access is only checked when the line
actually *runs*, which for rarely-taken branches means at trace time on a
chip, minutes into a launch. A typo'd field (``cfg.train.rpn_batchsize``)
or a field removed in a refactor is pure drift until then. This rule
recovers the contract statically (parsing config.py's AST — the linter
never imports the package) and resolves every attribute chain rooted at a
config-typed name against it at lint time.

Roots recognized: names in ``[tool.graftlint] cfg-roots`` (default
``cfg``), parameters annotated with a known dataclass type (``def f(net:
NetworkConfig)``), and one-hop section aliases (``train = cfg.train``).
``getattr``/``replace`` and any dynamic access are out of scope.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import FuncOrLambda, dotted_name

NAME = "cfg-contract"
RATIONALE = ("a typo'd/removed `cfg.section.field` only explodes at trace "
             "time; resolve every chain against config.py's dataclass "
             "tree at lint time")

_CONFIG_CACHE: Dict[str, "Contract"] = {}


class Contract:
    """Field/property/method sets per dataclass, parsed from config.py."""

    def __init__(self, config_path: str):
        with open(config_path, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=config_path)
        #: class name -> {attr name -> annotation class name or None}
        self.classes: Dict[str, Dict[str, Optional[str]]] = {}
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            if not any(_is_dataclass_deco(d) for d in node.decorator_list):
                continue
            attrs: Dict[str, Optional[str]] = {}
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name):
                    attrs[stmt.target.id] = _annotation_class(
                        stmt.annotation)
                elif isinstance(stmt, ast.FunctionDef):
                    attrs[stmt.name] = None  # property / method
            self.classes[node.name] = attrs

    def has(self, cls: str, attr: str) -> bool:
        return attr in self.classes.get(cls, {})

    def section_class(self, cls: str, attr: str) -> Optional[str]:
        """The dataclass type of ``cls.attr`` if it is itself a section."""
        target = self.classes.get(cls, {}).get(attr)
        return target if target in self.classes else None

    def attrs(self, cls: str) -> Set[str]:
        return set(self.classes.get(cls, ()))


def _is_dataclass_deco(deco: ast.AST) -> bool:
    name = dotted_name(deco.func if isinstance(deco, ast.Call) else deco)
    return name in ("dataclass", "dataclasses.dataclass",
                    "struct.dataclass", "flax.struct.dataclass")


def _annotation_class(ann: ast.AST) -> Optional[str]:
    # NetworkConfig / "NetworkConfig" (string annotation) / Optional[...]
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.strip("'\"")
    return None


def _contract(ctx: FileContext) -> Optional[Contract]:
    # analysis/rules/cfg_contract.py -> analysis/ -> mx_rcnn_tpu/config.py
    path = os.path.normpath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "..", "config.py"))
    if not os.path.isfile(path):
        return None
    if path not in _CONFIG_CACHE:
        _CONFIG_CACHE[path] = Contract(path)
    return _CONFIG_CACHE[path]


ROOT_CLASS = "Config"


def check(ctx: FileContext) -> Iterator[Finding]:
    contract = _contract(ctx)
    if contract is None or ROOT_CLASS not in contract.classes:
        return
    # Per-function (plus module scope, key None) typed-name tables:
    # name -> dataclass class name.
    typed: Dict[Optional[ast.AST], Dict[str, str]] = {}
    for node in ast.walk(ctx.tree):
        fn = ctx.traced.enclosing_function(node)
        table = typed.setdefault(fn, {})
        if isinstance(node, ast.arg):
            cls = _annotation_class(node.annotation) if node.annotation \
                else None
            if cls in contract.classes:
                # annotation attaches to the fn OWNING the arg, which is
                # the parent, not enclosing_function(arg-node)'s parent
                owner = ctx.parents.get(node)
                while owner is not None and not isinstance(
                        owner, FuncOrLambda):
                    owner = ctx.parents.get(owner)
                typed.setdefault(owner, {})[node.arg] = cls
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target = node.targets[0].id
            # one-hop alias: net = cfg.network
            section = None
            chain = _attr_chain(node.value)
            if chain and len(chain) == 2:
                root_cls = _root_class(ctx, contract, typed, fn, chain[0])
                if root_cls:
                    section = contract.section_class(root_cls, chain[1])
            if section:
                table[target] = section
            elif target in ctx.settings.cfg_roots and not _looks_like_config(
                    node.value, ctx.settings.cfg_roots):
                # `cfg = json.load(...)` / `cfg = {...}` — a visible
                # non-Config binding shadows the name-based assumption
                # for this scope (empty string = "known not-Config").
                table[target] = ""

    emitted: Set[Tuple[int, int]] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Attribute):
            continue
        chain = _attr_chain(node)
        if not chain or len(chain) < 2:
            continue
        key = (node.lineno, node.col_offset)
        # only report the OUTERMOST attribute of a chain once
        parent = ctx.parents.get(node)
        if isinstance(parent, ast.Attribute):
            continue
        fn = ctx.traced.enclosing_function(node)
        root_cls = _root_class(ctx, contract, typed, fn, chain[0])
        if not root_cls or key in emitted:
            continue
        emitted.add(key)
        finding = _resolve_chain(ctx, contract, root_cls, chain, node)
        if finding:
            yield finding


def _looks_like_config(value: ast.AST, cfg_roots) -> bool:
    """Could ``value`` evaluate to the Config tree? Conservative: literals
    and comprehensions cannot; calls/attributes keep the assumption when
    anything in them mentions a cfg root or a *config*-named callable
    (generate_config, Config, replace(cfg, ...), cfg.with_updates(...))."""
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.Tuple,
                          ast.Constant, ast.ListComp, ast.SetComp,
                          ast.DictComp, ast.GeneratorExp, ast.JoinedStr)):
        return False
    for n in ast.walk(value):
        if isinstance(n, ast.Name) and (
                n.id in cfg_roots or "config" in n.id.lower()
                or n.id == "replace"):
            return True
        if isinstance(n, ast.Attribute) and "config" in n.attr.lower():
            return True
    return False


def _root_class(ctx, contract, typed, fn, root_name: str) -> Optional[str]:
    cur = fn
    while True:
        table = typed.get(cur)
        if table and root_name in table:
            return table[root_name] or None  # "" = shadowed non-Config
        if cur is None:
            break
        cur = ctx.traced.enclosing_function(cur)
    if root_name in ctx.settings.cfg_roots:
        return ROOT_CLASS
    return None


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """cfg.train.lr -> ['cfg', 'train', 'lr']; None if not a pure chain."""
    name = dotted_name(node)
    return name.split(".") if name else None


def _resolve_chain(ctx: FileContext, contract: Contract, root_cls: str,
                   chain: List[str], node: ast.AST) -> Optional[Finding]:
    cls = root_cls
    for i, attr in enumerate(chain[1:], start=1):
        if not contract.has(cls, attr):
            known = ", ".join(sorted(contract.attrs(cls))[:8])
            return ctx.finding(
                NAME, node,
                f"`{'.'.join(chain[:i + 1])}` does not resolve: "
                f"`{cls}` has no field `{attr}` (config.py; fields "
                f"include: {known}, ...)")
        nxt = contract.section_class(cls, attr)
        if nxt is None:
            return None  # reached a leaf; deeper attrs (.shape etc.) are
            # on the VALUE, not the contract
        cls = nxt
    return None
