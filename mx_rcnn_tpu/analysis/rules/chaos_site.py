"""chaos-site-name: chaos injection sites must be known, literal names.

Every fault-injection point (``resilience/chaos.py``) is addressed by a
site name — ``chaos.site("checkpoint_finalize")``,
``chaos_spec.fire("train_dispatch", ...)``, ``spec.maybe_die(...)``. A
typo'd site string never fires: the armed injection silently does
nothing, the chaos test that depends on it passes vacuously, and a
"tested" resilience guarantee goes untested (the exact failure mode the
chaos parser's unknown-key check guards on the OTHER side of the
contract). Like obs-event-schema, the registered set is recovered from
``chaos.py::SITES`` in source — the linter never imports the package;
runtime validation exists too, but only on lines that run.

Recognized injectors (syntactic): a call to ``site``/``fire``/
``maybe_die`` whose receiver's final name segment is ``chaos``,
``chaos_spec``, ``spec``, or ``c``, or ends in ``_chaos``/``_spec`` —
the repo's naming convention for chaos bindings — plus the bare
``site(...)`` of a ``from ... import site``-free module (not used here,
but cheap to cover via the dotted form). Non-literal site names are
flagged too: a computed site defeats both this rule and reviewability.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, Optional, Set

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import dotted_name

NAME = "chaos-site-name"
RATIONALE = ("a typo'd chaos site string silently never fires and the "
             "guarantee it was meant to test goes untested; resolve site "
             "literals against resilience/chaos.py::SITES at lint time")

#: injector method names covered by this rule (maybe_hang takes a bench
#: CONFIG label, not a site — out of scope; maybe_sigterm takes a step).
_INJECTOR_ATTRS = frozenset({"site", "fire", "maybe_die"})

#: receiver name segments treated as chaos/ChaosSpec bindings
_RECEIVER_NAMES = frozenset({"chaos", "chaos_spec", "spec", "c"})
_RECEIVER_SUFFIXES = ("_chaos", "_spec")

_SITES_CACHE: dict = {}


def _chaos_path() -> str:
    # analysis/rules/chaos_site.py -> analysis/ -> mx_rcnn_tpu/resilience/
    return os.path.normpath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "..", "..", "resilience", "chaos.py"))


def _sites() -> Optional[Set[str]]:
    """SITES parsed from resilience/chaos.py's AST (cached)."""
    path = _chaos_path()
    if path in _SITES_CACHE:
        return _SITES_CACHE[path]
    sites: Optional[Set[str]] = None
    if os.path.isfile(path):
        with open(path, "r", encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=path)
            except SyntaxError:
                tree = None
        if tree is not None:
            for node in tree.body:
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id == "SITES"):
                    continue
                value = node.value
                # frozenset({...}) / set literal / tuple / list
                if (isinstance(value, ast.Call)
                        and dotted_name(value.func) in ("frozenset", "set")
                        and value.args):
                    value = value.args[0]
                if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
                    sites = {elt.value for elt in value.elts
                             if isinstance(elt, ast.Constant)
                             and isinstance(elt.value, str)}
    _SITES_CACHE[path] = sites
    return sites


def _is_chaos_receiver(receiver: Optional[str]) -> bool:
    if not receiver:
        return False
    base = receiver.rsplit(".", 1)[-1]
    return base in _RECEIVER_NAMES or base.endswith(_RECEIVER_SUFFIXES)


def check(ctx: FileContext) -> Iterator[Finding]:
    sites = _sites()
    if not sites:
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _INJECTOR_ATTRS):
            continue
        if not _is_chaos_receiver(dotted_name(node.func.value)):
            continue
        if not node.args:
            yield ctx.finding(
                NAME, node,
                f"chaos {node.func.attr}() needs the site name as its "
                "first positional argument")
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            yield ctx.finding(
                NAME, node,
                "chaos site name must be a string LITERAL so the "
                "registered-sites set is checkable at lint time (got "
                f"`{ast.unparse(first)}`)")
            continue
        if first.value not in sites:
            yield ctx.finding(
                NAME, node,
                f"unregistered chaos site {first.value!r}; the registered "
                f"set (resilience/chaos.py::SITES) is "
                f"{tuple(sorted(sites))}")
