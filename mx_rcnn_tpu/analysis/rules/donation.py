"""missing-donation: jitted state-updating wrappers must donate the state.

The convention set by ``train/step.py::make_train_step``: any ``jax.jit``
of a function whose first parameter is the train-state pytree passes
``donate_argnums=(0,)`` so XLA reuses the old state's buffers for the new
state. Dropping donation silently DOUBLES the parameter+optimizer HBM
footprint — invisible at toy sizes, an OOM at flagship sizes where the
state is most of the chip's memory. Which parameter names count as "a
state pytree" comes from ``[tool.graftlint] state-params`` (default:
``state``, ``train_state``).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import (
    JIT_DONATABLE, FuncNode, jit_call_kwargs, jit_expr_name,
)

NAME = "missing-donation"
RATIONALE = ("`jax.jit` of a state-first step function without "
             "`donate_argnums` doubles the state's HBM footprint")

_DONATE_KW = ("donate_argnums", "donate_argnames")


def check(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            yield from _check_call(ctx, node)
        elif isinstance(node, FuncNode):
            yield from _check_decorators(ctx, node)


def _check_call(ctx: FileContext, node: ast.Call) -> Iterator[Finding]:
    name = jit_expr_name(node.func)
    if name not in JIT_DONATABLE or not node.args:
        return
    # donate kwargs live on the jit call itself OR on the partial that
    # configured it: `partial(jax.jit, donate_argnums=(0,))(step)`.
    if _donates(jit_call_kwargs(node.func) + list(node.keywords)):
        return
    state_arg = _state_first_param(ctx, node.args[0], node)
    if state_arg:
        yield ctx.finding(
            NAME, node,
            f"`{name}` wraps a function whose first parameter "
            f"`{state_arg}` is a state pytree but passes no "
            "`donate_argnums` — the old state's buffers stay live "
            "(convention: train/step.py)")


def _check_decorators(ctx: FileContext, fn) -> Iterator[Finding]:
    for deco in fn.decorator_list:
        name = jit_expr_name(deco)
        if name not in JIT_DONATABLE:
            continue
        if _donates(jit_call_kwargs(deco)):
            continue
        state_arg = _first_param_if_state(ctx, fn)
        if state_arg:
            yield ctx.finding(
                NAME, deco if hasattr(deco, "lineno") else fn,
                f"`@{name}` on `{fn.name}` (state-first parameter "
                f"`{state_arg}`) without `donate_argnums`")


def _donates(keywords) -> bool:
    return any(k.arg in _DONATE_KW for k in keywords)


def _state_first_param(ctx: FileContext, target: ast.AST,
                       at_node: ast.AST) -> Optional[str]:
    if isinstance(target, ast.Lambda):
        return _first_param_if_state(ctx, target)
    if isinstance(target, ast.Name):
        resolved = ctx.traced._resolve(target.id, at_node)
        if isinstance(resolved, FuncNode):
            return _first_param_if_state(ctx, resolved)
    return None  # unresolvable (imported / computed) — out of scope


def _first_param_if_state(ctx: FileContext, fn) -> Optional[str]:
    args = fn.args.posonlyargs + fn.args.args
    if not args:
        return None
    first = args[0].arg
    if first in ctx.settings.state_params or first.endswith("_state"):
        return first
    return None
