"""donation-hazard: host (numpy-backed) trees donated into a jitted call.

The repo's costliest recurring bug family, root-caused three separate
times (PR 4's flat crash, PR 5's segfault, PR 7's 1e18-loss heap
corruption): a pytree whose leaves are numpy arrays — fresh from
``np.*`` construction, ``jax.device_get``, a checkpoint restore
(``load_checkpoint``) or a heal-carry capture (``host_tree_copy``) —
is passed at a donated position of a ``donate_argnums``-bearing jitted
call. On CPU the donated buffer IS the numpy array's memory: XLA writes
the new state into it while the caller still holds views, corrupting
the heap long after the call returns. The fix is always the same:
``jax.device_put`` the tree first, so donation consumes a device copy.

The rule is a lexical-order taint walk per scope: names bound from a
host-tree source (``np.``/``numpy.`` calls, ``jax.device_get``, plus
``[tool.graftlint] host-tree-sources`` — default ``load_checkpoint``,
``host_tree_copy``) are tainted; rebinding through ``jax.device_put``
cleanses; passing a tainted name (or a direct source call) at a donated
position flags. Donating callees are resolved through graftsight where
a Program is live (imported step factories included), else file-locally:

- ``jax.jit(f, donate_argnums=<literal>)`` called immediately or bound
  to a local name;
- a def decorated ``@partial(jax.jit, donate_argnums=<literal>)``;
- a name bound from a factory whose ``return jax.jit(...,
  donate_argnums=<literal>)`` (the ``make_train_step`` shape).

Only LITERAL donate_argnums count: ``donate_argnums=(0,) if donate
else ()`` is unresolvable statically and — deliberately — exactly the
sanctioned ``fit_detector`` CPU-no-donate path, which must stay a
near-miss, not a finding.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import (
    JIT_DONATABLE, FuncNode, dotted_name, jit_call_kwargs, jit_expr_name,
)

NAME = "donation-hazard"
RATIONALE = ("a numpy-backed host tree donated into a jitted call is "
             "freed/overwritten under the caller (PR 5/7 heap "
             "corruption) — jax.device_put it first")

_DEVICE_PUT = ("jax.device_put", "device_put")
_DEVICE_GET = ("jax.device_get", "device_get")


def _donate_literal(call: ast.AST) -> Optional[Tuple[int, ...]]:
    """Donated positional indices if ``call`` is a jit-like call with a
    LITERAL donate_argnums; None otherwise (incl. conditional exprs)."""
    if not isinstance(call, ast.Call):
        return None
    if jit_expr_name(call.func) not in JIT_DONATABLE:
        return None
    for kw in jit_call_kwargs(call.func) + list(call.keywords):
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in v.elts):
            return tuple(e.value for e in v.elts)
        return None  # computed/conditional — not statically donating
    return None


def _decorated_donate(fn: ast.AST) -> Optional[Tuple[int, ...]]:
    if not isinstance(fn, FuncNode):
        return None
    for deco in fn.decorator_list:
        if jit_expr_name(deco) is None:
            continue
        for kw in jit_call_kwargs(deco):
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)) and all(
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, int) for e in v.elts):
                return tuple(e.value for e in v.elts)
    return None


def _returned_donate(fn: ast.AST) -> Optional[Tuple[int, ...]]:
    """Donate indices if ``fn`` is a factory returning a donating jit
    (``return jax.jit(step, donate_argnums=(0,))``)."""
    if not isinstance(fn, FuncNode):
        return None
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            lit = _donate_literal(node.value)
            if lit is not None:
                return lit
    return None


def _resolve_callables(ctx: FileContext, expr: ast.AST,
                       at_node: ast.AST) -> List[ast.AST]:
    """Function defs ``expr`` may denote — whole-program when available,
    file-local lexical fallback otherwise."""
    if ctx.program is not None:
        return ctx.program.function_defs_of(ctx.rel_path, expr, at_node)
    if isinstance(expr, ast.Name):
        resolved = ctx.traced._resolve(expr.id, at_node)
        if isinstance(resolved, FuncNode):
            return [resolved]
    return []


def _source_name(node: ast.AST, settings) -> Optional[str]:
    """Dotted name of a host-tree-producing call, else None."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if name is None:
        return None
    if name.startswith("np.") or name.startswith("numpy."):
        return name
    if name in _DEVICE_GET:
        return name
    if (name in settings.host_tree_sources
            or name.split(".")[-1] in settings.host_tree_sources):
        return name
    return None


def _is_device_put(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and dotted_name(node.func) in _DEVICE_PUT)


def _names_in(expr: ast.AST) -> List[str]:
    return [n.id for n in ast.walk(expr) if isinstance(n, ast.Name)]


class _Scope:
    """One analysis scope: a function body or the module top level."""

    def __init__(self, owner: ast.AST, body_nodes: List[ast.AST]):
        self.owner = owner
        self.body_nodes = body_nodes


def _scopes(ctx: FileContext) -> Iterator[_Scope]:
    funcs = [n for n in ast.walk(ctx.tree) if isinstance(n, FuncNode)]
    # module top level: statements not inside any function
    top = [n for n in ast.walk(ctx.tree)
           if ctx.traced.enclosing_function(n) is None]
    yield _Scope(ctx.tree, top)
    for fn in funcs:
        nodes = [n for n in ast.walk(fn) if n is not fn]
        yield _Scope(fn, nodes)


def check(ctx: FileContext) -> Iterator[Finding]:
    seen = set()  # nested defs appear in their enclosing scope too
    # call node -> donate indices of its (binding-independent) callee,
    # shared across scopes: resolution through the program is the
    # expensive part and a node's callee never changes
    resolved_cache: Dict[int, Optional[Tuple[int, ...]]] = {}
    for scope in _scopes(ctx):
        for f in _check_scope(ctx, scope, resolved_cache):
            key = (f.line, f.col, f.message)
            if key not in seen:
                seen.add(key)
                yield f


def _check_scope(ctx: FileContext, scope: _Scope,
                 resolved_cache: Dict[int, Optional[Tuple[int, ...]]],
                 ) -> Iterator[Finding]:
    # ``name -> donate indices`` for locally-bound donating callables
    donating: Dict[str, Tuple[int, ...]] = {}
    tainted: Dict[str, str] = {}  # name -> source description

    events: List[Tuple[int, int, str, ast.AST]] = []
    for node in scope.body_nodes:
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            events.append((node.lineno, node.col_offset, "assign", node))
        elif isinstance(node, ast.Call):
            events.append((node.lineno, node.col_offset, "call", node))
    # calls sort before assigns at the same line: in `x = step(x, b)` the
    # RHS call evaluates (and must be judged) before `x` rebinds
    events.sort(key=lambda e: (e[0], 0 if e[2] == "call" else 1, e[1]))

    def donate_of_callee(call: ast.Call) -> Optional[Tuple[int, ...]]:
        f = call.func
        # local bindings are scope-dependent — checked before the cache
        if isinstance(f, ast.Name) and f.id in donating:
            return donating[f.id]
        if id(call) in resolved_cache:
            return resolved_cache[id(call)]
        lit = _donate_literal(f)  # jax.jit(g, donate_argnums=..)(x)
        if lit is None:
            for target in _resolve_callables(ctx, f, call):
                lit = _decorated_donate(target)
                if lit is not None:
                    break
        resolved_cache[id(call)] = lit
        return lit

    for _, _, kind, node in events:
        if kind == "assign":
            value = node.value
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            names: List[str] = []
            for t in targets:
                if isinstance(t, ast.Name):
                    names.append(t.id)
                elif isinstance(t, ast.Tuple):
                    names.extend(e.id for e in t.elts
                                 if isinstance(e, ast.Name))
            if not names or value is None:
                continue
            src = _source_name(value, ctx.settings)
            if src is not None:
                for n in names:
                    tainted[n] = src
                continue
            if _is_device_put(value):
                for n in names:
                    tainted.pop(n, None)
                continue
            # binding a donating callable?
            if isinstance(value, ast.Call):
                key = -id(value)  # distinct namespace from callee cache
                if key in resolved_cache:
                    lit = resolved_cache[key]
                else:
                    lit = _donate_literal(value)
                    if lit is None:
                        for target in _resolve_callables(ctx, value.func,
                                                         node):
                            lit = _returned_donate(target)
                            if lit is not None:
                                break
                    resolved_cache[key] = lit
                if lit is not None:
                    for n in names:
                        donating[n] = lit
                    continue
                # a call result is device-side unless it's a source
                for n in names:
                    tainted.pop(n, None)
                continue
            # plain data flow: tainted if any referenced name is
            carried = [tainted[n] for n in _names_in(value)
                       if n in tainted]
            for n in names:
                if carried:
                    tainted[n] = carried[0]
                else:
                    tainted.pop(n, None)
        else:  # call — is it a donating sink fed a host tree?
            argnums = donate_of_callee(node)
            if argnums is None:
                continue
            for i in argnums:
                if i >= len(node.args):
                    continue
                arg = node.args[i]
                src: Optional[str] = None
                if isinstance(arg, ast.Name) and arg.id in tainted:
                    src = tainted[arg.id]
                else:
                    src = _source_name(arg, ctx.settings)
                if src is None or _is_device_put(arg):
                    continue
                yield ctx.finding(
                    NAME, node,
                    f"argument {i} is a host (numpy-backed) tree from "
                    f"`{src}` donated into a jitted call — XLA reuses "
                    "the buffer in place and corrupts the host copy "
                    "(PR 5/7); `jax.device_put` it first")
