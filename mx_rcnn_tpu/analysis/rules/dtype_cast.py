"""dtype-cast-in-jit: hard-coded float dtype casts in model code.

graftcast (train/precision.py) makes the compute dtype a POLICY — one
knob (``train.compute_dtype``) decides what the forward/backward run in,
and the sanctioned f32 islands (norm statistics, losses, bbox
encode/decode, NMS scores) are routed through the central helpers
(``precision.island`` / ``precision.model_dtype``). A stray
``x.astype(jnp.float32)`` or ``jnp.asarray(x, jnp.bfloat16)`` in model
code re-hard-codes one dtype at one call site: under a policy flip it
either silently re-widens a tensor the policy wanted narrow (perf leak)
or narrows an island the policy promised stays f32 (numerics leak) —
and nobody can audit the island set because it is scattered.

Scope: files under ``mx_rcnn_tpu/models/``, in jit-reachable code only.
Reachability is graftsight's whole-program closure (callgraph.py): model
forwards traced from train/step.py and evaluation/tester.py are seen
cross-module, and genuinely host-side model helpers (checkpoint shape
inspection, config plumbing) are exempt rather than blanket-flagged.
Flagged:

- ``<expr>.astype(<float dtype literal>)``;
- ``jnp.asarray(x, <float dtype literal>)`` / ``jnp.array(x, ...)`` /
  ``dtype=``-keyword forms, when ``x`` is NOT itself a literal constant
  (building a constant in an explicit dtype is construction, not a cast
  of flowing data).

Not flagged: policy-routed dtypes (``self.dtype``, ``p.dtype``,
``precision.island``), integer/bool dtypes, ``self.param``/``zeros``
declarations, and constant construction. Pre-existing casts are adopted
via ``--write-baseline``, never by weakening the rule.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import dotted_name

NAME = "dtype-cast-in-jit"
RATIONALE = ("a hard-coded float dtype cast in model code bypasses the "
             "train.compute_dtype policy — route it through the "
             "train/precision.py helpers (island/model_dtype)")

#: path prefix of the model code the rule governs
_SCOPE = "mx_rcnn_tpu/models/"

#: dotted names that are float dtype literals
_FLOAT_DTYPES = frozenset(
    f"{mod}.{name}"
    for mod in ("jnp", "jax.numpy", "np", "numpy")
    for name in ("float32", "bfloat16", "float16", "float64"))
#: string spellings of the same
_FLOAT_STRINGS = frozenset({"float32", "bfloat16", "float16", "float64"})

#: array-coercion callables whose dtype argument the rule inspects
_COERCERS = frozenset({"jnp.asarray", "jnp.array",
                       "jax.numpy.asarray", "jax.numpy.array"})


def _float_dtype_literal(node: Optional[ast.AST]) -> Optional[str]:
    """'jnp.float32' (or the quoted spelling) if ``node`` is a
    hard-coded float dtype literal, else None."""
    if node is None:
        return None
    name = dotted_name(node)
    if name in _FLOAT_DTYPES:
        return name
    if isinstance(node, ast.Constant) and node.value in _FLOAT_STRINGS:
        return repr(node.value)
    return None


def _is_constant_expr(node: ast.AST) -> bool:
    """Literal data (numbers, or lists/tuples of literal data): building
    a constant in an explicit dtype is not a cast of flowing values."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(
            node.op, (ast.USub, ast.UAdd)):
        return _is_constant_expr(node.operand)
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_constant_expr(e) for e in node.elts)
    return False


def check(ctx: FileContext) -> Iterator[Finding]:
    if not ctx.rel_path.startswith(_SCOPE):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not ctx.traced.in_traced_code(node):
            continue
        # <expr>.astype(<float literal>) — positional or dtype=keyword
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"):
            arg = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "dtype"),
                None)
            lit = _float_dtype_literal(arg)
            if lit:
                yield ctx.finding(
                    NAME, node,
                    f".astype({lit}) hard-codes a float dtype in model "
                    "code — use the train/precision.py policy helpers "
                    "(island() for the sanctioned f32 islands, the "
                    "module's policy dtype otherwise)")
            continue
        # jnp.asarray(x, <float literal>) on non-constant x
        fn = dotted_name(node.func)
        if fn in _COERCERS:
            dtype_arg = node.args[1] if len(node.args) > 1 else next(
                (kw.value for kw in node.keywords if kw.arg == "dtype"),
                None)
            lit = _float_dtype_literal(dtype_arg)
            if lit and node.args and not _is_constant_expr(node.args[0]):
                yield ctx.finding(
                    NAME, node,
                    f"{fn}(..., {lit}) casts flowing data to a "
                    "hard-coded float dtype in model code — route it "
                    "through the train/precision.py policy helpers")
