"""broad-except: `except Exception` / bare `except` swallow real failures.

``train/checkpoint.py`` used to catch ``Exception`` around orbax restores
— a checkpoint I/O failure (full disk, corrupt shard, layout mismatch)
degraded into silently training from scratch. Broad handlers are allowed
in exactly one syntactic position: an optional-dependency probe whose
``try`` body contains only imports (the ``data/image.py`` PIL/cv2
fallbacks). Everything else must name the exception types it means to
handle, or carry an explicit ``# graftlint: disable=broad-except`` with a
reason.
"""

from __future__ import annotations

import ast
from typing import Iterator

from mx_rcnn_tpu.analysis.engine import FileContext, Finding

NAME = "broad-except"
RATIONALE = ("`except Exception:` turns checkpoint/IO failures into "
             "silent wrong behavior; name the types or justify inline")

_BROAD = {"Exception", "BaseException"}


def check(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        import_probe = all(_probe_stmt(s) for s in node.body)
        for handler in node.handlers:
            if not _is_broad(handler.type):
                continue
            if import_probe:
                continue  # optional-dependency fallback
            what = ("bare `except:`" if handler.type is None
                    else f"`except {ast.unparse(handler.type)}:`")
            yield ctx.finding(
                NAME, handler,
                f"{what} outside an import-probe swallows unrelated "
                "failures — name the exception types (and log what was "
                "lost)")


def _probe_stmt(stmt: ast.stmt) -> bool:
    """Imports plus trivial flag assignments (`_HAS_CV2 = True`) — the
    optional-dependency probe shape; anything with a call is real work."""
    if isinstance(stmt, (ast.Import, ast.ImportFrom)):
        return True
    if isinstance(stmt, ast.Assign):
        return isinstance(stmt.value, (ast.Constant, ast.Name,
                                       ast.Attribute))
    return False


def _is_broad(type_node) -> bool:
    if type_node is None:
        return True
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(elt) for elt in type_node.elts)
    return False
