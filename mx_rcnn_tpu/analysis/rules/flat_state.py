"""flat-state-access: no index-poking into optimizer state in traced code.

With flatcore (train/flatcore.py) the SAME logical state has two physical
layouts — the optax tree (per-leaf) and dtype-segregated flat buffers —
interchangeable at checkpoint boundaries. A jit-reachable
``opt_state[...]`` subscript hard-codes ONE layout's internals (optax's
chain position / namedtuple index, e.g. ``opt_state[1][0].trace``), which
silently breaks the moment the state arrives in the other form or optax
re-arranges its wrappers. Inside traced code, optimizer/param state may
only be touched through the flatcore segment API
(``SegmentTable.segment_view`` / ``unflatten``) or whole-tree
``tree_map`` — both are layout-agnostic.

Host-side code (checkpoint conversion, tests) may still index: the rule
only fires inside jit-reachable functions (tracing.py reachability).
Recognized receivers (syntactic): any name/attribute path whose final
segment is ``opt_state`` or ends in ``_opt_state`` — the repo's naming
convention for optimizer-state bindings (``state.opt_state``,
``new_opt_state``); names that merely CONTAIN the words (templates like
``opt_state_template``) are out of scope.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import dotted_name

NAME = "flat-state-access"
RATIONALE = ("a jit-reachable `opt_state[...]` subscript hard-codes one "
             "physical state layout; flatcore's flat/tree interchange "
             "requires the segment API or whole-tree tree_map")


def _subscript_root(node: ast.AST) -> Optional[str]:
    """Dotted name under a (possibly nested) Subscript chain:
    ``state.opt_state[1][0]`` → 'state.opt_state'."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return dotted_name(node)


def _is_opt_state(name: Optional[str]) -> bool:
    if not name:
        return False
    last = name.rsplit(".", 1)[-1]
    return last == "opt_state" or last.endswith("_opt_state")


def check(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Subscript):
            continue
        # report only the OUTERMOST subscript of an opt_state[...][...]
        # chain — one finding per access site
        parent = ctx.parents.get(node)
        if isinstance(parent, ast.Subscript) and parent.value is node:
            continue
        if not _is_opt_state(_subscript_root(node.value)):
            continue
        if not ctx.traced.in_traced_code(node):
            continue
        yield ctx.finding(
            NAME, node,
            "optimizer state indexed by position inside jit-reachable "
            "code — layout-fragile under the flat/tree state interchange "
            "(train/flatcore.py); go through the flatcore segment API or "
            "a whole-tree tree_map")
