"""health-host-pull: ad-hoc numerics probes outside the health pipeline.

graftpulse (train/health.py + obs/health.py) computes numerics health —
nonfinite counts, norms — INSIDE the compiled step, one fused reduction
per flat buffer, returned as extra step outputs so the host reads them
at a cadence with zero added syncs. The tempting alternative is an
ad-hoc probe at the call site: ``jnp.isnan(grads).any()`` inside a
jitted helper (a second reduction pass XLA may not fuse, invisible to
the HealthMonitor's tripwires/flight recorder), or worse
``jnp.isfinite(loss).item()`` (a device→host sync on EVERY step — the
exact per-step stall graftscope's StepTimer was built to keep out of the
hot loop). Both shapes rot independently of the sanctioned pipeline:
their readings reach nobody's trailing window, trip no checkpoint, and
land in no flight dump.

Flagged, when reachable from a jit root and outside the sanctioned
``mx_rcnn_tpu/train/health.py``:

- a REDUCTION of a finiteness probe — ``jnp.any/all/sum/...`` (or the
  ``.any()/.all()/.sum()`` method spellings) over ``jnp.isnan`` /
  ``jnp.isfinite`` / ``jnp.isinf`` (np/numpy/jax.numpy spellings and
  ``from jax.numpy import isnan`` aliases included);
- a HOST PULL of a probe — ``.item()`` / ``float()`` / ``bool()`` whose
  argument contains one.

Not flagged: algorithmic masks — ``jnp.where(jnp.isfinite(x), x, 0)``
and boolean-mask arithmetic (ops/matching.py, ops/roi_align.py) consume
the elementwise probe WITHOUT reducing it to a scalar health signal;
host-side test assertions (not trace-reachable); and train/health.py
itself, the one sanctioned home of in-graph health reductions.
"""

from __future__ import annotations

import ast
from typing import Iterator

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import dotted_name

NAME = "health-host-pull"
RATIONALE = ("ad-hoc `jnp.isnan`/`jnp.isfinite` probe reductions (or "
             "`.item()` pulls of them) in traced code bypass the fused "
             "graftpulse health outputs — route numerics probes through "
             "train/health.py so they ride the step's existing fetch")

#: the sanctioned home of in-graph health reductions
_SANCTIONED = "mx_rcnn_tpu/train/health.py"

#: finiteness probes, module-qualified
_PROBE_NAMES = frozenset({"isnan", "isfinite", "isinf"})
_PROBES = frozenset(
    f"{mod}.{name}"
    for mod in ("jnp", "jax.numpy", "np", "numpy")
    for name in _PROBE_NAMES)

#: reductions that fold an elementwise probe into a scalar health signal
_REDUCERS = frozenset(
    f"{mod}.{name}"
    for mod in ("jnp", "jax.numpy", "np", "numpy")
    for name in ("any", "all", "sum", "mean", "max", "min",
                 "count_nonzero"))
_REDUCER_METHODS = frozenset({"any", "all", "sum", "mean", "max", "min"})


def _probe_aliases(tree: ast.AST) -> frozenset:
    """Bare names bound to probes via ``from jax.numpy import isnan``
    (aliases included) — same coverage contract as time-in-jit."""
    names = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.ImportFrom)
                and node.module in ("jax.numpy", "numpy")):
            for alias in node.names:
                if alias.name in _PROBE_NAMES:
                    names.add(alias.asname or alias.name)
    return frozenset(names)


def _contains_probe(expr: ast.AST, aliases: frozenset) -> bool:
    for n in ast.walk(expr):
        if not isinstance(n, ast.Call):
            continue
        if dotted_name(n.func) in _PROBES:
            return True
        if isinstance(n.func, ast.Name) and n.func.id in aliases:
            return True
    return False


def check(ctx: FileContext) -> Iterator[Finding]:
    if ctx.rel_path == _SANCTIONED:
        return
    traced = ctx.traced
    if not traced.traced:
        return
    aliases = _probe_aliases(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not traced.in_traced_code(node):
            continue
        # <probe-expr>.item() / <probe-expr>.any() — method spellings
        if (isinstance(node.func, ast.Attribute) and not node.args
                and not node.keywords
                and node.func.attr in (_REDUCER_METHODS | {"item"})
                and _contains_probe(node.func.value, aliases)):
            yield ctx.finding(
                NAME, node,
                f"`.{node.func.attr}()` over a finiteness probe in traced "
                "code is an ad-hoc health reduction — route it through "
                "train/health.py's fused step outputs")
            continue
        name = dotted_name(node.func)
        if (name in _REDUCERS and node.args
                and _contains_probe(node.args[0], aliases)):
            yield ctx.finding(
                NAME, node,
                f"`{name}` over a finiteness probe in traced code is an "
                "ad-hoc health reduction — route it through "
                "train/health.py's fused step outputs")
        elif (name in ("float", "int", "bool") and node.args
              and _contains_probe(node.args[0], aliases)):
            yield ctx.finding(
                NAME, node,
                f"`{name}()` of a finiteness probe is a per-step "
                "device→host numerics pull — use the HealthMonitor's "
                "cadenced read over train/health.py outputs instead")
