"""host-sync-in-jit: host round-trips inside traced code.

``.item()``, ``float()``/``int()`` on a traced value, ``np.asarray`` /
``np.array``, and ``jax.device_get`` all force the accelerator pipeline to
drain so the host can materialize a value. Outside jit that is a
performance bug (a ~95 ms relay round trip per array on the axon tunnel,
PERF.md); *inside* jit it either fails to trace or — worse — silently
constant-folds a value that should be data-dependent. The repo's design
rule is "no host round-trips inside the compiled step" (package
docstring); this rule makes it mechanical.

float()/int() need care: ``int(cfg.train.rpn_min_size)`` on static config
is fine anywhere. Only conversions whose argument mentions a parameter of
an enclosing traced function (the syntactic stand-in for "a traced
value") are flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import dotted_name

NAME = "host-sync-in-jit"
RATIONALE = ("`.item()`/`float()`/`np.asarray`/`jax.device_get` on traced "
             "values inside jit fail to trace or silently constant-fold")

_NP_SYNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_DEVICE_SYNCS = {"jax.device_get", "jax.device_put"}


def check(ctx: FileContext) -> Iterator[Finding]:
    traced = ctx.traced
    if not traced.traced:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not traced.in_traced_code(node):
            continue
        # x.item() — a zero-arg method call; this syntactic shape has no
        # other common meaning in numeric code.
        if (isinstance(node.func, ast.Attribute) and node.func.attr == "item"
                and not node.args and not node.keywords):
            yield ctx.finding(NAME, node,
                              "`.item()` forces a device→host sync inside "
                              "traced code")
            continue
        name = dotted_name(node.func)
        if name in _NP_SYNCS:
            yield ctx.finding(NAME, node,
                              f"`{name}` materializes a host array inside "
                              "traced code (use jnp, or hoist to the host "
                              "side of the jit boundary)")
        elif name in _DEVICE_SYNCS:
            yield ctx.finding(NAME, node,
                              f"`{name}` inside traced code is a host "
                              "round-trip (move it outside the jit)")
        elif (name in ("float", "int", "bool") and node.args
              and _mentions_traced_value(node.args[0], traced, node)):
            yield ctx.finding(NAME, node,
                              f"`{name}()` on a traced value concretizes it "
                              "(TracerConversionError at best; use jnp "
                              "casts/astype)")


#: attribute/call accesses on a tracer that yield STATIC python values —
#: `int(x.shape[0])` / `len(x)` inside jit are fine (shapes are static)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize"}


def _mentions_traced_value(expr: ast.AST, traced, at_node: ast.AST) -> bool:
    tainted = _tainted_names(traced, at_node)
    static_names = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            static_names.update(
                id(sub) for sub in ast.walk(n.value)
                if isinstance(sub, ast.Name))
        elif (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
              and n.func.id == "len"):
            static_names.update(
                id(sub) for sub in ast.walk(n)
                if isinstance(sub, ast.Name) and sub is not n.func)
    return any(isinstance(n, ast.Name) and n.id in tainted
               and id(n) not in static_names
               for n in ast.walk(expr))


def _tainted_names(traced, at_node: ast.AST):
    """Params of the enclosing traced functions plus names assigned (even
    indirectly) from them — two fixpoint passes cover the straight-line
    chains that occur in practice; no kill-set (over-taint is fine, the
    conversion still deserves a look). Cached on the per-file
    TraceAnalysis so nothing outlives the file."""
    cache = getattr(traced, "_taint_cache", None)
    if cache is None:
        cache = traced._taint_cache = {}
    fn = traced.enclosing_function(at_node)
    if fn in cache:
        return cache[fn]
    tainted = set(traced.traced_param_names(at_node))
    if fn is not None:
        assigns = [n for n in ast.walk(fn) if isinstance(n, ast.Assign)]
        for _ in range(2):
            for a in assigns:
                if any(isinstance(n, ast.Name) and n.id in tainted
                       for n in ast.walk(a.value)):
                    for tgt in a.targets:
                        tainted.update(
                            n.id for n in ast.walk(tgt)
                            if isinstance(n, ast.Name))
    cache[fn] = tainted
    return tainted
