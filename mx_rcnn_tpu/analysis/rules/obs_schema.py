"""obs-event-schema: EventLog.emit calls must use known, literal types.

The graftscope event stream (``mx_rcnn_tpu/obs/events.py``) is a CLOSED
schema: ``EVENT_TYPES`` enumerates every record kind, and ``obs.report``
folds a run by those kinds. A typo'd type (``"stepp"``) raises only when
that line runs — for rarely-taken branches (crash/stall paths,
exactly the ones that matter) that means never in CI and once, fatally,
mid-incident. A non-literal type key defeats both this rule and the
schema's reviewability. Like cfg-contract, the schema is recovered from
the source AST — the linter never imports the package.

Recognized emitters (syntactic): an ``.emit(...)`` call whose receiver's
final name segment is one of ``obs``, ``obs_log``, ``event_log``,
``elog``, ``log``, or ends in ``_obs``/``_event_log`` — the repo's
naming convention for EventLog bindings. ``logging.Handler.emit(record)``
style calls land on receivers named ``handler``/``h`` and are out of
scope (and ``logging.Logger`` has no ``emit`` at all).
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, Optional, Set

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import dotted_name

NAME = "obs-event-schema"
RATIONALE = ("a typo'd or computed EventLog.emit record type only explodes "
             "when that (often rarely-taken) line runs; resolve it against "
             "obs/events.py::EVENT_TYPES at lint time")

#: receiver name segments treated as EventLog bindings
_EMITTER_NAMES = frozenset({"obs", "obs_log", "event_log", "elog", "log"})
_EMITTER_SUFFIXES = ("_obs", "_event_log", "_elog")

_SCHEMA_CACHE: dict = {}


def _events_path() -> str:
    # analysis/rules/obs_schema.py -> analysis/ -> mx_rcnn_tpu/obs/events.py
    return os.path.normpath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "..", "..", "obs", "events.py"))


def _schema() -> Optional[Set[str]]:
    """EVENT_TYPES parsed from obs/events.py's AST (cached)."""
    path = _events_path()
    if path in _SCHEMA_CACHE:
        return _SCHEMA_CACHE[path]
    types: Optional[Set[str]] = None
    if os.path.isfile(path):
        with open(path, "r", encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=path)
            except SyntaxError:
                tree = None
        if tree is not None:
            for node in tree.body:
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id == "EVENT_TYPES"
                        and isinstance(node.value, (ast.Tuple, ast.List))):
                    types = {elt.value for elt in node.value.elts
                             if isinstance(elt, ast.Constant)
                             and isinstance(elt.value, str)}
    _SCHEMA_CACHE[path] = types
    return types


def _is_emitter(receiver: Optional[str]) -> bool:
    if not receiver:
        return False
    base = receiver.rsplit(".", 1)[-1]
    return base in _EMITTER_NAMES or base.endswith(_EMITTER_SUFFIXES)


def check(ctx: FileContext) -> Iterator[Finding]:
    schema = _schema()
    if not schema:
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "emit"):
            continue
        if not _is_emitter(dotted_name(node.func.value)):
            continue
        if not node.args:
            yield ctx.finding(
                NAME, node,
                "EventLog.emit needs the record type as its first "
                "positional argument")
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            yield ctx.finding(
                NAME, node,
                "EventLog.emit record type must be a string LITERAL so "
                "the schema is checkable at lint time (got "
                f"`{ast.unparse(first)}`)")
            continue
        if first.value not in schema:
            yield ctx.finding(
                NAME, node,
                f"unknown event type {first.value!r}; the graftscope "
                f"schema (obs/events.py::EVENT_TYPES) is "
                f"{tuple(sorted(schema))}")
