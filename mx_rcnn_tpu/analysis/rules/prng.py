"""prng-key-reuse: one key, one consumption.

``jax.random.*`` functions are deterministic in the key: feeding the same
key to two sampling calls yields CORRELATED (often identical) draws — the
classic silent statistics bug (rpn and rcnn sampling the same subset
pattern, dropout masks repeating every step). The contract is linear: a
key is consumed exactly once (``split`` / ``fold_in`` count as
consumptions that retire it); fresh subkeys come from ``split``.

The check is a per-function linear walk with two refinements: ``if``
branches analyze from a copy of the consumed-set (uses on exclusive paths
don't alias) and loop bodies are walked twice, so a key defined outside a
loop but consumed inside it is caught as loop-carried reuse.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set, Tuple

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import FuncOrLambda, dotted_name

NAME = "prng-key-reuse"
RATIONALE = ("the same PRNG key fed to two `jax.random.*` calls yields "
             "correlated draws; `split` before each use")

#: jax.random attrs that do NOT consume a key argument
_NON_CONSUMING = {"PRNGKey", "key", "key_data", "wrap_key_data"}


def check(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, FuncOrLambda) and not isinstance(
                node, ast.Lambda):
            body = node.body
            findings: List[Finding] = []
            _walk_block(ctx, body, set(), findings, own_fn=node)
            # the two-pass loop walk revisits calls; one report per site
            seen = set()
            for f in findings:
                if (f.line, f.col) not in seen:
                    seen.add((f.line, f.col))
                    yield f


def _consuming_calls(stmt: ast.AST, own_fn) -> List[Tuple[ast.Call, str]]:
    """(call, key-name) for jax.random consumptions lexically in ``stmt``,
    skipping nested function bodies (they get their own analysis)."""
    out = []
    stack = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, FuncOrLambda) and node is not own_fn:
            continue
        stack.extend(ast.iter_child_nodes(node))
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if not name:
            continue
        # jax.random.* / the common `import jax.random as jrandom` alias;
        # deliberately NOT bare `random.` — that's the stdlib.
        if not ((name.startswith("jax.random.")
                 or name.startswith("jrandom."))
                and name.rsplit(".", 1)[-1] not in _NON_CONSUMING):
            continue
        args = list(node.args)
        key_arg = None
        for kw in node.keywords:
            if kw.arg in ("key", "seed"):
                key_arg = kw.value
        if key_arg is None and args:
            key_arg = args[0]
        if isinstance(key_arg, ast.Name):
            out.append((node, key_arg.id))
    return out


def _assigned_names(stmt: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(stmt):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    names.add(sub.id)
    return names


def _walk_block(ctx: FileContext, body, consumed: Set[str],
                findings: List[Finding], own_fn) -> None:
    for stmt in body:
        if isinstance(stmt, FuncOrLambda):
            continue
        if isinstance(stmt, ast.If):
            # the test runs FIRST; branches are exclusive alternatives
            # starting from the post-test state
            _consume_stmt(ctx, stmt.test, consumed, findings, own_fn)
            base = set(consumed)
            taken: List[Set[str]] = []
            for branch in (stmt.body, stmt.orelse):
                branch_consumed = set(base)
                _walk_block(ctx, branch, branch_consumed, findings, own_fn)
                taken.append(branch_consumed)
            # flow join REPLACES the state: consumed-on-some-path stays
            # consumed, but a key every branch rebound is fresh again.
            consumed.clear()
            consumed.update(taken[0] | taken[1])
            continue
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            # a For iter evaluates ONCE, before the loop; a While test
            # re-evaluates every iteration. The second body pass exposes
            # loop-carried reuse of an outer key.
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                _consume_stmt(ctx, stmt.iter, consumed, findings, own_fn)
            for _ in range(2):
                if isinstance(stmt, ast.While):
                    _consume_stmt(ctx, stmt.test, consumed, findings,
                                  own_fn)
                _walk_block(ctx, stmt.body, consumed, findings, own_fn)
                consumed -= _assigned_names(stmt)
            _walk_block(ctx, stmt.orelse, consumed, findings, own_fn)
            continue
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            _walk_block(ctx, stmt.body, consumed, findings, own_fn)
            continue
        if isinstance(stmt, ast.Try):
            # handlers are alternate outcomes of the body, not sequels:
            # they analyze from the PRE-body state (like if/else), then
            # everything merges for the code after the try.
            base = set(consumed)
            _walk_block(ctx, stmt.body, consumed, findings, own_fn)
            _walk_block(ctx, stmt.orelse, consumed, findings, own_fn)
            for handler in stmt.handlers:
                handler_consumed = set(base)
                _walk_block(ctx, handler.body, handler_consumed, findings,
                            own_fn)
                consumed |= handler_consumed
            _walk_block(ctx, stmt.finalbody, consumed, findings, own_fn)
            continue
        _consume_stmt(ctx, stmt, consumed, findings, own_fn)


def _consume_stmt(ctx: FileContext, stmt: ast.AST, consumed: Set[str],
                  findings: List[Finding], own_fn) -> None:
    seen_twice = set()
    for call, key in _consuming_calls(stmt, own_fn):
        if key in consumed:
            if key not in seen_twice:
                findings.append(ctx.finding(
                    NAME, call,
                    f"PRNG key `{key}` was already consumed by an earlier "
                    "`jax.random` call — draws will be correlated; "
                    "`jax.random.split` it first"))
                seen_twice.add(key)
        else:
            consumed.add(key)
    # assignments retire consumed marks for their targets
    consumed.difference_update(_assigned_names(stmt))
