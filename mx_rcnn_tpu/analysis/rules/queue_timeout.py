"""blocking-queue-no-timeout: an uncancellable wait inside a worker.

The graftfeed shape: a prefetch worker (or its consumer) blocks in
``queue.Queue.get()`` / ``.put()`` with no ``timeout=`` and no
``block=False``. Nothing interrupts a blocked queue wait — not the
iterator's stop event, not ``close()``, not a data-stall deadline — so
one wedged producer turns into a daemon thread pinned forever and a
``close()`` that hangs at ``join()``. The repo's prefetcher
(data/loader.py) deliberately uses ``Condition.wait(timeout=0.1)`` poll
loops instead, re-checking the stop/supervision state every wakeup;
this rule keeps new queue plumbing honest about the same discipline.

Per class that constructs a ``threading.Thread`` (same machinery as
thread-shared-mutation), the rule:

- finds queue-holding attrs: ``self.q = queue.Queue(...)`` (also bare
  ``Queue``/``SimpleQueue``/``LifoQueue``/``PriorityQueue``, instance
  or class level);
- closes the thread side transitively (``target=`` methods, ``run()``
  on Thread subclasses, plus same-class ``self.m()`` callees) — BOTH
  sides of a queue handoff can wedge, but only calls reachable from a
  class that actually spawns a thread are concurrent at all, so the
  whole class is in scope once it constructs one;
- flags ``self.q.get(...)`` / ``self.q.put(...)`` calls that pass
  neither ``timeout=`` nor ``block=False`` (positional forms
  ``get(False)`` / ``put(item, False)`` count as non-blocking too, as
  do ``get_nowait()`` / ``put_nowait()``, which never block).

Module-level worker functions (``threading.Thread(target=fn)``) get the
same treatment over locals assigned from a queue constructor. Classes
that never construct a thread are out of scope: a single-threaded queue
is just a deque with ceremony, and blocking there deadlocks loudly on
the first call — not the once-a-week hang this rule exists for.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.rules.thread_race import (
    _close_thread_side,
    _is_thread_subclass,
    _methods_of,
    _thread_targets,
)
from mx_rcnn_tpu.analysis.tracing import FuncNode, dotted_name

NAME = "blocking-queue-no-timeout"
RATIONALE = ("a Queue.get()/.put() with no timeout= and no block=False "
             "in thread-handoff code waits uninterruptibly — stop "
             "events and close() can never reach it (the graftfeed "
             "wedged-worker shape)")

_QUEUE_FACTORIES = {
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue",
}
_THREAD_NAMES = {"threading.Thread", "Thread"}
#: position of the ``block`` argument when passed positionally.
_BLOCK_POS = {"get": 0, "put": 1}


def _is_queue_ctor(value: ast.AST) -> bool:
    return (isinstance(value, ast.Call)
            and dotted_name(value.func) in _QUEUE_FACTORIES)


def _blocks_forever(call: ast.Call, method: str) -> bool:
    """True when this get/put call can wait without bound: no timeout=,
    no block=False (keyword or positional)."""
    for kw in call.keywords:
        if kw.arg == "timeout":
            return False
        if (kw.arg == "block"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is False):
            return False
    pos = _BLOCK_POS[method]
    if (len(call.args) > pos
            and isinstance(call.args[pos], ast.Constant)
            and call.args[pos].value is False):
        return False
    return True


def _queue_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attrs assigned from a queue constructor anywhere in the class
    (``self.q = queue.Queue()`` in any method, or a class-level
    default)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign)
                and _is_queue_ctor(node.value)):
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                out.add(t.attr)
            elif isinstance(t, ast.Name):
                out.add(t.id)
    return out


def _flag_calls(ctx: FileContext, body: ast.AST, is_queue,
                owner: str) -> Iterator[Finding]:
    """Findings for every forever-blocking get/put on a queue receiver
    inside ``body``; ``is_queue(node) -> bool`` recognizes receivers."""
    for node in ast.walk(body):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCK_POS):
            continue
        if not is_queue(node.func.value):
            continue
        method = node.func.attr
        if not _blocks_forever(node, method):
            continue
        yield ctx.finding(
            NAME, node,
            f"`.{method}()` on a queue in {owner} passes neither "
            "`timeout=` nor `block=False` — a wedged peer pins this "
            "wait forever (stop events and close() can't interrupt a "
            "blocked queue op); poll with a timeout and re-check the "
            "stop state, like data/loader.py's prefetcher")


def _check_class(ctx: FileContext,
                 cls: ast.ClassDef) -> Iterator[Finding]:
    methods = _methods_of(cls)
    seeds = _thread_targets(cls, methods)
    if _is_thread_subclass(cls) and "run" in methods:
        seeds.add("run")
    if not seeds:
        return  # no thread born here — a blocked call deadlocks loudly
    queues = _queue_attrs(cls)
    if not queues:
        return
    thread_side = _close_thread_side(methods, seeds)

    def _is_queue_recv(recv: ast.AST) -> bool:
        return (isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self" and recv.attr in queues)

    for mname, m in methods.items():
        where = ("the thread target" if mname in thread_side
                 else "the consumer side")
        yield from _flag_calls(ctx, m, _is_queue_recv,
                               f"`{cls.name}.{mname}` ({where})")


def _module_thread_fns(ctx: FileContext) -> Set[str]:
    """Top-level function names passed as ``target=`` to a Thread
    constructed anywhere in the module (outside any class)."""
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and dotted_name(node.func) in _THREAD_NAMES):
            continue
        for kw in node.keywords:
            if kw.arg == "target" and isinstance(kw.value, ast.Name):
                out.add(kw.value.id)
    return out


def _check_function(ctx: FileContext, fn: FuncNode) -> Iterator[Finding]:
    locals_q: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _is_queue_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    locals_q.add(t.id)
    if not locals_q:
        return

    def _is_queue_recv(recv: ast.AST) -> bool:
        return isinstance(recv, ast.Name) and recv.id in locals_q

    yield from _flag_calls(ctx, fn, _is_queue_recv,
                           f"thread target `{fn.name}`")


def check(ctx: FileContext) -> Iterator[Finding]:
    for node in ctx.tree.body:
        if isinstance(node, ast.ClassDef):
            yield from _check_class(ctx, node)
    thread_fns = _module_thread_fns(ctx)
    for node in ctx.tree.body:
        if isinstance(node, FuncNode) and node.name in thread_fns:
            yield from _check_function(ctx, node)
