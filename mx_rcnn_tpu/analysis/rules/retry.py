"""unbounded-retry: blind sleep-retry loops with no deadline or backoff.

The round-5 outage (TPU_OUTAGE_r5.log) was survived by a hand-rolled
watcher: ``while True: try: jax.devices() except: time.sleep(540)`` —
25+ fixed-cadence probes over ~11 hours, no backoff, no deadline, no
error classification, and no structured record. graftguard
(resilience/backend.py) is the sanctioned shape: exponential backoff +
jitter under a configurable deadline. This rule flags the anti-pattern
so it cannot grow back: a ``while`` loop (or a ``for`` over an unbounded
iterator) that retries through an exception handler and sleeps with
neither

- a **deadline**: some clock read inside the loop (``time.monotonic`` /
  ``time.time`` / ``perf_counter`` / an injected ``clock()``) that a
  bounded loop compares against, nor
- a **backoff**: a sleep duration that the loop body actually updates
  (``delay *= 2`` and friends) or computes per-iteration.

``for`` loops over ``range(...)`` (or any finite collection) are bounded
retry — never flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from mx_rcnn_tpu.analysis.engine import FileContext, Finding

NAME = "unbounded-retry"
RATIONALE = ("a retry loop that sleeps without a deadline or backoff "
             "(the TPU_OUTAGE_r5 watcher shape) — use "
             "resilience/backend.py's classified acquire instead")

#: Callable names whose invocation inside the loop counts as reading a
#: clock — evidence the loop tracks elapsed time against a deadline.
#: ``clock`` covers the injectable-clock idiom (resilience/backend.py).
_CLOCK_NAMES = {"monotonic", "time", "perf_counter", "perf_counter_ns",
                "monotonic_ns", "clock"}

#: Iterator factories that make a ``for`` loop unbounded.
_UNBOUNDED_ITERS = {"count", "cycle", "repeat"}


def check(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.While):
            loop_body = node.body
        elif isinstance(node, ast.For) and _unbounded_for(node.iter):
            loop_body = node.body
        else:
            continue
        sleeps = _sleep_calls(loop_body)
        if not sleeps:
            continue
        if not _has_retry_handler(loop_body):
            continue  # a poll/wait loop, not a retry loop
        if _reads_clock(loop_body):
            continue  # deadline evidence
        if any(_is_backoff_arg(call, loop_body) for call in sleeps):
            continue  # backoff evidence
        yield ctx.finding(
            NAME, node,
            "retry loop sleeps with no deadline and no backoff — a relay "
            "outage spins here forever at a fixed cadence; bound it with "
            "a clock check (or use resilience.backend.acquire_backend)")


def _unbounded_for(iter_node: ast.expr) -> bool:
    """``for _ in itertools.count()`` and friends — a while-True in
    disguise. ``range(...)``/finite collections are bounded retry."""
    if not isinstance(iter_node, ast.Call):
        return False
    return _call_name(iter_node) in _UNBOUNDED_ITERS


def _call_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _walk_body(body: List[ast.stmt]) -> Iterator[ast.AST]:
    for stmt in body:
        yield from ast.walk(stmt)


def _sleep_calls(body: List[ast.stmt]) -> List[ast.Call]:
    return [n for n in _walk_body(body)
            if isinstance(n, ast.Call) and _call_name(n) == "sleep"]


def _has_retry_handler(body: List[ast.stmt]) -> bool:
    """An except handler that lets the loop continue (anything but an
    unconditional re-raise) — the failure path loops back around."""
    for n in _walk_body(body):
        if not isinstance(n, ast.ExceptHandler):
            continue
        if not all(isinstance(s, ast.Raise) for s in n.body):
            return True
    return False


def _reads_clock(body: List[ast.stmt]) -> bool:
    return any(isinstance(n, ast.Call) and _call_name(n) in _CLOCK_NAMES
               for n in _walk_body(body))


def _is_backoff_arg(call: ast.Call, body: List[ast.stmt]) -> bool:
    """True when the sleep duration can change between iterations: a
    non-constant expression (``sleep(delay * 2)``, ``sleep(min(d, cap))``)
    or a plain name the loop body reassigns/augments. A constant —
    including constant arithmetic like ``sleep(9 * 60)``, the literal
    round-5 watcher cadence — or a name the loop never touches is a
    fixed cadence."""
    if not call.args:
        return False  # sleep() — malformed; not our concern
    arg = call.args[0]
    if _is_constant_expr(arg):
        return False
    if isinstance(arg, ast.Name):
        return _assigned_in(arg.id, body)
    return True  # computed per-iteration: treated as backoff


def _is_constant_expr(node: ast.expr) -> bool:
    """``540``, ``9 * 60``, ``-(5)``: arithmetic over literals folds to
    the same value every iteration."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.BinOp):
        return _is_constant_expr(node.left) and _is_constant_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_constant_expr(node.operand)
    return False


def _assigned_in(name: str, body: List[ast.stmt]) -> bool:
    for n in _walk_body(body):
        if isinstance(n, ast.AugAssign):
            t = n.target
            if isinstance(t, ast.Name) and t.id == name:
                return True
        elif isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return True
        elif isinstance(n, (ast.AnnAssign, ast.NamedExpr)):
            t = n.target
            if isinstance(t, ast.Name) and t.id == name:
                return True
    return False
