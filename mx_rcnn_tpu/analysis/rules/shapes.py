"""data-dependent-shape: dynamic result shapes inside traced code.

``jnp.nonzero(x)`` / boolean-mask indexing produce arrays whose SHAPE
depends on runtime values. Under jit that raises; with shape polymorphism
or repeated retracing it becomes a TPU recompile bomb — each distinct
count is a fresh XLA compile of the whole program (minutes at detector
sizes). The repo's static-shape design rule (fixed max counts + validity
masks, package docstring) exists precisely to avoid this; JAX's own
escape hatch is the ``size=`` argument, which pins the output shape.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import dotted_name

NAME = "data-dependent-shape"
RATIONALE = ("`jnp.nonzero`/boolean-mask indexing without `size=` in "
             "traced code forces per-count recompiles (use masks + fixed "
             "budgets)")

#: jnp calls whose output shape is value-dependent unless size= pins it
_SIZED = {"nonzero", "flatnonzero", "argwhere", "unique"}
_JNP_PREFIXES = ("jnp.", "jax.numpy.", "np.", "numpy.")


def check(ctx: FileContext) -> Iterator[Finding]:
    traced = ctx.traced
    if not traced.traced:
        return
    # Map of name -> assigned-from-Compare, per nearest enclosing function,
    # for the `mask = x > 0; y = x[mask]` spelling.
    compare_names = _compare_assignments(ctx)
    for node in ast.walk(ctx.tree):
        if not traced.in_traced_code(node):
            continue
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is None:
                continue
            base = name.rsplit(".", 1)[-1]
            if (base in _SIZED
                    and any(name == p + base for p in _JNP_PREFIXES)
                    and not any(k.arg == "size" for k in node.keywords)):
                yield ctx.finding(
                    NAME, node,
                    f"`{name}` without `size=` has a value-dependent "
                    "output shape in traced code")
            elif (name in ("jnp.where", "jax.numpy.where")
                  and len(node.args) == 1
                  and not any(k.arg == "size" for k in node.keywords)):
                yield ctx.finding(
                    NAME, node,
                    "single-argument `jnp.where(cond)` is `nonzero` — "
                    "value-dependent shape; pass `size=` or use the "
                    "three-argument select form")
        elif isinstance(node, ast.Subscript):
            sl = node.slice
            is_mask = isinstance(sl, ast.Compare) or (
                isinstance(sl, ast.Name)
                and _is_mask_at(compare_names,
                                traced.enclosing_function(node),
                                sl.id, node.lineno))
            if is_mask:
                yield ctx.finding(
                    NAME, node,
                    "boolean-mask indexing has a value-dependent output "
                    "shape in traced code (use jnp.where masking or a "
                    "fixed top-k budget)")


def _compare_assignments(ctx: FileContext) -> Dict[ast.AST, list]:
    """fn-node -> [(lineno, name, assigned-from-bare-Compare)], unsorted."""
    out: Dict[ast.AST, list] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        fn = ctx.traced.enclosing_function(node)
        flag = isinstance(node.value, ast.Compare)
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out.setdefault(fn, []).append((node.lineno, tgt.id, flag))
    return out


def _is_mask_at(compare_names: Dict[ast.AST, list], fn: ast.AST,
                name: str, use_line: int) -> bool:
    """Was ``name``'s LAST assignment before ``use_line`` a bare Compare?
    (Position-sensitive: a mask rebound to something else after the use,
    or a non-mask rebound to a Compare later, must not leak backwards.)"""
    best = None
    for lineno, nm, flag in compare_names.get(fn, ()):
        if nm == name and lineno <= use_line:
            if best is None or lineno > best[0]:
                best = (lineno, flag)
    return best[1] if best else False
