"""thread-shared-mutation: unlocked attrs shared with a daemon thread.

The PR 9 `_note_pad` race shape: a counter/dict/flag on an object is
mutated both from a `threading.Thread` worker and from the main thread,
with no common lock — increments vanish, dicts resize under iteration,
and the failure reproduces once a week on a loaded host. The repo's
long-lived thread owners (StallWatchdog, the loader's prefetch worker,
the async CheckpointWriter) all follow the same discipline: every
mutation of cross-thread state happens under `with self._lock` (or a
Condition, which is a lock plus a waitset).

Per class, the rule:

- finds the thread side: `run()` when the class subclasses
  `threading.Thread`, plus any method passed as `target=` to a
  `threading.Thread(...)` constructed in the class, closed transitively
  over same-class `self.m()` calls;
- collects every write to `self.<attr>` (plain assign, augmented
  assign, and `self.<attr>[k] = v` item writes), outside `__init__`
  (anything before `.start()` is happens-before and uninteresting);
- knows which attrs are locks: assigned from `threading.Lock()`,
  `RLock()`, or `Condition()` (instance or class level); a write is
  "locked" when an enclosing `with self.<lock>:` holds one;
- flags attrs written on BOTH sides when any of those writes is
  unlocked — each unlocked write site is a finding.

Attrs written on one side only, fully-locked attrs, and classes that
never construct a thread are all clean. Dynamic dispatch
(`getattr(self, name)()` into the thread target) resolves to nothing
and under-approximates — never over-flags.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import FuncNode, dotted_name

NAME = "thread-shared-mutation"
RATIONALE = ("an attribute mutated both inside and outside a "
             "threading.Thread target without a common `with "
             "self._lock` is the PR 9 pad-counter race")

_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}
_THREAD_NAMES = {"threading.Thread", "Thread"}


def _is_thread_subclass(cls: ast.ClassDef) -> bool:
    return any(dotted_name(b) in _THREAD_NAMES for b in cls.bases)


def _methods_of(cls: ast.ClassDef) -> Dict[str, ast.AST]:
    return {item.name: item for item in cls.body
            if isinstance(item, FuncNode)}


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attrs holding a Lock/RLock/Condition (self.x = ... in any method,
    or a class-level x = ... default)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and dotted_name(node.value.func) in _LOCK_FACTORIES):
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                out.add(t.attr)
            elif isinstance(t, ast.Name):  # class-level default
                out.add(t.id)
    return out


def _thread_targets(cls: ast.ClassDef,
                    methods: Dict[str, ast.AST]) -> Set[str]:
    """Method names passed as ``target=`` to a Thread constructed
    anywhere in the class (``threading.Thread(target=self._worker)``)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Call)
                and dotted_name(node.func) in _THREAD_NAMES):
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            if (isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self" and v.attr in methods):
                out.add(v.attr)
    return out


def _close_thread_side(methods: Dict[str, ast.AST],
                       seeds: Set[str]) -> Set[str]:
    """Transitive same-class closure: self.m() from a thread-side
    method drags m onto the thread side."""
    side = set(seeds)
    work = list(seeds)
    while work:
        m = methods.get(work.pop())
        if m is None:
            continue
        for node in ast.walk(m):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                    and node.func.attr in methods
                    and node.func.attr not in side):
                side.add(node.func.attr)
                work.append(node.func.attr)
    return side


def _self_attr_written(target: ast.AST) -> Optional[str]:
    """'attr' when ``target`` writes self.attr (directly or through a
    subscript: ``self.attr[k] = v`` mutates attr)."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"):
        return target.attr
    return None


def _is_locked(ctx: FileContext, node: ast.AST,
               locks: Set[str]) -> bool:
    """Any enclosing ``with self.<lock>:`` (or bare ``with <lock>:``
    for class-level locks)."""
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                e = item.context_expr
                # with self._lock:  /  with self._cond:
                if (isinstance(e, ast.Attribute)
                        and isinstance(e.value, ast.Name)
                        and e.value.id == "self" and e.attr in locks):
                    return True
                # with self._lock.acquire_timeout(...) style wrappers
                if (isinstance(e, ast.Call)
                        and isinstance(e.func, ast.Attribute)
                        and isinstance(e.func.value, ast.Attribute)
                        and isinstance(e.func.value.value, ast.Name)
                        and e.func.value.value.id == "self"
                        and e.func.value.attr in locks):
                    return True
                if isinstance(e, ast.Name) and e.id in locks:
                    return True
        cur = ctx.parents.get(cur)
    return False


def check(ctx: FileContext) -> Iterator[Finding]:
    for cls in ast.walk(ctx.tree):
        if isinstance(cls, ast.ClassDef):
            yield from _check_class(ctx, cls)


def _check_class(ctx: FileContext,
                 cls: ast.ClassDef) -> Iterator[Finding]:
    methods = _methods_of(cls)
    seeds = _thread_targets(cls, methods)
    if _is_thread_subclass(cls) and "run" in methods:
        seeds.add("run")
    if not seeds:
        return  # no thread born here — nothing is concurrent
    thread_side = _close_thread_side(methods, seeds)
    locks = _lock_attrs(cls)

    # (attr) -> list of (write node, on thread side?, locked?)
    writes: Dict[str, List[Tuple[ast.AST, bool, bool]]] = {}
    for mname, m in methods.items():
        if mname == "__init__":
            continue  # pre-start writes happen-before the thread
        on_thread = mname in thread_side
        for node in ast.walk(m):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            else:
                continue
            for t in targets:
                attr = _self_attr_written(t)
                if attr is None or attr in locks:
                    continue
                writes.setdefault(attr, []).append(
                    (node, on_thread, _is_locked(ctx, node, locks)))

    for attr, sites in sorted(writes.items()):
        both = (any(on for _, on, _ in sites)
                and any(not on for _, on, _ in sites))
        if not both:
            continue
        unlocked = [(n, on) for n, on, locked in sites if not locked]
        for node, on_thread in unlocked:
            where = ("the thread side" if on_thread
                     else "the main thread")
            yield ctx.finding(
                NAME, node,
                f"`self.{attr}` is written both inside and outside "
                f"`{cls.name}`'s thread target; this write (on "
                f"{where}) holds no `with self._lock` — the PR 9 "
                "pad-counter race shape")
