"""time-in-jit: host clock reads inside traced code.

``time.monotonic()`` / ``time.perf_counter()`` / ``time.time()`` (and
their ``_ns`` variants) evaluate ONCE, at trace time, inside jit — the
"timestamp" baked into the compiled program is the moment of the trace,
not of any execution, and every later call reuses it. The bug is silent:
nothing fails, durations come out as 0 or constant, and a cost/telemetry
hook wired one call too deep (exactly the graftprof wiring shape —
StepTimer/CostTracker sit one function away from the jit boundary)
quietly measures nothing. Clock reads belong on the host side of the
boundary; this rule makes the placement mechanical.

Both spellings are covered: attribute calls (``time.perf_counter()``)
and names bound by ``from time import perf_counter``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import dotted_name

NAME = "time-in-jit"
RATIONALE = ("time.time()/monotonic()/perf_counter() inside traced code "
             "evaluates at TRACE time and becomes a compiled-in constant "
             "— timing hooks belong outside the jit boundary")

#: clock reads that concretize host time (time module surface)
_CLOCKS = frozenset({
    "time", "monotonic", "perf_counter",
    "time_ns", "monotonic_ns", "perf_counter_ns",
})
_DOTTED = frozenset(f"time.{c}" for c in _CLOCKS)


def _from_time_imports(tree: ast.AST) -> frozenset:
    """Local names bound to time-module clocks via ``from time import``
    (including aliases: ``from time import perf_counter as clock``)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name in _CLOCKS:
                    names.add(alias.asname or alias.name)
    return frozenset(names)


def check(ctx: FileContext) -> Iterator[Finding]:
    traced = ctx.traced
    if not traced.traced:
        return
    bare = _from_time_imports(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name in _DOTTED:
            clock = name
        elif (isinstance(node.func, ast.Name) and node.func.id in bare):
            clock = f"time.{node.func.id}"
        else:
            continue
        if not traced.in_traced_code(node):
            continue
        yield ctx.finding(
            NAME, node,
            f"`{clock}()` inside traced code is evaluated once at trace "
            "time and compiled in as a constant — move the clock read to "
            "the host side of the jit boundary")
