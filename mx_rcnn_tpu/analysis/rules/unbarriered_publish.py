"""unbarriered-publish: primary-only checkpoint publication needs a
preceding all-host barrier.

The multi-host save pattern is ``if is_primary(): save_checkpoint(...)``
— one host publishes for the fleet. Without a barrier in front of it,
the primary can publish a checkpoint cut at a boundary some peer never
reached (it was still dispatching, or it died mid-drain): the save LOOKS
complete but encodes a state the fleet never collectively held, and a
``--resume auto`` restart silently rewinds the stragglers' progress —
or, under graftquorum's torn-save detection, records a host set the meta
sidecar cannot vouch for. graftquorum's contract (resilience/quorum.py)
is barrier-then-publish: ``quorum.barrier(...)`` first, so the emergency
and epoch-boundary saves in tools/train.py are cut only after every
active host arrived.

Recognized publication calls (syntactic): a call whose final name
segment is ``save_checkpoint``, lexically inside the body of an ``if``
whose test mentions the primary guard — a call to ``is_primary`` or a
``process_index() == 0`` comparison. The rule is satisfied when a
barrier call (final segment ``barrier`` — ``quorum.barrier``,
``q.barrier``) appears EARLIER (lexically) in the same enclosing
function. Known limitation, on purpose: an early-return guard
(``if not is_primary(): return`` followed by the save) is not matched —
the rule targets the repo's guarded-body idiom, where the reviewer can
see guard and publication as one unit.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import dotted_name

NAME = "unbarriered-publish"
RATIONALE = ("a primary-only checkpoint publication without a preceding "
             "all-host barrier can persist a state some peer never "
             "reached; barrier first (resilience/quorum.py), then let "
             "process 0 publish")

#: publication entry points (final dotted segment)
_PUBLISH_NAMES = frozenset({"save_checkpoint"})


def _final_segment(func: ast.expr) -> Optional[str]:
    name = dotted_name(func)
    if not name:
        return None
    return name.rsplit(".", 1)[-1]


def _is_primary_guard(test: ast.expr) -> bool:
    """Does this if-test gate on being the primary/zeroth process?"""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            seg = _final_segment(node.func)
            if seg == "is_primary":
                return True
        if isinstance(node, ast.Compare):
            operands = [node.left] + list(node.comparators)
            has_pi = any(
                isinstance(op, ast.Call)
                and _final_segment(op.func) == "process_index"
                for op in operands)
            has_zero = any(
                isinstance(op, ast.Constant) and op.value == 0
                for op in operands)
            if has_pi and has_zero:
                return True
    return False


def _calls_by_line(func: ast.AST) -> List[Tuple[int, str]]:
    """(lineno, final segment) of every call in the function, including
    nested defs — a barrier factored into a helper closure still counts,
    as long as it is defined before the publication site."""
    out: List[Tuple[int, str]] = []
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            seg = _final_segment(node.func)
            if seg:
                out.append((node.lineno, seg))
    return out


def check(ctx: FileContext) -> Iterator[Finding]:
    seen = set()  # ast.walk visits nested defs from every enclosing scope
    for func in ast.walk(ctx.tree):
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        calls = _calls_by_line(func)
        barrier_lines = sorted(line for line, seg in calls
                               if seg == "barrier")
        for stmt in ast.walk(func):
            if not (isinstance(stmt, ast.If)
                    and _is_primary_guard(stmt.test)):
                continue
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                seg = _final_segment(node.func)
                if seg not in _PUBLISH_NAMES:
                    continue
                if any(line < node.lineno for line in barrier_lines):
                    continue
                if (node.lineno, node.col_offset) in seen:
                    continue
                seen.add((node.lineno, node.col_offset))
                yield ctx.finding(
                    NAME, node,
                    f"{seg}() under a primary-only guard with no "
                    "preceding all-host barrier in "
                    f"`{func.name}` — a peer still dispatching (or dead) "
                    "makes this a torn publication; call "
                    "quorum.barrier(...) first (resilience/quorum.py)")
