"""wall-time-duration: durations computed from wall-clock samples.

``time.time()`` answers "what o'clock is it" — it steps whenever NTP
corrects the host clock, by whole seconds on a preemptible fleet that
just woke up. Subtracting two wall samples therefore measures the clock's
drift as much as the code's elapsed time; on a multi-host run the skew is
per-host, which is exactly the bug family the grafttower clock anchor
(obs/fleet.py) exists to cancel. Durations belong on the monotonic clock
(``time.monotonic()`` / ``time.perf_counter()``); wall stamps are for
correlation and display only.

Flags a subtraction where BOTH operands are wall samples:

- a direct ``time.time()`` / ``time.time_ns()`` call (dotted or bound by
  ``from time import time``),
- a name or attribute assigned from such a call anywhere in the file
  (``t0 = time.time()`` … ``time.time() - t0``; ``self._tic``),
- a ``t_wall`` record field (``e["t_wall"]``, ``e.get("t_wall")``,
  ``e.t_wall`` — the graftscope event stamp).

Monotonic/perf_counter subtractions, comparisons, max/min over stamps,
and mixed expressions with an unknown side stay legal — the rule only
fires when both sides are provably wall time.
"""

from __future__ import annotations

import ast
from typing import Iterator

from mx_rcnn_tpu.analysis.engine import FileContext, Finding
from mx_rcnn_tpu.analysis.tracing import dotted_name

NAME = "wall-time-duration"
RATIONALE = ("subtracting time.time()/t_wall samples measures NTP drift "
             "along with elapsed time — durations belong on "
             "time.monotonic()/perf_counter(); wall stamps are for "
             "cross-host correlation only")

#: the wall clocks (monotonic/perf_counter are the fix, not the bug)
_WALL_DOTTED = frozenset({"time.time", "time.time_ns"})
_WALL_BARE = frozenset({"time", "time_ns"})
_FIELD = "t_wall"


def _from_time_imports(tree: ast.AST) -> frozenset:
    """Local names bound to the wall clock via ``from time import time``
    (including aliases)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name in _WALL_BARE:
                    names.add(alias.asname or alias.name)
    return frozenset(names)


def _is_wall_call(node: ast.AST, bare: frozenset) -> bool:
    if not isinstance(node, ast.Call):
        return False
    if dotted_name(node.func) in _WALL_DOTTED:
        return True
    return isinstance(node.func, ast.Name) and node.func.id in bare


def _wall_bindings(tree: ast.AST, bare: frozenset):
    """Names and attribute fields assigned from a wall-clock call
    anywhere in the file (file-scope heuristic — good enough: a name
    that EVER holds a wall stamp being subtracted is the bug)."""
    names, attrs = set(), set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            continue
        value = getattr(node, "value", None)
        if value is None or not _is_wall_call(value, bare):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                names.add(tgt.id)
            elif isinstance(tgt, ast.Attribute):
                attrs.add(tgt.attr)
    return frozenset(names), frozenset(attrs)


def _is_wall_sample(node: ast.AST, bare: frozenset, names: frozenset,
                    attrs: frozenset) -> bool:
    """Is this expression provably a wall-clock sample?"""
    if _is_wall_call(node, bare):
        return True
    if isinstance(node, ast.Name) and node.id in names:
        return True
    if isinstance(node, ast.Attribute):
        return node.attr == _FIELD or node.attr in attrs
    if isinstance(node, ast.Subscript):
        sl = node.slice
        return isinstance(sl, ast.Constant) and sl.value == _FIELD
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get" and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == _FIELD):
        return True  # e.get("t_wall")
    return False


def check(ctx: FileContext) -> Iterator[Finding]:
    bare = _from_time_imports(ctx.tree)
    names, attrs = _wall_bindings(ctx.tree, bare)
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Sub)):
            continue
        if (_is_wall_sample(node.left, bare, names, attrs)
                and _is_wall_sample(node.right, bare, names, attrs)):
            yield ctx.finding(
                NAME, node,
                "duration computed by subtracting wall-clock samples "
                "(time.time()/t_wall) — an NTP step lands in the result; "
                "use time.monotonic()/perf_counter() for durations and "
                "keep wall stamps for correlation")
