"""Declarative graftlint configuration: ``[tool.graftlint]`` in pyproject.

The CLI takes paths/flags for ad-hoc runs, but the repo's own invocation
(script/lint.sh, tests/test_lint_clean.py, pre-commit) is configured here
so every entry point agrees on what "the lint gate" means. Python 3.11's
``tomllib`` is preferred; 3.10 falls back to ``tomli``; if neither parser
exists the defaults below (which mirror the committed pyproject) apply —
the linter itself must never gain a hard dependency.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Tuple

try:  # pragma: no cover - version-dependent import
    import tomllib as _toml
except ImportError:  # pragma: no cover
    try:
        import tomli as _toml  # type: ignore[no-redef]
    except ImportError:
        _toml = None


@dataclass(frozen=True)
class Settings:
    #: default lint targets when the CLI gets no paths
    paths: Tuple[str, ...] = ("mx_rcnn_tpu", "tests")
    #: repo-relative prefixes never linted (fixture snippets are not code)
    exclude: Tuple[str, ...] = ()
    #: baseline suppression file, repo-relative
    baseline: str = ".graftlint-baseline.json"
    #: rule NAMEs switched off entirely
    disable: Tuple[str, ...] = ()
    #: first-parameter names that mark a jitted function as holding a
    #: donatable state pytree (rules/donation.py)
    state_params: Tuple[str, ...] = ("state", "train_state")
    #: variable names assumed to hold the frozen Config tree
    cfg_roots: Tuple[str, ...] = ("cfg",)
    #: callables whose result is a host-side (numpy-backed) pytree —
    #: feeding one into a donating jit without jax.device_put is the
    #: PR 5/7 heap-corruption family (rules/donation_hazard.py)
    host_tree_sources: Tuple[str, ...] = (
        "load_checkpoint", "host_tree_copy")

    @staticmethod
    def load(root: str) -> "Settings":
        path = os.path.join(root, "pyproject.toml")
        if _toml is None or not os.path.isfile(path):
            return Settings()
        with open(path, "rb") as fh:
            data = _toml.load(fh)
        tool = data.get("tool", {}).get("graftlint", {})
        kw = {}
        for key, attr in (("paths", "paths"), ("exclude", "exclude"),
                          ("disable", "disable"),
                          ("state-params", "state_params"),
                          ("cfg-roots", "cfg_roots"),
                          ("host-tree-sources", "host_tree_sources")):
            if key in tool:
                kw[attr] = tuple(tool[key])
        if "baseline" in tool:
            kw["baseline"] = str(tool["baseline"])
        return Settings(**kw)


def find_repo_root(start: Optional[str] = None) -> str:
    """Nearest ancestor holding pyproject.toml or .git; cwd otherwise."""
    cur = os.path.abspath(start or os.getcwd())
    while True:
        if (os.path.isfile(os.path.join(cur, "pyproject.toml"))
                or os.path.isdir(os.path.join(cur, ".git"))):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return os.path.abspath(start or os.getcwd())
        cur = nxt
