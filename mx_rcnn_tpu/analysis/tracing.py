"""Which functions run under a JAX trace? — shared syntactic reachability.

Trace roots are functions that are (a) decorated with ``@jax.jit`` /
``@jit`` / ``@pjit`` / ``@partial(jax.jit, ...)``, (b) passed as the first
positional argument to a ``jax.jit(...)`` / ``pjit(...)`` /
``shard_map(...)`` call, or (c) passed as the kernel to
``pl.pallas_call(...)``. From the roots the set closes transitively over
same-module calls resolved lexically (enclosing function scopes outward
to module level) — ``jax.jit(step)`` in ``train/step.py`` marks ``step``,
which marks the sibling closures ``_one_update`` / ``_grads_of`` and the
module-level ``_metric_parts``.

Cross-module reachability comes from graftsight (callgraph.py): when the
engine runs over a whole tree it builds one Program — module-qualified
symbol resolution over imports, attribute calls and class methods, with
jit roots propagated transitively across files — and seeds each file's
TraceAnalysis with the program's traced nodes for that file
(``extra_traced``). A helper in ``models/`` called only from a jitted
wrapper in ``train/`` is then just as visible to the host-sync/shape
rules as a same-module helper. Single-snippet runs (``lint_source`` with
no program) keep the file-local under-approximation: flow-insensitive
and false-positive-free on host-side helper code.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)
FuncOrLambda = FuncNode + (ast.Lambda,)

#: dotted names that wrap a python callable into a traced computation
JIT_CALLABLES = {
    "jit", "jax.jit", "pjit", "jax.pjit", "pjit.pjit",
    "pallas_call", "pl.pallas_call", "pallas.pallas_call",
    "checkify.checkify",
    "shard_map", "jax.shard_map",
    "jax.experimental.shard_map.shard_map", "shard_map.shard_map",
}
#: of those, the ones with jit's ``donate_argnums`` API (rules/donation.py)
JIT_DONATABLE = {"jit", "jax.jit", "pjit", "jax.pjit", "pjit.pjit"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.random.split' for nested Attribute/Name, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def jit_expr_name(node: ast.AST) -> Optional[str]:
    """If ``node`` evaluates to a jit-like wrapper, its dotted name.

    Handles the bare callable (``jax.jit``) and the configured-partial
    idiom (``partial(jax.jit, static_argnums=...)``).
    """
    name = dotted_name(node)
    if name in JIT_CALLABLES:
        return name
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn in ("partial", "functools.partial") and node.args:
            inner = dotted_name(node.args[0])
            if inner in JIT_CALLABLES:
                return inner
    return None


def jit_call_kwargs(node: ast.AST) -> List[ast.keyword]:
    """Keywords carried by a jit-like expression (call or partial form)."""
    if isinstance(node, ast.Call):
        return list(node.keywords)
    return []


class _ScopeIndex(ast.NodeVisitor):
    """function node -> chain of enclosing scopes, each a {name: def} map."""

    def __init__(self):
        self.scopes: List[Dict[str, ast.AST]] = [{}]
        self.chain_of: Dict[ast.AST, Tuple[Dict[str, ast.AST], ...]] = {}
        self.module_scope = self.scopes[0]

    def _visit_func(self, node):
        self.scopes[-1].setdefault(node.name, node)
        self.chain_of[node] = tuple(self.scopes)
        self.scopes.append({})
        self.generic_visit(node)
        self.scopes.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Lambda(self, node):
        self.chain_of[node] = tuple(self.scopes)
        self.generic_visit(node)


class TraceAnalysis:
    def __init__(self, tree: ast.AST, parents: Dict[ast.AST, ast.AST],
                 extra_traced: Iterable[ast.AST] = ()):
        """``extra_traced``: function nodes of THIS tree that a
        whole-program pass (callgraph.Program) proved jit-reachable from
        roots in other modules; they seed the same-module closure."""
        self.tree = tree
        self.parents = parents
        self._index = _ScopeIndex()
        self._index.visit(tree)
        self._own_cache: Dict[ast.AST, Dict[str, ast.AST]] = {}
        self.traced: Set[ast.AST] = set()
        self._find_roots()
        self.traced.update(extra_traced)
        self._close_over_calls()

    # -- root discovery ----------------------------------------------------

    def _find_roots(self):
        for node in ast.walk(self.tree):
            if isinstance(node, FuncNode):
                for deco in node.decorator_list:
                    if jit_expr_name(deco):
                        self.traced.add(node)
            elif isinstance(node, ast.Call) and jit_expr_name(node.func):
                if node.args:
                    target = node.args[0]
                    # pallas_call(partial(kernel, ...), ...) — unwrap
                    if (isinstance(target, ast.Call)
                            and dotted_name(target.func)
                            in ("partial", "functools.partial")
                            and target.args):
                        target = target.args[0]
                    if isinstance(target, ast.Lambda):
                        self.traced.add(target)
                    elif isinstance(target, ast.Name):
                        resolved = self._resolve(target.id, node)
                        if resolved is not None:
                            self.traced.add(resolved)

    def _resolve(self, name: str, at_node: ast.AST) -> Optional[ast.AST]:
        """Resolve ``name`` to a def lexically visible at ``at_node``."""
        fn = self.enclosing_function(at_node)
        while fn is not None:
            chain = self._index.chain_of.get(fn, ())
            # innermost first: the fn's own locals, then outward
            for scope in (self._own_scope(fn),) + tuple(reversed(chain)):
                if scope and name in scope:
                    return scope[name]
            fn = self.enclosing_function(fn)
        if name in self._index.module_scope:
            return self._index.module_scope[name]
        return None

    def _own_scope(self, fn: ast.AST) -> Dict[str, ast.AST]:
        cached = self._own_cache.get(fn)
        if cached is not None:
            return cached
        out: Dict[str, ast.AST] = {}
        for child in ast.walk(fn):
            if child is fn or not isinstance(child, FuncNode):
                continue
            # only defs whose nearest enclosing function is fn
            if self.enclosing_function(child) is fn:
                out.setdefault(child.name, child)
        self._own_cache[fn] = out
        return out

    # -- transitive closure ------------------------------------------------

    def _close_over_calls(self):
        work = list(self.traced)
        while work:
            fn = work.pop()
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)):
                    continue
                resolved = self._resolve(node.func.id, node)
                if (resolved is not None
                        and isinstance(resolved, FuncNode)
                        and resolved not in self.traced):
                    self.traced.add(resolved)
                    work.append(resolved)

    # -- queries -----------------------------------------------------------

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, FuncOrLambda):
            cur = self.parents.get(cur)
        return cur

    def in_traced_code(self, node: ast.AST) -> bool:
        """True if any enclosing function is traced (nested defs inside a
        traced function are traced: jit traces through closure calls)."""
        fn = self.enclosing_function(node)
        while fn is not None:
            if fn in self.traced:
                return True
            fn = self.enclosing_function(fn)
        return False

    def traced_param_names(self, node: ast.AST) -> Set[str]:
        """Parameter names of every enclosing traced function — the
        syntactic stand-ins for 'traced values' at ``node``."""
        names: Set[str] = set()
        fn = self.enclosing_function(node)
        while fn is not None:
            if fn in self.traced or self.in_traced_code(fn):
                names |= param_names(fn)
            fn = self.enclosing_function(fn)
        return names

    def iter_traced_functions(self) -> Iterator[ast.AST]:
        return iter(self.traced)


def param_names(fn: ast.AST) -> Set[str]:
    if not isinstance(fn, FuncOrLambda):
        return set()
    a = fn.args
    names = {p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names
