"""Immutable configuration tree.

Replaces the reference's global mutable ``easydict`` config
(rcnn/config.py: ``config``, ``default``, ``network``, ``dataset``,
``generate_config(net, ds)``) with a frozen dataclass tree. Numeric defaults
follow the reference's classic Faster R-CNN hyperparameters; every field that
the reference exposes has an equivalent here. ``generate_config`` keeps the
same name and role: merge per-network and per-dataset presets.

TPU delta vs the reference: shapes are static. ``TrainConfig.max_gt_boxes``
pads the gt-box tensor, ``rpn_post_nms_top_n`` / ``batch_rois`` are exact
(masked) counts, and image batches are padded to ``image_pad_shape``.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field, replace
from typing import Any, Mapping, Optional


@dataclass(frozen=True)
class NetworkConfig:
    """Per-backbone structural config (reference: rcnn/config.py `network.*`)."""

    name: str = "resnet50"
    # Anchors (reference: generate_anchors(base_size=16, ratios, scales)).
    anchor_base_size: int = 16
    anchor_ratios: tuple = (0.5, 1.0, 2.0)
    anchor_scales: tuple = (8, 16, 32)
    rpn_feat_stride: int = 16
    # Backbone freezing (reference: fixed_param_prefix in train_end2end.py).
    fixed_param_patterns: tuple = ("conv0", "bn0", "stage1", "gamma", "beta")
    # Head pooling (reference: ROIPooling 7x7 VGG / 14x14 ResNet, 1/16 scale).
    roi_pool_size: int = 14
    roi_pool_type: str = "align"  # "align" | "max" — reference uses max-pool
    # Channels of the stride-16 feature map (C4): 1024 for ResNet, 512 VGG.
    feat_channels: int = 1024
    depth: int = 50  # resnet depth; unused for vgg
    # Backbone normalization: "frozen_bn" (reference parity — REQUIRES
    # pretrained statistics to be restored) or "group" (GroupNorm; the
    # stable choice for from-scratch training, see models/backbones.py).
    norm: str = "frozen_bn"
    # Stop-gradient freeze cut: 0 = none, 1 = stem, 2 = stem+stage1
    # (reference fixed_param_prefix default). Use 0 when training from
    # scratch — freezing random weights is pointless.
    freeze_at: int = 2
    # Rematerialize ResNet stage activations in the backward (jax.checkpoint
    # via nn.remat) — trades ~1/3 extra FLOPs for HBM, enabling bigger
    # images / per-chip batches (models/backbones.py).
    remat: bool = False
    # FPN (off for the classic C4 configs).
    use_fpn: bool = False
    fpn_strides: tuple = (4, 8, 16, 32, 64)
    fpn_channels: int = 256
    # Fused shared-RPN-head application: pack P2..P6 into one zero-gapped
    # canvas and run the head ONCE instead of five small-grid convs
    # (models/fpn.py::rpn_forward_packed; semantics identical — tested).
    fpn_packed_rpn_head: bool = True
    # Mask head (Mask R-CNN configs).
    use_mask: bool = False
    mask_pool_size: int = 14
    mask_resolution: int = 28
    # ViTDet (stretch config; models/vit.py).
    use_vit: bool = False
    vit_patch: int = 16
    vit_dim: int = 768
    vit_depth: int = 12
    vit_heads: int = 12
    vit_window: int = 8  # local-attention window (tokens per side)
    # Sequence-parallel attention for the global blocks (long context,
    # ops/ring_attention.py); needs a mesh at model build time.
    # use_ring_attention selects the ppermute ring; sp_mode overrides the
    # formulation: "ring" | "ulysses" (all-to-all; heads must divide by
    # the mesh model-axis size).
    use_ring_attention: bool = False
    sp_mode: str = "ring"
    # Single-device attention formulation for the ViTDet GLOBAL blocks
    # (the only dense-attention site with enough tokens to matter —
    # DETR's 640-token encoder is below any practical chunk, and its MHA
    # stays dense; windowed blocks are 64-token tiles): "dense" (one
    # (S,S) score buffer — XLA fuses well at detector sequence lengths)
    # or "streaming" (flash-style key-block scan, O(S·chunk) memory;
    # ops/ring_attention.py). Exact either way; a speed/memory knob
    # measured in PERF.md r5. Ignored (with a warning) under pp_stages.
    attn_impl: str = "dense"
    attn_kv_chunk: int = 1024
    # Tensor parallelism over the mesh `model` axis (parallel/partition.py):
    # Megatron-split transformer MLP/attention weights and the paired
    # fc6/fc7 detection heads; GSPMD inserts the collectives. Composes
    # with DP (data axis) and SP (same model axis, different tensors).
    tensor_parallel: bool = False
    # Pipeline parallelism for the ViT encoder (parallel/pipeline.py):
    # pp_stages > 0 selects the staged backbone (ViTBackbonePP) pipelined
    # over the mesh `model` axis (whose size must equal pp_stages). The
    # staged model reproduces the sequential ViTDet global-attention
    # placement EXACTLY for every buildable stage count; stage counts
    # that cannot preserve it (placement not periodic in the stage size,
    # e.g. depth 12 into 3 stages) hard-error at build time
    # (models/vit.py::_stage_global_pattern). Mutually exclusive with SP.
    # pp_microbatches=0 → one microbatch per stage.
    pp_stages: int = 0
    pp_microbatches: int = 0
    # Proposal pre-NMS top-k: "exact" (lax.top_k) or "approx"
    # (lax.approx_max_k, recall 0.95 — the TPU PartialReduce op; ~1.2 ms
    # off the FPN step, exact kept default for determinism. PERF.md).
    proposal_topk: str = "exact"
    # DETR (stretch config; models/detr.py).
    use_detr: bool = False
    detr_queries: int = 100
    detr_hidden: int = 256
    detr_heads: int = 8
    detr_enc_layers: int = 6
    detr_dec_layers: int = 6

    @property
    def num_anchors(self) -> int:
        return len(self.anchor_ratios) * len(self.anchor_scales)


@dataclass(frozen=True)
class TrainConfig:
    """Training hyperparameters (reference: rcnn/config.py `config.TRAIN`)."""

    # RPN anchor target assignment (reference: rcnn/io/rpn.py assign_anchor).
    rpn_batch_size: int = 256
    rpn_fg_fraction: float = 0.5
    rpn_positive_overlap: float = 0.7
    rpn_negative_overlap: float = 0.3
    rpn_clobber_positives: bool = False
    rpn_allowed_border: int = 0
    # Proposal op (train mode) (reference: rcnn/symbol/proposal.py).
    rpn_pre_nms_top_n: int = 12000
    rpn_post_nms_top_n: int = 2000
    rpn_nms_thresh: float = 0.7
    rpn_min_size: int = 16
    # RCNN roi sampling (reference: rcnn/io/rcnn.py sample_rois).
    batch_rois: int = 128
    fg_fraction: float = 0.25
    fg_thresh: float = 0.5
    bg_thresh_hi: float = 0.5
    # None is a SENTINEL meaning "unset": it resolves to the reference's
    # end2end default 0.0 (see bg_thresh_lo_value), while the alternate
    # Fast-RCNN path (tools/stages.py::train_rcnn) replaces it with the
    # reference's 0.1 preset. An EXPLICIT value — including 0.0, which the
    # sentinel makes expressible — is respected everywhere.
    bg_thresh_lo: Optional[float] = None
    # bbox regression target normalization (reference: config.TRAIN.BBOX_*).
    bbox_normalization_precomputed: bool = True
    bbox_means: tuple = (0.0, 0.0, 0.0, 0.0)
    bbox_stds: tuple = (0.1, 0.1, 0.2, 0.2)
    # Optimizer (reference: train_end2end.py fit kwargs).
    lr: float = 0.001
    lr_step: tuple = (7,)  # epochs at which lr is divided by lr_factor
    lr_factor: float = 0.1
    momentum: float = 0.9
    wd: float = 0.0005
    clip_gradient: float = 5.0
    # "sgd" (reference parity: SGD+momentum, elementwise clip) or "adamw"
    # (the transformer families — DETR/ViTDet train with AdamW + global-
    # norm clip per their papers; SGD barely converges there).
    optimizer: str = "sgd"
    begin_epoch: int = 0
    end_epoch: int = 10
    # Non-blocking epoch-end saves (orbax AsyncCheckpointer — the train
    # loop keeps stepping while the write lands; train/checkpoint.py
    # CheckpointWriter). Auto-falls back to synchronous saves multi-host.
    async_checkpoint: bool = True
    # Gradient accumulation: each optimizer step averages grads over this
    # many sequential micro-steps (unrolled inside the jitted step —
    # compile time and HLO size grow with the count; see train/step.py
    # for why not lax.scan), so effective batch = batch_images x
    # grad_accum_steps x data-axis size without the activation memory of
    # the big batch. The reference has no equivalent (SURVEY.md §3.2).
    # 1 = off.
    grad_accum_steps: int = 1
    # graftcast (train/precision.py): the mixed-precision policy. "bf16"
    # (default — the MXU's native dtype, ~2x the f32 peak on v5e) runs
    # the forward/backward in bfloat16 with f32 master weights, f32
    # islands (norm statistics, losses, bbox decode/encode, NMS scores)
    # and f32 gradients/optimizer updates; "f32" runs everything float32
    # (the numerics reference the bf16 parity gates compare against).
    # Checkpoints are f32 tree-form either way and interchange between
    # the two bit-for-bit at the master-weight level. Under
    # train.flat_params the bf16 param casts collapse to ONE cast kernel
    # per dtype buffer (the FlatTrainState.compute shadow); tree mode
    # keeps flax's per-leaf promotion (same values). Accepts the long
    # spellings "float32"/"bfloat16" too.
    compute_dtype: str = "bf16"
    # Optimizer slot dtype: "float32" (default) or "bfloat16" — stores
    # the SGD momentum / AdamW first-moment accumulator in bf16 (halves
    # that tree's memory; the AdamW second moment always stays f32 — its
    # precision matters for the rsqrt). NOTE measured NEUTRAL on step
    # time on v5e (7.14 vs 7.21 ms DETR update — the update cost is a
    # formulation-invariant floor, PERF.md r4); this knob is a MEMORY
    # lever for big models, not a speed lever here.
    opt_state_dtype: str = "float32"
    # flatcore (train/flatcore.py): store all trainable leaves in ONE
    # contiguous dtype-segregated buffer per tree (params / momentum /
    # both Adam moments) with a static segment table; the optimizer
    # update runs as a handful of fused elementwise kernels over the
    # flat buffers instead of hundreds of per-leaf kernels (the ~6 ms
    # many-buffer update floor, PERF.md r4 item 3), and the DP gradient
    # allreduce becomes one psum per buffer. Exact — parity-gated
    # against the tree path (tests/test_flatcore.py). TP/PP configs
    # route back to the per-leaf path (a sharded leaf has no contiguous
    # image in a flat buffer). Checkpoints stay in TREE form on disk,
    # interchangeable between modes. Default off until the on-chip A/B
    # (bench.py update_* recipes) confirms the win.
    flat_params: bool = False
    # Multi-step dispatch: each host call drives this many FULL optimizer
    # steps through one jitted lax.scan over step-stacked batches
    # (train/step.py), amortizing the fixed per-dispatch host/relay
    # overhead (~15-20 ms through the axon tunnel — PERF.md) across K
    # steps. Orthogonal to grad_accum_steps (which merges micro-grads
    # into ONE update; this performs K separate updates). 1 = off.
    multi_step_dispatch: int = 1
    # Data
    batch_images: int = 1  # images per device
    shuffle: bool = True
    flip: bool = True
    aspect_grouping: bool = True
    # Static-shape padding (TPU design decision — no reference equivalent).
    max_gt_boxes: int = 100
    # FPN proposal budget per pyramid level (Detectron convention: 2000/level
    # at train time); only read when network.use_fpn.
    fpn_rpn_pre_nms_per_level: int = 2000
    # FPN RPN NMS scope: per-level (True — the Detectron-lineage
    # semantics; measured equal in cost to one joint NMS over the
    # 10k-candidate union at v5e train sizes, PERF.md) or joint across
    # the union (False).
    fpn_nms_per_level: bool = True
    # Mask target rasterization resolution (gt instance masks are stored
    # box-frame at this size; only read when network.use_mask).
    mask_gt_resolution: int = 56
    # Loss scaling constants (reference scales smooth-L1 by 1/RPN_BATCH and
    # 1/BATCH_ROIS via grad_scale, NOT by live fg counts).
    # DETR set-loss knobs (models/detr.py; Carion et al. defaults).
    detr_eos_coef: float = 0.1
    detr_cost_class: float = 1.0
    detr_cost_l1: float = 5.0
    detr_cost_giou: float = 2.0
    # Auxiliary decoding losses: the matched set loss at EVERY decoder
    # layer through shared heads (Carion et al. §3.2).
    detr_aux_loss: bool = True
    # end2end switch retained for the alternate-training tools.
    end2end: bool = True

    @property
    def bg_thresh_lo_value(self) -> float:
        """bg_thresh_lo with the None sentinel resolved to the end2end
        default (0.0). Model forwards read this; only the Fast-RCNN stage
        driver inspects the raw sentinel."""
        return 0.0 if self.bg_thresh_lo is None else self.bg_thresh_lo


@dataclass(frozen=True)
class TestConfig:
    """Inference hyperparameters (reference: rcnn/config.py `config.TEST`)."""

    rpn_pre_nms_top_n: int = 6000
    rpn_post_nms_top_n: int = 300
    rpn_nms_thresh: float = 0.7
    rpn_min_size: int = 16
    # Final detection post-processing (reference: rcnn/core/tester.py pred_eval).
    nms_thresh: float = 0.3
    score_thresh: float = 0.05
    max_per_image: int = 100
    # Proposal-generation mode (alternate training / Fast R-CNN) — the
    # reference's TEST.PROPOSAL_* knobs: dump MORE proposals (→2000) than the
    # detection path keeps (→300).
    proposal_nms_thresh: float = 0.7
    proposal_pre_nms_top_n: int = 20000
    proposal_post_nms_top_n: int = 2000
    # FPN per-level proposal budget at test time (Detectron: 1000/level).
    fpn_rpn_pre_nms_per_level: int = 1000
    fpn_nms_per_level: bool = True  # see TrainConfig.fpn_nms_per_level


@dataclass(frozen=True)
class DatasetConfig:
    """Per-dataset config (reference: rcnn/config.py `dataset.*`)."""

    name: str = "coco"
    root_path: str = "data"
    dataset_path: str = "data/coco"
    image_set: str = "train2017"
    test_image_set: str = "val2017"
    num_classes: int = 81  # incl. background
    class_names: tuple = ()
    # Extra get_dataset(...) kwargs as (key, value) pairs — kept a tuple so
    # the frozen config stays hashable (e.g. synthetic dataset sizing:
    # (("num_images", 8), ("image_size", 128))).
    kwargs: tuple = ()


@dataclass(frozen=True)
class ImageConfig:
    """Image pipeline (reference: config.SCALES / PIXEL_MEANS, rcnn/io/image.py)."""

    scales: tuple = ((600, 1000),)  # (target short side, max long side)
    pixel_means: tuple = (123.68, 116.779, 103.939)  # RGB (reference stores BGR)
    pixel_stds: tuple = (1.0, 1.0, 1.0)
    # Static padded shape (H, W) every image batch is padded to. Must be a
    # multiple of the max feature stride. 1024 covers the (600,1000) scale.
    pad_shape: tuple = (1024, 1024)
    # Multi-scale training (BASELINE config 3): one (H, W) pad bucket per
    # entry of `scales`. Used ONLY when len(pad_shapes) == len(scales);
    # an EMPTY tuple falls back to the single pad_shape (the documented
    # override path — generate_config empties it when scales/pad_shape
    # are overridden alone), while a NON-empty length mismatch is a
    # config error (loader.pad_shape_for raises — the stale-pair trap).
    # Each bucket is its own static shape → its own jit compile of the
    # train step (documented cost: one extra compile per extra scale).
    # The loader samples one scale PER BATCH — the per-image random
    # scale of reference-lineage forks would break the single static
    # batch shape.
    pad_shapes: tuple = ()
    # graftcanvas (data/canvas.py): whole-batch canvas packing. The
    # loader shelf-packs every batch's mixed-size images into ONE fixed
    # (canvas_shape) canvas per data shard instead of padding each image
    # to its orientation x scale pad bucket — every STEP then has one
    # static shape, period (the pad-bucket compile zoo collapses to a
    # single train-step executable) and the model pays for canvas
    # pixels, not bucket pixels. Placement metadata rides im_info
    # ([h, w, scale, y0, x0] rows) through anchors/targets, proposals
    # and ROI extraction, so per-image semantics are exact: proposals
    # and ROIs never cross a placement border (gated in
    # tests/test_canvas.py). TRAIN-time only — eval/checkpoints are
    # unaffected. Default off until the on-chip A/B (bench.py
    # c4_r101_canvas / fpn_r101_canvas recipes).
    canvas_pack: bool = False
    # Fixed canvas (H, W); () derives a never-overflowing cover from
    # scales/canvas_images (data/canvas.py::resolve_canvas — the
    # conservative default; set a TIGHT canvas for the pixel win and let
    # scale-to-fit absorb the rare overflow batch).
    canvas_shape: tuple = ()
    # Minimum zero gap (px) between any two placements and alignment of
    # every placement offset; 0 derives the model family's max feature
    # stride (64 for FPN/ViTDet, 16 for C4). Must stay >= that stride:
    # alignment keeps every downsampled grid exact and the gap keeps
    # activations from leaking across images (the rpn_forward_packed
    # zero-gap argument, per-block re-masked in the backbone).
    canvas_gap: int = 0
    # Images packed per canvas plane; 0 = train.batch_images (each data
    # shard packs its whole per-device batch into one plane). Packing
    # pays off at >= 2 images per plane — mixed aspects share a canvas.
    canvas_images: int = 0


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout (replaces the reference's --gpus/--kvstore flags).

    The reference's only parallelism is data parallel (rcnn/core/module.py
    MutableModule over a context list + KVStore allreduce). Here a
    `jax.sharding.Mesh` with axes (data, model) covers DP and leaves room for
    model/spatial sharding; `mesh_shape="8"` or `"4x2"` style strings come
    from the `--tpu-mesh` CLI flag.
    """

    mesh_shape: str = "1"
    data_axis: str = "data"
    model_axis: str = "model"


@dataclass(frozen=True)
class ObsConfig:
    """graftscope telemetry (mx_rcnn_tpu/obs — event stream, step timing,
    compile tracking, stall watchdog). Off by default: the disabled path
    is a no-op sink and adds nothing to the train hot path."""

    enabled: bool = False
    # Event-log directory; "" derives one from the run (fit_detector uses
    # "<checkpoint-prefix>.obs"). Each process writes its own JSONL.
    dir: str = ""
    # step/compile records buffer this many lines before hitting disk
    # (other record kinds flush immediately).
    flush_every: int = 64
    # Emit a `compile` event (with shape signature) per XLA compile via
    # jax.monitoring.
    track_compiles: bool = True
    # Heartbeat watchdog: emit a `stall` event (with stack dumps) when no
    # step completes within max(stall_min_s, stall_factor x trailing
    # median step time). Before the FIRST completed step the floor is
    # COLD_GRACE (10x) x stall_min_s, so a healthy multi-minute cold
    # compile is not reported as a stall (obs/watchdog.py).
    watchdog: bool = True
    stall_factor: float = 10.0
    stall_min_s: float = 120.0
    watchdog_poll_s: float = 5.0
    # grafttower (obs/fleet.py): liveness beacon cadence — the watchdog
    # thread additionally emits a `heartbeat` event every this many
    # seconds (flushed immediately; ring-buffered into the flight
    # recorder) plus one final=True beat at clean shutdown, so the fleet
    # report tells a KILLED host (stale trail, no final beat) from a
    # slow one (fresh beats, fat step tail). 0 disables. Requires
    # obs.watchdog (the beacon shares its daemon thread).
    heartbeat_every_s: float = 15.0
    # graftprof (obs/costs.py): per-compiled-shape-bucket XLA cost/memory
    # accounting — one `cost` event per bucket (flops, HBM split), the
    # basis of the computed MFU in step/bench reports. Costs one AOT
    # trace per bucket (the XLA compile itself is a cache hit).
    cost_analysis: bool = True
    # graftprof (obs/profile.py): arm a jax.profiler capture window
    # around global step K (0 = off), N completed steps long, saved
    # under "<obs dir>/trace/stepK" and folded into a `trace` event.
    # The stall watchdog additionally auto-arms one window when it
    # fires, independent of this knob.
    trace_at_step: int = 0
    trace_steps: int = 3
    # graftpulse (train/health.py + obs/health.py): in-graph numerics
    # health. health_every=N computes per-buffer nonfinite counts and
    # grad/param/update norms INSIDE the compiled step (same executable,
    # zero added per-step host syncs) and folds them into a `health`
    # event every N dispatches; 0 = off (the step program is then
    # bit-identical to pre-graftpulse). Tripwires — any nonfinite, a
    # grad-norm explosion past health_grad_factor x the trailing median,
    # or a loss z-score beyond health_loss_z vs the health_window
    # trailing readings — emit an `anomaly` event, arm one jax.profiler
    # window, dump the flight-recorder ring and (health_checkpoint)
    # write an emergency checkpoint of the last known-good state; then
    # health_action "abort" raises NumericsAnomaly (restart with
    # --resume auto) while "warn" keeps training. Runbook: OUTAGES.md
    # "run went nonfinite".
    health_every: int = 0
    health_window: int = 64
    health_grad_factor: float = 100.0
    health_loss_z: float = 10.0
    health_action: str = "abort"
    # Refresh a host-side known-good snapshot after each CLEAN health
    # check (one device_get per interval — size health_every
    # accordingly) and save it as the emergency checkpoint on anomaly.
    health_checkpoint: bool = True
    # Flight recorder: capacity of the last-K in-memory event ring
    # dumped to <obs dir>/flight_<reason>.json on anomaly/stall/heal/
    # preempt/crash (obs/health.py FlightRecorder).
    flight_events: int = 256


@dataclass(frozen=True)
class ResilienceConfig:
    """graftguard fault tolerance (mx_rcnn_tpu/resilience — classified
    backend acquisition, preemption-safe training, deadline-isolated
    benching, chaos injection). Runbook: OUTAGES.md."""

    # Acquire the backend through resilience/backend.py: transient
    # failures (UNAVAILABLE — the TPU_OUTAGE_r5 signature) retry with
    # exponential backoff + jitter under the deadline below; permanent
    # errors fail fast. False = raw first-touch jax behavior.
    backend_acquire: bool = True
    # Require this platform in the acquired device list ("tpu" for real
    # runs): jax can silently fall back to CPU when the relay is down —
    # the probe then "succeeds" instantly and a multi-hour run proceeds
    # at CPU speed. When set, a fallback device list is classified as a
    # transient failure (backend cache cleared, retried under the
    # deadline). "" accepts whatever comes up (CPU tests/dev boxes).
    backend_platform: str = ""
    # Give up after this long of CONTINUOUS transient failure (the r5
    # outage lasted ~11 h; 12 h rides out a same-shaped one).
    backend_deadline_s: float = 43200.0
    backend_backoff_base_s: float = 2.0
    backend_backoff_max_s: float = 300.0
    # Multiplicative jitter fraction on every sleep (decorrelates a fleet
    # of hosts re-probing a recovering relay).
    backend_backoff_jitter: float = 0.25
    # Install SIGTERM/SIGINT handlers that request a checkpoint at the
    # next step boundary and exit with the resumable rc (75) instead of
    # dying mid-step (resilience/preempt.py).
    preempt_handlers: bool = True
    # On preemption, write a step-granular emergency checkpoint (a
    # dispatch-tagged dir under the prefix; picked up by --resume auto).
    # False: exit resumable-rc without saving (epoch checkpoints only).
    preempt_save: bool = True
    # graftheal (resilience/heal.py): a step-time transient backend loss
    # (the TPU_OUTAGE_r5 signature, mid-run) is healed IN-PROCESS —
    # emergency capture of the last known-good host state, backend
    # teardown + re-acquisition under backend_deadline_s, resume from
    # the captured state. If the backend returns with fewer devices the
    # mesh is re-cut (model axis kept, data axis shrunk; global batch
    # invariant). False = the pre-heal behavior: the error propagates.
    heal: bool = True
    # Give up (re-raise) after this many consecutive heals with no
    # completed dispatch in between — a fault that recurs instantly is
    # not an outage.
    heal_consecutive_max: int = 3
    # Refresh the host-side fallback snapshot every N completed
    # dispatches (one device_get sync each). 0 = live capture only:
    # fine when the post-loss state is readable (no donation, or chaos
    # injection); on real hardware with donated buffers the snapshot is
    # what bounds the deterministic replay after a mid-step loss.
    heal_snapshot_dispatches: int = 200
    # graftquorum (resilience/quorum.py): multi-host coordination for
    # preemption and heal. Deadline on every barrier / agree wait — a
    # host that misses it is excluded from the round (and exits
    # resumable when it discovers the sealed quorum moved on without
    # it).
    quorum_timeout_s: float = 60.0
    # A heal quorum below this fraction of the host set aborts the run
    # instead of limping on (half a fleet re-healing every few minutes
    # is an outage, not elasticity).
    quorum_min_fraction: float = 0.5
    # Filesystem-backed KV store directory for the quorum protocol.
    # "" = use jax.distributed's coordination-service KV client (real
    # pods); a path = FileKVStore rooted there (the N-process CPU
    # tests, or any fleet sharing a filesystem). Single-process runs
    # never construct a quorum.
    quorum_store_dir: str = ""
    # Elastic phase 2 policy when a heal re-acquires a different device
    # count (parallel/partition.py elastic_mesh_spec):
    #   "shrink"  — phase 1 behavior: shrink the data axis to the
    #               largest micro-batch divisor; never grow past the
    #               nominal footprint.
    #   "grow"    — shrink, plus GROW onto devices beyond the nominal
    #               footprint when the re-acquire returns more.
    #   "rescale" — grow, and on shrinks too deep to hold the global
    #               batch keep rows-per-device constant instead: the
    #               global batch scales with the fleet and the LR
    #               schedule position is rebased in images-seen terms
    #               via rebase_schedule_count.
    elastic_mode: str = "shrink"


@dataclass(frozen=True)
class DataConfig:
    """graftfeed input-plane fault tolerance (mx_rcnn_tpu/data/feedguard.py
    — classified record IO retry, deterministic quarantine, prefetch worker
    supervision, data-stall deadlines). Runbook: OUTAGES.md."""

    # Per-record retry window for TRANSIENT IO failures (EIO/ETIMEDOUT/
    # stale NFS handle/truncated read — the storage flake taxonomy of
    # resilience/backend.py applied to the input plane). A record that
    # stays broken past the deadline is reclassified as permanent and
    # quarantined. 0 disables retry (first failure classifies directly).
    record_deadline_s: float = 60.0
    record_backoff_base_s: float = 0.05
    record_backoff_max_s: float = 5.0
    # PERMANENTLY corrupt records (bad JPEG, malformed roidb entry) are
    # quarantined — `data` event + <obs dir>/quarantine.jsonl append — and
    # replaced by a deterministic substitute record f(seed, epoch, index)
    # so the epoch stream (and kill->resume parity) stays bit-exact. When
    # more than this fraction of the dataset lands in quarantine the
    # dataset itself is broken: abort loudly (flight-recorder dump)
    # instead of training on a stream of substitutes.
    quarantine_max_fraction: float = 0.01
    # A crashed prefetch worker thread is resurrected in place
    # (`data_worker` event); after this many deaths within one iterator
    # the input plane is declared broken and the run fails hard.
    worker_restart_max: int = 3
    # A blocking next() on the prefetch queue that exceeds this deadline
    # raises DataStallError (classified, flight-dumped, names data-wait
    # as the culprit) instead of hanging forever on dead storage.
    # 0 disables the deadline (wait forever — pre-graftfeed behavior).
    wait_deadline_s: float = 600.0


@dataclass(frozen=True)
class Config:
    network: NetworkConfig = field(default_factory=NetworkConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    test: TestConfig = field(default_factory=TestConfig)
    dataset: DatasetConfig = field(default_factory=DatasetConfig)
    image: ImageConfig = field(default_factory=ImageConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    data: DataConfig = field(default_factory=DataConfig)
    seed: int = 0

    def with_updates(self, **kw) -> "Config":
        return replace(self, **kw)


# ---------------------------------------------------------------------------
# Presets (reference: rcnn/config.py per-network / per-dataset dicts merged by
# generate_config)
# ---------------------------------------------------------------------------

_NETWORK_PRESETS: Mapping[str, Mapping[str, Any]] = {
    "vgg": dict(
        name="vgg",
        feat_channels=512,
        roi_pool_size=7,
        depth=16,
        fixed_param_patterns=("conv1_1", "conv1_2", "conv2_1", "conv2_2"),
    ),
    "resnet50": dict(name="resnet50", depth=50),
    "resnet101": dict(name="resnet101", depth=101),
    # FPN-family presets default proposal_topk="approx": the per-level
    # exact lax.top_k over the stride-4 level's ~123k scores costs
    # ~2.2 ms/img in situ (7% of fwd+bwd, PERF.md r4 roofline) while
    # approx_max_k (recall 0.95) only perturbs MEMBERSHIP at the pre-NMS
    # tail — score order within the kept set is preserved, so NMS
    # semantics are unchanged and the Detectron-lineage recipe is
    # insensitive to the tail. `--set network.proposal_topk=exact`
    # restores bit-deterministic selection (and stays the C4 default).
    "resnet50_fpn": dict(
        name="resnet50_fpn", depth=50, use_fpn=True, roi_pool_size=7,
        anchor_scales=(8,), proposal_topk="approx",
    ),
    "resnet101_fpn": dict(
        name="resnet101_fpn", depth=101, use_fpn=True, roi_pool_size=7,
        anchor_scales=(8,), proposal_topk="approx",
    ),
    "resnet50_fpn_mask": dict(
        name="resnet50_fpn_mask", depth=50, use_fpn=True, roi_pool_size=7,
        anchor_scales=(8,), use_mask=True, proposal_topk="approx",
    ),
    "resnet101_fpn_mask": dict(
        name="resnet101_fpn_mask", depth=101, use_fpn=True, roi_pool_size=7,
        anchor_scales=(8,), use_mask=True, proposal_topk="approx",
    ),
    "vitdet_b": dict(
        name="vitdet_b", use_vit=True, roi_pool_size=7, anchor_scales=(8,),
        vit_dim=768, vit_depth=12, vit_heads=12, vit_window=8,
        norm="group",  # detector-side norms; the ViT itself uses LayerNorm
        proposal_topk="approx",
    ),
    "vitdet_b_mask": dict(
        name="vitdet_b_mask", use_vit=True, roi_pool_size=7,
        anchor_scales=(8,), use_mask=True,
        vit_dim=768, vit_depth=12, vit_heads=12, vit_window=8,
        norm="group", proposal_topk="approx",
    ),
    "detr_r50": dict(name="detr_r50", depth=50, use_detr=True),
}

# Per-network ImageConfig presets. The FPN/Mask configs default to the
# BASELINE-config-3 multi-scale recipe: short side sampled per batch from
# {640, 800}. Buckets are stored landscape-oriented (short, long) in
# stride-32 multiples (exact FPN top-down upsample-and-add shapes); the
# loader transposes them for portrait batches and squares only the rare
# mixed-orientation seam batch (loader.resolve_pad_bucket) — square-only
# covers would waste ~60% of the conv FLOPs on landscape COCO batches.
_IMAGE_PRESETS: Mapping[str, Mapping[str, Any]] = {
    name: dict(
        scales=((640, 1066), (800, 1333)),
        pad_shapes=((672, 1088), (832, 1344)),
        pad_shape=(1344, 1344),
    )
    for name in ("resnet50_fpn", "resnet101_fpn",
                 "resnet50_fpn_mask", "resnet101_fpn_mask")
}

# Per-network TrainConfig presets: the transformer families train with
# AdamW + global-norm clip 0.1 at transformer learning rates (Carion et
# al. §4: AdamW 1e-4, clip 0.1; ViTDet likewise AdamW) — SGD+momentum at
# detector rates barely converges there. The LR schedules are the papers'
# too (DETR: 300 epochs, ÷10 at 200; ViTDet: ~100 epochs, ÷10 at 88/96) —
# inheriting the SGD default lr_step=(7,) would silently decimate the LR
# at epoch 7.
_TRAIN_PRESETS: Mapping[str, Mapping[str, Any]] = {
    "detr_r50": dict(optimizer="adamw", lr=1e-4, clip_gradient=0.1,
                     wd=1e-4, lr_step=(200,), end_epoch=300),
    **{name: dict(optimizer="adamw", lr=1e-4, clip_gradient=0.1,
                  wd=1e-4, lr_step=(88, 96), end_epoch=100)
       for name in ("vitdet_b", "vitdet_b_mask")},
}

VOC_CLASSES = (
    "__background__",
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)

_DATASET_PRESETS: Mapping[str, Mapping[str, Any]] = {
    "PascalVOC": dict(
        name="PascalVOC",
        dataset_path="data/VOCdevkit",
        image_set="2007_trainval",
        test_image_set="2007_test",
        num_classes=21,
        class_names=VOC_CLASSES,
    ),
    "coco": dict(
        name="coco",
        dataset_path="data/coco",
        image_set="train2017",
        test_image_set="val2017",
        num_classes=81,
    ),
    "synthetic": dict(
        name="synthetic",
        dataset_path="",
        image_set="train",
        test_image_set="test",
        num_classes=4,
    ),
}


def generate_config(network: str, dataset: str, **overrides) -> Config:
    """Build a Config from a network preset + dataset preset.

    Mirrors the reference's ``generate_config(network, dataset)``
    (rcnn/config.py) which merges ``network.<net>`` and ``dataset.<ds>``
    dicts into the globals; here it returns a fresh immutable Config.
    """
    if network not in _NETWORK_PRESETS:
        raise KeyError(f"unknown network {network!r}; have {sorted(_NETWORK_PRESETS)}")
    if dataset not in _DATASET_PRESETS:
        raise KeyError(f"unknown dataset {dataset!r}; have {sorted(_DATASET_PRESETS)}")
    cfg = Config(
        network=NetworkConfig(**_NETWORK_PRESETS[network]),
        dataset=DatasetConfig(**_DATASET_PRESETS[dataset]),
        image=ImageConfig(**_IMAGE_PRESETS.get(network, {})),
        train=TrainConfig(**_TRAIN_PRESETS.get(network, {})),
    )
    if overrides:
        # Overriding scales or pad_shape without pad_shapes must not pair
        # with the preset's stale buckets: a pad_shape override would be
        # silently ignored while len(pad_shapes) == len(scales), and a
        # scales override of the same length would keep too-small buckets
        # that overflow mid-epoch. Dropping the preset buckets falls back
        # to the single pad_shape (loader.pad_shape_for).
        if (("image.scales" in overrides or "image.pad_shape" in overrides)
                and "image.pad_shapes" not in overrides):
            overrides = dict(overrides, **{"image.pad_shapes": ()})
        if ("image.pad_shape" in overrides
                and "image.scales" not in overrides):
            # pad_shape-only override: the preset scales may exceed the
            # new canvas (the FPN presets' (800,1333) against a 640-pad
            # would crash pad_image mid-epoch). The canvas IS the intent:
            # train at the pad-sized scale.
            ph, pw = overrides["image.pad_shape"]
            overrides = dict(
                overrides,
                **{"image.scales": ((min(ph, pw), max(ph, pw)),)})
        cfg = _apply_dotted_overrides(cfg, overrides)
    return cfg


def _apply_dotted_overrides(cfg: Config, overrides: Mapping[str, Any]) -> Config:
    """Apply {"train.lr": 0.002, "test.nms_thresh": 0.5}-style overrides."""
    grouped: dict = {}
    for key, value in overrides.items():
        if "." in key:
            section, leaf = key.split(".", 1)
            grouped.setdefault(section, {})[leaf] = value
        else:
            grouped[key] = value
    updates = {}
    for section, value in grouped.items():
        current = getattr(cfg, section)
        if isinstance(value, Mapping) and dataclasses.is_dataclass(current):
            for leaf, leaf_value in value.items():
                # A string landing on a bool field is always a mistake
                # (e.g. a CLI "false" that failed literal parsing would be
                # TRUTHY); fail loudly instead of silently enabling it.
                if isinstance(getattr(current, leaf, None), bool) and isinstance(
                        leaf_value, str):
                    raise ValueError(
                        f"override {section}.{leaf}={leaf_value!r}: field is "
                        f"a bool; pass True/False")
            updates[section] = replace(current, **value)
        else:
            updates[section] = value
    return replace(cfg, **updates)


def parse_cli_overrides(pairs) -> dict:
    """['a.b=1', ...] (the CLI --set flag) → {'a.b': 1}.

    Values parse as python literals; the common CLI bool spellings
    (true/false/yes/no/on/off, any case) map to real bools BEFORE the
    literal fallback so '--set network.tensor_parallel=false' can never
    come through as a truthy string; anything else unparseable stays a
    string (e.g. network.norm=group).

    Caveat: the bool coercion is unconditional (it does not consult the
    target field's type), so a STRING-typed field can never receive the
    literal strings 'true'/'false'/'yes'/'no'/'on'/'off' (or quoted
    variants — quotes survive literal_eval as str only for other values)
    through --set. No current config field has such a value domain; if
    one ever does, route it around --set or rename the value.
    """
    import ast

    out = {}
    for pair in pairs:
        key, sep, raw = pair.partition("=")
        if not sep or not key:
            raise ValueError(f"--set expects KEY=VALUE, got {pair!r}")
        low = raw.strip().lower()
        if low in ("true", "yes", "on"):
            out[key] = True
        elif low in ("false", "no", "off"):
            out[key] = False
        else:
            try:
                out[key] = ast.literal_eval(raw)
            except (ValueError, SyntaxError):
                out[key] = raw
    return out
