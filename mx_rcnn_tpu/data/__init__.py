"""Data pipeline — host-side image IO, batching, dataset (roidb) handling.

Reference layers L3/L4/L7 (SURVEY.md §2): rcnn/io/image.py, rcnn/core/loader.py
(AnchorLoader/TestLoader), rcnn/dataset/*. TPU delta: target assignment
(assign_anchor / sample_rois) moved INTO the jitted step (targets/), so the
host loader only decodes, resizes, pads to static shapes, and prefetches.
"""

from mx_rcnn_tpu.data.image import (
    load_image,
    resize_image,
    transform_image,
    pad_image,
)
from mx_rcnn_tpu.data.loader import AnchorLoader, TestLoader

__all__ = [
    "load_image",
    "resize_image",
    "transform_image",
    "pad_image",
    "AnchorLoader",
    "TestLoader",
]
