"""ctypes bridge to the fused normalize+pad kernels (cc/imgproc.c).

Same pattern as masks/_native.py: built on first use with the system
compiler into cc/build/libimgproc.so, loaded via ctypes (which releases
the GIL around the call — the whole point: the numpy normalize/pad
stages hold the GIL and make loader worker threads scale inversely,
PERF.md r4). Every entry point returns None when the toolchain or .so
is missing, so callers keep their numpy fallback — the native layer is
a pure accelerator, never a dependency.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

from mx_rcnn_tpu.utils.native_build import build_and_load

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "cc", "imgproc.c")
_SO = os.path.join(_REPO, "cc", "build", "libimgproc.so")

_lib = None
_tried = False
_init_lock = threading.Lock()


def _bind(lib):
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    for name, srcp in (("normalize_pad_u8", u8p),
                       ("normalize_pad_f32", f32p),
                       ("normalize_pad_u8_flip", u8p)):
        fn = getattr(lib, name)
        fn.restype = None
        fn.argtypes = [srcp, ctypes.c_long, ctypes.c_long,
                       f32p, ctypes.c_long, ctypes.c_long, f32p, f32p]


def get_lib():
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _init_lock:
        if _lib is None and not _tried:
            _lib = build_and_load(_SRC, _SO, _bind)
            _tried = True
    return _lib


def available() -> bool:
    return get_lib() is not None


def normalize_pad(img: np.ndarray, means, stds,
                  pad_shape, flip: bool = False) -> Optional[np.ndarray]:
    """Fused (img - mean) / std + zero-pad (+ optional x-mirror) in one
    GIL-free pass. img: (h, w, 3) uint8 or float32, C-contiguous.
    Returns (ph, pw, 3) float32, or None when the native layer is
    unavailable (caller falls back to numpy)."""
    lib = get_lib()
    if lib is None:
        return None
    h, w = img.shape[:2]
    ph, pw = pad_shape
    if h > ph or w > pw:
        raise ValueError(f"image {h}x{w} exceeds pad shape {ph}x{pw}")
    mean = np.ascontiguousarray(means, np.float32)
    inv_std = np.ascontiguousarray(
        1.0 / np.asarray(stds, np.float32), np.float32)
    dst = np.empty((ph, pw, 3), np.float32)
    if img.dtype == np.uint8:
        src = np.ascontiguousarray(img)
        fn = lib.normalize_pad_u8_flip if flip else lib.normalize_pad_u8
        fn(src, h, w, dst, ph, pw, mean, inv_std)
        return dst
    if flip:  # f32 source flips rarely (jpeg path flips pre-resize)
        img = img[:, ::-1]
    src = np.ascontiguousarray(img, np.float32)
    lib.normalize_pad_f32(src, h, w, dst, ph, pw, mean, inv_std)
    return dst
