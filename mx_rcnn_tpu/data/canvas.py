"""graftcanvas — host-side whole-batch canvas packing (planner + contract).

Generalizes models/fpn.py::pack_placements (the shelf-packer that fused
the five per-level RPN head convs into one, PERF.md round 5) from pyramid
LEVELS to the BATCH: each training batch's mixed-size images are
shelf-packed into one fixed-shape canvas per data shard, so every step
compiles exactly ONE train-step shape — the orientation x scale pad-bucket
zoo (loader.resolve_pad_bucket: up to 3 shapes per scale) collapses, and
the model pays for canvas pixels instead of bucket pixels (the measured
``pad_waste`` the graftprof counters track).

Exactness contract (the rpn_forward_packed zero-gap argument, one level
up): placement offsets are aligned to the model's max feature stride (so
every downsampled grid lands on exact cells), placements are separated by
at least one aligned gap of zeros, and the backbone re-zeros the gap cells
after every residual block (models/backbones.py masks) — so an image's
activations inside its placement equal the per-image padded forward's
bit-for-bit under frozen-BN (every conv sees zeros beyond the content
boundary, exactly like the bucketed canvas edge's implicit SAME padding).
GroupNorm models are ACCEPTED with a documented approximation: GroupNorm
pools statistics over the whole sample, so a packed plane shares stats
across its images the same way the bucketed path already pools stats over
its zero padding. Attention models (ViTDet) mix tokens across the canvas
inside the ViT encoder (the pyramid is re-masked after the SFP neck);
DETR has no per-image proposal path to thread placements through and is
rejected.

Overflow policy (scale-to-fit): a batch whose content cannot pack into
the fixed canvas is uniformly downscaled by 0.9 steps until it fits —
the canvas shape NEVER changes (one compiled shape is the whole point),
and multi-scale training already randomizes scale, so the rare shrunken
batch is a scale perturbation, not a semantic change. Size the canvas to
the workload (image.canvas_shape) so this stays rare; the derived
default (resolve_canvas) is a conservative never-overflow cover.

Pure numpy/stdlib — runs in loader worker threads.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.logger import logger

#: im_info row layout of a packed batch: [h, w, scale, y0, x0] per image
#: ((h, w) content extent INSIDE the canvas, (y0, x0) its placement
#: offset). Bucketed batches keep the classic 3-column [h, w, scale].
PACKED_INFO_COLS = 5

#: scale-to-fit shrink step and attempt cap (0.9^20 ~ 0.12 — a canvas
#: needing more than that is a config error, not an unlucky batch).
FIT_STEP = 0.9
FIT_MAX_TRIES = 20


def align_up(v: int, a: int) -> int:
    return ((int(v) + a - 1) // a) * a


def canvas_align_for(cfg: Config) -> int:
    """The model family's max feature stride — placement offsets must be
    multiples of it so every downsampled grid is exact (FPN/ViTDet build
    a P2..P6 pyramid, stride 64 at P6; C4/VGG stop at stride 16)."""
    if cfg.network.use_fpn or cfg.network.use_vit:
        return 64
    return 16


def canvas_images_for(cfg: Config) -> int:
    """Images packed per canvas plane (image.canvas_images, defaulting to
    the per-device batch)."""
    return int(cfg.image.canvas_images or cfg.train.batch_images)


class CanvasSpec:
    """Resolved packing geometry: shape, gap/alignment, images per plane."""

    def __init__(self, shape: Tuple[int, int], gap: int, align: int,
                 images: int):
        self.shape = (int(shape[0]), int(shape[1]))
        self.gap = int(gap)
        self.align = int(align)
        self.images = int(images)

    def __repr__(self):
        return (f"CanvasSpec(shape={self.shape}, gap={self.gap}, "
                f"align={self.align}, images={self.images})")


def resolve_canvas(cfg: Config) -> CanvasSpec:
    """image.canvas_* knobs → CanvasSpec, deriving what is unset.

    Derived canvas (canvas_shape=()): a conservative vertical stack of
    `canvas_images` worst-case SHORT-side slots over the widest scale —
    (ipp * align(max_short + gap), align(max_long + gap)). It never
    overflows on landscape batches and holds one max portrait whenever
    ipp * slot >= the long side (true at ipp >= 2 for the COCO recipes);
    tighter canvases — the actual pixel win — are a per-workload
    image.canvas_shape choice with scale-to-fit absorbing the tail."""
    align = int(cfg.image.canvas_gap) or canvas_align_for(cfg)
    gap = align  # one aligned slot row/col of guaranteed zeros
    ipp = canvas_images_for(cfg)
    if cfg.image.canvas_shape:
        ch, cw = cfg.image.canvas_shape
    else:
        max_short = max(s[0] for s in cfg.image.scales)
        max_long = max(s[1] for s in cfg.image.scales)
        ch = ipp * align_up(max_short + gap, align)
        cw = align_up(max_long + gap, align)
        if ch < max_long:
            logger.warning(
                "canvas_pack: derived canvas %dx%d cannot hold a "
                "max-size portrait (long side %d) unscaled — portrait "
                "batches will scale-to-fit. Set image.canvas_shape for "
                "the workload's mix", ch, cw, max_long)
    return CanvasSpec((ch, cw), gap, align, ipp)


def validate_canvas_pack(cfg: Config) -> CanvasSpec:
    """The canvas_pack config contract — raise early, with the real cause
    (cfg-contract family), instead of failing mid-epoch in a worker
    thread or silently training different semantics.

    Norms: "frozen_bn" is the exact case (per-channel affine, re-masked
    gaps — see module docstring). "group" is ACCEPTED: GroupNorm already
    pools its per-sample statistics over the bucketed path's zero
    padding, so pooling over a shared canvas is the same class of
    approximation, and rejecting it would break every from-scratch
    recipe (--from-scratch flips norm to GroupNorm — the known breakage
    this validate must not reintroduce; regression-gated in
    tests/test_canvas.py)."""
    if not cfg.image.canvas_pack:
        raise ValueError("validate_canvas_pack called with "
                         "image.canvas_pack=False")
    if cfg.network.use_detr:
        raise ValueError(
            "image.canvas_pack does not support DETR: set prediction has "
            "no proposal path to thread placement borders through, and "
            "its global encoder attention mixes packed images freely. "
            "Disable canvas_pack for network.use_detr configs")
    if cfg.network.norm not in ("frozen_bn", "group"):
        raise ValueError(
            f"image.canvas_pack: unknown network.norm {cfg.network.norm!r} "
            "— packing is exact for 'frozen_bn' and a documented "
            "approximation for 'group' (canvas-pooled statistics); other "
            "norms have no analyzed packing semantics")
    if cfg.network.norm == "group":
        logger.info(
            "canvas_pack with GroupNorm: per-sample statistics pool over "
            "the shared canvas (same approximation class as the bucketed "
            "path's zero-padding already in the stats); frozen_bn is the "
            "exact case")
    if cfg.network.use_vit:
        logger.info(
            "canvas_pack with ViTDet: the ViT encoder attends across the "
            "canvas (windows/global blocks may span placements); the SFP "
            "pyramid is re-masked and the proposal/ROI path stays "
            "border-exact")
    if not cfg.network.use_fpn and not cfg.network.use_vit \
            and cfg.network.roi_pool_type != "align":
        raise ValueError(
            "image.canvas_pack needs network.roi_pool_type='align': the "
            "quantized max-pool path has no per-placement sample-clamp "
            "window support")
    spec = resolve_canvas(cfg)
    align = canvas_align_for(cfg)
    if spec.align <= 0 or spec.align % align:
        raise ValueError(
            f"image.canvas_gap={cfg.image.canvas_gap} must be a positive "
            f"multiple of the model's max feature stride ({align}) — "
            "placement offsets must land on exact cells of every pyramid "
            "level, with at least one empty cell between placements")
    ch, cw = spec.shape
    if ch % align or cw % align:
        raise ValueError(
            f"image.canvas_shape {spec.shape} must be a multiple of the "
            f"max feature stride ({align}) in both dims")
    if cfg.network.use_vit:
        tile = cfg.network.vit_patch * cfg.network.vit_window
        if ch % tile or cw % tile:
            raise ValueError(
                f"image.canvas_shape {spec.shape} must be a multiple of "
                f"patch*window ({tile}) for the ViT windowed attention")
    if cfg.train.batch_images % spec.images:
        raise ValueError(
            f"image.canvas_images={spec.images} must divide "
            f"train.batch_images={cfg.train.batch_images} (whole planes "
            "per device)")
    # Every scale's SHORT side must fit unscaled in both dims, or every
    # single batch of that scale pays the scale-to-fit shrink — that is
    # a mis-sized canvas, not a tail case.
    for t, _m in cfg.image.scales:
        if t > min(ch, cw):
            raise ValueError(
                f"image.canvas_shape {spec.shape} is smaller than scale "
                f"short side {t} — every batch would scale-to-fit; size "
                "the canvas for the workload")
    return spec


def content_size(height: int, width: int, target: int, max_size: int
                 ) -> Tuple[int, int, float]:
    """(h, w, scale) after the reference resize rule — bit-identical to
    data/image.py::resize_image's arithmetic so planned placements match
    loaded pixels exactly."""
    short, long = min(height, width), max(height, width)
    scale = float(target) / short
    if round(scale * long) > max_size:
        scale = float(max_size) / long
    return int(round(height * scale)), int(round(width * scale)), scale


def plan_plane(sizes: Sequence[Tuple[int, int]], canvas: Tuple[int, int],
               gap: int, align: int
               ) -> Optional[List[Tuple[int, int]]]:
    """Shelf-pack (h, w) rects into one fixed canvas; offsets aligned.

    First-fit-decreasing by height (the pack_placements greedy, with a
    fixed canvas width and an explicit fit check). Returns per-input
    (y0, x0) offsets in INPUT order, or None when the batch does not fit.
    Every offset is a multiple of `align` and any two rects are separated
    by >= gap zeros (slot advance = align_up(extent + gap))."""
    ch, cw = canvas
    order = sorted(range(len(sizes)), key=lambda i: -sizes[i][0])
    out: List[Optional[Tuple[int, int]]] = [None] * len(sizes)
    shelf_y = 0   # top row of the current shelf
    shelf_h = 0   # aligned slot height of the tallest rect on it
    cur_x = 0
    for i in order:
        h, w = sizes[i]
        if h > ch or w > cw:
            return None
        if cur_x > 0 and cur_x + w > cw:  # start a new shelf
            shelf_y += shelf_h
            shelf_h, cur_x = 0, 0
        if shelf_y + h > ch:
            return None
        out[i] = (shelf_y, cur_x)
        shelf_h = max(shelf_h, align_up(h + gap, align))
        cur_x += align_up(w + gap, align)
    return out  # type: ignore[return-value]


def plan_batch(sizes_fn, n_images: int, spec: CanvasSpec
               ) -> Tuple[List[Tuple[int, int, int]], float,
                          List[Tuple[int, int]]]:
    """Place a batch's content rects into fixed-canvas planes.

    sizes_fn(fit) -> per-image (h, w) content sizes at scale-to-fit
    factor `fit` (the loader computes these with the SAME resize
    arithmetic the load path uses, so planned rects match loaded pixels
    exactly). Images group into consecutive planes of spec.images.
    Returns (placements, fit, sizes): placements[i] = (plane, y0, x0),
    fit <= 1.0 the uniform factor actually used (1.0 almost always;
    each shrink step is logged), sizes the planned content sizes at that
    fit. Raises when even the floor factor cannot pack — a mis-sized
    canvas, not an unlucky batch.
    """
    ipp = spec.images
    assert n_images % ipp == 0, (n_images, ipp)
    fit = 1.0
    sizes: List[Tuple[int, int]] = []
    for _ in range(FIT_MAX_TRIES):
        sizes = list(sizes_fn(fit))
        placements: List[Tuple[int, int, int]] = []
        ok = True
        for plane in range(n_images // ipp):
            offs = plan_plane(sizes[plane * ipp:(plane + 1) * ipp],
                              spec.shape, spec.gap, spec.align)
            if offs is None:
                ok = False
                break
            placements.extend((plane, y, x) for y, x in offs)
        if ok:
            if fit < 1.0:
                logger.info(
                    "canvas_pack: batch scaled-to-fit by %.3f (canvas %s, "
                    "%d images/plane) — size image.canvas_shape up if this "
                    "recurs", fit, spec.shape, ipp)
            return placements, fit, sizes
        last_fit = fit
        fit *= FIT_STEP
    raise ValueError(
        f"canvas_pack: batch of {n_images} images (sizes {sizes} at fit "
        f"{last_fit:.3f}, the smallest attempted) cannot pack into canvas "
        f"{spec.shape} — image.canvas_shape is mis-sized for the workload")


def packed_strides(cfg: Config) -> Tuple[int, ...]:
    """Feature strides the placement masks are built at (ops/canvas.py):
    every point the backbone/neck re-zeros gap cells."""
    if cfg.network.use_vit:
        return (4, 8, 16, 32, 64)  # SFP pyramid levels P2..P6
    if cfg.network.use_fpn:
        return (2, 4, 8, 16, 32)   # stem + C2..C5 (+ neck reuse)
    if cfg.network.name == "vgg":
        return (1, 2, 4, 8, 16)
    return (2, 4, 8, 16)           # C4 stem + stages 1-3
