"""Datasets — roidb construction, caching, evaluation.

Reference layer L7 (SURVEY.md §2): rcnn/dataset/ (imdb.py, pascal_voc.py,
pascal_voc_eval.py, coco.py). Plus a synthetic dataset (no reference analog)
so the fully-offline CI can exercise the end-to-end path.
"""

from mx_rcnn_tpu.data.datasets.imdb import IMDB
from mx_rcnn_tpu.data.datasets.pascal_voc import PascalVOC
from mx_rcnn_tpu.data.datasets.coco import COCODataset
from mx_rcnn_tpu.data.datasets.synthetic import SyntheticDataset


def get_dataset(name: str, image_set: str, root_path: str, dataset_path: str,
                **kwargs) -> IMDB:
    """Dataset registry (reference: the eval(dataset)(...) dispatch in
    train_end2end.py / rcnn/utils/load_data.py)."""
    registry = {
        "PascalVOC": PascalVOC,
        "coco": COCODataset,
        "synthetic": SyntheticDataset,
    }
    if name not in registry:
        raise KeyError(f"unknown dataset {name!r}; have {sorted(registry)}")
    return registry[name](image_set, root_path, dataset_path, **kwargs)


def dataset_from_config(ds_cfg, image_set: str = None) -> IMDB:
    """get_dataset driven by a DatasetConfig, including its extra
    ``kwargs`` pairs (e.g. synthetic dataset sizing)."""
    return get_dataset(ds_cfg.name, image_set or ds_cfg.image_set,
                       ds_cfg.root_path, ds_cfg.dataset_path,
                       **dict(ds_cfg.kwargs))


__all__ = ["IMDB", "PascalVOC", "COCODataset", "SyntheticDataset",
           "get_dataset", "dataset_from_config"]
