"""COCO dataset.

Reference: rcnn/dataset/coco.py, which drives the vendored
rcnn/pycocotools COCO api. pycocotools is not installed in this environment
(SURVEY.md §8), so the annotation index is built directly from the
instances_*.json here, and evaluation delegates to the in-repo
evaluation/coco_eval.py reimplementation of COCOeval's bbox protocol.

COCO boxes are (x, y, w, h) EXCLUSIVE; converted on load to the framework's
inclusive (x1, y1, x2, y2) via x2 = x + w − 1 (matching the reference's
coco.py gt load which does x2 = x1 + w - 1 with clipping).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.data.datasets.imdb import IMDB
from mx_rcnn_tpu.logger import logger


class COCODataset(IMDB):
    def __init__(self, image_set: str, root_path: str = "data",
                 dataset_path: str = "data/coco"):
        super().__init__("coco", image_set, root_path, dataset_path)
        self.anno_file = os.path.join(
            dataset_path, "annotations", f"instances_{image_set}.json")
        self._index = None  # lazy

    def gt_roidb(self):
        # The class list lives in the annotation json; make sure it is
        # loaded even when the roidb comes from the pickle cache (otherwise
        # num_classes would be 0 on cache hits).
        self._load_index()
        return super().gt_roidb()

    def _load_index(self):
        if self._index is not None:
            return self._index
        with open(self.anno_file) as f:
            data = json.load(f)
        cats = sorted(data["categories"], key=lambda c: c["id"])
        # contiguous class ids 1..80 (reference: coco.py category mapping)
        self.classes = ("__background__",) + tuple(c["name"] for c in cats)
        self._cat_to_class = {c["id"]: i + 1 for i, c in enumerate(cats)}
        self._class_to_cat = {i + 1: c["id"] for i, c in enumerate(cats)}
        images = {im["id"]: im for im in data["images"]}
        anns_by_image: Dict[int, List] = {}
        for ann in data["annotations"]:
            if ann.get("iscrowd", 0):
                continue  # reference skips crowd boxes for training
            anns_by_image.setdefault(ann["image_id"], []).append(ann)
        self._index = (images, anns_by_image, data)
        self.num_images = len(images)
        return self._index

    def _image_path(self, im: Dict) -> str:
        return os.path.join(self.dataset_path, self.image_set, im["file_name"])

    def _load_gt_roidb(self) -> List[Dict]:
        images, anns_by_image, _ = self._load_index()
        roidb = []
        for im_id in sorted(images):
            im = images[im_id]
            anns = anns_by_image.get(im_id, [])
            boxes, classes, segs = [], [], []
            w, h = im["width"], im["height"]
            for a in anns:
                x, y, bw, bh = a["bbox"]
                x1 = max(0.0, x)
                y1 = max(0.0, y)
                x2 = min(w - 1.0, x + max(0.0, bw - 1))
                y2 = min(h - 1.0, y + max(0.0, bh - 1))
                if a.get("area", 0) > 0 and x2 >= x1 and y2 >= y1:
                    boxes.append([x1, y1, x2, y2])
                    classes.append(self._cat_to_class[a["category_id"]])
                    # Polygon segmentations feed the mask pipeline
                    # (data/loader.py rasterizes box-frame gt masks); non-
                    # polygon (RLE) forms only occur on crowd anns, which
                    # are filtered above.
                    seg = a.get("segmentation")
                    segs.append(seg if isinstance(seg, list) else None)
            roidb.append({
                "index": im_id,
                "image": self._image_path(im),
                "height": h,
                "width": w,
                "boxes": np.asarray(boxes, np.float32).reshape(-1, 4),
                "gt_classes": np.asarray(classes, np.int32),
                "segmentations": segs,
                "flipped": False,
            })
        return roidb

    def results_to_json(self, all_boxes) -> List[Dict]:
        """Detections → COCO results format (reference: coco.py
        _write_coco_results writing detections json; xywh EXCLUSIVE)."""
        images, _, _ = self._load_index()
        image_ids = sorted(images)
        results = []
        for c in range(1, self.num_classes):
            cat_id = self._class_to_cat[c]
            for i, im_id in enumerate(image_ids):
                dets = all_boxes[c][i]
                if dets is None or len(dets) == 0:
                    continue
                for d in np.asarray(dets):
                    results.append({
                        "image_id": int(im_id),
                        "category_id": int(cat_id),
                        "bbox": [float(d[0]), float(d[1]),
                                 float(d[2] - d[0] + 1), float(d[3] - d[1] + 1)],
                        "score": float(d[4]),
                    })
        return results

    def evaluate_detections(self, all_boxes, out_json: str = None, **kwargs):
        """COCO bbox mAP@[.5:.95] via the in-repo COCOeval reimplementation."""
        from mx_rcnn_tpu.evaluation.coco_eval import COCOEval

        results = self.results_to_json(all_boxes)
        if out_json:
            os.makedirs(os.path.dirname(out_json) or ".", exist_ok=True)
            with open(out_json, "w") as f:
                json.dump(results, f)
            logger.info("wrote %d detections to %s", len(results), out_json)
        _, _, data = self._load_index()
        evaluator = COCOEval(data, results)
        stats = evaluator.summarize()
        return stats

    def evaluate_segmentations(self, all_boxes, all_masks,
                               out_json: str = None, **kwargs):
        """Instance-segmentation eval: bbox AND segm COCO metrics.

        all_masks mirrors all_boxes: all_masks[class][image] is a list of
        RLE dicts (mx_rcnn_tpu.masks format) aligned row-for-row with
        all_boxes[class][image]. Reference analog: coco.py's segm results
        path through vendored pycocotools COCOeval(iouType='segm').
        """
        from mx_rcnn_tpu.evaluation.coco_eval import COCOEval

        images, _, data = self._load_index()
        image_ids = sorted(images)
        results = []
        for c in range(1, self.num_classes):
            cat_id = self._class_to_cat[c]
            for i, im_id in enumerate(image_ids):
                dets = all_boxes[c][i]
                rles = all_masks[c][i]
                if dets is None or len(dets) == 0:
                    continue
                for d, rle in zip(np.asarray(dets), rles):
                    counts = rle["counts"]
                    if isinstance(counts, bytes):
                        counts = counts.decode("ascii")
                    results.append({
                        "image_id": int(im_id),
                        "category_id": int(cat_id),
                        "bbox": [float(d[0]), float(d[1]),
                                 float(d[2] - d[0] + 1),
                                 float(d[3] - d[1] + 1)],
                        "score": float(d[4]),
                        "segmentation": {"size": list(rle["size"]),
                                         "counts": counts},
                    })
        if out_json:
            os.makedirs(os.path.dirname(out_json) or ".", exist_ok=True)
            with open(out_json, "w") as f:
                json.dump(results, f)
            logger.info("wrote %d segm results to %s", len(results), out_json)
        box_stats = COCOEval(data, results).summarize()
        segm_stats = COCOEval(data, results, iou_type="segm").summarize()
        out = dict(box_stats)
        out.update({f"segm_{k}": v for k, v in segm_stats.items()})
        out["mAP"] = box_stats["AP"]
        return out
