"""IMDB — image-database ABC with roidb caching and augmentation.

Reference: rcnn/dataset/imdb.py — gt_roidb with pickle cache under
data/cache/, append_flipped_images (x-mirror, doubles the roidb),
proposal-roidb loading/merging for the alternate/Fast paths, and the
evaluate_detections contract.

roidb record schema (all datasets):
  image: str path (or image_data: ndarray for synthetic)
  height, width: int
  boxes: (n, 4) float32 x1,y1,x2,y2
  gt_classes: (n,) int32 (1..C-1; background never appears)
  flipped: bool
"""

from __future__ import annotations

import os
import pickle
import zlib
from typing import Dict, List, Optional

import numpy as np

from mx_rcnn_tpu.logger import logger


def append_flipped_roidb(roidb: List[Dict], name: str = "roidb"
                         ) -> List[Dict]:
    """Double any roidb with flipped copies (flag + bookkeeping only —
    pixels/boxes mirror at load time). Standalone so roidbs that never
    touch a dataset instance (packed shards on a host without the raw
    files) can flip too."""
    flipped = []
    for entry in roidb:
        e = dict(entry)
        e["flipped"] = True
        flipped.append(e)
    logger.info("%s appended flipped images: %d -> %d", name,
                len(roidb), len(roidb) + len(flipped))
    return roidb + flipped


class IMDB:
    def __init__(self, name: str, image_set: str, root_path: str,
                 dataset_path: str):
        self.name = f"{name}_{image_set}"
        self.image_set = image_set
        self.root_path = root_path
        self.dataset_path = dataset_path
        self.classes: tuple = ()
        self.num_images = 0

    @property
    def num_classes(self) -> int:
        return len(self.classes)

    @property
    def cache_path(self) -> str:
        path = os.path.join(self.root_path, "cache")
        os.makedirs(path, exist_ok=True)
        return path

    # -- roidb ------------------------------------------------------------

    # Bump when the roidb record schema changes — a stale pickle from an
    # older schema must be rebuilt, not silently reused (e.g. v2 added
    # 'segmentations', without which mask targets degrade to box masks).
    ROIDB_SCHEMA_VERSION = 2

    def gt_roidb(self) -> List[Dict]:
        """Ground-truth roidb with a pickle cache (reference behavior,
        plus schema versioning the reference lacks, plus a dataset_path
        discriminator the reference also lacks: two datasets sharing a
        split name but living at different paths must not reuse each
        other's cache — found by the r5 on-disk rehearsal, where a
        small-copy dataset silently loaded the full set's 2400-entry
        roidb)."""
        path_tag = ""
        if self.dataset_path:
            digest = zlib.crc32(
                os.path.realpath(self.dataset_path).encode())
            path_tag = f"_{digest:08x}"
        cache_file = os.path.join(
            self.cache_path,
            f"{self.name}{path_tag}_gt_roidb_v"
            f"{self.ROIDB_SCHEMA_VERSION}.pkl")
        if os.path.exists(cache_file):
            with open(cache_file, "rb") as f:
                roidb = pickle.load(f)
            logger.info("%s gt roidb loaded from %s", self.name, cache_file)
            return roidb
        roidb = self._load_gt_roidb()
        with open(cache_file, "wb") as f:
            pickle.dump(roidb, f, pickle.HIGHEST_PROTOCOL)
        logger.info("%s wrote gt roidb to %s", self.name, cache_file)
        return roidb

    def _load_gt_roidb(self) -> List[Dict]:
        raise NotImplementedError

    def append_flipped_images(self, roidb: List[Dict]) -> List[Dict]:
        """Double the roidb with flipped copies. The pixel flip happens at
        load time (data/loader.py); here only the flag + box bookkeeping
        (reference: imdb.py append_flipped_images)."""
        return append_flipped_roidb(roidb, name=self.name)

    # -- proposal roidb (alternate training / Fast R-CNN path) -----------

    def load_rpn_data(self, rpn_file: str) -> List[np.ndarray]:
        """Load per-image proposal arrays saved by generate_proposals
        (reference: imdb.load_rpn_data reading rpn_data/*_rpn.pkl)."""
        with open(rpn_file, "rb") as f:
            return pickle.load(f)

    def rpn_roidb(self, gt_roidb: List[Dict], rpn_file: str) -> List[Dict]:
        """Merge RPN proposals with gt into a Fast-RCNN-trainable roidb
        (reference: imdb.rpn_roidb + merge_roidbs)."""
        return self.proposal_roidb(gt_roidb, self.load_rpn_data(rpn_file))

    def load_proposal_roidb(self, gt_roidb: List[Dict],
                            proposal_file: str) -> List[Dict]:
        """Fast R-CNN path over EXTERNAL (e.g. selective-search) proposals
        (reference: rcnn/utils/load_data.py::load_proposal_roidb over
        rcnn/dataset selective_search pickles). The pickle holds one
        (n, 4) or (n, 5) [x1,y1,x2,y2(,score)] array per image, original
        coordinates, image order matching gt_roidb."""
        with open(proposal_file, "rb") as f:
            boxes_list = pickle.load(f)
        return self.proposal_roidb(gt_roidb, boxes_list)

    def proposal_roidb(self, gt_roidb: List[Dict],
                       boxes_list: List[np.ndarray]) -> List[Dict]:
        """Attach per-image proposal arrays ((n,4) or (n,5); an optional
        trailing score column is dropped) to copies of the gt entries."""
        assert len(boxes_list) == len(gt_roidb), (
            f"proposal count {len(boxes_list)} != roidb {len(gt_roidb)}")
        out = []
        for entry, prop in zip(gt_roidb, boxes_list):
            prop = np.asarray(prop, np.float32)
            if prop.size == 0:
                prop = prop.reshape(0, 4)
            if prop.ndim != 2 or prop.shape[1] not in (4, 5):
                raise ValueError(
                    f"proposal arrays must be (n,4) or (n,5); got {prop.shape}")
            e = dict(entry)
            e["proposals"] = prop[:, :4]
            out.append(e)
        return out

    # -- evaluation -------------------------------------------------------

    def evaluate_detections(self, all_boxes: List[List[np.ndarray]],
                            **kwargs) -> Dict[str, float]:
        """all_boxes[class][image] = (n, 5) [x1,y1,x2,y2,score] in ORIGINAL
        image coordinates. Returns metric dict (e.g. {'mAP': ...})."""
        raise NotImplementedError

    def evaluate_recall(self, roidb: List[Dict],
                        candidate_boxes: Optional[List[np.ndarray]] = None,
                        at=(300, 1000, 2000),
                        iou_thresh: float = 0.5) -> Dict[str, float]:
        """Proposal recall over a roidb — the classic way to grade an RPN
        stage without training the RCNN head (reference:
        rcnn/dataset/imdb.py::evaluate_recall driven by tools/test_rpn.py).

        candidate_boxes[i]: (n, 4|5) [x1,y1,x2,y2(,score)] proposals for
        image i in DESCENDING score order (generate_proposals' dump order;
        a (n,5) array with a score column is re-sorted by it to be safe).
        None → entry['proposals'] from an attached roidb. Returns
        {'recall@N': covered-gt / total-gt at IoU >= iou_thresh using the
        top-N proposals per image} plus 'num_gt'/'num_proposals' counts.

        Matching is GREEDY ONE-TO-ONE exactly as the reference: repeatedly
        take the (proposal, gt) pair with the highest IoU, record it, and
        remove both — a single proposal covering two clustered gts counts
        ONE, not two.
        """
        cutoffs = sorted(int(n) for n in at)
        covered = {n: 0 for n in cutoffs}
        num_gt = 0
        num_props = 0
        for i, entry in enumerate(roidb):
            gt = np.asarray(entry["boxes"], np.float32).reshape(-1, 4)
            if "gt_classes" in entry:
                gt = gt[np.asarray(entry["gt_classes"]) > 0]
            props = (candidate_boxes[i] if candidate_boxes is not None
                     else entry.get("proposals"))
            props = (np.zeros((0, 4), np.float32) if props is None
                     else np.asarray(props, np.float32))
            if props.ndim == 2 and props.shape[1] == 5:
                props = props[np.argsort(-props[:, 4])][:, :4]
            num_gt += len(gt)
            num_props += len(props)
            if not len(gt) or not len(props):
                continue
            from mx_rcnn_tpu.evaluation.voc_eval import _iou_matrix

            iou_full = _iou_matrix(props, gt)  # (P, G), host-side numpy
            for n in cutoffs:
                # Greedy one-to-one: best remaining pair wins, both drop.
                iou = iou_full[:n].copy()
                for _ in range(min(len(gt), iou.shape[0])):
                    p_idx, g_idx = np.unravel_index(iou.argmax(),
                                                    iou.shape)
                    if iou[p_idx, g_idx] < iou_thresh:
                        break
                    covered[n] += 1
                    iou[p_idx, :] = -1
                    iou[:, g_idx] = -1
        out = {f"recall@{n}": (covered[n] / num_gt if num_gt else 0.0)
               for n in cutoffs}
        out["num_gt"] = float(num_gt)
        out["num_proposals"] = float(num_props)
        logger.info(
            "%s proposal recall (IoU>=%.2f): %s", self.name, iou_thresh,
            "  ".join(f"recall@{n}={out[f'recall@{n}']:.4f}"
                      for n in cutoffs))
        return out


def filter_roidb(roidb: List[Dict]) -> List[Dict]:
    """Drop images without valid gt (reference:
    rcnn/utils/load_data.py::filter_roidb)."""
    out = [r for r in roidb if len(r["boxes"]) > 0]
    logger.info("filter_roidb: %d -> %d images", len(roidb), len(out))
    return out


def merge_roidb(roidbs: List[List[Dict]]) -> List[Dict]:
    """Concatenate roidbs from multiple image sets (reference:
    load_data.py::merge_roidb for '07+12'-style sets)."""
    out: List[Dict] = []
    for r in roidbs:
        out.extend(r)
    return out
