"""PASCAL VOC dataset.

Reference: rcnn/dataset/pascal_voc.py — VOCdevkit layout, XML annotation
parsing, imageset lists, comp4 result-file writing, voc_eval per class.
image_set strings are '<year>_<set>' (e.g. '2007_trainval'); the reference's
'07+12' multi-set merging happens above this class
(rcnn/utils/load_data.py::merge_roidb → data/datasets/imdb.merge_roidb).
"""

from __future__ import annotations

import os
import xml.etree.ElementTree as ET
from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.config import VOC_CLASSES
from mx_rcnn_tpu.data.datasets.imdb import IMDB
from mx_rcnn_tpu.evaluation.voc_eval import eval_class
from mx_rcnn_tpu.logger import logger


class PascalVOC(IMDB):
    def __init__(self, image_set: str, root_path: str = "data",
                 dataset_path: str = "data/VOCdevkit"):
        year, sset = image_set.split("_", 1)
        super().__init__(f"voc_{year}", sset, root_path, dataset_path)
        self.year = year
        self.classes = VOC_CLASSES
        self._class_to_ind = {c: i for i, c in enumerate(self.classes)}
        self.data_path = os.path.join(dataset_path, f"VOC{year}")
        self.image_index = self._load_image_index()
        self.num_images = len(self.image_index)

    def _load_image_index(self) -> List[str]:
        path = os.path.join(self.data_path, "ImageSets", "Main",
                            f"{self.image_set}.txt")
        with open(path) as f:
            return [line.strip().split()[0] for line in f if line.strip()]

    def image_path_from_index(self, index: str) -> str:
        return os.path.join(self.data_path, "JPEGImages", f"{index}.jpg")

    def _parse_annotation(self, index: str) -> Dict:
        tree = ET.parse(
            os.path.join(self.data_path, "Annotations", f"{index}.xml"))
        size = tree.find("size")
        width = int(size.find("width").text)
        height = int(size.find("height").text)
        boxes, classes, difficult = [], [], []
        for obj in tree.findall("object"):
            name = obj.find("name").text.lower().strip()
            if name not in self._class_to_ind:
                continue
            diff = obj.find("difficult")
            is_diff = int(diff.text) if diff is not None else 0
            bb = obj.find("bndbox")
            # VOC is 1-indexed; convert to 0-indexed inclusive.
            x1 = float(bb.find("xmin").text) - 1
            y1 = float(bb.find("ymin").text) - 1
            x2 = float(bb.find("xmax").text) - 1
            y2 = float(bb.find("ymax").text) - 1
            boxes.append([x1, y1, x2, y2])
            classes.append(self._class_to_ind[name])
            difficult.append(is_diff)
        boxes = np.asarray(boxes, np.float32).reshape(-1, 4)
        classes = np.asarray(classes, np.int32)
        difficult = np.asarray(difficult, bool)
        # Training uses only non-difficult objects (reference behavior).
        keep = ~difficult
        return {
            "image": self.image_path_from_index(index),
            "height": height,
            "width": width,
            "boxes": boxes[keep],
            "gt_classes": classes[keep],
            "all_boxes": boxes,
            "all_classes": classes,
            "difficult": difficult,
            "flipped": False,
        }

    def _load_gt_roidb(self) -> List[Dict]:
        return [self._parse_annotation(idx) for idx in self.image_index]

    # -- evaluation -------------------------------------------------------

    def write_results(self, all_boxes, out_dir: str):
        """comp4-style per-class result files (reference:
        pascal_voc.py write_pascal_results)."""
        os.makedirs(out_dir, exist_ok=True)
        for c, cls in enumerate(self.classes):
            if cls == "__background__":
                continue
            path = os.path.join(out_dir, f"comp4_det_{self.image_set}_{cls}.txt")
            with open(path, "w") as f:
                for i, index in enumerate(self.image_index):
                    dets = all_boxes[c][i]
                    if dets is None or len(dets) == 0:
                        continue
                    for d in dets:
                        # back to 1-indexed VOC coords
                        f.write(f"{index} {d[4]:.6f} {d[0]+1:.1f} "
                                f"{d[1]+1:.1f} {d[2]+1:.1f} {d[3]+1:.1f}\n")

    def evaluate_detections(self, all_boxes, use_07_metric: bool = None,
                            iou_thresh: float = 0.5, **kwargs):
        """Per-class VOC AP + mAP. 07 metric for year 2007 (reference
        default)."""
        if use_07_metric is None:
            use_07_metric = self.year == "2007"
        annos = {idx: self._parse_annotation(idx) for idx in self.image_index}
        aps = {}
        for c, cls in enumerate(self.classes):
            if cls == "__background__":
                continue
            gt_by_image, diff_by_image, det_by_image = {}, {}, {}
            for i, idx in enumerate(self.image_index):
                a = annos[idx]
                sel = a["all_classes"] == c
                gt_by_image[idx] = a["all_boxes"][sel]
                diff_by_image[idx] = a["difficult"][sel]
                dets = all_boxes[c][i]
                if dets is not None and len(dets):
                    det_by_image[idx] = np.asarray(dets)
            aps[cls] = eval_class(gt_by_image, det_by_image, diff_by_image,
                                  iou_thresh, use_07_metric)
        m = float(np.mean(list(aps.values())))
        for cls, ap in aps.items():
            logger.info("AP for %s = %.4f", cls, ap)
        logger.info("Mean AP = %.4f", m)
        return {"mAP": m, **aps}
