"""Synthetic detection dataset — offline CI stand-in.

No reference analog (the reference assumes downloaded VOC/COCO; this
environment is fully offline, SURVEY.md §8 'Environment facts'). Generates
images with colored axis-aligned rectangles on textured noise; class = color.
Deterministic per (split, index) so roidb caching and eval are stable.
"""

from __future__ import annotations

import zlib
from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.data.datasets.imdb import IMDB

_CLASS_COLORS = np.asarray(
    [
        (0, 0, 0),        # background, unused
        (220, 40, 40),    # class 1: red
        (40, 200, 60),    # class 2: green
        (50, 80, 230),    # class 3: blue
    ],
    np.float32,
)


class SyntheticDataset(IMDB):
    classes_tuple = ("__background__", "red_box", "green_box", "blue_box")

    def __init__(self, image_set: str, root_path: str = "data",
                 dataset_path: str = "", num_images: int = 32,
                 image_size: int = 320, max_objects: int = 4, seed: int = 0,
                 with_masks: bool = False, mask_resolution: int = 56,
                 min_size_frac: int = 8, max_size_frac: int = 2):
        super().__init__("synthetic", image_set, root_path, dataset_path)
        self.classes = self.classes_tuple
        self.num_images = num_images
        self.image_size = image_size
        self.max_objects = max_objects
        # Mask mode draws filled ellipses (so instance masks differ from the
        # boxes) and attaches box-frame gt_masks to every roidb entry.
        self.with_masks = with_masks
        self.mask_resolution = mask_resolution
        # Object side range: [s/min_size_frac, s/max_size_frac). Tests use a
        # narrower, larger range to keep tiny-image training learnable.
        self.min_size_frac = min_size_frac
        self.max_size_frac = max_size_frac
        # crc32, not hash(): str hashing is randomized per process and would
        # break the deterministic-per-(split, index) contract.
        self._seed = seed + (zlib.crc32(image_set.encode()) % 1000)

    def gt_roidb(self) -> List[Dict]:  # no cache — cheap to regenerate
        return self._load_gt_roidb()

    def _gen(self, index: int):
        rs = np.random.RandomState(self._seed * 100003 + index)
        s = self.image_size
        img = rs.uniform(80, 150, (s, s, 3)).astype(np.float32)
        n = rs.randint(1, self.max_objects + 1)
        boxes, classes, gmasks = [], [], []
        for _ in range(n):
            w = rs.randint(s // self.min_size_frac, s // self.max_size_frac)
            h = rs.randint(s // self.min_size_frac, s // self.max_size_frac)
            x1 = rs.randint(0, s - w)
            y1 = rs.randint(0, s - h)
            cls = rs.randint(1, len(self.classes))
            color = _CLASS_COLORS[cls] + rs.uniform(-15, 15, 3)
            if self.with_masks:
                # Filled ellipse inscribed in the box: the instance mask is
                # a strict subset of the box, exercising the mask pipeline.
                yy, xx = np.mgrid[0:h, 0:w]
                ell = (((xx - (w - 1) / 2) / (w / 2)) ** 2
                       + ((yy - (h - 1) / 2) / (h / 2)) ** 2) <= 1.0
                region = img[y1:y1 + h, x1:x1 + w]
                region[ell] = color
                m = self.mask_resolution
                yi = np.minimum((np.arange(m) * h // m), h - 1)
                xi = np.minimum((np.arange(m) * w // m), w - 1)
                gmasks.append(ell[np.ix_(yi, xi)].astype(np.uint8))
            else:
                img[y1:y1 + h, x1:x1 + w] = color
            boxes.append([x1, y1, x1 + w - 1, y1 + h - 1])
            classes.append(cls)
        return (img, np.asarray(boxes, np.float32),
                np.asarray(classes, np.int32),
                np.asarray(gmasks, np.uint8) if gmasks else None)

    def _load_gt_roidb(self) -> List[Dict]:
        roidb = []
        for i in range(self.num_images):
            img, boxes, classes, gmasks = self._gen(i)
            entry = {
                "index": i,
                "image_data": img,
                "height": img.shape[0],
                "width": img.shape[1],
                "boxes": boxes,
                "gt_classes": classes,
                "flipped": False,
            }
            if gmasks is not None:
                entry["gt_masks"] = gmasks
            roidb.append(entry)
        return roidb

    def evaluate_detections(self, all_boxes, iou_thresh: float = 0.5,
                            use_07_metric: bool = False, **kwargs):
        """VOC-protocol mAP over the synthetic gt (reuses eval/voc_eval)."""
        from mx_rcnn_tpu.evaluation.voc_eval import voc_ap_from_arrays

        roidb = self._load_gt_roidb()
        aps = {}
        for c in range(1, self.num_classes):
            gts = {
                r["index"]: r["boxes"][r["gt_classes"] == c] for r in roidb
            }
            dets = all_boxes[c]
            ap = voc_ap_from_arrays(gts, dets, iou_thresh, use_07_metric)
            aps[self.classes[c]] = ap
        m = float(np.mean(list(aps.values()))) if aps else 0.0
        return {"mAP": m, **aps}
