"""graftfeed — input-plane fault tolerance for the batch loaders.

The resilience stack classifies backend failures (graftguard), heals
mid-run device loss (graftheal), coordinates fleets (graftquorum) and
trips on bad numerics (graftpulse) — but until this module the data
plane had none of it: `_PrefetchIterator` re-raised ANY worker exception
in the consumer, so one corrupt JPEG, truncated mmap read or stale NFS
handle killed a multi-hour run, and a hung storage read stalled forever.

This module applies the r5 postmortem treatment (classify, retry under a
deadline, leave an event trail — resilience/backend.py) to per-record
loads:

- **Transient IO** (EIO / ETIMEDOUT / stale handle / truncated read) is
  retried with exponential backoff + jitter under
  ``data.record_deadline_s``; a record still failing past the deadline
  is reclassified as permanent.
- **Permanent corruption** (bad JPEG, malformed roidb entry) is
  **quarantined**: a typed ``data`` event with the record id + reason,
  an append to ``<obs dir>/quarantine.jsonl``, and a substitute record
  chosen as a pure function of ``(seed, epoch, record_index)`` — the
  epoch stream stays deterministic, so the kill→resume bit-exact parity
  gate holds with quarantine active (``--resume auto`` re-applies the
  prior run's quarantine file before replaying the epoch prefix).
- **A broken dataset** (quarantined fraction above
  ``data.quarantine_max_fraction``) aborts loudly instead of silently
  training on a stream of substitutes.

The stall/worker-death halves of graftfeed live in data/loader.py
(``_PrefetchIterator``): a blocking ``next()`` past
``data.wait_deadline_s`` raises :class:`DataStallError`, and a crashed
prefetch worker is resurrected at its queue position up to
``data.worker_restart_max`` times (:class:`DataWorkerError` past it).
All three error classes deliberately do NOT subclass ``RuntimeError``:
graftheal's session loop heals transient RuntimeErrors in-process, and a
broken input plane must reach the crash-telemetry path (``crash`` event
+ flight-recorder dump), not a heal retry — the ``NumericsAnomaly``
precedent (obs/health.py).

Fault injection: chaos keys ``data_corrupt_at=E:I``,
``data_io_error_at=E:I:N``, ``data_hang_at=E:I``,
``data_worker_die_at=K`` (resilience/chaos.py; sites
``data_record_load`` / ``data_worker_loop``). Runbook: OUTAGES.md.
"""

from __future__ import annotations

import errno
import json
import os
import random
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.resilience import chaos

#: OSError errnos that mark a record read as transient (retry): flaky
#: local disk (EIO), network filesystem timeouts (ETIMEDOUT), NFS
#: failover (ESTALE), and the interrupted/again pair.
TRANSIENT_IO_ERRNOS = frozenset({
    errno.EIO, errno.ETIMEDOUT, errno.ESTALE, errno.EAGAIN, errno.EINTR,
})

#: Message substrings that mark a non-OSError record failure as
#: transient — the storage-flake signatures that surface wrapped in
#: ValueError/RuntimeError from decoders and mmap readers.
TRANSIENT_IO_MARKERS = (
    "Input/output error",
    "Stale file handle",
    "timed out",
    "ETIMEDOUT",
    "ESTALE",
    "truncated read",
    "Resource temporarily unavailable",
)


class DataStallError(Exception):
    """A blocking next() on the prefetch queue outlasted
    ``data.wait_deadline_s`` — dead storage / wedged workers. NOT a
    RuntimeError: must escape graftheal to the crash-telemetry path."""


class DataWorkerError(Exception):
    """Prefetch workers died more than ``data.worker_restart_max`` times
    within one iterator — the input plane itself is broken."""


class QuarantineExceededError(Exception):
    """Quarantined fraction crossed ``data.quarantine_max_fraction`` —
    the dataset is broken; training on substitutes would be silent
    garbage. The quarantine.jsonl on disk is the evidence."""


def classify_record_error(exc: BaseException) -> str:
    """'transient' (retry under the record deadline) or 'permanent'
    (quarantine) for one record-load failure — errno first (the honest
    signal), message markers second (wrapped decoder/mmap errors)."""
    if isinstance(exc, OSError) and exc.errno in TRANSIENT_IO_ERRNOS:
        return "transient"
    msg = str(exc)
    return ("transient" if any(m in msg for m in TRANSIENT_IO_MARKERS)
            else "permanent")


class FeedGuard:
    """Per-run input-plane guard: classification + retry + quarantine.

    One instance per fit/eval (tools/train.py builds it next to the
    loader), shared across epochs and across heal-time loader rebuilds —
    the quarantine set is run-scoped state, like the checkpoint prefix.
    Thread-safe: prefetch workers call :meth:`load` concurrently.

    ``quarantine_path`` ("" disables persistence) is
    ``<obs dir>/quarantine.jsonl``; with ``resume=True`` an existing
    file is re-applied at construction so a resumed run substitutes the
    same records the interrupted run did — the bit-exact parity
    contract. ``sleep``/``clock``/``rng`` are injectable for tests.
    """

    def __init__(self, dcfg, n_records: int, seed: int = 0, elog=None,
                 quarantine_path: str = "", resume: bool = False,
                 chaos_spec: Optional[chaos.ChaosSpec] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None):
        self.dcfg = dcfg
        self.n_records = max(1, int(n_records))
        self._seed = int(seed)
        self._elog = elog
        self._path = quarantine_path or ""
        self._spec = chaos_spec if chaos_spec is not None else chaos.from_env()
        self._sleep = sleep
        self._clock = clock
        # Backoff jitter decorrelates workers hammering a recovering
        # mount; pid-seeded like backend.py, order-independent.
        self._rng = rng or random.Random(os.getpid())
        self._epoch = 0
        self._lock = threading.Lock()
        self._quarantined: Dict[int, str] = {}
        self.retry_count = 0
        if resume and self._path and os.path.exists(self._path):
            self._reapply()

    # -- knobs the prefetcher reads ------------------------------------

    @property
    def wait_deadline_s(self) -> float:
        return self.dcfg.wait_deadline_s

    @property
    def worker_restart_max(self) -> int:
        return self.dcfg.worker_restart_max

    @property
    def chaos_spec(self) -> chaos.ChaosSpec:
        return self._spec

    def set_epoch(self, epoch: int):
        """Forwarded by AnchorLoader.set_epoch — the epoch feeds both
        the chaos E:I keys and the deterministic replacement draw."""
        self._epoch = int(epoch)

    @property
    def quarantined_count(self) -> int:
        with self._lock:
            return len(self._quarantined)

    # -- event plumbing (thread-safe: EventLog locks internally) -------

    def _emit(self, **fields):
        if self._elog is not None and self._elog.enabled:
            self._elog.emit("data", **fields)

    def emit_worker_event(self, **fields):
        """``data_worker`` emission hook for the prefetcher's worker
        supervision (data/loader.py) — kept here so the loader needs no
        EventLog plumbing of its own."""
        if self._elog is not None and self._elog.enabled:
            self._elog.emit("data_worker", **fields)

    # -- quarantine ----------------------------------------------------

    def _reapply(self):
        """Re-arm a prior run's quarantine file (--resume auto): the
        replayed epoch prefix must substitute the same records the
        interrupted run did, without re-discovering them. Torn trailing
        lines (SIGKILL mid-append) are a warning, not a crash."""
        from mx_rcnn_tpu.obs.report import load_jsonl_tolerant

        applied = 0
        for rec in load_jsonl_tolerant(self._path, hint="quarantine file"):
            try:
                idx = int(rec["record"])
            except (KeyError, TypeError, ValueError):
                continue  # foreign line — the tolerant read already warned
            if idx not in self._quarantined:
                self._quarantined[idx] = str(rec.get("reason", ""))
                applied += 1
        if applied:
            logger.info(
                "graftfeed: re-applied %d quarantined record(s) from %s",
                applied, self._path)
            self._emit(kind="quarantine_applied", count=applied,
                       path=self._path)

    def _replacement(self, index: int) -> int:
        """The substitute for a quarantined record: a pure function of
        (seed, epoch, record_index) — kill→resume replays the same draw
        — avoiding the current quarantine set (identical at equivalent
        stream positions in both runs, because resume re-applies the
        jsonl before replaying)."""
        rng = np.random.RandomState(
            ((self._seed * 1_000_003 + self._epoch) * 1_000_033
             + index) % (2 ** 32))
        with self._lock:
            bad = set(self._quarantined)
        bad.add(index)
        for _ in range(64):
            j = int(rng.randint(self.n_records))
            if j not in bad:
                return j
        for j in range(self.n_records):  # nearly everything quarantined
            if j not in bad:
                return j
        raise QuarantineExceededError(
            f"every record is quarantined ({len(bad)}/{self.n_records}) — "
            "no replacement exists; the dataset is broken "
            f"(evidence: {self._path or 'quarantine persistence disabled'})")

    def _quarantine(self, index: int, exc: BaseException) -> int:
        """Quarantine ``index``: record it, persist it, emit the event,
        enforce the cap, and return the deterministic replacement."""
        reason = f"{type(exc).__name__}: {str(exc)[:300]}"
        with self._lock:
            fresh = index not in self._quarantined
            if fresh:
                self._quarantined[index] = reason
            count = len(self._quarantined)
        replacement = self._replacement(index)
        if fresh:
            logger.warning(
                "graftfeed: quarantined record %d (epoch %d): %s — "
                "substituting record %d", index, self._epoch, reason,
                replacement)
            self._persist(index, reason, replacement)
            self._emit(kind="quarantine", record=index, epoch=self._epoch,
                       reason=reason, replacement=replacement,
                       quarantined=count, total=self.n_records)
        cap = self.dcfg.quarantine_max_fraction
        if count / self.n_records > cap:
            # Evidence is already on disk (persist above) — abort loudly;
            # the crash path dumps the flight recorder.
            self._emit(kind="quarantine_cap", quarantined=count,
                       total=self.n_records, cap=cap,
                       path=self._path)
            raise QuarantineExceededError(
                f"{count}/{self.n_records} records quarantined "
                f"({count / self.n_records:.1%} > "
                f"data.quarantine_max_fraction={cap}) — the dataset is "
                f"broken, refusing to train on substitutes "
                f"(see {self._path or 'the data events'})")
        return replacement

    def _persist(self, index: int, reason: str, replacement: int):
        if not self._path:
            return
        line = json.dumps({
            "record": index, "epoch": self._epoch, "reason": reason,
            "replacement": replacement, "t_wall": time.time(),
        })
        with self._lock:
            try:
                with open(self._path, "a", encoding="utf-8") as fh:
                    fh.write(line + "\n")
                    fh.flush()
            except OSError as io_exc:
                # The quarantine still holds in memory; losing the file
                # only costs resume re-discovery.
                logger.warning(
                    "graftfeed: could not append to %s: %s",
                    self._path, io_exc)

    # -- the guarded load ----------------------------------------------

    def resolve(self, index: int) -> int:
        """Pre-load substitution: a record already known quarantined (a
        prior epoch, or a resumed run's re-applied file) is replaced
        WITHOUT re-attempting its load — a rotten record costs one
        discovery, not one IO error per epoch."""
        index = int(index)  # loaders hand over numpy ints; keep the
        # quarantine set (and the persisted jsonl) in plain-int space
        with self._lock:
            known = index in self._quarantined
        return self._replacement(index) if known else index

    def load(self, load_fn: Callable[[int], object], index: int,
             cancel: Optional[Callable[[], bool]] = None) -> Tuple[object, int]:
        """Load record ``index`` via ``load_fn(i)``, riding transient IO
        flakes and quarantining permanent corruption (substituting
        deterministically, chaining if the substitute is rotten too).
        Returns ``(result, actual_index)``. ``cancel`` is the
        prefetcher's stop predicate, threaded into the hang injection so
        an abandoned worker releases. Raises QuarantineExceededError
        past the cap; with ``data.record_deadline_s == 0`` a transient
        failure propagates raw (retry disabled — pre-graftfeed
        behavior)."""
        i = self.resolve(index)
        while True:
            try:
                return self._attempt(load_fn, i, cancel), i
            except QuarantineExceededError:
                raise
            except BaseException as exc:  # noqa: BLE001  # graftlint: disable=broad-except — classified: only give-up errors escape _attempt, and each is quarantined here, not swallowed
                if (self.dcfg.record_deadline_s <= 0
                        and classify_record_error(exc) == "transient"):
                    raise  # retry disabled: the raw IO error stays loud
                i = self._quarantine(i, exc)

    def _attempt(self, load_fn: Callable[[int], object], i: int,
                 cancel: Optional[Callable[[], bool]]):
        """One record's retry loop — the backend.py shape: transient
        failures back off exponentially (jittered) under
        ``data.record_deadline_s``; permanent ones raise immediately;
        a transient that outlasts the deadline raises too (the caller
        reclassifies it as permanent and quarantines)."""
        d = self.dcfg
        spec = self._spec
        start = self._clock()
        deadline = start + max(0.0, d.record_deadline_s)
        delay = max(0.001, d.record_backoff_base_s)
        attempt = 0
        while True:
            attempt += 1
            try:
                if spec.active:
                    spec.maybe_die("data_record_load")
                    spec.maybe_data_corrupt(self._epoch, i)
                    spec.maybe_data_io_error(self._epoch, i)
                    spec.maybe_data_hang(self._epoch, i, cancel)
                result = load_fn(i)
            except BaseException as exc:  # noqa: BLE001  # graftlint: disable=broad-except — classified transient-vs-permanent and re-raised, not swallowed
                if classify_record_error(exc) == "permanent":
                    raise
                waited = self._clock() - start
                remaining = deadline - self._clock()
                if d.record_deadline_s <= 0:
                    raise  # retry disabled: propagate the raw IO error
                if remaining <= 0:
                    raise OSError(
                        errno.EIO,
                        f"record {i} still transiently failing after "
                        f"{attempt} attempts / {waited:.1f}s (deadline "
                        f"data.record_deadline_s="
                        f"{d.record_deadline_s:.0f}s); last error: {exc}"
                    ) from exc
                pause = min(delay, d.record_backoff_max_s)
                pause *= 1.0 + 0.25 * self._rng.random()
                pause = min(pause, remaining)
                with self._lock:
                    self.retry_count += 1
                self._emit(kind="retry", record=i, epoch=self._epoch,
                           attempt=attempt, sleep_s=round(pause, 3),
                           error=str(exc)[:200])
                logger.warning(
                    "graftfeed: transient IO on record %d (attempt %d, "
                    "waited %.1fs): %s — retrying in %.2fs", i, attempt,
                    waited, exc, pause)
                self._sleep(pause)
                delay = min(delay * 2.0, d.record_backoff_max_s)
            else:
                return result
