"""Host image IO and geometry.

Reference: rcnn/io/image.py — cv2 BGR load, `resize` (target short side, max
long side), `transform` (mean-subtract, HWC→CHW), `transform_inverse`,
`tensor_vstack` pad-and-stack.

TPU deltas: images stay HWC (NHWC is the TPU layout), RGB order, and every
batch is padded to ONE static shape (config.image.pad_shape) instead of the
reference's per-batch max-shape padding — that is what makes the whole train
step a single compiled program.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

try:  # cv2 when present (fast JPEG decode), PIL fallback.
    import cv2

    _HAS_CV2 = True
except Exception:  # pragma: no cover
    _HAS_CV2 = False

try:
    from PIL import Image

    _HAS_PIL = True
except Exception:  # pragma: no cover
    _HAS_PIL = False


def load_image(path: str) -> np.ndarray:
    """Load an image file as RGB float32 HWC."""
    if _HAS_CV2:
        img = cv2.imread(path, cv2.IMREAD_COLOR)
        if img is None:
            raise FileNotFoundError(path)
        return img[:, :, ::-1].astype(np.float32)  # BGR→RGB
    if _HAS_PIL:
        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"), dtype=np.float32)
    raise RuntimeError("neither cv2 nor PIL available")


def resize_image(
    img: np.ndarray, target_size: int, max_size: int
) -> Tuple[np.ndarray, float]:
    """Scale so the short side is target_size, capped so the long side
    <= max_size (reference: rcnn/io/image.py::resize)."""
    h, w = img.shape[:2]
    short, long = min(h, w), max(h, w)
    scale = float(target_size) / short
    if round(scale * long) > max_size:
        scale = float(max_size) / long
    nh, nw = int(round(h * scale)), int(round(w * scale))
    if _HAS_CV2:
        out = cv2.resize(img, (nw, nh), interpolation=cv2.INTER_LINEAR)
    else:
        out = np.asarray(
            Image.fromarray(img.astype(np.uint8)).resize((nw, nh), Image.BILINEAR),
            dtype=np.float32,
        )
    return out.astype(np.float32), scale


def transform_image(img: np.ndarray, pixel_means: Sequence[float],
                    pixel_stds: Sequence[float] = (1.0, 1.0, 1.0)) -> np.ndarray:
    """Mean-subtract (RGB). Stays HWC (reference transposes to CHW)."""
    return (img - np.asarray(pixel_means, np.float32)) / np.asarray(
        pixel_stds, np.float32)


def transform_inverse(img: np.ndarray, pixel_means: Sequence[float],
                      pixel_stds: Sequence[float] = (1.0, 1.0, 1.0)) -> np.ndarray:
    """Undo transform_image for visualization (reference: transform_inverse)."""
    out = img * np.asarray(pixel_stds, np.float32) + np.asarray(
        pixel_means, np.float32)
    return np.clip(out, 0, 255).astype(np.uint8)


def pad_image(img: np.ndarray, pad_shape: Tuple[int, int]) -> np.ndarray:
    """Zero-pad HWC image to the static (H, W) canvas (bottom/right)."""
    ph, pw = pad_shape
    h, w = img.shape[:2]
    if h > ph or w > pw:
        raise ValueError(f"image {h}x{w} exceeds pad shape {ph}x{pw}")
    out = np.zeros((ph, pw, img.shape[2]), img.dtype)
    out[:h, :w] = img
    return out


def flip_image_and_boxes(img: np.ndarray, boxes: np.ndarray):
    """Horizontal flip of image + boxes (reference: append_flipped_images'
    box mirror — x1' = W-1-x2, x2' = W-1-x1)."""
    w = img.shape[1]
    flipped = img[:, ::-1].copy()
    out = boxes.copy()
    if boxes.size:
        out[:, 0] = w - 1 - boxes[:, 2]
        out[:, 2] = w - 1 - boxes[:, 0]
    return flipped, out
