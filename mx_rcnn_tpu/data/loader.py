"""Batch loaders with background prefetch.

Reference: rcnn/core/loader.py — AnchorLoader (training DataIter: shuffle
with aspect-ratio grouping, load+resize, host-side assign_anchor) and
TestLoader (batch-1 inference iterator).

TPU deltas:
- anchor/ROI target assignment moved on-device (targets/), so AnchorLoader
  only yields images + padded gt boxes;
- every batch has ONE static shape (config.image.pad_shape + max_gt_boxes);
- a worker-thread pool decodes/resizes ahead of the device (the reference
  overlaps only via MXNet's PrefetchingIter when wired, SURVEY.md §4.1 'hot
  loops'). Thread scaling is INVERSE beyond ~2 workers (GIL contention on
  the numpy normalize/pad stages — measured 71.8 img/s at 1 worker vs
  52.3 at 8, flagship shapes; PERF.md r4), so the default is 2; the
  packed shard format (data/packed.py) is the throughput path;
- aspect grouping survives as a perf knob (groups portrait/landscape so the
  short-side resize wastes less canvas), not a correctness feature.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.data.feedguard import DataStallError, DataWorkerError
from mx_rcnn_tpu.data.image import (
    flip_image_and_boxes,
    load_image,
    pad_image,
    resize_image,
    transform_image,
)


def pad_shape_for(cfg: Config, scale_idx: int) -> tuple:
    """The static pad bucket for scale `scale_idx`: image.pad_shapes when
    present (must then match image.scales entry-for-entry), else the
    single image.pad_shape.

    An EMPTY pad_shapes is the documented fallback path (generate_config
    empties it when scales/pad_shape are overridden alone). A NON-empty
    length mismatch is the stale-pair trap — scales overridden next to
    leftover buckets would silently train under-/over-padded — and is a
    loud config error (cfg-contract family), not a silent fallback.

    A pad_shapes entry is stored LANDSCAPE-oriented ((H, W), H <= W);
    resolve_pad_bucket orients it per batch."""
    n = len(cfg.image.pad_shapes)
    if n and n != len(cfg.image.scales):
        raise ValueError(
            f"image.pad_shapes has {n} entries but image.scales has "
            f"{len(cfg.image.scales)} — the lists pair entry-for-entry. "
            "Override them together, or set image.pad_shapes=() to fall "
            "back to the single image.pad_shape")
    if n:
        return tuple(cfg.image.pad_shapes[scale_idx])
    return tuple(cfg.image.pad_shape)


def resolve_pad_bucket(cfg: Config, scale_idx: int,
                       landscape_flags: Sequence[bool]) -> tuple:
    """Orientation-aware bucket for one batch.

    Square-covering both orientations pads the dominant (landscape COCO)
    batches to ~1.6x their needed pixel area — measurable MFU on the conv
    hot path. With aspect grouping, batches are orientation-pure except at
    the group seam, so: all-landscape → (H, W) as stored; all-portrait →
    transposed; mixed (the rare seam batch) → the square cover. At most 3
    static shapes per scale, compiled once each."""
    h, w = pad_shape_for(cfg, scale_idx)
    h, w = min(h, w), max(h, w)  # normalize to landscape orientation
    if all(landscape_flags):
        return (h, w)
    if not any(landscape_flags):
        return (w, h)
    return (w, w)


def _load_roidb_entry(entry: Dict, cfg: Config, scale_idx: int = 0,
                      pad: Optional[tuple] = None):
    """roidb record → (padded image f32 HWC, im_info, boxes, classes) at the
    chosen training scale. Handles the `flipped` flag the imdb sets.

    Packed entries (data/packed.py shards) take the mmap fast path: the
    decode+resize already happened at pack time."""
    if "packed" in entry:
        from mx_rcnn_tpu.data.packed import load_packed_entry

        return load_packed_entry(entry, cfg, scale_idx, pad)
    if "image_data" in entry:  # synthetic datasets embed pixels directly
        img = entry["image_data"].astype(np.float32)
    else:
        img = load_image(entry["image"])
    boxes = entry["boxes"].astype(np.float32).copy()
    if entry.get("flipped"):
        img, boxes = flip_image_and_boxes(img, boxes)
    target, max_size = cfg.image.scales[scale_idx]
    img, scale = resize_image(img, target, max_size)
    boxes *= scale
    h, w = img.shape[:2]
    pad = pad if pad is not None else pad_shape_for(cfg, scale_idx)
    # Fused GIL-free normalize+pad (cc/imgproc.c); numpy fallback.
    from mx_rcnn_tpu.data._native_img import normalize_pad

    fused = normalize_pad(np.ascontiguousarray(img, np.float32),
                          cfg.image.pixel_means, cfg.image.pixel_stds, pad)
    if fused is not None:
        img = fused
    else:
        img = pad_image(
            transform_image(img, cfg.image.pixel_means,
                            cfg.image.pixel_stds), pad)
    im_info = np.asarray([h, w, scale], np.float32)
    return img, im_info, boxes, entry["gt_classes"].astype(np.int32)


def _load_roidb_content(entry: Dict, cfg: Config, scale_idx: int,
                        fit: float = 1.0):
    """roidb record → (normalized UNPADDED image, im_info [h, w, scale],
    boxes, classes) at the drawn scale × the scale-to-fit factor — the
    graftcanvas packed path's load: the batch assembler places the raw
    content into a shared canvas instead of padding per image.

    Packed entries take the mmap fast path (data/packed.py
    load_packed_content): the stored content slice feeds the placement
    directly; only a fit < 1 batch pays a second resample."""
    if "packed" in entry:
        from mx_rcnn_tpu.data.packed import load_packed_content

        return load_packed_content(entry, cfg, scale_idx, fit)
    if "image_data" in entry:
        img = entry["image_data"].astype(np.float32)
    else:
        img = load_image(entry["image"])
    boxes = entry["boxes"].astype(np.float32).copy()
    if entry.get("flipped"):
        img, boxes = flip_image_and_boxes(img, boxes)
    target, max_size = cfg.image.scales[scale_idx]
    if fit < 1.0:
        target = max(1, int(round(target * fit)))
        max_size = max(1, int(round(max_size * fit)))
    img, scale = resize_image(img, target, max_size)
    boxes *= scale
    h, w = img.shape[:2]
    # Fused GIL-free normalize (cc/imgproc.c) with pad == content dims
    # (a no-op pad keeps the one-pass kernel); numpy fallback.
    from mx_rcnn_tpu.data._native_img import normalize_pad

    fused = normalize_pad(np.ascontiguousarray(img, np.float32),
                          cfg.image.pixel_means, cfg.image.pixel_stds,
                          (h, w))
    img = (fused if fused is not None else
           transform_image(img, cfg.image.pixel_means,
                           cfg.image.pixel_stds))
    im_info = np.asarray([h, w, scale], np.float32)
    return img, im_info, boxes, entry["gt_classes"].astype(np.int32)


def _pad_gt(boxes: np.ndarray, classes: np.ndarray, max_gt: int):
    g = min(len(boxes), max_gt)
    out_b = np.zeros((max_gt, 4), np.float32)
    out_c = np.zeros((max_gt,), np.int32)
    out_v = np.zeros((max_gt,), bool)
    out_b[:g] = boxes[:g]
    out_c[:g] = classes[:g]
    out_v[:g] = True
    return out_b, out_c, out_v


def _entry_gt_masks(entry: Dict, m: int, max_gt: int) -> np.ndarray:
    """Box-frame (max_gt, m, m) instance masks for one roidb entry.

    Sources, in priority order: a precomputed entry["gt_masks"] (G, m', m')
    array (synthetic dataset / caches; nearest-resampled if m' != m), or
    entry["segmentations"] polygon lists rasterized against entry["boxes"]
    (COCO). Missing masks default to all-ones (box == mask). Horizontal flip
    (entry["flipped"]) mirrors the box-frame mask content — the box coords
    were already mirrored by the imdb."""
    from mx_rcnn_tpu import masks as _masks

    boxes = entry["boxes"]
    g = min(len(boxes), max_gt)
    out = np.zeros((max_gt, m, m), np.uint8)
    pre = entry.get("gt_masks")
    segs = entry.get("segmentations")
    for i in range(g):
        if pre is not None:
            mm = pre[i]
            if mm.shape != (m, m):
                yi = (np.arange(m) * mm.shape[0] // m)
                xi = (np.arange(m) * mm.shape[1] // m)
                mm = mm[np.ix_(yi, xi)]
            out[i] = mm.astype(np.uint8)
        elif segs is not None and segs[i]:
            # roidb boxes and polygons are both stored unflipped (the loader
            # mirrors at load time), so they line up directly; the content
            # mirror below handles the flipped copies.
            out[i] = _masks.poly_box_frame_mask(segs[i], boxes[i], m)
        else:
            out[i] = 1
    if entry.get("flipped"):
        out = out[:, :, ::-1]
    return out


class _PrefetchIterator:
    """Thread-pool prefetcher: indices → assembled batches, `depth` ahead.

    Backpressure: workers acquire a slot semaphore (depth total) before
    building a batch; the consumer releases it on yield — so at most `depth`
    batches are buffered. Worker exceptions are captured and re-raised in the
    consumer at that batch position (a dead loader must fail loudly, not
    hang the train loop).

    Lifecycle: workers are daemon threads (an abandoned iterator can never
    wedge interpreter exit), but `close()` is the REAL shutdown — it stops
    the pool, drains the buffered results, and JOINS every worker, so a
    disposed iterator leaves no thread alive (the epoch-end contract
    tools/train.py relies on; tested in tests/test_datasets.py).

    graftfeed (``guard`` — a data/feedguard.py FeedGuard): the consumer
    supervises the pool while it waits — a worker thread that died
    without a clean exit has its claimed queue position requeued and a
    replacement spawned (``data_worker`` event; DataWorkerError past
    ``data.worker_restart_max`` deaths) — and a blocking wait that
    outlasts ``data.wait_deadline_s`` raises DataStallError instead of
    hanging on dead storage. Without a guard both behaviors are off
    (wait forever, die with the worker) — the pre-graftfeed contract.
    """

    _ids = iter(range(1_000_000_000))

    def __init__(self, make_batch, batch_indices: Sequence, depth: int = 4,
                 workers: int = 4, guard=None):
        self._make = make_batch
        self._indices = list(batch_indices)
        self._slots = threading.Semaphore(max(1, depth))
        self._threads: List[threading.Thread] = []
        self._next = 0
        self._lock = threading.Lock()
        self._emitted = {}
        self._emit_cond = threading.Condition()
        self._stop = threading.Event()
        self._guard = guard
        self._claims: Dict[str, int] = {}   # thread name -> claimed pos
        self._requeue: List[int] = []       # positions lost to dead workers
        self._done: set = set()             # names that exited CLEANLY
        self._deaths = 0
        self._worker_fail: Optional[BaseException] = None
        self._closed = False
        pool = next(self._ids)
        for i in range(max(1, workers)):
            t = threading.Thread(target=self._worker, args=(i,), daemon=True,
                                 name=f"loader-worker-{pool}-{i}")
            t.start()
            self._threads.append(t)

    def _worker(self, widx: int):
        name = threading.current_thread().name
        spec = self._guard.chaos_spec if self._guard is not None else None
        while not self._stop.is_set():
            if not self._slots.acquire(timeout=0.1):
                continue  # re-check stop flag
            with self._lock:
                if self._requeue:  # a dead sibling's lost claim first
                    pos = self._requeue.pop(0)
                elif self._next < len(self._indices):
                    pos = self._next
                    self._next += 1
                else:
                    self._slots.release()
                    self._done.add(name)
                    return
                self._claims[name] = pos
            if spec is not None and spec.active:
                spec.maybe_die("data_worker_loop")
                if spec.maybe_worker_die(widx):
                    # Abrupt chaos death: claim kept, slot kept, no
                    # result — what a segfaulting decoder leaves behind;
                    # consumer-side supervision must requeue + resurrect.
                    return
            try:
                result = ("ok", self._make(self._indices[pos]))
            except BaseException as exc:  # noqa: BLE001  # graftlint: disable=broad-except — captured and re-raised in the consumer, not swallowed
                result = ("err", exc)
            with self._lock:
                self._claims.pop(name, None)
            with self._emit_cond:
                # Preserve order: the consumer pops positions sequentially.
                self._emitted[pos] = result
                self._emit_cond.notify_all()
        with self._lock:
            self._done.add(name)

    def _supervise(self):
        """Consumer-side worker supervision (runs between waits): a
        thread that died without a clean exit gets its claimed position
        requeued and — restart budget permitting — a replacement thread
        spawned; past ``data.worker_restart_max`` deaths the pool is
        declared broken (the consumer raises DataWorkerError)."""
        dead = [t for t in self._threads if not t.is_alive()]
        for t in dead:
            self._threads.remove(t)
            with self._lock:
                clean = t.name in self._done
                pos = self._claims.pop(t.name, None)
                if pos is not None:
                    self._requeue.append(pos)
            if clean:
                continue
            self._deaths += 1
            guard = self._guard
            limit = guard.worker_restart_max if guard is not None else 0
            resurrect = guard is not None and self._deaths <= limit
            logger.warning(
                "loader worker %s died (death %d/%d)%s%s", t.name,
                self._deaths, limit,
                f", requeued position {pos}" if pos is not None else "",
                " — resurrecting" if resurrect
                else " — restart budget spent")
            if guard is not None:
                guard.emit_worker_event(
                    worker=t.name, deaths=self._deaths, restart_max=limit,
                    requeued=pos if pos is not None else -1,
                    resurrected=resurrect)
            if not resurrect:
                self._worker_fail = DataWorkerError(
                    f"{self._deaths} prefetch worker death(s) exceed "
                    f"data.worker_restart_max={limit} — the input plane "
                    "itself is broken (decoder/native crash loop); last "
                    f"casualty: {t.name}")
                return
            if pos is not None:
                # The dead worker still held its backpressure slot —
                # hand it back or the pool deadlocks at depth exhaustion.
                self._slots.release()
            r = threading.Thread(target=self._worker, args=(-1,),
                                 daemon=True,
                                 name=f"{t.name}-r{self._deaths}")
            r.start()
            self._threads.append(r)

    def __iter__(self):
        deadline_s = (self._guard.wait_deadline_s
                      if self._guard is not None else 0.0)
        for pos in range(len(self._indices)):
            t0 = time.monotonic()
            while True:
                with self._emit_cond:
                    if pos in self._emitted:
                        result = self._emitted.pop(pos)
                        break
                    if self._stop.is_set():
                        result = None
                        break
                    self._emit_cond.wait(timeout=0.1)
                self._supervise()
                if self._worker_fail is not None:
                    self._stop.set()
                    raise self._worker_fail
                if deadline_s and time.monotonic() - t0 > deadline_s:
                    self._stop.set()
                    raise DataStallError(
                        f"no batch arrived at queue position {pos} within "
                        f"data.wait_deadline_s={deadline_s:.0f}s "
                        f"({len(self._threads)} worker(s) alive, "
                        f"{self._deaths} death(s)) — storage is stuck or "
                        "the input plane is wedged")
            if result is None:
                return
            kind, payload = result
            self._slots.release()
            if kind == "err":
                self._stop.set()
                raise payload
            yield payload

    def close(self):
        """Stop, drain, and JOIN the pool. Idempotent (a second close —
        or closing after a worker already crashed — is a no-op/skip, not
        a block on a thread that will never drain). Workers poll the
        stop flag every 0.1 s while waiting for a slot and exit after at
        most one in-flight batch build, so the join is bounded by one
        batch's assembly time; chaos-hung loads poll the same flag."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        for t in self._threads:
            if t.is_alive():
                t.join(timeout=30.0)
                if t.is_alive():
                    # a worker wedged inside make_batch (>30 s) breaks
                    # the no-survivor contract — say so, don't hide it
                    logger.warning(
                        "loader worker %s did not join within 30s; "
                        "leaking a daemon thread", t.name)
        with self._emit_cond:
            self._emitted.clear()
            self._emit_cond.notify_all()


class _CloseableLoader:
    """Shared shutdown surface for the batch loaders: tracks every live
    prefetcher (overlapping iterations over the same loader each get
    their own pool), so `close()` (or `with loader: ...`) joins all
    worker threads even when an epoch was abandoned mid-stream.
    Exhausting an iterator closes its prefetcher automatically; close()
    is the explicit hook for early exits (tools/train.py epoch end).

    Also hosts the graftprof pad-waste counters: batch assembly calls
    ``_note_pad(real_px, canvas_px)`` (from worker threads — locked), so
    ``pad_waste_stats()`` reports what fraction of every canvas pixel
    the run paid for padding — the measured baseline of the ROADMAP's
    canvas-packing lever. Counters are cumulative over the loader's
    lifetime (fit_detector folds them into each epoch event)."""

    _active: Tuple[_PrefetchIterator, ...] = ()
    #: shared class-level lock — _note_pad is called from prefetch WORKER
    #: threads, which start before any per-instance init could run; a
    #: lazily-created instance lock would race its own creation.
    #: Contention is a few batches/sec across all loaders — negligible.
    _pad_lock: threading.Lock = threading.Lock()
    _pad_real_px = 0
    _pad_canvas_px = 0
    _pad_batches = 0

    def _note_pad(self, real_px: float, canvas_px: float):
        with self._pad_lock:
            self._pad_real_px += int(real_px)
            self._pad_canvas_px += int(canvas_px)
            self._pad_batches += 1

    def pad_waste_stats(self) -> Optional[Dict[str, float]]:
        """Cumulative padding accounting, or None before the first
        batch. ``pad_waste`` = 1 − real/canvas pixels."""
        if not self._pad_canvas_px:
            return None
        return {
            "real_px": self._pad_real_px,
            "canvas_px": self._pad_canvas_px,
            "batches": self._pad_batches,
            "pad_waste": round(
                1.0 - self._pad_real_px / self._pad_canvas_px, 4),
        }

    #: graftfeed guard (data/feedguard.py FeedGuard) — None keeps every
    #: pre-graftfeed behavior (no retry, no quarantine, wait forever).
    _guard = None

    def _feed_cancel(self) -> bool:
        """Stop predicate threaded into the guard's cancel-aware hooks
        (chaos hang injection): True once any of this loader's live
        prefetchers has been stopped — a hung worker must release when
        the consumer gives up (DataStallError) or the loader closes."""
        return any(p._stop.is_set() for p in self._active)

    def _guarded(self, load_one, i: int):
        """Route one record load through graftfeed when armed: classified
        transient-IO retry under data.record_deadline_s, quarantine +
        deterministic substitution for permanent corruption. Returns
        ``(result, actual_index)`` — the index differs from ``i`` when a
        quarantine substituted, and per-entry side lookups (gt masks)
        must follow it."""
        if self._guard is None:
            return load_one(i), i
        return self._guard.load(load_one, i, cancel=self._feed_cancel)

    def _run_prefetch(self, it: _PrefetchIterator):
        self._active = self._active + (it,)
        try:
            yield from it
        finally:
            it.close()
            self._active = tuple(p for p in self._active if p is not it)

    def close(self):
        for it in self._active:
            it.close()
        self._active = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class AnchorLoader(_CloseableLoader):
    """Training loader: roidb → static-shape batches.

    Yields dicts with keys image (B,H,W,3) f32, im_info (B,3),
    gt_boxes (B,G,4), gt_classes (B,G), gt_valid (B,G) — the forward_train
    batch contract. B = cfg.train.batch_images × num_shards (devices).

    graftcanvas (cfg.image.canvas_pack): batches are instead PACKED —
    each shard's images shelf-packed into one fixed canvas plane
    (data/canvas.py planner), yielding image (P,Hc,Wc,3) + im_info
    (P,I,5) placement rows + (P,I,G,·) canvas-coordinate gt tensors (the
    ops/canvas.py contract). Every batch of every scale draw then has
    the SAME shape — one compiled train step, period — and the pad
    counters below measure canvas utilization instead of bucket waste.
    """

    def __init__(self, roidb: List[Dict], cfg: Config, num_shards: int = 1,
                 shuffle: Optional[bool] = None, seed: int = 0,
                 prefetch_depth: int = 4, workers: int = 2,
                 process_count: int = 1, process_index: int = 0,
                 guard=None):
        """num_shards = data-axis shards THIS process feeds. Multi-host
        (process_count > 1): every process must use the SAME seed — the
        epoch order is computed over the global batch and each process
        loads its own column slice, preserving exact global-batch DP
        semantics (parallel/distributed.py).

        ``guard`` is a graftfeed FeedGuard (data/feedguard.py) — built
        once per run by fit_detector and shared across heal-time loader
        rebuilds, because the quarantine set is run-scoped state. None
        (standalone/dev iteration) keeps the pre-graftfeed behavior."""
        self.roidb = roidb
        self.cfg = cfg
        self.batch_size = cfg.train.batch_images * num_shards
        self.process_count = process_count
        self.process_index = process_index
        self.global_batch_size = self.batch_size * process_count
        self.shuffle = cfg.train.shuffle if shuffle is None else shuffle
        self.aspect_grouping = cfg.train.aspect_grouping
        self._seed = seed
        self._rng = np.random.RandomState(seed)
        self._depth = prefetch_depth
        self._workers = workers
        self._guard = guard
        self._canvas_spec = None
        if cfg.image.canvas_pack:
            from mx_rcnn_tpu.data.canvas import validate_canvas_pack

            self._canvas_spec = validate_canvas_pack(cfg)

    def __len__(self):
        return len(self.roidb) // self.global_batch_size

    def set_epoch(self, epoch: int):
        """Reseed the order rng as a pure function of (seed, epoch) — the
        distributed-sampler idiom. fit_detector calls this at every epoch
        start so the epoch's batch order (and scale-bucket draw) is
        reproducible in isolation: a run resumed at epoch E (or mid-epoch
        via a graftguard emergency save, which SKIPS the already-trained
        prefix) replays exactly the order the uninterrupted run saw —
        the bit-exact kill→resume parity gate depends on it. Multi-host:
        identical on every process (same seed, same epoch). Standalone
        iteration without set_epoch keeps the legacy advancing stream."""
        self._rng = np.random.RandomState(
            (self._seed * 1_000_003 + epoch) % (2 ** 32))
        if self._guard is not None:
            # graftfeed: the epoch feeds the chaos E:I keys and the
            # deterministic quarantine-replacement draw.
            self._guard.set_epoch(epoch)

    def _epoch_order(self) -> np.ndarray:
        n = len(self.roidb)
        if not self.shuffle:
            return np.arange(n)
        if self.aspect_grouping:
            # Reference: group landscape vs portrait (loader.py) so resize
            # shapes cluster; with one static pad it just improves locality.
            widths = np.array([r.get("width", 1) for r in self.roidb])
            heights = np.array([r.get("height", 1) for r in self.roidb])
            horz = np.where(widths >= heights)[0]
            vert = np.where(widths < heights)[0]
            self._rng.shuffle(horz)
            self._rng.shuffle(vert)
            inds = np.hstack([horz, vert])
            # Rotate by a random offset so the trimmed epoch tail (below)
            # doesn't always fall on the second (vert) group — without this
            # the minority orientation is dropped disproportionately every
            # epoch. Costs one extra mixed-orientation seam, same as the
            # horz/vert boundary batch already present.
            inds = np.roll(inds, int(self._rng.randint(max(n, 1))))
            # Shuffle at (global) batch granularity to keep groups together.
            gb = self.global_batch_size
            nb = n // gb
            trimmed = inds[: nb * gb].reshape(nb, gb)
            self._rng.shuffle(trimmed)
            return trimmed.reshape(-1)
        inds = np.arange(n)
        self._rng.shuffle(inds)
        return inds

    def _content_sizes_fn(self, idxs, scale_idx):
        """sizes_fn for the canvas planner: per-image content (h, w) at
        the drawn scale × fit, via the SAME arithmetic the load path
        uses (data/canvas.py::content_size; packed entries read their
        stored post-resize dims) — planned rects match loaded pixels."""
        from mx_rcnn_tpu.data.canvas import content_size

        cfg = self.cfg
        target0, max0 = cfg.image.scales[scale_idx]

        def sizes_at(fit):
            if fit < 1.0:
                t = max(1, int(round(target0 * fit)))
                mx = max(1, int(round(max0 * fit)))
            else:
                t, mx = target0, max0
            out = []
            for i in idxs:
                e = self.roidb[i]
                if "packed" in e:
                    ref = e["packed"].get(scale_idx)
                    if ref is None:
                        # Same remediation hint as load_packed_content —
                        # the planner runs BEFORE any load, so the error
                        # must be raised (descriptively) here too.
                        raise ValueError(
                            f"scale_idx {scale_idx} is not packed (have "
                            f"{sorted(e['packed'])}); re-pack with "
                            "write_packed_dataset covering every "
                            "training scale")
                    rh, rw = ref["hw"]
                    out.append((rh, rw) if fit >= 1.0
                               else content_size(rh, rw, t, mx)[:2])
                    continue
                if "image_data" in e:
                    h0, w0 = e["image_data"].shape[:2]
                else:
                    h0, w0 = e["height"], e["width"]
                out.append(content_size(h0, w0, t, mx)[:2])
            return out

        return sizes_at

    def _make_packed_batch(self, idxs, scale_idx) -> Dict[str, np.ndarray]:
        """graftcanvas batch assembly: plan placements (scale-to-fit on
        overflow), load unpadded content, place into fixed canvas
        planes, shift gt boxes to canvas coordinates."""
        from mx_rcnn_tpu.data.canvas import plan_batch

        cfg = self.cfg
        if self._guard is not None:
            # graftfeed pre-resolution: records already known quarantined
            # are substituted BEFORE the planner measures content sizes,
            # so planned rects match loaded pixels. A mid-batch DISCOVERY
            # still substitutes at load time (the slot clamp below
            # absorbs the size delta — one batch, once per record).
            idxs = [self._guard.resolve(i) for i in idxs]
        spec = self._canvas_spec
        g = cfg.train.max_gt_boxes
        with_masks = cfg.network.use_mask
        m = cfg.train.mask_gt_resolution
        ch, cw = spec.shape
        placements, fit, _ = plan_batch(
            self._content_sizes_fn(idxs, scale_idx), len(idxs), spec)
        planes = len(idxs) // spec.images
        image = np.zeros((planes, ch, cw, 3), np.float32)
        info = np.zeros((planes, spec.images, 5), np.float32)
        gtb = np.zeros((planes, spec.images, g, 4), np.float32)
        gtc = np.zeros((planes, spec.images, g), np.int32)
        gtv = np.zeros((planes, spec.images, g), bool)
        gtm = (np.zeros((planes, spec.images, g, m, m), np.uint8)
               if with_masks else None)
        real_px = 0.0
        for j, i in enumerate(idxs):
            def _load_content(k, _s=scale_idx, _f=fit):
                return _load_roidb_content(self.roidb[k], cfg, _s, _f)

            (img, iminfo, boxes, classes), ri = self._guarded(
                _load_content, i)
            entry = self.roidb[ri]
            pl, y0, x0 = placements[j]
            slot = j % spec.images
            # Clamp into the canvas: a fit<1 double-resample can round a
            # pixel past the plan; the slot's gap margin absorbs it.
            h = min(img.shape[0], ch - y0)
            w = min(img.shape[1], cw - x0)
            image[pl, y0:y0 + h, x0:x0 + w] = img[:h, :w]
            if len(boxes):
                boxes = boxes + np.asarray([x0, y0, x0, y0], np.float32)
            b_, c_, v_ = _pad_gt(boxes, classes, g)
            info[pl, slot] = (h, w, iminfo[2], y0, x0)
            gtb[pl, slot] = b_
            gtc[pl, slot] = c_
            gtv[pl, slot] = v_
            if with_masks:
                gtm[pl, slot] = _entry_gt_masks(entry, m, g)
            real_px += float(h) * float(w)
        batch = {
            "image": image,
            "im_info": info,
            "gt_boxes": gtb,
            "gt_classes": gtc,
            "gt_valid": gtv,
        }
        if with_masks:
            batch["gt_masks"] = gtm
        # graftprof: in packed mode the counters measure CANVAS
        # utilization — real content pixels over compiled canvas pixels.
        self._note_pad(real_px, planes * ch * cw)
        return batch

    def _make_batch(self, item) -> Dict[str, np.ndarray]:
        idxs, scale_idx = item
        cfg = self.cfg
        if self._canvas_spec is not None:
            return self._make_packed_batch(idxs, scale_idx)
        g = cfg.train.max_gt_boxes
        with_masks = cfg.network.use_mask
        m = cfg.train.mask_gt_resolution
        if self._guard is not None:
            # graftfeed pre-resolution: known-quarantined records swap out
            # BEFORE the orientation vote below, so the pad bucket matches
            # what actually loads (a mid-batch discovery is clamped).
            idxs = [self._guard.resolve(i) for i in idxs]
        pad = resolve_pad_bucket(cfg, scale_idx, [
            self.roidb[i].get("width", 1) >= self.roidb[i].get("height", 1)
            for i in idxs])
        imgs, infos, gtb, gtc, gtv, gtm = [], [], [], [], [], []
        for i in idxs:
            def _load_entry(k, _s=scale_idx, _p=pad):
                # A quarantine substitute can carry the other orientation;
                # pad_image refuses overflow, so load those against the
                # square cover and let the clamp below cut the batch shape.
                e = self.roidb[k]
                land = e.get("width", 1) >= e.get("height", 1)
                fits = _p[1] >= _p[0] if land else _p[0] >= _p[1]
                p = _p if fits else (max(_p), max(_p))
                return _load_roidb_entry(e, cfg, _s, p)

            (img, info, boxes, classes), ri = self._guarded(_load_entry, i)
            entry = self.roidb[ri]
            if img.shape[:2] != tuple(pad):
                # A mid-batch quarantine substitute with the other
                # orientation overflowed this batch's bucket — clamp its
                # content in (deterministic; once per discovered record).
                clamped = np.zeros((pad[0], pad[1], img.shape[2]),
                                   img.dtype)
                ch = min(img.shape[0], pad[0])
                cw = min(img.shape[1], pad[1])
                clamped[:ch, :cw] = img[:ch, :cw]
                img = clamped
            b, c, v = _pad_gt(boxes, classes, g)
            imgs.append(img)
            infos.append(info)
            gtb.append(b)
            gtc.append(c)
            gtv.append(v)
            if with_masks:
                gtm.append(_entry_gt_masks(entry, m, g))
        batch = {
            "image": np.stack(imgs),
            "im_info": np.stack(infos),
            "gt_boxes": np.stack(gtb),
            "gt_classes": np.stack(gtc),
            "gt_valid": np.stack(gtv),
        }
        if with_masks:
            batch["gt_masks"] = np.stack(gtm)
        # graftprof pad accounting: im_info rows are [h, w, scale] with
        # (h, w) the pre-pad content size — a few adds per batch.
        self._note_pad(sum(float(i[0]) * float(i[1]) for i in infos),
                       len(idxs) * pad[0] * pad[1])
        return batch

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        order = self._epoch_order()
        gb = self.global_batch_size
        nb = len(order) // gb
        batches = order[: nb * gb].reshape(nb, gb)
        # Multi-host: this process loads only its column slice of each
        # global batch (same order on every process — same seed).
        lo = self.process_index * self.batch_size
        batches = batches[:, lo:lo + self.batch_size]
        # Multi-scale: one scale bucket per GLOBAL batch (drawn from the
        # shared-seed rng AFTER the order draw, so every host picks the
        # same buckets). Each distinct bucket is one static shape.
        n_scales = len(self.cfg.image.scales)
        scale_ids = (self._rng.randint(n_scales, size=nb) if n_scales > 1
                     else np.zeros(nb, np.int64))
        items = [(batches[i], int(scale_ids[i])) for i in range(nb)]
        yield from self._run_prefetch(
            _PrefetchIterator(self._make_batch, items,
                              depth=self._depth, workers=self._workers,
                              guard=self._guard))


class ROIIter(AnchorLoader):
    """Fast-R-CNN-stage loader over precomputed proposals.

    Reference: rcnn/core/loader.py::ROIIter (selective-search or RPN-dumped
    proposals from imdb.rpn_roidb). Adds proposals (B, P, 4) +
    proposal_valid (B, P) to the batch, padded to `max_proposals`.
    """

    def __init__(self, roidb: List[Dict], cfg: Config, num_shards: int = 1,
                 max_proposals: int = 2000, **kw):
        if cfg.image.canvas_pack:
            raise NotImplementedError(
                "image.canvas_pack is not supported by ROIIter: "
                "precomputed proposals would need placement shifting and "
                "the Fast-RCNN stage forward runs bucketed. Disable "
                "canvas_pack for alternate-stage training")
        super().__init__(roidb, cfg, num_shards, **kw)
        self.max_proposals = max_proposals

    def _make_batch(self, item) -> Dict[str, np.ndarray]:
        idxs, _scale_idx = item
        batch = super()._make_batch(item)
        p = self.max_proposals
        props = np.zeros((len(idxs), p, 4), np.float32)
        pvalid = np.zeros((len(idxs), p), bool)
        for j, i in enumerate(idxs):
            entry = self.roidb[i]
            raw = entry.get("proposals",
                            np.zeros((0, 4), np.float32)).astype(np.float32)
            if entry.get("flipped") and len(raw):
                w = entry["width"]
                raw = raw.copy()
                x1 = raw[:, 0].copy()
                raw[:, 0] = w - 1 - raw[:, 2]
                raw[:, 2] = w - 1 - x1
            scale = batch["im_info"][j, 2]
            n = min(len(raw), p)
            props[j, :n] = raw[:n] * scale
            pvalid[j, :n] = True
        batch["proposals"] = props
        batch["proposal_valid"] = pvalid
        return batch


class TestLoader(_CloseableLoader):
    """Inference loader (reference: rcnn/core/loader.py TestLoader).

    Yields (batch_dict, meta) where meta carries the per-image scale and true
    size for mapping detections back to original image coordinates.
    """

    __test__ = False  # pytest: not a test class, despite the name

    def __init__(self, roidb: List[Dict], cfg: Config, batch_size: int = 1,
                 prefetch_depth: int = 4, workers: int = 2, guard=None):
        self.roidb = roidb
        self.cfg = cfg
        self.batch_size = batch_size
        self._depth = prefetch_depth
        self._workers = workers
        self._guard = guard  # graftfeed (epoch stays 0 for inference)

    def __len__(self):
        return (len(self.roidb) + self.batch_size - 1) // self.batch_size

    def _make_batch(self, idxs):
        cfg = self.cfg
        # Inference uses ONE scale — the last (largest) entry, the
        # reference's TEST.SCALE convention under multi-scale training.
        scale_idx = len(cfg.image.scales) - 1
        real_idxs = [i if i >= 0 else len(self.roidb) - 1 for i in idxs]
        pad = resolve_pad_bucket(cfg, scale_idx, [
            self.roidb[i].get("width", 1) >= self.roidb[i].get("height", 1)
            for i in real_idxs])
        imgs, infos, metas = [], [], []
        for i in idxs:
            if i < 0:  # tail padding repeats the last real image
                i = len(self.roidb) - 1
                real = False
            else:
                real = True

            def _load_entry(k, _s=scale_idx, _p=pad):
                return _load_roidb_entry(
                    {**self.roidb[k], "boxes": np.zeros((0, 4), np.float32),
                     "gt_classes": np.zeros((0,), np.int32)}, cfg, _s, _p)

            (img, info, _, _), _ri = self._guarded(_load_entry, i)
            imgs.append(img)
            infos.append(info)
            metas.append({"index": i, "scale": float(info[2]), "real": real})
        self._note_pad(sum(float(i[0]) * float(i[1]) for i in infos),
                       len(idxs) * pad[0] * pad[1])
        return {"image": np.stack(imgs), "im_info": np.stack(infos)}, metas

    def __iter__(self):
        n = len(self.roidb)
        # Orientation-grouped order (landscape first, stable): with
        # batch_size > 1 this keeps batches orientation-pure so they take
        # the rectangular pad bucket, not the ~1.6x square mixed cover —
        # at most one mixed seam batch. metas carry the original index,
        # so detection ordering is unaffected.
        land = np.array([r.get("width", 1) >= r.get("height", 1)
                         for r in self.roidb])
        idxs = np.concatenate([np.nonzero(land)[0], np.nonzero(~land)[0]])
        pad = (-n) % self.batch_size
        if pad:
            idxs = np.concatenate([idxs, -np.ones(pad, np.int64)])
        batches = idxs.reshape(-1, self.batch_size)
        yield from self._run_prefetch(
            _PrefetchIterator(self._make_batch, batches,
                              depth=self._depth, workers=self._workers,
                              guard=self._guard))
