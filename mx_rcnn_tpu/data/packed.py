"""Packed pre-decoded shard format for the input pipeline.

SURVEY.md §8 hard-part 5: host cv2 JPEG decode + resize cannot sustain a
v5e chip (measured: ~19 img/s single-thread, with INVERSE thread scaling
from GIL contention, vs a 40-55 img/s chip step rate — PERF.md r4). The
reference has no equivalent (MXNet's .rec IndexedRecordIO is the closest
ancestor); this is the TPU-era replacement: decode and resize ONCE at pack
time, then train-time loading is an mmap slice + normalize + pad.

Format (one directory):
  shard_{k:04d}.npy   (N, Hb, Wb, 3) uint8 RGB, mmap-able; every image is
                      resized to the packed scale and zero-padded to its
                      ORIENTED pad bucket (landscape/portrait shards are
                      packed separately so rows are uniform).
  manifest.pkl        per-image dicts: shard path/row, resized (rh, rw),
                      scale, original roidb gt fields (boxes in ORIGINAL
                      coordinates, gt_classes, segmentations/gt_masks...).

`load_packed_roidb(dir)` returns a normal roidb whose entries carry
packed_* keys; data/loader.py::_load_roidb_entry takes the mmap fast path
for them — same AnchorLoader/ROIIter API, same batches, no other changes.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Dict, List, Optional

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.logger import logger

_GT_KEYS = ("gt_classes", "segmentations", "gt_masks")


def _oriented_bucket(cfg: Config, scale_idx: int, landscape: bool) -> tuple:
    from mx_rcnn_tpu.data.loader import pad_shape_for

    h, w = pad_shape_for(cfg, scale_idx)
    h, w = min(h, w), max(h, w)
    return (h, w) if landscape else (w, h)


def write_packed_dataset(roidb: List[Dict], cfg: Config, out_dir: str,
                         scale_idx: int = 0,
                         shard_images: int = 512) -> str:
    """Decode+resize every roidb image once and write packed shards.

    Only UNFLIPPED entries are packed (flip is a view at load time —
    append_flipped_images after load_packed_roidb works as usual).
    """
    from mx_rcnn_tpu.data.image import load_image, resize_image

    os.makedirs(out_dir, exist_ok=True)
    target, max_size = cfg.image.scales[scale_idx]
    manifest: List[Dict] = []
    # Group by orientation so every shard has uniform row shape.
    by_orient = {True: [], False: []}
    for i, entry in enumerate(roidb):
        if entry.get("flipped"):
            raise ValueError(
                "pack the UNFLIPPED roidb; apply append_flipped_images "
                "after load_packed_roidb")
        landscape = entry.get("width", 1) >= entry.get("height", 1)
        by_orient[landscape].append(i)

    shard_id = 0
    for landscape, idxs in by_orient.items():
        bucket = _oriented_bucket(cfg, scale_idx, landscape)
        for lo in range(0, len(idxs), shard_images):
            chunk = idxs[lo:lo + shard_images]
            arr = np.zeros((len(chunk), *bucket, 3), np.uint8)
            rows = []
            for row, i in enumerate(chunk):
                entry = roidb[i]
                img = (entry["image_data"].astype(np.float32)
                       if "image_data" in entry
                       else load_image(entry["image"]))
                img, scale = resize_image(img, target, max_size)
                rh, rw = img.shape[:2]
                if rh > bucket[0] or rw > bucket[1]:
                    raise ValueError(
                        f"resized image ({rh},{rw}) exceeds pad bucket "
                        f"{bucket} — check image.scales/pad_shapes")
                arr[row, :rh, :rw] = np.clip(np.rint(img), 0,
                                             255).astype(np.uint8)
                rows.append((i, rh, rw, float(scale)))
            path = os.path.join(out_dir, f"shard_{shard_id:04d}.npy")
            np.save(path, arr)
            for row, (i, rh, rw, scale) in enumerate(rows):
                entry = roidb[i]
                rec = {
                    "packed_file": os.path.basename(path),
                    "packed_index": row,
                    "packed_hw": (rh, rw),
                    "packed_scale": scale,
                    "packed_scale_idx": scale_idx,
                    "height": entry.get("height"),
                    "width": entry.get("width"),
                    "boxes": np.asarray(entry["boxes"], np.float32),
                    "flipped": False,
                }
                for k in _GT_KEYS:
                    if k in entry:
                        rec[k] = entry[k]
                manifest.append(rec)
            shard_id += 1
    mpath = os.path.join(out_dir, "manifest.pkl")
    with open(mpath, "wb") as f:
        pickle.dump(manifest, f, pickle.HIGHEST_PROTOCOL)
    logger.info("packed %d images into %d shards under %s",
                len(manifest), shard_id, out_dir)
    return mpath


def load_packed_roidb(out_dir: str) -> List[Dict]:
    """Manifest → roidb (entries carry packed_* keys; paths resolved)."""
    with open(os.path.join(out_dir, "manifest.pkl"), "rb") as f:
        manifest = pickle.load(f)
    for rec in manifest:
        rec["packed_file"] = os.path.join(out_dir, rec["packed_file"])
    return manifest


# -- load-time fast path (called from data/loader.py) -----------------------

_MMAPS: Dict[str, np.ndarray] = {}
_MMAP_LOCK = threading.Lock()


def _shard_mmap(path: str) -> np.ndarray:
    arr = _MMAPS.get(path)
    if arr is None:
        with _MMAP_LOCK:
            arr = _MMAPS.get(path)
            if arr is None:
                arr = np.load(path, mmap_mode="r")
                _MMAPS[path] = arr
    return arr


def load_packed_entry(entry: Dict, cfg: Config, scale_idx: int,
                      pad: Optional[tuple]):
    """Packed analog of loader._load_roidb_entry: mmap slice → f32 →
    normalize → pad. Returns (img, im_info, boxes, classes)."""
    from mx_rcnn_tpu.data.image import pad_image, transform_image
    from mx_rcnn_tpu.data.loader import pad_shape_for

    if scale_idx != entry["packed_scale_idx"]:
        raise ValueError(
            f"packed at scale_idx {entry['packed_scale_idx']} but batch "
            f"drew scale_idx {scale_idx}; pack every training scale or "
            "use a single-scale config")
    rh, rw = entry["packed_hw"]
    scale = entry["packed_scale"]
    img_u8 = np.asarray(_shard_mmap(entry["packed_file"])
                        [entry["packed_index"], :rh, :rw])
    boxes = entry["boxes"].astype(np.float32).copy()
    flipped = bool(entry.get("flipped"))
    if flipped:
        w0 = entry["width"]
        x1 = boxes[:, 0].copy()
        boxes[:, 0] = w0 - boxes[:, 2] - 1
        boxes[:, 2] = w0 - x1 - 1
    boxes *= scale
    pad = pad if pad is not None else pad_shape_for(cfg, scale_idx)
    # Fused GIL-free mirror+normalize+pad (cc/imgproc.c) with the numpy
    # chain as fallback.
    from mx_rcnn_tpu.data._native_img import normalize_pad

    img = normalize_pad(img_u8, cfg.image.pixel_means,
                        cfg.image.pixel_stds, pad, flip=flipped)
    if img is None:
        arr = img_u8[:, ::-1] if flipped else img_u8
        img = pad_image(
            transform_image(arr.astype(np.float32),
                            cfg.image.pixel_means, cfg.image.pixel_stds),
            pad)
    im_info = np.asarray([rh, rw, scale], np.float32)
    return img, im_info, boxes, entry["gt_classes"].astype(np.int32)
