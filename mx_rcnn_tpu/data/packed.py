"""Packed pre-decoded shard format for the input pipeline.

SURVEY.md §8 hard-part 5: host cv2 JPEG decode + resize cannot sustain a
v5e chip (measured: ~19 img/s single-thread, with INVERSE thread scaling
from GIL contention, vs a 40-55 img/s chip step rate — PERF.md r4). The
reference has no equivalent (MXNet's .rec IndexedRecordIO is the closest
ancestor); this is the TPU-era replacement: decode and resize ONCE at pack
time, then train-time loading is an mmap slice + normalize + pad.

Format (one directory):
  s{j}_shard_{k:04d}_{l|p}.npy
                      (N, Hb, Wb, 3) uint8 RGB, mmap-able; every image
                      is resized to training scale j and zero-padded to
                      its ORIENTED pad bucket (landscape `_l` and
                      portrait `_p` shards are packed separately so rows
                      are uniform). One shard set per cfg.image.scales
                      entry — multi-scale training draws a scale per
                      batch and reads the matching set.
  manifest.pkl        ONE dict per image: a `packed` map
                      {scale_idx: {file, index, hw, scale}} plus the
                      original roidb gt fields (boxes in ORIGINAL
                      coordinates, gt_classes, segmentations/gt_masks...).

`load_packed_roidb(dir)` returns a normal roidb whose entries carry the
`packed` scale map; data/loader.py::_load_roidb_entry takes the mmap fast
path for them — same AnchorLoader/ROIIter API, same batches, no other
changes.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Dict, List, Optional

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.logger import logger

_GT_KEYS = ("gt_classes", "segmentations", "gt_masks")


def _oriented_bucket(cfg: Config, scale_idx: int, landscape: bool) -> tuple:
    from mx_rcnn_tpu.data.loader import pad_shape_for

    h, w = pad_shape_for(cfg, scale_idx)
    h, w = min(h, w), max(h, w)
    return (h, w) if landscape else (w, h)


def write_packed_dataset(roidb: List[Dict], cfg: Config, out_dir: str,
                         scale_idx=None,
                         shard_images: int = 512) -> str:
    """Decode every roidb image once and write packed shards for EVERY
    training scale (multi-scale configs pack one shard set per
    cfg.image.scales entry — the loader draws a scale per batch and reads
    the matching set). scale_idx: an int or list restricts the packed
    scales (single-scale fixtures, tests).

    Only UNFLIPPED entries are packed (flip is a view at load time —
    append_flipped_images after load_packed_roidb works as usual).
    """
    from mx_rcnn_tpu.data.image import load_image, resize_image

    os.makedirs(out_dir, exist_ok=True)
    if scale_idx is None:
        scale_ids = list(range(len(cfg.image.scales)))
    elif isinstance(scale_idx, int):
        scale_ids = [scale_idx]
    else:
        scale_ids = [int(s) for s in scale_idx]
    # Group by orientation so every shard has uniform row shape.
    by_orient = {True: [], False: []}
    for i, entry in enumerate(roidb):
        if entry.get("flipped"):
            raise ValueError(
                "pack the UNFLIPPED roidb; apply append_flipped_images "
                "after load_packed_roidb")
        landscape = entry.get("width", 1) >= entry.get("height", 1)
        by_orient[landscape].append(i)

    # One manifest record per image, carrying every packed scale.
    recs: Dict[int, Dict] = {}
    for i, entry in enumerate(roidb):
        rec = {
            "packed": {},
            "height": entry.get("height"),
            "width": entry.get("width"),
            "boxes": np.asarray(entry["boxes"], np.float32),
            "flipped": False,
        }
        for k in _GT_KEYS:
            if k in entry:
                rec[k] = entry[k]
        recs[i] = rec

    # Scale is the INNER loop: each image decodes ONCE and feeds every
    # per-scale shard row from that decode (JPEG decode is the cost this
    # format exists to amortize — a scale-outer loop would multiply it).
    n_shards = 0
    for landscape, idxs in by_orient.items():
        shard_id = 0
        for lo in range(0, len(idxs), shard_images):
            chunk = idxs[lo:lo + shard_images]
            arrs = {s: np.zeros(
                (len(chunk), *_oriented_bucket(cfg, s, landscape), 3),
                np.uint8) for s in scale_ids}
            fnames = {s: (f"s{s}_shard_{shard_id:04d}_"
                          f"{'l' if landscape else 'p'}.npy")
                      for s in scale_ids}  # ONE name per (scale, shard)
            for row, i in enumerate(chunk):
                entry = roidb[i]
                img = (entry["image_data"].astype(np.float32)
                       if "image_data" in entry
                       else load_image(entry["image"]))
                for s in scale_ids:
                    target, max_size = cfg.image.scales[s]
                    rimg, scale = resize_image(img, target, max_size)
                    rh, rw = rimg.shape[:2]
                    bucket = arrs[s].shape[1:3]
                    if rh > bucket[0] or rw > bucket[1]:
                        raise ValueError(
                            f"resized image ({rh},{rw}) exceeds pad "
                            f"bucket {bucket} — check image.scales/"
                            "pad_shapes")
                    arrs[s][row, :rh, :rw] = np.clip(
                        np.rint(rimg), 0, 255).astype(np.uint8)
                    recs[i]["packed"][s] = {
                        "file": fnames[s],
                        "index": row, "hw": (rh, rw),
                        "scale": float(scale),
                    }
            for s in scale_ids:
                np.save(os.path.join(out_dir, fnames[s]), arrs[s])
                n_shards += 1
            shard_id += 1
    manifest = {
        # Pack-time geometry: load_packed_roidb validates it against the
        # training config so a pack made for another network/resolution
        # fails loudly instead of training at the wrong scale.
        "meta": {
            "scales": tuple(cfg.image.scales),
            "pad_shapes": tuple(cfg.image.pad_shapes),
            "pad_shape": tuple(cfg.image.pad_shape),
            "scale_ids": scale_ids,
        },
        "records": [recs[i] for i in range(len(roidb))],
    }
    mpath = os.path.join(out_dir, "manifest.pkl")
    with open(mpath, "wb") as f:
        pickle.dump(manifest, f, pickle.HIGHEST_PROTOCOL)
    logger.info("packed %d images x %d scale(s) into %d shards under %s",
                len(recs), len(scale_ids), n_shards, out_dir)
    return mpath


def load_packed_roidb(out_dir: str, cfg: Optional[Config] = None
                      ) -> List[Dict]:
    """Manifest → roidb (entries carry the `packed` scale map; shard
    paths resolved). With ``cfg``, the pack-time image geometry is
    validated against the training config — a shard set packed for a
    different network/resolution fails here, loudly, instead of silently
    training at the wrong scale."""
    with open(os.path.join(out_dir, "manifest.pkl"), "rb") as f:
        manifest = pickle.load(f)
    if not isinstance(manifest, dict) or "records" not in manifest:
        raise ValueError(
            f"{out_dir} holds a pre-multi-scale packed manifest (or not a "
            "packed dataset); re-pack with tools/pack_dataset.py")
    if cfg is not None:
        meta = manifest["meta"]
        want = {"scales": tuple(cfg.image.scales),
                "pad_shapes": tuple(cfg.image.pad_shapes),
                "pad_shape": tuple(cfg.image.pad_shape)}
        have = {k: tuple(meta[k]) for k in want}
        if want != have:
            raise ValueError(
                f"packed dataset geometry {have} does not match the "
                f"training config {want}; re-pack with the same "
                "network/image settings (tools/pack_dataset.py)")
        missing = (set(range(len(cfg.image.scales)))
                   - set(meta["scale_ids"]))
        if missing:
            raise ValueError(
                f"packed dataset covers scale_ids {meta['scale_ids']} "
                f"but the training config draws from "
                f"{len(cfg.image.scales)} scales (missing {sorted(missing)})"
                "; re-pack without scale_idx restriction")
    records = manifest["records"]
    for rec in records:
        for s in rec["packed"].values():
            s["file"] = os.path.join(out_dir, os.path.basename(s["file"]))
    return records


# -- load-time fast path (called from data/loader.py) -----------------------

_MMAPS: Dict[str, np.ndarray] = {}
_MMAP_LOCK = threading.Lock()


def _shard_mmap(path: str) -> np.ndarray:
    arr = _MMAPS.get(path)
    if arr is None:
        with _MMAP_LOCK:
            arr = _MMAPS.get(path)
            if arr is None:
                arr = np.load(path, mmap_mode="r")
                _MMAPS[path] = arr
    return arr


def load_packed_entry(entry: Dict, cfg: Config, scale_idx: int,
                      pad: Optional[tuple]):
    """Packed analog of loader._load_roidb_entry: mmap slice → f32 →
    normalize → pad. Returns (img, im_info, boxes, classes)."""
    from mx_rcnn_tpu.data.image import pad_image, transform_image
    from mx_rcnn_tpu.data.loader import pad_shape_for

    ref = entry["packed"].get(scale_idx)
    if ref is None:
        raise ValueError(
            f"scale_idx {scale_idx} is not packed (have "
            f"{sorted(entry['packed'])}); re-pack with "
            "write_packed_dataset covering every training scale")
    rh, rw = ref["hw"]
    scale = ref["scale"]
    img_u8 = np.asarray(_shard_mmap(ref["file"])[ref["index"], :rh, :rw])
    boxes = entry["boxes"].astype(np.float32).copy()
    flipped = bool(entry.get("flipped"))
    if flipped:
        w0 = entry["width"]
        x1 = boxes[:, 0].copy()
        boxes[:, 0] = w0 - boxes[:, 2] - 1
        boxes[:, 2] = w0 - x1 - 1
    boxes *= scale
    pad = pad if pad is not None else pad_shape_for(cfg, scale_idx)
    # Fused GIL-free mirror+normalize+pad (cc/imgproc.c) with the numpy
    # chain as fallback.
    from mx_rcnn_tpu.data._native_img import normalize_pad

    img = normalize_pad(img_u8, cfg.image.pixel_means,
                        cfg.image.pixel_stds, pad, flip=flipped)
    if img is None:
        arr = img_u8[:, ::-1] if flipped else img_u8
        img = pad_image(
            transform_image(arr.astype(np.float32),
                            cfg.image.pixel_means, cfg.image.pixel_stds),
            pad)
    im_info = np.asarray([rh, rw, scale], np.float32)
    return img, im_info, boxes, entry["gt_classes"].astype(np.int32)


def load_packed_content(entry: Dict, cfg: Config, scale_idx: int,
                        fit: float = 1.0):
    """graftcanvas analog of load_packed_entry: mmap slice → f32 →
    normalize, UNPADDED — the content feeds a canvas placement directly
    (data/loader.py::_make_packed_batch), so the pack-time decode+resize
    is all the geometry work the hot path pays. fit < 1 (a scale-to-fit
    batch) re-resamples the stored content to the shrunken targets —
    rare by construction; the planner logs it.

    Returns (img f32 HWC unpadded, im_info [h, w, scale], boxes,
    classes) with `scale` the ORIGINAL-image → content scale (stored
    pack scale × any fit resample)."""
    from mx_rcnn_tpu.data.image import resize_image, transform_image

    ref = entry["packed"].get(scale_idx)
    if ref is None:
        raise ValueError(
            f"scale_idx {scale_idx} is not packed (have "
            f"{sorted(entry['packed'])}); re-pack with "
            "write_packed_dataset covering every training scale")
    from mx_rcnn_tpu.data._native_img import normalize_pad

    rh, rw = ref["hw"]
    scale = ref["scale"]
    img_u8 = np.asarray(_shard_mmap(ref["file"])[ref["index"], :rh, :rw])
    boxes = entry["boxes"].astype(np.float32).copy()
    flipped = bool(entry.get("flipped"))
    if flipped:
        w0 = entry["width"]
        x1 = boxes[:, 0].copy()
        boxes[:, 0] = w0 - boxes[:, 2] - 1
        boxes[:, 2] = w0 - x1 - 1
    if fit < 1.0:
        target, max_size = cfg.image.scales[scale_idx]
        arr = (img_u8[:, ::-1] if flipped else img_u8).astype(np.float32)
        arr, s2 = resize_image(arr, max(1, int(round(target * fit))),
                               max(1, int(round(max_size * fit))))
        scale *= s2
        img = normalize_pad(np.ascontiguousarray(arr, np.float32),
                            cfg.image.pixel_means, cfg.image.pixel_stds,
                            arr.shape[:2])
        if img is None:
            img = transform_image(arr, cfg.image.pixel_means,
                                  cfg.image.pixel_stds)
    else:
        # Fused u8→f32 mirror+normalize (cc/imgproc.c), pad == content
        # dims — the same one-pass kernel the bucketed mmap path uses.
        img = normalize_pad(img_u8, cfg.image.pixel_means,
                            cfg.image.pixel_stds, (rh, rw), flip=flipped)
        if img is None:
            arr = (img_u8[:, ::-1] if flipped else img_u8)
            img = transform_image(arr.astype(np.float32),
                                  cfg.image.pixel_means,
                                  cfg.image.pixel_stds)
    boxes *= scale
    im_info = np.asarray([img.shape[0], img.shape[1], scale], np.float32)
    return img, im_info, boxes, entry["gt_classes"].astype(np.int32)
