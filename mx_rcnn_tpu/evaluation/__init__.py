"""Evaluation — inference loop + dataset metrics.

Reference layer L9 (rcnn/core/tester.py) plus the eval halves of
rcnn/dataset/pascal_voc_eval.py and the vendored rcnn/pycocotools. COCO eval
is reimplemented in-repo because pycocotools is not installed in this
environment (SURVEY.md §8).
"""

from mx_rcnn_tpu.evaluation.tester import Predictor, im_detect, pred_eval

__all__ = ["Predictor", "im_detect", "pred_eval"]
