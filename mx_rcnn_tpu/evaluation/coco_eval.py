"""COCO detection evaluation — in-repo reimplementation of the bbox protocol.

Reference: the vendored rcnn/pycocotools/cocoeval.py (COCOeval) driven by
rcnn/dataset/coco.py. pycocotools is NOT installed in this environment
(SURVEY.md §8), so the matching + accumulation protocol is reimplemented
from its published definition:

- 10 IoU thresholds 0.50:0.05:0.95, 101 recall points, 4 area ranges,
  maxDets (1, 10, 100);
- COCO boxes are (x, y, w, h) with EXCLUSIVE widths (no +1);
- crowd ground truths are ignore regions: IoU against a crowd is
  intersection / det area, and a crowd match marks the detection ignored
  rather than true-positive;
- greedy matching in score order; each non-ignore gt matches at most once;
  an already-found non-ignore match is never displaced by an ignore one.

Validated against hand-checked small cases and a scalar reference matcher
in tests/test_eval.py.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Sequence

import numpy as np

from mx_rcnn_tpu.logger import logger

IOU_THRS = np.linspace(0.5, 0.95, 10)
REC_THRS = np.linspace(0.0, 1.0, 101)
AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0 ** 2),
    "medium": (32.0 ** 2, 96.0 ** 2),
    "large": (96.0 ** 2, 1e10),
}
MAX_DETS = (1, 10, 100)


def bbox_iou_xywh(dets: np.ndarray, gts: np.ndarray,
                  iscrowd: np.ndarray) -> np.ndarray:
    """(D,4) x (G,4) xywh IoU, exclusive widths; crowd gt → inter/det_area."""
    d = dets[:, None]
    g = gts[None, :]
    ix = np.minimum(d[..., 0] + d[..., 2], g[..., 0] + g[..., 2]) - np.maximum(
        d[..., 0], g[..., 0])
    iy = np.minimum(d[..., 1] + d[..., 3], g[..., 1] + g[..., 3]) - np.maximum(
        d[..., 1], g[..., 1])
    inter = np.maximum(ix, 0) * np.maximum(iy, 0)
    area_d = d[..., 2] * d[..., 3]
    area_g = g[..., 2] * g[..., 3]
    union = np.where(iscrowd[None, :], area_d, area_d + area_g - inter)
    return inter / np.maximum(union, 1e-10)


class COCOEval:
    """Evaluation of a results list against an instances-json dict.

    iou_type: "bbox" (default) or "segm". Segm mode matches with RLE mask
    IoU (mx_rcnn_tpu.masks — the maskApi path of the reference's vendored
    pycocotools): gt `segmentation` fields (polygons or RLE) are rasterized
    per image, detections must carry an RLE `segmentation`, and areas come
    from the masks, as COCOeval's segm iouType does.
    """

    def __init__(self, dataset: Dict, results: Sequence[Dict],
                 max_dets: Sequence[int] = MAX_DETS, iou_type: str = "bbox"):
        if iou_type not in ("bbox", "segm"):
            raise ValueError(f"unknown iou_type {iou_type!r}")
        self.iou_type = iou_type
        self.max_dets = tuple(max_dets)
        self.img_ids = sorted(im["id"] for im in dataset["images"])
        self._img_size = {im["id"]: (im["height"], im["width"])
                          for im in dataset["images"]}
        self.cat_ids = sorted(c["id"] for c in dataset["categories"])
        self._gts = defaultdict(list)
        for ann in dataset["annotations"]:
            self._gts[(ann["image_id"], ann["category_id"])].append(ann)
        self._dts = defaultdict(list)
        for r in results:
            self._dts[(r["image_id"], r["category_id"])].append(r)
        self.stats: Dict[str, float] = {}

    # -- per image/category matching --------------------------------------

    def _evaluate_img(self, gts, gt_areas, iscrowd, dts, ious, area_rng,
                      d_areas=None):
        """Greedy matching for one (image, category, area-range) cell.

        gts/dts are already sorted (dets by score desc, capped at
        max(max_dets)); ious computed once by the caller — only the ignore
        flags depend on the area range, so matching runs 4×, not 12×
        (the pycocotools structure: computeIoU once, evaluateImg per area,
        maxDet sliced at accumulate time).
        """
        gt_ignore_area = np.array([
            bool(g.get("iscrowd", 0))
            or not (area_rng[0] <= a < area_rng[1])
            for g, a in zip(gts, gt_areas)
        ], bool)
        # non-ignore gts first (stable) — matching prefers them.
        g_order = np.argsort(gt_ignore_area, kind="stable")
        gt_ignore = gt_ignore_area[g_order]
        iscrowd = iscrowd[g_order]
        ious = ious[:, g_order] if ious.size else ious

        d_boxes = np.array([d["bbox"] for d in dts], np.float64).reshape(-1, 4)
        T, D, G = len(IOU_THRS), len(dts), len(gts)
        dt_match = np.zeros((T, D), bool)
        dt_ignore = np.zeros((T, D), bool)
        gt_match = np.zeros((T, G), bool)
        # Greedy matching, vectorized over the T and G axes (the det loop is
        # inherently sequential: each match consumes a gt). Per det, per
        # threshold: among still-available gts with IoU ≥ thr, prefer
        # non-ignore gts; pick the max IoU, ties going to the LAST gt in
        # sorted order (the sequential scan updates on `>=`).
        if D and G:
            thr_init = np.minimum(IOU_THRS, 1 - 1e-10)[:, None]  # (T, 1)
            for di in range(D):
                iou_d = ious[di][None, :]  # (1, G)
                avail = ~(gt_match & ~iscrowd[None, :])
                cand = avail & (iou_d >= thr_init)  # (T, G)
                cand_ni = cand & ~gt_ignore[None, :]
                sel = np.where(cand_ni.any(axis=1)[:, None],
                               cand_ni, cand & gt_ignore[None, :])
                has = sel.any(axis=1)
                masked = np.where(sel, iou_d, -np.inf)
                m = G - 1 - np.argmax(masked[:, ::-1], axis=1)  # last-tie argmax
                t_idx = np.nonzero(has)[0]
                mm = m[t_idx]
                dt_match[t_idx, di] = True
                dt_ignore[t_idx, di] = gt_ignore[mm]
                gt_match[t_idx, mm] = True
        # Detections outside the area range and unmatched → ignored.
        if d_areas is None:  # bbox mode; segm passes mask areas
            d_areas = d_boxes[:, 2] * d_boxes[:, 3]
        d_out = (d_areas < area_rng[0]) | (d_areas >= area_rng[1])
        dt_ignore |= (~dt_match) & d_out[None, :]
        return {
            "scores": np.array([d["score"] for d in dts]),
            "dt_match": dt_match,
            "dt_ignore": dt_ignore,
            "num_gt": int((~gt_ignore).sum()),
        }

    # -- accumulation ------------------------------------------------------

    def _evaluate_category(self, cat_id: int):
        """Per-area matching results for one category, IoUs computed once."""
        cap = max(self.max_dets)
        per_area = {name: [] for name in AREA_RANGES}
        for img_id in self.img_ids:
            gts = self._gts.get((img_id, cat_id), [])
            dts = self._dts.get((img_id, cat_id), [])
            if not gts and not dts:
                continue
            d_order = np.argsort([-d["score"] for d in dts],
                                 kind="stable")[:cap]
            dts = [dts[i] for i in d_order]
            iscrowd = np.array([bool(g.get("iscrowd", 0)) for g in gts], bool)
            gt_areas = [g.get("area", g["bbox"][2] * g["bbox"][3]) for g in gts]
            d_areas = None
            if self.iou_type == "segm":
                from mx_rcnn_tpu import masks as _masks

                h, w = self._img_size[img_id]
                g_rles = [_masks.fr_py_objects(g["segmentation"], h, w)
                          for g in gts]
                d_rles = [_masks.fr_py_objects(d["segmentation"], h, w)
                          for d in dts]
                ious = (_masks.iou(d_rles, g_rles, iscrowd.tolist())
                        if len(gts) and len(dts)
                        else np.zeros((len(dts), len(gts))))
                d_areas = np.array([_masks.area(r) for r in d_rles],
                                   np.float64)
            else:
                g_boxes = np.array([g["bbox"] for g in gts],
                                   np.float64).reshape(-1, 4)
                d_boxes = np.array([d["bbox"] for d in dts],
                                   np.float64).reshape(-1, 4)
                ious = (bbox_iou_xywh(d_boxes, g_boxes, iscrowd)
                        if len(gts) and len(dts)
                        else np.zeros((len(dts), len(gts))))
            for name, rng in AREA_RANGES.items():
                per_area[name].append(
                    self._evaluate_img(gts, gt_areas, iscrowd, dts, ious, rng,
                                       d_areas=d_areas))
        return per_area

    def _accumulate_cell(self, evals, max_det: int) -> np.ndarray:
        """precision (T, R) for one (category, area, maxDet) cell; −1 where
        no gt exists. Per-image det lists are score-sorted, so the maxDet cap
        is a per-image slice (pycocotools accumulate semantics)."""
        T, R = len(IOU_THRS), len(REC_THRS)
        precision = -np.ones((T, R))
        if not evals:
            return precision
        npos = sum(e["num_gt"] for e in evals)
        if npos == 0:
            return precision
        scores = np.concatenate([e["scores"][:max_det] for e in evals])
        order = np.argsort(-scores, kind="mergesort")
        dt_match = np.concatenate(
            [e["dt_match"][:, :max_det] for e in evals], axis=1)[:, order]
        dt_ignore = np.concatenate(
            [e["dt_ignore"][:, :max_det] for e in evals], axis=1)[:, order]
        tps = dt_match & ~dt_ignore
        fps = ~dt_match & ~dt_ignore
        tp_cum = np.cumsum(tps, axis=1).astype(np.float64)
        fp_cum = np.cumsum(fps, axis=1).astype(np.float64)
        for t in range(T):
            tp, fp = tp_cum[t], fp_cum[t]
            rec = tp / npos
            prec = tp / np.maximum(tp + fp, 1e-10)
            # precision envelope (monotone non-increasing from the right)
            for i in range(len(prec) - 1, 0, -1):
                prec[i - 1] = max(prec[i - 1], prec[i])
            idx = np.searchsorted(rec, REC_THRS, side="left")
            for r, pi in enumerate(idx):
                precision[t, r] = prec[pi] if pi < len(prec) else 0.0
        return precision

    def accumulate(self):
        self._precision = {}  # (area, maxDet) -> (T, R, K)
        per_cat = {cat: self._evaluate_category(cat) for cat in self.cat_ids}
        for area_name in AREA_RANGES:
            for max_det in self.max_dets:
                cells = [
                    self._accumulate_cell(per_cat[cat][area_name], max_det)
                    for cat in self.cat_ids
                ]
                self._precision[(area_name, max_det)] = np.stack(cells, axis=-1)
        return self

    def _ap(self, area: str = "all", max_det: int = 100, iou_thr=None) -> float:
        p = self._precision[(area, max_det)]
        if iou_thr is not None:
            t = int(np.argmin(np.abs(IOU_THRS - iou_thr)))
            p = p[t:t + 1]
        valid = p[p > -1]
        return float(valid.mean()) if valid.size else -1.0

    def summarize(self) -> Dict[str, float]:
        if not hasattr(self, "_precision"):
            self.accumulate()
        self.stats = {
            "AP": self._ap(),
            "AP50": self._ap(iou_thr=0.5),
            "AP75": self._ap(iou_thr=0.75),
            "APs": self._ap(area="small"),
            "APm": self._ap(area="medium"),
            "APl": self._ap(area="large"),
        }
        for k, v in self.stats.items():
            logger.info("COCO %s = %.4f", k, v)
        self.stats["mAP"] = self.stats["AP"]
        return self.stats
