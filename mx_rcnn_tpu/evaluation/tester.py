"""Inference core: Predictor, im_detect, pred_eval, proposal dumping.

Reference: rcnn/core/tester.py — Predictor (module bound for test shapes),
im_detect (forward → decode → clip), pred_eval (loop over TestLoader,
per-class threshold + NMS + max_per_image, then imdb.evaluate_detections),
im_proposal/generate_proposals (RPN proposal dump for alternate training).

TPU deltas: decode + per-class NMS run INSIDE the jitted forward
(ops/detection.py::multiclass_nms); only ONE final packed
(B, max_per_image, 7) tensor reaches the host per batch. Batch > 1 inference is supported (the reference's
TestLoader is batch-1 only).
"""

from __future__ import annotations

import pickle
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.data.loader import TestLoader
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models.zoo import forward_rpn, forward_test
from mx_rcnn_tpu.ops.detection import multiclass_nms


class Predictor:
    """Jitted test-forward + post-processing bound to one param set.

    Reference: rcnn/core/tester.py::Predictor (an mx.mod.Module bound with
    max test shapes); here binding = jit caching per input shape.
    """

    def __init__(self, model, params, cfg: Config):
        self.model = model
        self.params = params
        self.cfg = cfg
        self.use_mask = bool(getattr(model, "use_mask", False))

        def _detect(params, image, im_info):
            rois, roi_valid, scores, boxes = forward_test(
                model, params, image, im_info, cfg)
            dets = multiclass_nms(
                scores, boxes, roi_valid,
                score_thresh=cfg.test.score_thresh,
                nms_thresh=cfg.test.nms_thresh,
                max_per_image=cfg.test.max_per_image,
            )
            # Pack into ONE (B, M, 7) tensor [cls, score, x1, y1, x2, y2,
            # valid] so a single device→host read returns everything —
            # through a remote-relay device each separate read pays a full
            # round trip (measured ~95 ms/array on axon; see PERF.md).
            return jnp.concatenate(
                [dets.classes[..., None].astype(jnp.float32),
                 dets.scores[..., None],
                 dets.boxes,
                 dets.valid[..., None].astype(jnp.float32)], axis=-1)

        def _propose(params, image, im_info):
            # RPN-only path: backbone + RPN + proposal op, no box head
            # (reference: tester.py im_proposal runs the rpn-test symbol).
            return forward_rpn(model, params, image, im_info, cfg)

        def _masks(params, image, det_boxes, det_classes, det_valid):
            from mx_rcnn_tpu.models.fpn import forward_test_masks

            return forward_test_masks(model, params, image, det_boxes,
                                      det_classes, det_valid)

        self._detect = jax.jit(_detect)
        self._propose = jax.jit(_propose)
        self._masks = jax.jit(_masks) if self.use_mask else None

    def detect(self, image: np.ndarray, im_info: np.ndarray):
        """Packed (B, M, 7) detections [cls, score, x1, y1, x2, y2, valid],
        network-input coordinates, still on device. Host numpy args go
        straight to the jitted call (one dispatch does both transfers)."""
        return self._detect(self.params, image, im_info)

    def propose(self, image: np.ndarray, im_info: np.ndarray):
        return self._propose(self.params, jnp.asarray(image), jnp.asarray(im_info))

    def mask_probs(self, image: np.ndarray, det_boxes: np.ndarray,
                   det_classes: np.ndarray, det_valid: np.ndarray):
        """(B, D, m, m) mask probabilities for NETWORK-scale detection boxes
        (the Mask R-CNN inference tail; see models/fpn.forward_test_masks)."""
        return self._masks(self.params, jnp.asarray(image),
                           jnp.asarray(det_boxes), jnp.asarray(det_classes),
                           jnp.asarray(det_valid))


def im_detect(predictor: Predictor, image: np.ndarray, im_info: np.ndarray,
              scale: float) -> List[np.ndarray]:
    """Detections for one batch, mapped back to ORIGINAL image coordinates.

    Returns per-image arrays (n, 6): [cls, score, x1, y1, x2, y2].
    """
    return _split_packed(
        np.asarray(predictor.detect(image, im_info)), scale)


def _split_packed(packed: np.ndarray, scale: float) -> List[np.ndarray]:
    """(B, M, 7) packed detections → per-image (n, 6) arrays at 1/scale."""
    out = []
    for b in range(packed.shape[0]):
        v = packed[b, :, 6] > 0.5
        arr = packed[b, v, :6]  # advanced indexing -> fresh array
        arr[:, 2:6] /= scale
        out.append(arr)
    return out


def pred_eval(predictor: Predictor, test_loader: TestLoader, imdb,
              vis: bool = False, thresh: float = 0.0,
              out_json: Optional[str] = None,
              vis_dir: str = "vis", pipeline_depth: int = 3,
              event_log=None) -> Dict[str, float]:
    """Evaluate over an imdb (reference: tester.py::pred_eval).

    Builds all_boxes[class][image] = (n, 5) [x1..y2, score] in original
    coords and hands it to imdb.evaluate_detections. vis=True writes box
    overlays (score ≥ 0.5) to vis_dir, as the reference's vis branch shows
    them interactively.

    pipeline_depth: how many batches of device work stay enqueued before
    the oldest result is read back. Through the remote-relay device the
    read is round-trip-latency-bound, so deeper pipelining (with
    batch_size > 1 in the loader) amortizes it. 1 = fully serial
    (enqueue, then immediately read); 2 ≈ the previous fixed 1-in-flight
    pipeline.

    event_log: optional graftscope EventLog — the pass then ends with an
    ``eval`` event carrying the result dict and wall time (obs/report.py
    folds these into the run summary).
    """
    import time as _time

    t_start = _time.perf_counter()
    num_classes = imdb.num_classes
    num_images = len(test_loader.roidb)
    all_boxes: List[List] = [
        [np.zeros((0, 5), np.float32) for _ in range(num_images)]
        for _ in range(num_classes)
    ]
    want_masks = predictor.use_mask
    all_masks: List[List] = [
        [[] for _ in range(num_images)] for _ in range(num_classes)
    ] if want_masks else None
    done = 0

    def _process(dev_packed, batch, metas):
        nonlocal done
        # The host read happens HERE — one batch after the detect was
        # enqueued, so it overlaps the next batch's device work (through a
        # remote-relay device the synchronous read-per-batch pattern is
        # round-trip-latency-bound; see PERF.md).
        per_image = _split_packed(np.asarray(dev_packed), metas[0]["scale"])
        if vis:
            _vis_batch(batch, metas, per_image, imdb, test_loader, vis_dir)
        if want_masks:
            per_image_rles = _batch_mask_rles(
                predictor, batch, metas, per_image, test_loader)
        # per-image scales differ; recompute per image (the packed split
        # used the first scale — fix up here for the general batch case).
        for i, meta in enumerate(metas):
            if not meta["real"]:
                continue
            dets = per_image[i]
            if metas[0]["scale"] != meta["scale"]:
                dets = dets.copy()
                dets[:, 2:6] *= metas[0]["scale"] / meta["scale"]
            img_idx = meta["index"]
            for c in range(1, num_classes):
                sel = (dets[:, 0] == c) & (dets[:, 1] >= thresh)
                cls_dets = np.concatenate(
                    [dets[sel, 2:6], dets[sel, 1:2]], axis=1)
                all_boxes[c][img_idx] = cls_dets.astype(np.float32)
                if want_masks:
                    rles = per_image_rles[i]
                    all_masks[c][img_idx] = [
                        rles[j] for j in np.nonzero(sel)[0]]
            done += 1
        if done % 100 < len(metas):
            logger.info("im_detect: %d/%d", done, num_images)

    # N-deep pipeline: keep up to pipeline_depth batches of device work
    # in flight before reading the oldest result, so host post-processing
    # and relay round trips overlap device compute.
    from collections import deque

    pending = deque()
    for batch, metas in test_loader:
        pending.append((predictor.detect(batch["image"], batch["im_info"]),
                        batch, metas))
        if len(pending) >= max(1, pipeline_depth):
            _process(*pending.popleft())
    while pending:
        _process(*pending.popleft())
    kwargs = {}
    if out_json:
        kwargs["out_json"] = out_json
    if want_masks and hasattr(imdb, "evaluate_segmentations"):
        results = imdb.evaluate_segmentations(all_boxes, all_masks, **kwargs)
    else:
        if want_masks:
            logger.warning("%s has no segm evaluation; reporting boxes only",
                           type(imdb).__name__)
        results = imdb.evaluate_detections(all_boxes, **kwargs)
    if event_log is not None and event_log.enabled:
        event_log.emit("eval", images=num_images, results=results,
                       wall_s=round(_time.perf_counter() - t_start, 3))
    return results


def _batch_mask_rles(predictor: Predictor, batch, metas, per_image,
                     test_loader):
    """Run the mask head on one batch's final detections and paste to
    original-size RLEs. Returns per image a list of RLEs aligned with
    per_image[i]'s det rows."""
    from mx_rcnn_tpu.masks.paste import paste_masks_to_rles

    d = predictor.cfg.test.max_per_image
    b = batch["image"].shape[0]
    det_boxes = np.zeros((b, d, 4), np.float32)
    det_classes = np.zeros((b, d), np.int32)
    det_valid = np.zeros((b, d), bool)
    for i, meta in enumerate(metas):
        dets = per_image[i]
        n = min(len(dets), d)
        # per_image is at ORIGINAL scale (divided by metas[0]); map back to
        # this image's network-input coords for pooling.
        det_boxes[i, :n] = dets[:n, 2:6] * metas[0]["scale"]
        det_classes[i, :n] = dets[:n, 0]
        det_valid[i, :n] = True
    probs = np.asarray(predictor.mask_probs(
        batch["image"], det_boxes, det_classes, det_valid))
    out = []
    for i, meta in enumerate(metas):
        if not meta["real"]:
            out.append([])
            continue
        entry = test_loader.roidb[meta["index"]]
        h, w = entry["height"], entry["width"]
        dets = per_image[i]
        n = min(len(dets), d)
        # Paste with ORIGINAL-scale boxes (same rows the eval consumes).
        boxes_orig = dets[:n, 2:6] * (metas[0]["scale"] / meta["scale"])
        out.append(paste_masks_to_rles(probs[i, :n], boxes_orig, h, w))
    return out


def _vis_batch(batch, metas, per_image, imdb, test_loader, vis_dir):
    """Save detection overlays for one batch (score ≥ 0.5)."""
    from mx_rcnn_tpu.data.image import transform_inverse
    from mx_rcnn_tpu.utils.vis import save_vis

    cfg = test_loader.cfg
    class_names = getattr(imdb, "classes", ()) or tuple(
        str(i) for i in range(imdb.num_classes))
    for i, meta in enumerate(metas):
        if not meta["real"]:
            continue
        dets = per_image[i]
        dets = dets[dets[:, 1] >= 0.5].copy()
        # im_detect divided every image's boxes by metas[0]["scale"]; undo
        # exactly that to return to network-input coords.
        dets[:, 2:6] *= metas[0]["scale"]
        img = transform_inverse(batch["image"][i], cfg.image.pixel_means,
                                cfg.image.pixel_stds)
        save_vis(img, dets, class_names,
                 f"{vis_dir}/{meta['index']}.jpg")


def generate_proposals(predictor: Predictor, test_loader: TestLoader,
                       rpn_file: str) -> List[np.ndarray]:
    """Run the RPN over an imdb and dump proposals (reference:
    tester.py::generate_proposals writing *_rpn.pkl for alternate training).

    Saves a list (image order) of (n, 5) [x1,y1,x2,y2,score] proposal arrays
    at ORIGINAL scale (consumers use [:, :4]; scores kept for inspection).
    """
    num_images = len(test_loader.roidb)
    out: List[Optional[np.ndarray]] = [None] * num_images
    for batch, metas in test_loader:
        rois, roi_valid, roi_scores = predictor.propose(
            batch["image"], batch["im_info"])
        rois = np.asarray(rois)
        roi_valid = np.asarray(roi_valid)
        roi_scores = np.asarray(roi_scores)
        for i, meta in enumerate(metas):
            if not meta["real"]:
                continue
            v = roi_valid[i]
            out[meta["index"]] = np.concatenate(
                [rois[i][v] / meta["scale"], roi_scores[i][v, None]],
                axis=1).astype(np.float32)
    with open(rpn_file, "wb") as f:
        pickle.dump(out, f, pickle.HIGHEST_PROTOCOL)
    logger.info("wrote %d proposal sets to %s", num_images, rpn_file)
    return out
