"""PASCAL VOC AP evaluation.

Reference: rcnn/dataset/pascal_voc_eval.py::voc_eval — per-class ranked
matching at IoU 0.5, greedy per-gt assignment, AP via either the VOC-07
11-point metric or the continuous (area-under-PR) metric. Reimplemented from
the protocol definition; operates either on result files (voc_eval) or
directly on arrays (voc_ap_from_arrays — used by the synthetic dataset and
unit tests).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np


def voc_ap(rec: np.ndarray, prec: np.ndarray, use_07_metric: bool = False) -> float:
    """AP from a PR curve (reference: pascal_voc_eval.py::voc_ap)."""
    if use_07_metric:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = np.max(prec[rec >= t]) if np.any(rec >= t) else 0.0
            ap += p / 11.0
        return float(ap)
    mrec = np.concatenate([[0.0], rec, [1.0]])
    mpre = np.concatenate([[0.0], prec, [0.0]])
    for i in range(mpre.size - 1, 0, -1):
        mpre[i - 1] = max(mpre[i - 1], mpre[i])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))


def _iou_matrix(det: np.ndarray, gt: np.ndarray) -> np.ndarray:
    """(D,4) x (G,4) -> (D,G) IoU with the VOC inclusive-pixel convention."""
    ixmin = np.maximum(det[:, None, 0], gt[None, :, 0])
    iymin = np.maximum(det[:, None, 1], gt[None, :, 1])
    ixmax = np.minimum(det[:, None, 2], gt[None, :, 2])
    iymax = np.minimum(det[:, None, 3], gt[None, :, 3])
    iw = np.maximum(ixmax - ixmin + 1.0, 0.0)
    ih = np.maximum(iymax - iymin + 1.0, 0.0)
    inter = iw * ih
    a_det = (det[:, 2] - det[:, 0] + 1.0) * (det[:, 3] - det[:, 1] + 1.0)
    a_gt = (gt[:, 2] - gt[:, 0] + 1.0) * (gt[:, 3] - gt[:, 1] + 1.0)
    union = a_det[:, None] + a_gt[None, :] - inter
    return inter / np.maximum(union, 1e-10)


def eval_class(
    gt_by_image: Dict,
    det_by_image: Dict,
    difficult_by_image: Dict = None,
    iou_thresh: float = 0.5,
    use_07_metric: bool = False,
) -> float:
    """AP for one class.

    gt_by_image: image_id -> (G, 4) gt boxes.
    det_by_image: image_id -> (D, 5) [x1,y1,x2,y2,score].
    difficult_by_image: image_id -> (G,) bool (VOC 'difficult' flags —
      excluded from the positive pool and never counted as FP).
    """
    difficult_by_image = difficult_by_image or {}
    npos = 0
    matched = {}
    for img, gt in gt_by_image.items():
        diff = difficult_by_image.get(img)
        if diff is None:
            diff = np.zeros(len(gt), bool)
        matched[img] = np.zeros(len(gt), bool)
        npos += int((~diff).sum())

    # Flatten detections, rank by score (reference sorts globally).
    rows = []
    for img, det in det_by_image.items():
        for d in np.asarray(det).reshape(-1, 5):
            rows.append((img, d))
    if not rows or npos == 0:
        return 0.0
    rows.sort(key=lambda r: -r[1][4])

    tp = np.zeros(len(rows))
    fp = np.zeros(len(rows))
    for i, (img, d) in enumerate(rows):
        gt = gt_by_image.get(img)
        if gt is None or len(gt) == 0:
            fp[i] = 1
            continue
        ious = _iou_matrix(d[None, :4], gt)[0]
        j = int(np.argmax(ious))
        diff = difficult_by_image.get(img)
        if ious[j] >= iou_thresh:
            if diff is not None and diff[j]:
                continue  # difficult gt: detection ignored entirely
            if not matched[img][j]:
                matched[img][j] = True
                tp[i] = 1
            else:
                fp[i] = 1  # duplicate detection of a matched gt
        else:
            fp[i] = 1

    ctp = np.cumsum(tp)
    cfp = np.cumsum(fp)
    rec = ctp / float(npos)
    prec = ctp / np.maximum(ctp + cfp, np.finfo(np.float64).eps)
    return voc_ap(rec, prec, use_07_metric)


def voc_ap_from_arrays(gt_by_image: Dict, dets: List[np.ndarray],
                       iou_thresh: float = 0.5,
                       use_07_metric: bool = False) -> float:
    """AP where dets is indexed by position: dets[i] = (D,5) for image id i
    (the all_boxes[class] layout of pred_eval)."""
    det_by_image = {
        i: d for i, d in enumerate(dets) if d is not None and len(d)
    }
    return eval_class(gt_by_image, det_by_image, None, iou_thresh, use_07_metric)
