"""Module-level logger (reference: rcnn/logger.py)."""

import logging

logging.basicConfig(
    format="%(asctime)s %(levelname)s %(message)s", level=logging.INFO
)
logger = logging.getLogger("mx_rcnn_tpu")
