"""Mask toolkit: RLE codec + rasterization (pycocotools mask API analog).

Reference: rcnn/pycocotools/mask.py public surface (encode/decode/merge/
iou/area/frPyObjects/toBbox) over the C maskApi (rcnn/pycocotools/maskApi.c).
"""

from mx_rcnn_tpu.masks.rle import (
    area,
    compress,
    decode,
    decompress,
    encode,
    fr_bbox,
    fr_poly,
    fr_py_objects,
    iou,
    merge,
    poly_box_frame_mask,
    poly_to_mask,
    to_bbox,
)

__all__ = [
    "area", "compress", "decode", "decompress", "encode", "fr_bbox",
    "fr_poly", "fr_py_objects", "iou", "merge", "poly_box_frame_mask",
    "poly_to_mask", "to_bbox",
]
