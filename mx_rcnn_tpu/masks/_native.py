"""ctypes bridge to the C RLE kernels (cc/maskapi.c).

The reference ships its mask engine as C compiled at install time
(rcnn/pycocotools/setup.py building _mask.pyx + maskApi.c); here the shared
library is built on first use with the system compiler into
``cc/build/libmaskapi.so`` and loaded via ctypes (pybind11 is unavailable
in this environment — SURVEY.md §8). Every entry point degrades to the
numpy implementation in rle.py when the toolchain or the .so is missing,
so the native layer is a pure accelerator, never a dependency.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional, Sequence

import numpy as np

from mx_rcnn_tpu.utils.native_build import build_and_load

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "cc", "maskapi.c")
_SO = os.path.join(_REPO, "cc", "build", "libmaskapi.so")

_lib = None
_tried = False
_init_lock = threading.Lock()


def _bind(lib):
    u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    lib.rle_encode.restype = ctypes.c_long
    lib.rle_encode.argtypes = [u8p, ctypes.c_long, u32p]
    lib.rle_decode.restype = ctypes.c_long
    lib.rle_decode.argtypes = [u32p, ctypes.c_long, u8p, ctypes.c_long]
    lib.rle_area.restype = ctypes.c_long
    lib.rle_area.argtypes = [u32p, ctypes.c_long]
    lib.rle_merge.restype = ctypes.c_long
    lib.rle_merge.argtypes = [u32p, ctypes.c_long, u32p, ctypes.c_long,
                              u32p, ctypes.c_int]
    lib.rle_iou.restype = None
    lib.rle_iou.argtypes = [u32p, i64p, i64p, ctypes.c_long,
                            u32p, i64p, i64p, ctypes.c_long,
                            u8p, f64p]


def get_lib():
    """The loaded CDLL, building it if needed; None if unavailable.

    Build/load/staleness/race handling lives in utils/native_build.py
    (shared with data/_native_img.py).
    """
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _init_lock:
        if _lib is None and not _tried:
            _lib = build_and_load(_SRC, _SO, _bind)
            _tried = True
    return _lib


def available() -> bool:
    return get_lib() is not None


# -- numpy-facing wrappers (counts as uint32 arrays) ------------------------


def encode_counts(mask: np.ndarray) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    flat = np.asfortranarray(mask.astype(np.uint8)).ravel(order="F")
    flat = np.ascontiguousarray(flat)
    out = np.empty(flat.size + 1, np.uint32)
    m = lib.rle_encode(flat, flat.size, out)
    return out[:m].copy()


def decode_counts(counts: np.ndarray, h: int, w: int) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    counts = np.ascontiguousarray(counts, np.uint32)
    mask = np.empty(h * w, np.uint8)
    rc = lib.rle_decode(counts, counts.size, mask, mask.size)
    if rc != 0:
        raise ValueError(f"RLE length {int(counts.sum())} != h*w {h * w}")
    return mask.reshape(w, h).T


def area_counts(counts: np.ndarray) -> Optional[int]:
    lib = get_lib()
    if lib is None:
        return None
    counts = np.ascontiguousarray(counts, np.uint32)
    return int(lib.rle_area(counts, counts.size))


def merge_counts(ca: np.ndarray, cb: np.ndarray,
                 intersect: bool) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    ca = np.ascontiguousarray(ca, np.uint32)
    cb = np.ascontiguousarray(cb, np.uint32)
    out = np.empty(ca.size + cb.size + 2, np.uint32)
    m = lib.rle_merge(ca, ca.size, cb, cb.size, out, int(intersect))
    return out[:m].copy()


def iou_counts(dt: Sequence[np.ndarray], gt: Sequence[np.ndarray],
               iscrowd: Sequence[bool]) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None

    def pack(rles: Sequence[np.ndarray]):
        lens = np.asarray([r.size for r in rles], np.int64)
        offs = np.zeros(len(rles), np.int64)
        if len(rles) > 1:
            offs[1:] = np.cumsum(lens)[:-1]
        packed = (np.concatenate([np.ascontiguousarray(r, np.uint32)
                                  for r in rles])
                  if rles else np.zeros(0, np.uint32))
        return np.ascontiguousarray(packed), offs, lens

    dp, do, dl = pack(list(dt))
    gp, go, gl = pack(list(gt))
    out = np.zeros((len(dt), len(gt)), np.float64)
    if len(dt) and len(gt):
        crowd = np.asarray(iscrowd, np.uint8)
        lib.rle_iou(dp, do, dl, len(dt), gp, go, gl, len(gt), crowd,
                    out.reshape(-1))
    return out
