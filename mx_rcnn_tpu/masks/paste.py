"""Paste predicted ROI masks into full-image binary masks.

The Mask R-CNN inference tail (Detectron lineage, and the reference's
pycocotools consumers): the head's (m, m) sigmoid probabilities live in the
detection box's frame; producing a COCO segm result means bilinear-resizing
them to the box extent, thresholding at 0.5, and writing into an (H, W)
canvas clipped to the image. Host-side numpy — this feeds json/RLE encoding,
never the TPU.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from mx_rcnn_tpu.masks.rle import RLE, encode


def _resize_bilinear_1d(m: int, out: int) -> np.ndarray:
    """(out, m) bilinear interpolation weights, align_corners=False (the
    cv2.resize convention Detectron's paste uses)."""
    if out <= 0:
        return np.zeros((0, m), np.float64)
    # Output pixel centres mapped into input coordinates.
    u = (np.arange(out, dtype=np.float64) + 0.5) * (m / out) - 0.5
    u = np.clip(u, 0.0, m - 1.0)
    grid = np.arange(m, dtype=np.float64)
    return np.maximum(0.0, 1.0 - np.abs(u[:, None] - grid[None, :]))


def paste_mask(prob: np.ndarray, box: Sequence[float], h: int, w: int,
               thresh: float = 0.5) -> np.ndarray:
    """(m, m) probabilities + inclusive (x1, y1, x2, y2) box → (H, W) uint8.

    The box is rounded outward to whole pixels (floor/ceil) and intersected
    with the image; the mask is resized to the box size and thresholded.
    """
    m = prob.shape[0]
    x1 = int(np.floor(box[0]))
    y1 = int(np.floor(box[1]))
    x2 = int(np.ceil(box[2]))
    y2 = int(np.ceil(box[3]))
    bw = max(x2 - x1 + 1, 1)
    bh = max(y2 - y1 + 1, 1)
    wy = _resize_bilinear_1d(m, bh)  # (bh, m)
    wx = _resize_bilinear_1d(m, bw)  # (bw, m)
    big = wy @ prob.astype(np.float64) @ wx.T  # (bh, bw)
    canvas = np.zeros((h, w), np.uint8)
    ix1, iy1 = max(x1, 0), max(y1, 0)
    ix2, iy2 = min(x2, w - 1), min(y2, h - 1)
    if ix2 >= ix1 and iy2 >= iy1:
        crop = big[iy1 - y1:iy2 - y1 + 1, ix1 - x1:ix2 - x1 + 1]
        canvas[iy1:iy2 + 1, ix1:ix2 + 1] = (crop >= thresh).astype(np.uint8)
    return canvas


def paste_masks_to_rles(probs: np.ndarray, boxes: np.ndarray, h: int, w: int,
                        thresh: float = 0.5) -> list:
    """Batch paste_mask + RLE-encode: (N, m, m) + (N, 4) → N compressed RLEs."""
    return [encode(paste_mask(p, b, h, w, thresh))
            for p, b in zip(probs, boxes)]
