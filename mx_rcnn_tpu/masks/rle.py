"""RLE mask API — the pycocotools mask toolkit, rebuilt.

Reference: rcnn/pycocotools/ (`mask.py`, `_mask.pyx`, `maskApi.c/.h`) — the
vendored C run-length-encoding kernels COCO evaluation depends on
(encode/decode/merge/iou/area, polygon + bbox rasterization, and the
compressed-string codec used by COCO json `segmentation` fields). This
environment has no pycocotools, so the API is re-provided here: an exact
numpy implementation (this module) with an optional C fast path
(mx_rcnn_tpu/masks/_native.py wrapping cc/maskapi.c via ctypes) for the
dense-mask hot calls. Host-side, eval-only code — nothing here traces.

RLE format (identical to pycocotools):
  a binary (H, W) mask is read in COLUMN-major (Fortran) order; `counts`
  holds alternating run lengths, starting with the count of 0s (possibly 0).
  `{"size": [h, w], "counts": [...]}` is the uncompressed dict form;
  `{"size": [h, w], "counts": b"..."}` is the compressed form using the
  COCO varint/delta string codec (see `compress`).

Design deltas vs the reference, documented per SURVEY.md §3.1 item 5:
  - polygon rasterization uses a standard even-odd scanline fill at pixel
    centers rather than maskApi's 5x-upsampled boundary walk; boundary
    pixels can differ by ±1 on polygon edges (irrelevant to the eval
    protocol, which is validated against hand-computed cases).
"""

from __future__ import annotations

from typing import List, Sequence, Union

import numpy as np

RLE = dict  # {"size": [h, w], "counts": list[int] | bytes}


# ---------------------------------------------------------------------------
# Core encode / decode
# ---------------------------------------------------------------------------


def encode(mask: np.ndarray) -> RLE:
    """Binary (H, W) mask -> compressed RLE.

    Matches pycocotools.mask.encode for a single mask (pass masks
    individually; the (H, W, N) batched form is a thin loop away).
    Dispatches to the C kernel (cc/maskapi.c) when built.
    """
    from mx_rcnn_tpu.masks import _native

    h, w = mask.shape
    counts = _native.encode_counts(mask)
    if counts is None:
        flat = np.asfortranarray(mask.astype(bool)).ravel(order="F")
        counts = _runs(flat)
    return {"size": [int(h), int(w)], "counts": compress(counts)}


def decode(rle: RLE) -> np.ndarray:
    """RLE (compressed or not) -> binary (H, W) uint8 mask."""
    from mx_rcnn_tpu.masks import _native

    h, w = rle["size"]
    counts = _counts(rle)
    total = int(sum(counts))
    if total != h * w:
        raise ValueError(f"RLE length {total} != h*w {h * w}")
    if _native.available():
        return _native.decode_counts(np.asarray(counts, np.uint32), h, w)
    flat = np.zeros(h * w, np.uint8)
    pos = 0
    val = 0
    for c in counts:
        if val:
            flat[pos:pos + c] = 1
        pos += c
        val ^= 1
    return flat.reshape(w, h).T  # column-major -> (H, W)


def _runs(flat: np.ndarray) -> List[int]:
    """Run lengths of a flat boolean array, starting with the 0-run."""
    n = flat.shape[0]
    if n == 0:
        return []
    change = np.flatnonzero(flat[1:] != flat[:-1]) + 1
    bounds = np.concatenate([[0], change, [n]])
    runs = np.diff(bounds).tolist()
    if flat[0]:  # counts must start with a (possibly empty) 0-run
        runs = [0] + runs
    return [int(r) for r in runs]


def _counts(rle: RLE) -> List[int]:
    c = rle["counts"]
    if isinstance(c, (bytes, str)):
        return decompress(c)
    return list(c)


# ---------------------------------------------------------------------------
# Compressed-string codec (COCO json `counts` strings)
# ---------------------------------------------------------------------------
#
# Each count is a signed varint in base-32 "6-bit char" encoding (chars
# offset from 48), more significant groups later, with bit 5 of each char as
# the continuation flag; counts at index > 2 store the DELTA to counts[i-2]
# (maskApi rleToString: the first three counts are stored raw).


def compress(counts: Sequence[int]) -> bytes:
    out = bytearray()
    for i, c in enumerate(counts):
        x = int(c)
        if i > 2:
            x -= int(counts[i - 2])
        more = True
        while more:
            chunk = x & 0x1F
            x >>= 5
            # Sign-aware termination: stop when remaining bits are pure sign
            # extension of the chunk's high bit.
            more = not (x == -1 and (chunk & 0x10)) and not (
                x == 0 and not (chunk & 0x10))
            if more:
                chunk |= 0x20
            out.append(chunk + 48)
    return bytes(out)


def decompress(s: Union[bytes, str]) -> List[int]:
    if isinstance(s, str):
        s = s.encode("ascii")
    counts: List[int] = []
    pos = 0
    n = len(s)
    while pos < n:
        x = 0
        shift = 0
        while True:
            c = s[pos] - 48
            pos += 1
            x |= (c & 0x1F) << shift
            if not (c & 0x20):
                # Sign-extend from the top bit of the last chunk.
                if c & 0x10:
                    x |= -1 << (shift + 5)
                break
            shift += 5
        if len(counts) > 2:
            x += counts[-2]
        counts.append(int(x))
    return counts


# ---------------------------------------------------------------------------
# Derived ops: area / merge / iou / toBbox
# ---------------------------------------------------------------------------


def area(rle: RLE) -> int:
    counts = _counts(rle)
    return int(sum(counts[1::2]))


def merge(rles: Sequence[RLE], intersect: bool = False) -> RLE:
    """Union (default) or intersection of masks, all the same size.

    With the C kernels, the merge walks run lists directly and never
    materializes a dense mask (maskApi rleMerge behavior)."""
    from mx_rcnn_tpu.masks import _native

    if not rles:
        raise ValueError("merge of empty list")
    if len(rles) == 1:
        return {"size": list(rles[0]["size"]), "counts": compress(_counts(rles[0]))}
    h, w = rles[0]["size"]
    for r in rles[1:]:
        if list(r["size"]) != [h, w]:
            raise ValueError("merge of differently-sized masks")
    if _native.available():
        acc = np.asarray(_counts(rles[0]), np.uint32)
        for r in rles[1:]:
            acc = _native.merge_counts(
                acc, np.asarray(_counts(r), np.uint32), intersect)
        return {"size": [int(h), int(w)], "counts": compress(acc.tolist())}
    acc = decode(rles[0]).astype(bool)
    for r in rles[1:]:
        m = decode(r).astype(bool)
        acc = (acc & m) if intersect else (acc | m)
    return encode(acc)


def iou(dt: Sequence[RLE], gt: Sequence[RLE],
        iscrowd: Sequence[bool]) -> np.ndarray:
    """Pairwise mask IoU matrix (len(dt), len(gt)).

    Crowd semantics (maskApi rleIou): for a crowd gt the denominator is the
    DETECTION's area (i.e. intersection-over-detection), matching the
    reference's use for ignore regions. The C kernel computes intersection
    areas by run-walking, skipping dense decode entirely.
    """
    from mx_rcnn_tpu.masks import _native

    if _native.available():
        res = _native.iou_counts(
            [np.asarray(_counts(d), np.uint32) for d in dt],
            [np.asarray(_counts(g), np.uint32) for g in gt],
            list(iscrowd))
        if res is not None:
            return res
    out = np.zeros((len(dt), len(gt)), np.float64)
    dms = [decode(d).astype(bool) for d in dt]
    gms = [decode(g).astype(bool) for g in gt]
    das = [m.sum() for m in dms]
    gas = [m.sum() for m in gms]
    for j, gm in enumerate(gms):
        for i, dm in enumerate(dms):
            inter = np.logical_and(dm, gm).sum()
            if iscrowd[j]:
                denom = das[i]
            else:
                denom = das[i] + gas[j] - inter
            out[i, j] = inter / denom if denom > 0 else 0.0
    return out


def to_bbox(rle: RLE) -> np.ndarray:
    """RLE -> (x, y, w, h) tight bbox (maskApi rleToBbox)."""
    m = decode(rle)
    ys, xs = np.nonzero(m)
    if ys.size == 0:
        return np.zeros(4, np.float64)
    x0, x1 = xs.min(), xs.max()
    y0, y1 = ys.min(), ys.max()
    return np.asarray([x0, y0, x1 - x0 + 1, y1 - y0 + 1], np.float64)


# ---------------------------------------------------------------------------
# Rasterization: polygons / bboxes -> RLE (maskApi rleFrPoly / rleFrBbox)
# ---------------------------------------------------------------------------


def poly_to_mask(poly: Sequence[float], h: int, w: int) -> np.ndarray:
    """Rasterize one polygon [x0, y0, x1, y1, ...] to a (H, W) mask.

    Even-odd scanline fill sampled at pixel centers (x+0.5, y+0.5). See the
    module docstring for the (boundary-pixel) delta vs maskApi's upsampled
    boundary walk.
    """
    xs = np.asarray(poly[0::2], np.float64)
    ys = np.asarray(poly[1::2], np.float64)
    n = xs.shape[0]
    if n < 3:
        return np.zeros((h, w), np.uint8)
    x0 = xs
    y0 = ys
    x1 = np.roll(xs, -1)
    y1 = np.roll(ys, -1)
    yc = np.arange(h, dtype=np.float64) + 0.5  # scanline centers (H,)
    # Edge k crosses scanline y iff min < y <= max — half-open, so a vertex
    # shared by two edges is counted exactly once.
    ymin = np.minimum(y0, y1)[None, :]
    ymax = np.maximum(y0, y1)[None, :]
    crosses = (yc[:, None] > ymin) & (yc[:, None] <= ymax)  # (H, E)
    dy = y1 - y0
    safe_dy = np.where(dy == 0, 1.0, dy)
    t = (yc[:, None] - y0[None, :]) / safe_dy[None, :]
    xi = x0[None, :] + t * (x1 - x0)[None, :]  # (H, E) crossing x
    # Non-crossing edges must never count as "to the right" -> -inf.
    xi = np.where(crosses, xi, -np.inf)
    xc = np.arange(w, dtype=np.float64) + 0.5  # pixel-center x (W,)
    # Pixel inside iff an odd number of crossings lie to its right.
    cnt = (xi[:, None, :] > xc[None, :, None]).sum(axis=2)  # (H, W)
    return ((cnt % 2) == 1).astype(np.uint8)


def fr_poly(polys: Sequence[Sequence[float]], h: int, w: int) -> RLE:
    """Multi-part polygon -> merged RLE (pycocotools frPyObjects + merge)."""
    m = np.zeros((h, w), bool)
    for poly in polys:
        m |= poly_to_mask(poly, h, w).astype(bool)
    return encode(m)


def fr_bbox(bbox: Sequence[float], h: int, w: int) -> RLE:
    """(x, y, w, h) box -> RLE (maskApi rleFrBbox: pixel (i,j) is inside iff
    its center is within the box extent, via integer rounding of edges)."""
    x, y, bw, bh = bbox
    m = np.zeros((h, w), np.uint8)
    x0 = int(np.floor(x + 0.5))
    y0 = int(np.floor(y + 0.5))
    x1 = int(np.floor(x + bw + 0.5))
    y1 = int(np.floor(y + bh + 0.5))
    m[max(y0, 0):max(y1, 0), max(x0, 0):max(x1, 0)] = 1
    return encode(m)


def poly_box_frame_mask(polys: Sequence[Sequence[float]],
                        box: Sequence[float], m: int) -> np.ndarray:
    """Rasterize polygons into an (m, m) mask over the gt BOX frame.

    This is the storage form the mask-target pipeline uses
    (targets/mask_targets.py): each instance's mask kept at a fixed
    resolution over its own box, so ROI targets resample it in-graph. box is
    (x1, y1, x2, y2) inclusive image coords; polygon coords are image-frame.
    """
    x1, y1, x2, y2 = box
    w = max(float(x2) - float(x1) + 1.0, 1.0)
    h = max(float(y2) - float(y1) + 1.0, 1.0)
    out = np.zeros((m, m), bool)
    for poly in polys:
        p = np.asarray(poly, np.float64).reshape(-1, 2)
        q = np.empty_like(p)
        q[:, 0] = (p[:, 0] - x1) / w * m
        q[:, 1] = (p[:, 1] - y1) / h * m
        out |= poly_to_mask(q.ravel().tolist(), m, m).astype(bool)
    return out.astype(np.uint8)


def fr_py_objects(obj, h: int, w: int) -> RLE:
    """COCO `segmentation` field (polygon list / RLE dict / uncompressed
    dict) -> compressed RLE. pycocotools.mask.frPyObjects equivalent for the
    single-object case."""
    if isinstance(obj, dict):
        return {"size": list(obj["size"]), "counts": compress(_counts(obj))}
    if isinstance(obj, (list, tuple)) and obj and isinstance(
            obj[0], (list, tuple)):
        return fr_poly(obj, h, w)
    if isinstance(obj, (list, tuple)):
        return fr_poly([obj], h, w)
    raise TypeError(f"unsupported segmentation object: {type(obj)}")
