"""Model graphs — flax modules replacing the reference's symbolic networks.

Reference layer: rcnn/symbol/symbol_vgg.py and rcnn/symbol/symbol_resnet.py
(get_*_train / get_*_test symbol builders). Here the graph is a flax module
tree + pure functions (`forward_train`, `forward_test`) instead of a static
symbol graph; train/test variants share parameters by construction.
"""

from mx_rcnn_tpu.models.backbones import ResNetC4, ResNetHead, VGGConv, VGGHead
from mx_rcnn_tpu.models.rpn import RPNHead
from mx_rcnn_tpu.models.faster_rcnn import (
    FasterRCNN,
    build_model,
    forward_test,
    forward_train,
)

__all__ = [
    "ResNetC4",
    "ResNetHead",
    "VGGConv",
    "VGGHead",
    "RPNHead",
    "FasterRCNN",
    "build_model",
    "forward_train",
    "forward_test",
]
