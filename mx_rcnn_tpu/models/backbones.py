"""Backbones: ResNet-50/101 C4 and VGG-16, TPU-native.

Replaces the reference's symbolic graph builders (rcnn/symbol/symbol_resnet.py
``residual_unit``/``get_resnet_conv`` and rcnn/symbol/symbol_vgg.py
``get_vgg_conv``) with flax modules. Deliberate deltas from the reference,
chosen for TPU:

- NHWC layout (MXU-native) instead of the reference's NCHW (cuDNN-native).
- bfloat16 compute / float32 params via flax ``dtype``/``param_dtype``.
- Frozen BatchNorm is an affine constant (reference: BN with
  ``use_global_stats=True`` and fixed gamma/beta) — params carry
  ``stop_gradient`` in the forward so the backward pass is structurally free,
  and the trainer additionally masks them out of the optimizer.
- The frozen prefix (reference ``fixed_param_prefix``: ResNet conv0+stage1,
  VGG conv1-conv2) is a ``stop_gradient`` cut on the activation at the freeze
  boundary, so XLA never materializes the early backward graph at all —
  cheaper than the reference's approach of computing and discarding nothing
  (MXNet skips those grads too via fixed_param_names; we keep parity).
- ResNet block is the post-activation v1.5 bottleneck (stride on the 3x3).
  The reference uses the tornadomeet v2 pre-act variant; since pretrained
  MXNet checkpoints cannot be loaded in this environment the standard
  detection (Detectron-lineage) block is used and documented here.
- ``norm="group"`` swaps FrozenBatchNorm for GroupNorm(32) (Wu & He).
  Frozen BN is only sound when restoring PRETRAINED statistics — the
  reference always fine-tunes from an ImageNet checkpoint
  (train_end2end.py --pretrained). Training from scratch (the only option
  in this offline environment) with identity-initialized frozen BN is
  numerically unstable; GroupNorm is the batch-independent, SPMD-friendly
  alternative detection codebases use for from-scratch runs. Default stays
  "frozen_bn" for reference parity.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any

STAGE_BLOCKS = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}


class FrozenBatchNorm(nn.Module):
    """BN with frozen statistics AND frozen affine (reference semantics).

    Reference: rcnn/symbol/symbol_resnet.py BatchNorm(use_global_stats=True,
    fixed gamma/beta via fixed_param_prefix). At train and test time this is
    y = gamma * (x - mean) * rsqrt(var + eps) + beta with every tensor
    constant, which XLA folds into the preceding conv.
    """

    features: int
    eps: float = 1e-5
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        f = self.features
        gamma = self.param("gamma", nn.initializers.ones, (f,), jnp.float32)
        beta = self.param("beta", nn.initializers.zeros, (f,), jnp.float32)
        mean = self.param("moving_mean", nn.initializers.zeros, (f,), jnp.float32)
        var = self.param("moving_var", nn.initializers.ones, (f,), jnp.float32)
        # Fold to a single scale/bias pair; stop_gradient makes freezing
        # structural (no backward graph through BN params).
        scale = jax.lax.stop_gradient(gamma * jax.lax.rsqrt(var + self.eps))
        bias = jax.lax.stop_gradient(beta - mean * scale)
        return x * scale.astype(self.dtype) + bias.astype(self.dtype)


def make_norm(norm: str, features: int, dtype: Dtype, name: str):
    """Norm-layer factory: "frozen_bn" (reference parity) or "group"."""
    if norm == "frozen_bn":
        return FrozenBatchNorm(features, dtype=dtype, name=name)
    if norm == "group":
        return nn.GroupNorm(num_groups=min(32, features), dtype=dtype,
                            param_dtype=jnp.float32, name=name)
    raise ValueError(f"unknown norm {norm!r}")


class Bottleneck(nn.Module):
    """ResNet v1.5 bottleneck: 1x1 -> 3x3(stride) -> 1x1, post-activation.

    graftcanvas masks: `mask_in` (input stride) re-zeros packed-canvas
    gap cells on the 3x3 conv's INPUT — the 1x1 conv + norm turn masked
    zeros into a bias value (frozen-BN beta, GroupNorm bias), and the
    3x3 is the block's only cross-cell read, so masking exactly there
    makes every spatial window see zeros beyond the content boundary,
    identical to the bucketed path's implicit SAME padding. `mask_out`
    (output stride) re-zeros the block output so the NEXT cross-cell
    consumer (the following block's 3x3, the RPN head, ROIAlign border
    taps) reads clean gaps too. None = no-op (bucketed path HLO
    unchanged)."""

    filters: int  # inner width; output is 4*filters
    stride: int = 1
    norm: str = "frozen_bn"
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, mask_in=None,
                 mask_out=None) -> jnp.ndarray:
        needs_proj = x.shape[-1] != self.filters * 4 or self.stride != 1
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype,
                    param_dtype=jnp.float32, name="conv1")(x)
        y = make_norm(self.norm, self.filters, self.dtype, "bn1")(y)
        y = nn.relu(y)
        if mask_in is not None:
            y = y * mask_in.astype(y.dtype)
        y = nn.Conv(self.filters, (3, 3), strides=(self.stride, self.stride),
                    padding=[(1, 1), (1, 1)], use_bias=False, dtype=self.dtype,
                    param_dtype=jnp.float32, name="conv2")(y)
        y = make_norm(self.norm, self.filters, self.dtype, "bn2")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False, dtype=self.dtype,
                    param_dtype=jnp.float32, name="conv3")(y)
        y = make_norm(self.norm, self.filters * 4, self.dtype, "bn3")(y)
        if needs_proj:
            residual = nn.Conv(self.filters * 4, (1, 1),
                               strides=(self.stride, self.stride),
                               use_bias=False, dtype=self.dtype,
                               param_dtype=jnp.float32, name="downsample_conv")(x)
            residual = make_norm(self.norm, self.filters * 4, self.dtype,
                                 "downsample_bn")(residual)
        out = nn.relu(y + residual)
        if mask_out is not None:
            out = out * mask_out.astype(out.dtype)
        return out


class ResNetStage(nn.Module):
    """graftcanvas masks (ops/canvas.py::placement_masks): `mask_in` at
    the stage's INPUT stride and `mask` at its OUTPUT stride. Block 0
    (which may downsample) reads mask_in for its 3x3 input, every later
    block the output-stride mask; all blocks re-zero their outputs
    (Bottleneck.mask_out) so packed-canvas gap cells stay exactly zero
    through the stage. None = no-op (identical HLO to the pre-canvas
    code)."""

    blocks: int
    filters: int
    stride: int
    norm: str = "frozen_bn"
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, mask_in=None, mask=None) -> jnp.ndarray:
        for i in range(self.blocks):
            x = Bottleneck(self.filters, stride=self.stride if i == 0 else 1,
                           norm=self.norm, dtype=self.dtype,
                           name=f"block{i}")(x, mask_in if i == 0 else mask,
                                             mask)
        return x


class ResNetC4(nn.Module):
    """ResNet conv0 + stages 1-3 -> stride-16, 1024-channel C4 features.

    Reference: rcnn/symbol/symbol_resnet.py get_resnet_conv (units for 50/101
    layers, ends at the stage-4-in-torch-numbering res4 block). ``freeze_at=2``
    reproduces fixed_param_prefix=['conv0','stage1'] via an activation
    stop_gradient cut.
    """

    depth: int = 50
    freeze_at: int = 2  # 0=no freeze, 1=stem, 2=stem+stage1 (reference default)
    norm: str = "frozen_bn"
    dtype: Dtype = jnp.bfloat16
    remat: bool = False  # rematerialize stage activations in the backward

    @nn.compact
    def __call__(self, x: jnp.ndarray, masks=None) -> jnp.ndarray:
        """masks (graftcanvas): {stride: (B, H/s, W/s, 1)} placement
        masks of a packed canvas; gap cells are re-zeroed after the stem
        (before AND after the max-pool — the pool's post-relu window max
        over zero gap cells matches the bucketed -inf edge padding only
        when its inputs are masked) and after every residual block
        (ResNetStage.mask). None = the classic bucketed path."""
        blocks = STAGE_BLOCKS[self.depth]
        m = masks or {}
        # jax.checkpoint per stage: trades ~1/3 extra FLOPs for not keeping
        # every block's activations live through the backward — the HBM
        # lever for big images / batch > 1 (network.remat).
        Stage = nn.remat(ResNetStage) if self.remat else ResNetStage
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, param_dtype=jnp.float32,
                    name="conv0")(x)
        x = make_norm(self.norm, 64, self.dtype, "bn0")(x)
        x = nn.relu(x)
        if 2 in m:
            x = x * m[2].astype(x.dtype)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        if 4 in m:
            x = x * m[4].astype(x.dtype)
        if self.freeze_at >= 1:
            x = jax.lax.stop_gradient(x)
        x = Stage(blocks[0], 64, stride=1, norm=self.norm,
                  dtype=self.dtype, name="stage1")(x, m.get(4), m.get(4))
        if self.freeze_at >= 2:
            x = jax.lax.stop_gradient(x)
        x = Stage(blocks[1], 128, stride=2, norm=self.norm,
                  dtype=self.dtype, name="stage2")(x, m.get(4), m.get(8))
        x = Stage(blocks[2], 256, stride=2, norm=self.norm,
                  dtype=self.dtype, name="stage3")(x, m.get(8), m.get(16))
        return x  # (B, H/16, W/16, 1024)


class ResNetStages(nn.Module):
    """All four stages with per-stage outputs — the FPN backbone variant.

    Returns (C2, C3, C4, C5) at strides (4, 8, 16, 32).
    """

    depth: int = 50
    freeze_at: int = 2
    norm: str = "frozen_bn"
    dtype: Dtype = jnp.bfloat16
    remat: bool = False  # see ResNetC4.remat

    @nn.compact
    def __call__(self, x: jnp.ndarray, masks=None) -> Sequence[jnp.ndarray]:
        """masks: packed-canvas placement masks (see ResNetC4)."""
        blocks = STAGE_BLOCKS[self.depth]
        m = masks or {}
        Stage = nn.remat(ResNetStage) if self.remat else ResNetStage
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, param_dtype=jnp.float32,
                    name="conv0")(x)
        x = make_norm(self.norm, 64, self.dtype, "bn0")(x)
        x = nn.relu(x)
        if 2 in m:
            x = x * m[2].astype(x.dtype)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        if 4 in m:
            x = x * m[4].astype(x.dtype)
        if self.freeze_at >= 1:
            x = jax.lax.stop_gradient(x)
        c2 = Stage(blocks[0], 64, stride=1, norm=self.norm,
                   dtype=self.dtype, name="stage1")(x, m.get(4), m.get(4))
        if self.freeze_at >= 2:
            c2 = jax.lax.stop_gradient(c2)
        c3 = Stage(blocks[1], 128, stride=2, norm=self.norm,
                   dtype=self.dtype, name="stage2")(c2, m.get(4), m.get(8))
        c4 = Stage(blocks[2], 256, stride=2, norm=self.norm,
                   dtype=self.dtype, name="stage3")(c3, m.get(8), m.get(16))
        c5 = Stage(blocks[3], 512, stride=2, norm=self.norm,
                   dtype=self.dtype, name="stage4")(c4, m.get(16), m.get(32))
        return c2, c3, c4, c5


class ResNetHead(nn.Module):
    """C4 detection head: stage 5 on pooled 14x14 ROIs -> global avg pool.

    Reference: rcnn/symbol/symbol_resnet.py — ROIPooling 14x14 then the
    stage-5 residual blocks (stride 2 -> 7x7) then global average pooling,
    feeding cls_score/bbox_pred FCs.
    Input (R, 14, 14, 1024) -> output (R, 2048).
    """

    depth: int = 50
    norm: str = "frozen_bn"
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, rois_feat: jnp.ndarray) -> jnp.ndarray:
        blocks = STAGE_BLOCKS[self.depth]
        x = ResNetStage(blocks[3], 512, stride=2, norm=self.norm,
                        dtype=self.dtype,
                        name="stage4")(rois_feat.astype(self.dtype))
        return jnp.mean(x, axis=(1, 2))  # (R, 2048)


class VGGConv(nn.Module):
    """VGG-16 conv1_1..conv5_3, stride-16 512-channel features.

    Reference: rcnn/symbol/symbol_vgg.py get_vgg_conv (13 convs, 4 pools —
    pool5 omitted so the feature stride stays 16; conv1-conv2 frozen via
    fixed_param_prefix, here a stop_gradient cut after block 2).
    """

    freeze_blocks: int = 2
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, masks=None) -> jnp.ndarray:
        """masks: packed-canvas placement masks (see ResNetC4). VGG convs
        carry biases, so gap cells are re-zeroed after EVERY conv — a
        biased conv turns zeros into a bias halo that the next conv
        would read where the bucketed path reads implicit zero pad."""
        plan = ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512))
        m = masks or {}
        x = x.astype(self.dtype)
        stride = 1
        for b, (n_convs, width) in enumerate(plan, start=1):
            for c in range(1, n_convs + 1):
                x = nn.Conv(width, (3, 3), padding=[(1, 1), (1, 1)],
                            dtype=self.dtype, param_dtype=jnp.float32,
                            name=f"conv{b}_{c}")(x)
                x = nn.relu(x)
                if stride in m:
                    x = x * m[stride].astype(x.dtype)
            if b < 5:  # no pool5 — keep stride 16
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
                stride *= 2
            if b == self.freeze_blocks:
                x = jax.lax.stop_gradient(x)
        return x  # (B, H/16, W/16, 512)


class VGGHead(nn.Module):
    """fc6/fc7 head on 7x7 pooled ROIs (reference: symbol_vgg.py fc6, fc7).

    Input (R, 7, 7, 512) -> (R, 4096). Dropout as in the reference (0.5),
    active only when ``deterministic=False``.
    """

    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, rois_feat: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        r = rois_feat.shape[0]
        x = rois_feat.astype(self.dtype).reshape(r, -1)
        x = nn.Dense(4096, dtype=self.dtype, param_dtype=jnp.float32, name="fc6")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=deterministic)(x)
        x = nn.Dense(4096, dtype=self.dtype, param_dtype=jnp.float32, name="fc7")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=deterministic)(x)
        return x
