"""DETR — end-to-end set-prediction detector (stretch config 5, with ViTDet).

Carion et al., "End-to-End Object Detection with Transformers". The
reference repo predates this family entirely (SURVEY.md §3.2); the TPU
design choices:

- the Hungarian matcher runs IN-GRAPH via the auction assignment
  (ops/matching.py) — torch DETRs bounce to scipy on the host every step,
  the same serialization the reference suffered with its Python CustomOps;
- everything is static-shape: padded gt sets with validity masks flow
  straight into the matcher (invalid columns are never assigned);
- no NMS, no anchors, no proposals — but forward_test emits the SAME
  (rois, valid, scores, boxes) contract as the other families so
  Predictor/pred_eval drive it unchanged (the per-class NMS it applies is
  a near-no-op on DETR's non-overlapping predictions);
- class index 0 is "no object", matching the framework's background
  convention (DETR's ∅ class), down-weighted by eos_coef in the CE loss.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models.backbones import ResNetStages
from mx_rcnn_tpu.ops.boxes import generalized_iou_xyxy
from mx_rcnn_tpu.ops.matching import auction_assign
from mx_rcnn_tpu.ops.ring_attention import dense_attention
from mx_rcnn_tpu.train.precision import island, model_dtype

Dtype = Any


def sine_position_encoding(h: int, w: int, dim: int) -> np.ndarray:
    """2D sine/cosine positional encoding, (H, W, dim) — DETR's fixed PE."""
    assert dim % 4 == 0
    d = dim // 4
    ys = np.arange(h, dtype=np.float32)[:, None, None] + 0.5
    xs = np.arange(w, dtype=np.float32)[None, :, None] + 0.5
    freqs = np.exp(np.arange(d, dtype=np.float32) * -(np.log(10000.0) / d))
    yf = ys * freqs[None, None, :]
    xf = xs * freqs[None, None, :]
    pe = np.concatenate([
        np.broadcast_to(np.sin(yf), (h, w, d)),
        np.broadcast_to(np.cos(yf), (h, w, d)),
        np.broadcast_to(np.sin(xf), (h, w, d)),
        np.broadcast_to(np.cos(xf), (h, w, d)),
    ], axis=-1)
    return pe.astype(np.float32)


class MHA(nn.Module):
    """Multi-head attention with separate q/kv inputs (B, N, C) tokens."""

    dim: int
    heads: int
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, q_in, k_in, v_in):
        b, nq, _ = q_in.shape
        nk = k_in.shape[1]
        h = self.heads
        d = self.dim // h
        q = nn.Dense(self.dim, dtype=self.dtype, param_dtype=jnp.float32,
                     name="q")(q_in).reshape(b, nq, h, d)
        k = nn.Dense(self.dim, dtype=self.dtype, param_dtype=jnp.float32,
                     name="k")(k_in).reshape(b, nk, h, d)
        v = nn.Dense(self.dim, dtype=self.dtype, param_dtype=jnp.float32,
                     name="v")(v_in).reshape(b, nk, h, d)
        out = dense_attention(q, k, v).reshape(b, nq, self.dim)
        return nn.Dense(self.dim, dtype=self.dtype, param_dtype=jnp.float32,
                        name="proj")(out)


class EncoderLayer(nn.Module):
    dim: int
    heads: int
    ffn: int = 2048
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, pos):
        q = x + pos
        y = MHA(self.dim, self.heads, dtype=self.dtype, name="self_attn")(
            q, q, x)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="norm1")(x + y)
        y = nn.Dense(self.ffn, dtype=self.dtype, param_dtype=jnp.float32,
                     name="ffn1")(x)
        y = nn.Dense(self.dim, dtype=self.dtype, param_dtype=jnp.float32,
                     name="ffn2")(nn.relu(y))
        return nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                            name="norm2")(x + y)


class DecoderLayer(nn.Module):
    dim: int
    heads: int
    ffn: int = 2048
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, tgt, query_pos, memory, pos):
        q = tgt + query_pos
        y = MHA(self.dim, self.heads, dtype=self.dtype, name="self_attn")(
            q, q, tgt)
        tgt = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                           name="norm1")(tgt + y)
        y = MHA(self.dim, self.heads, dtype=self.dtype, name="cross_attn")(
            tgt + query_pos, memory + pos, memory)
        tgt = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                           name="norm2")(tgt + y)
        y = nn.Dense(self.ffn, dtype=self.dtype, param_dtype=jnp.float32,
                     name="ffn1")(tgt)
        y = nn.Dense(self.dim, dtype=self.dtype, param_dtype=jnp.float32,
                     name="ffn2")(nn.relu(y))
        return nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                            name="norm3")(tgt + y)


class DETR(nn.Module):
    """ResNet backbone (C5, stride 32) + transformer encoder-decoder."""

    depth: int = 50
    num_classes: int = 81  # index 0 = no-object
    num_queries: int = 100
    hidden: int = 256
    heads: int = 8
    enc_layers: int = 6
    dec_layers: int = 6
    norm: str = "frozen_bn"
    freeze_at: int = 2
    dtype: Dtype = jnp.bfloat16
    remat: bool = False

    @nn.compact
    def __call__(self, images: jnp.ndarray, aux_outputs: bool = False):
        """images (B, H, W, 3) → (logits (B, Q, C), boxes (B, Q, 4)).

        boxes are (cx, cy, w, h) in [0, 1] of the PADDED canvas.

        aux_outputs=True returns every decoder layer's predictions instead
        — (L, B, Q, C) / (L, B, Q, 4), final layer last — through the SAME
        norm + prediction heads (Carion et al. §3.2 auxiliary decoding
        losses use shared heads across layers).
        """
        feats = ResNetStages(depth=self.depth, freeze_at=self.freeze_at,
                             norm=self.norm, dtype=self.dtype,
                             remat=self.remat, name="backbone")(images)
        c5 = feats[3]  # stride 32
        b, h, w, _ = c5.shape
        x = nn.Conv(self.hidden, (1, 1), dtype=self.dtype,
                    param_dtype=jnp.float32, name="input_proj")(c5)
        pos = jnp.asarray(sine_position_encoding(h, w, self.hidden))
        pos = jnp.broadcast_to(pos[None], (b, h, w, self.hidden))
        x = x.reshape(b, h * w, self.hidden)
        pos = pos.reshape(b, h * w, self.hidden).astype(x.dtype)
        for i in range(self.enc_layers):
            x = EncoderLayer(self.hidden, self.heads, dtype=self.dtype,
                             name=f"enc{i}")(x, pos)
        query_pos = self.param("query_embed", nn.initializers.normal(1.0),
                               (self.num_queries, self.hidden), jnp.float32)
        query_pos = jnp.broadcast_to(
            query_pos[None], (b, self.num_queries, self.hidden)).astype(
                x.dtype)
        tgt = jnp.zeros_like(query_pos)
        layer_out = []
        for i in range(self.dec_layers):
            tgt = DecoderLayer(self.hidden, self.heads, dtype=self.dtype,
                               name=f"dec{i}")(tgt, query_pos, x, pos)
            layer_out.append(tgt)
        # (L, B, Q, H) or (1, B, Q, H): the heads below act on the last
        # axis only, so one application covers all layers with one set of
        # shared parameters either way.
        hs = jnp.stack(layer_out if aux_outputs else layer_out[-1:])
        hs = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                          name="dec_norm")(hs)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="class_embed")(
                              island(hs))
        y = island(hs)
        for i in range(2):
            y = nn.relu(nn.Dense(self.hidden, dtype=jnp.float32,
                                 name=f"bbox_mlp{i}")(y))
        boxes = jax.nn.sigmoid(
            nn.Dense(4, dtype=jnp.float32, name="bbox_out")(y))
        if aux_outputs:
            return logits, boxes
        return logits[0], boxes[0]


# ---------------------------------------------------------------------------
# Set-prediction loss with in-graph matching
# ---------------------------------------------------------------------------


def _cxcywh_to_xyxy(b: jnp.ndarray) -> jnp.ndarray:
    cx, cy, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def _giou_elementwise(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Row-wise gIoU of paired (N, 4) xyxy boxes — the loss only needs the
    matched pairs, not the (N, M) matrix the matcher's cost uses."""
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0) * jnp.clip(a[:, 3] - a[:, 1], 0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0) * jnp.clip(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, :2], b[:, :2])
    rb = jnp.minimum(a[:, 2:], b[:, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[:, 0] * wh[:, 1]
    union = area_a + area_b - inter
    iou = inter / jnp.maximum(union, 1e-9)
    hlt = jnp.minimum(a[:, :2], b[:, :2])
    hrb = jnp.maximum(a[:, 2:], b[:, 2:])
    hwh = jnp.clip(hrb - hlt, 0)
    hull = hwh[:, 0] * hwh[:, 1]
    return iou - (hull - union) / jnp.maximum(hull, 1e-9)


def _one_image_loss(logits, boxes, gt_boxes_n, gt_classes, gt_valid, *,
                    eos_coef, cost_class, cost_l1, cost_giou):
    """Matched set loss for one image. gt_boxes_n: (G, 4) xyxy NORMALIZED."""
    q = logits.shape[0]
    prob = jax.nn.softmax(logits, axis=-1)  # (Q, C)
    pred_xyxy = _cxcywh_to_xyxy(boxes)
    gt_cxcywh = jnp.stack([
        (gt_boxes_n[:, 0] + gt_boxes_n[:, 2]) / 2,
        (gt_boxes_n[:, 1] + gt_boxes_n[:, 3]) / 2,
        gt_boxes_n[:, 2] - gt_boxes_n[:, 0],
        gt_boxes_n[:, 3] - gt_boxes_n[:, 1],
    ], axis=-1)

    c_class = -prob[:, gt_classes]  # (Q, G)
    c_l1 = jnp.sum(jnp.abs(boxes[:, None, :] - gt_cxcywh[None, :, :]),
                   axis=-1)
    c_giou = -generalized_iou_xyxy(pred_xyxy, gt_boxes_n)
    cost = cost_class * c_class + cost_l1 * c_l1 + cost_giou * c_giou
    row_to_col, row_matched = auction_assign(
        jax.lax.stop_gradient(cost), gt_valid)

    # Classification: matched queries predict their gt class, the rest ∅
    # (class 0), weighted eos_coef.
    target = jnp.where(row_matched, gt_classes[row_to_col], 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ce = -jnp.take_along_axis(logp, target[:, None], axis=-1)[:, 0]
    wgt = jnp.where(row_matched, 1.0, eos_coef)
    cls_loss = jnp.sum(ce * wgt) / jnp.maximum(jnp.sum(wgt), 1e-6)

    # Box losses on matched pairs, normalized by gt count.
    n_gt = jnp.maximum(jnp.sum(island(gt_valid)), 1.0)
    mg = gt_cxcywh[row_to_col]
    l1 = jnp.sum(jnp.abs(boxes - mg), axis=-1) * row_matched
    l1_loss = jnp.sum(l1) / n_gt
    giou_matched = _giou_elementwise(pred_xyxy, gt_boxes_n[row_to_col])
    giou_loss = jnp.sum((1.0 - giou_matched) * row_matched) / n_gt
    acc = jnp.sum((jnp.argmax(logits, -1) == target) & row_matched)
    return cls_loss, l1_loss, giou_loss, acc, jnp.sum(row_matched)


def forward_train(model: DETR, params, batch: Dict[str, jnp.ndarray],
                  rng: jax.Array, cfg: Config
                  ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """DETR train forward — same batch contract as the other families."""
    images = batch["image"]
    b, hh, ww, _ = images.shape
    use_aux = cfg.train.detr_aux_loss
    # (L, B, Q, ·): every decoder layer's predictions through the shared
    # heads (Carion et al. §3.2 — the per-layer losses are reported as
    # important for convergence); L=1 (final layer only) when disabled.
    logits_all, boxes_all = model.apply(params, images, aux_outputs=use_aux)
    if not use_aux:
        logits_all, boxes_all = logits_all[None], boxes_all[None]
    scale = island(jnp.asarray([ww, hh, ww, hh]))
    gt_n = batch["gt_boxes"] / scale  # normalized xyxy

    per_image = lambda lg, bx, g, c, v: _one_image_loss(  # noqa: E731
        lg, bx, g, c, v,
        eos_coef=cfg.train.detr_eos_coef,
        cost_class=cfg.train.detr_cost_class,
        cost_l1=cfg.train.detr_cost_l1,
        cost_giou=cfg.train.detr_cost_giou)
    # outer vmap: decoder layers (each re-matched, as in the paper);
    # inner vmap: batch. Shapes (L, B).
    cls_l, l1_l, giou_l, acc, nmatch = jax.vmap(
        lambda lg, bx: jax.vmap(per_image)(
            lg, bx, gt_n, batch["gt_classes"], batch["gt_valid"])
    )(logits_all, boxes_all)

    cls_per_layer = jnp.mean(cls_l, axis=1)                          # (L,)
    l1_per_layer = jnp.mean(l1_l, axis=1) * cfg.train.detr_cost_l1
    giou_per_layer = jnp.mean(giou_l, axis=1) * cfg.train.detr_cost_giou
    total = jnp.sum(cls_per_layer + l1_per_layer + giou_per_layer)
    aux = {
        # metric slots report the FINAL layer (comparable across configs);
        # total_loss carries the aux sum actually optimized.
        "rcnn_cls_loss": cls_per_layer[-1],
        "rcnn_bbox_loss": l1_per_layer[-1] + giou_per_layer[-1],
        "detr_giou_loss": giou_per_layer[-1],
        "total_loss": total,
        "num_fg": island(jnp.sum(nmatch[-1])),
    }
    return total, aux


def forward_test(model: DETR, params, images: jnp.ndarray,
                 im_info: jnp.ndarray, cfg: Config):
    """DETR inference in the framework's (rois, valid, scores, boxes)
    contract (see module docstring)."""
    b, hh, ww, _ = images.shape
    logits, nboxes = model.apply(params, images)
    q = nboxes.shape[1]
    c = logits.shape[-1]
    scale = island(jnp.asarray([ww, hh, ww, hh]))
    xyxy = _cxcywh_to_xyxy(nboxes) * scale  # padded-canvas pixels
    scores = jax.nn.softmax(logits, axis=-1)  # (B, Q, C); class 0 = ∅
    boxes_tiled = jnp.tile(xyxy, (1, 1, c))  # (B, Q, 4C)
    valid = jnp.ones((b, q), bool)
    return xyxy, valid, scores, boxes_tiled


def build_detr_model(cfg: Config) -> DETR:
    return DETR(
        depth=cfg.network.depth,
        num_classes=cfg.dataset.num_classes,
        num_queries=cfg.network.detr_queries,
        hidden=cfg.network.detr_hidden,
        heads=cfg.network.detr_heads,
        enc_layers=cfg.network.detr_enc_layers,
        dec_layers=cfg.network.detr_dec_layers,
        norm=cfg.network.norm,
        freeze_at=cfg.network.freeze_at,
        dtype=model_dtype(cfg),
        remat=cfg.network.remat,
    )


def init_detr_params(model: DETR, cfg: Config, rng: jax.Array,
                     image_shape=None):
    h, w = image_shape or (64, 64)
    return model.init(rng, jnp.zeros((1, h, w, 3), jnp.float32))
