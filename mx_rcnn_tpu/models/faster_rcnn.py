"""Faster R-CNN — model module + pure train/test forwards.

Replaces the reference's train/test symbol builders
(rcnn/symbol/symbol_vgg.py::get_vgg_train/get_vgg_test,
rcnn/symbol/symbol_resnet.py::get_resnet_train/get_resnet_test) and the
graph-embedded Proposal/ProposalTarget custom ops
(rcnn/symbol/proposal.py, rcnn/symbol/proposal_target.py).

The single biggest design delta vs the reference (SURVEY.md §8): the whole
step — backbone, RPN, proposal generation, anchor/ROI target assignment, ROI
pooling, heads, losses — is ONE traced XLA program. The reference bounces to
the host for ProposalTarget (numpy sampling) every step; here everything is
static-shape and stays on device.

Data layout: NHWC images, (B, N, ·) flattened anchor grids where
N = H/16 · W/16 · A, matching ops/anchors.anchor_grid ordering.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models.backbones import ResNetC4, ResNetHead, VGGConv, VGGHead
from mx_rcnn_tpu.models.losses import rcnn_losses, rpn_losses
from mx_rcnn_tpu.models.rpn import RPNHead
from mx_rcnn_tpu.ops.anchors import anchor_grid
from mx_rcnn_tpu.ops.boxes import bbox_pred, clip_boxes
from mx_rcnn_tpu.ops.proposal import generate_proposals
from mx_rcnn_tpu.ops.roi_align import roi_align, roi_pool
from mx_rcnn_tpu.targets.rcnn_targets import sample_rois
from mx_rcnn_tpu.targets.rpn_targets import assign_anchor
from mx_rcnn_tpu.train.precision import island, model_dtype


class FasterRCNN(nn.Module):
    """Backbone + RPN + box head as one parameter tree.

    Methods are exposed individually (via ``apply(..., method=...)``) so the
    train and test forwards can wire the non-parametric middle (proposals,
    target sampling, ROI pooling) differently while sharing parameters —
    the analog of the reference's get_*_train/get_*_test sharing arg_params.
    """

    backbone: str = "resnet50"  # "resnet50" | "resnet101" | "vgg"
    num_classes: int = 81
    num_anchors: int = 9
    roi_pool_size: int = 14
    roi_pool_type: str = "align"
    norm: str = "frozen_bn"
    freeze_at: int = 2
    dtype: Any = jnp.bfloat16
    remat: bool = False

    def setup(self):
        if self.backbone.startswith("resnet"):
            depth = int(self.backbone.replace("resnet", ""))
            self.features = ResNetC4(depth=depth, freeze_at=self.freeze_at,
                                     norm=self.norm, dtype=self.dtype,
                                     remat=self.remat)
            self.head = ResNetHead(depth=depth, norm=self.norm,
                                   dtype=self.dtype)
        elif self.backbone == "vgg":
            if self.remat:
                from mx_rcnn_tpu.logger import logger

                logger.warning("network.remat is not implemented for the "
                               "VGG backbone; running without remat")
            # freeze_at=0 (from-scratch profile) unfreezes conv1-2 too;
            # any other value keeps the reference's conv1-2 cut.
            self.features = VGGConv(
                freeze_blocks=0 if self.freeze_at == 0 else 2,
                dtype=self.dtype)
            self.head = VGGHead(dtype=self.dtype)
        else:
            raise ValueError(f"unknown backbone {self.backbone!r}")
        self.rpn = RPNHead(num_anchors=self.num_anchors, dtype=self.dtype)
        self.cls_score = nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=nn.initializers.normal(0.01), name="cls_score")
        self.bbox_pred = nn.Dense(
            self.num_classes * 4, dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=nn.initializers.normal(0.001), name="bbox_pred")

    def extract(self, images: jnp.ndarray, masks=None) -> jnp.ndarray:
        """masks (graftcanvas): {stride: (B, H/s, W/s, 1)} packed-canvas
        placement masks the backbone re-zeros its gap cells with
        (models/backbones.py). None = the classic bucketed path."""
        return self.features(images, masks)

    def rpn_forward(self, feat: jnp.ndarray):
        return self.rpn(feat)

    def box_head(self, pooled: jnp.ndarray, deterministic: bool = True):
        if self.backbone == "vgg":
            x = self.head(pooled, deterministic=deterministic)
        else:
            x = self.head(pooled)
        cls = island(self.cls_score(x))
        box = island(self.bbox_pred(x))
        return cls, box

    def __call__(self, images: jnp.ndarray, rois: jnp.ndarray):
        """Init-only path touching every submodule."""
        feat = self.extract(images)
        rpn_cls, rpn_box = self.rpn_forward(feat)
        pooled = roi_align(feat, rois, self.roi_pool_size, 1.0 / 16.0)
        cls, box = self.box_head(pooled)
        return feat, rpn_cls, rpn_box, cls, box


# ---------------------------------------------------------------------------
# Functional forwards
# ---------------------------------------------------------------------------


def _rpn_softmax(cls_logits: jnp.ndarray, num_anchors: int) -> jnp.ndarray:
    """(B,H,W,2A) logits, [bg×A, fg×A] layout → softmaxed probs same layout.

    Reference: rpn_cls_score reshape to (2, A·H·W) + SoftmaxOutput over the
    2-way axis (symbol_*.py rpn_cls_prob).
    """
    a = num_anchors
    bg, fg = cls_logits[..., :a], cls_logits[..., a:]
    m = jnp.maximum(bg, fg)
    ebg = jnp.exp(bg - m)
    efg = jnp.exp(fg - m)
    denom = ebg + efg
    return jnp.concatenate([ebg / denom, efg / denom], axis=-1)


def _pair_logits(cls_logits: jnp.ndarray, num_anchors: int) -> jnp.ndarray:
    """(B,H,W,2A) → (B, H·W·A, 2) per-anchor [bg, fg] logits."""
    b, h, w, _ = cls_logits.shape
    a = num_anchors
    bg = cls_logits[..., :a].reshape(b, -1)
    fg = cls_logits[..., a:].reshape(b, -1)
    return jnp.stack([bg, fg], axis=-1)


def _pool_rois(feat, rois, roi_valid, pool_size, pool_type,
               plane_of=None, windows=None):
    """Batched ROI pooling: (B,Hf,Wf,C) + (B,R,4) → (B·R,P,P,C).

    Builds the (batch_idx, x1..y2) 5-vector layout the pooling ops share with
    the reference's ROIPooling input convention.

    graftcanvas: on a packed batch `feat` holds PLANES — `plane_of` (B,)
    maps each image row to its plane, `windows` (B, 4) [y0, x0, h, w]
    clamps border samples to the image's own cells (ops/roi_align.py).
    """
    b, r = rois.shape[0], rois.shape[1]
    ids = (jnp.arange(b, dtype=jnp.float32) if plane_of is None
           else island(plane_of))
    batch_idx = jnp.repeat(ids, r)[:, None]
    flat = jnp.concatenate([batch_idx, rois.reshape(b * r, 4)], axis=1)
    if pool_type == "align":
        win = None if windows is None else jnp.repeat(windows, r, axis=0)
        pooled = roi_align(feat, flat, pool_size, 1.0 / 16.0, windows=win)
    else:
        pooled = roi_pool(feat, flat, pool_size, 1.0 / 16.0)
    # Zero padded slots so dead rois contribute nothing downstream.
    return pooled * roi_valid.reshape(b * r, 1, 1, 1).astype(pooled.dtype)


def _backbone_rpn(model: FasterRCNN, params, images: jnp.ndarray, cfg: Config,
                  masks=None):
    """Shared preamble: backbone features + RPN outputs + the anchor grid
    (compile-time const). Used by every forward variant."""
    feat = model.apply(params, images, masks, method=FasterRCNN.extract)
    rpn_cls_logits, rpn_bbox_deltas = model.apply(
        params, feat, method=FasterRCNN.rpn_forward)
    anchors = jnp.asarray(anchor_grid(
        feat.shape[1], feat.shape[2],
        stride=cfg.network.rpn_feat_stride,
        base_size=cfg.network.anchor_base_size,
        ratios=cfg.network.anchor_ratios,
        scales=cfg.network.anchor_scales,
    ))
    return feat, rpn_cls_logits, rpn_bbox_deltas, anchors


def _assign_anchors_batch(anchors, gt_boxes, gt_valid, im_info, rng,
                          cfg: Config):
    """vmapped assign_anchor over per-image rows (train-mode RPN
    targets). Rows may be bucketed (im_info (B, 3)) or graftcanvas
    packed ((B, 5) placement rows in canvas coordinates)."""
    b = gt_boxes.shape[0]
    return jax.vmap(
        partial(
            assign_anchor,
            rpn_batch_size=cfg.train.rpn_batch_size,
            rpn_fg_fraction=cfg.train.rpn_fg_fraction,
            positive_overlap=cfg.train.rpn_positive_overlap,
            negative_overlap=cfg.train.rpn_negative_overlap,
            allowed_border=cfg.train.rpn_allowed_border,
            clobber_positives=cfg.train.rpn_clobber_positives,
        ),
        in_axes=(None, 0, 0, 0, 0),
    )(anchors, gt_boxes, gt_valid, im_info, jax.random.split(rng, b))


def forward_train(
    model: FasterRCNN,
    params,
    batch: Dict[str, jnp.ndarray],
    rng: jax.Array,
    cfg: Config,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """One fused train forward: images → total loss + metric auxiliaries.

    batch keys: image (B,H,W,3) float32 (mean-subtracted), im_info (B,3),
    gt_boxes (B,G,4), gt_classes (B,G) int32, gt_valid (B,G) bool.

    graftcanvas: a PACKED batch (ops/canvas.py contract) instead carries
    canvas planes + (P, I, 5) placement im_info; the backbone runs once
    over the planes (gap cells re-masked) and placements thread through
    anchors/targets, proposals and ROI pooling so per-image semantics
    match the bucketed path (tests/test_canvas.py).
    """
    from mx_rcnn_tpu.ops.canvas import (is_packed_batch, packed_views,
                                        placement_masks, plane_take)
    from mx_rcnn_tpu.ops.proposal import generate_proposals_packed

    images = batch["image"]
    a = model.num_anchors
    stride = cfg.network.rpn_feat_stride
    packed = is_packed_batch(batch)
    if packed:
        from mx_rcnn_tpu.data.canvas import packed_strides

        v = packed_views(batch)
        im_info, plane_of = v["im_info"], v["plane_of"]
        gt = {k: v[k] for k in ("gt_boxes", "gt_classes", "gt_valid")}
        b = im_info.shape[0]
        windows = jnp.stack([im_info[:, 3], im_info[:, 4],
                             im_info[:, 0], im_info[:, 1]], axis=1)
        masks = placement_masks(batch["im_info"], images.shape[1:3],
                                packed_strides(cfg))
    else:
        im_info, plane_of, windows, masks = batch["im_info"], None, None, None
        gt = {k: batch[k] for k in ("gt_boxes", "gt_classes", "gt_valid")}
        b = images.shape[0]

    feat, rpn_cls_logits, rpn_bbox_deltas, anchors = _backbone_rpn(
        model, params, images, cfg, masks)

    # --- RPN targets (reference: assign_anchor on host in AnchorLoader) ---
    k_anchor, k_sample, k_drop = jax.random.split(rng, 3)
    rpn_t = _assign_anchors_batch(anchors, gt["gt_boxes"], gt["gt_valid"],
                                  im_info, k_anchor, cfg)

    rpn_logits_pairs = _pair_logits(rpn_cls_logits, a)
    rpn_deltas_rows = rpn_bbox_deltas.reshape(rpn_bbox_deltas.shape[0], -1, 4)
    if packed:
        # Per-plane head outputs → per-image rows over the canvas grid.
        rpn_logits_pairs = plane_take(rpn_logits_pairs, plane_of)
        rpn_deltas_rows = plane_take(rpn_deltas_rows, plane_of)
    rpn_l = rpn_losses(
        rpn_logits_pairs,
        rpn_deltas_rows,
        rpn_t.labels,
        rpn_t.bbox_targets,
        rpn_t.bbox_weights,
        cfg.train.rpn_batch_size,
    )

    # --- Proposals (reference: Proposal op; gradients do not flow) ---
    rpn_prob = _rpn_softmax(jax.lax.stop_gradient(rpn_cls_logits), a)
    if packed:
        p = rpn_prob.shape[0]
        fg = rpn_prob[..., a:].reshape(p, -1)
        rois, roi_valid, _ = generate_proposals_packed(
            plane_take(fg, plane_of),
            jax.lax.stop_gradient(rpn_deltas_rows),  # already per-image
            im_info,
            anchors,
            pre_nms_top_n=cfg.train.rpn_pre_nms_top_n,
            post_nms_top_n=cfg.train.rpn_post_nms_top_n,
            nms_thresh=cfg.train.rpn_nms_thresh,
            min_size=cfg.train.rpn_min_size,
            topk_impl=cfg.network.proposal_topk,
        )
    else:
        rois, roi_valid, _ = generate_proposals(
            rpn_prob,
            jax.lax.stop_gradient(rpn_bbox_deltas),
            im_info,
            anchors,
            pre_nms_top_n=cfg.train.rpn_pre_nms_top_n,
            post_nms_top_n=cfg.train.rpn_post_nms_top_n,
            nms_thresh=cfg.train.rpn_nms_thresh,
            min_size=cfg.train.rpn_min_size,
            feat_stride=stride,
            topk_impl=cfg.network.proposal_topk,
        )

    # --- ROI sampling (reference: ProposalTarget op — host numpy there) ---
    samples = jax.vmap(
        partial(
            sample_rois,
            num_classes=model.num_classes,
            batch_rois=cfg.train.batch_rois,
            fg_fraction=cfg.train.fg_fraction,
            fg_thresh=cfg.train.fg_thresh,
            bg_thresh_hi=cfg.train.bg_thresh_hi,
            bg_thresh_lo=cfg.train.bg_thresh_lo_value,
            bbox_means=cfg.train.bbox_means,
            bbox_stds=cfg.train.bbox_stds,
        ),
    )(rois, roi_valid, gt["gt_boxes"], gt["gt_classes"], gt["gt_valid"],
      jax.random.split(k_sample, b))

    r = cfg.train.batch_rois
    pooled = _pool_rois(feat, samples.rois, samples.valid,
                        model.roi_pool_size, model.roi_pool_type,
                        plane_of=plane_of, windows=windows)
    cls_logits, bbox_deltas = model.apply(
        params, pooled, False, method=FasterRCNN.box_head,
        rngs={"dropout": k_drop})

    labels = jnp.where(samples.valid.reshape(-1), samples.labels.reshape(-1), -1)
    rcnn_l = rcnn_losses(
        cls_logits,
        bbox_deltas,
        labels,
        samples.bbox_targets.reshape(b * r, -1),
        samples.bbox_weights.reshape(b * r, -1),
        cfg.train.batch_rois,
        b,
    )

    total = (rpn_l["rpn_cls_loss"] + rpn_l["rpn_bbox_loss"]
             + rcnn_l["rcnn_cls_loss"] + rcnn_l["rcnn_bbox_loss"])

    aux = {
        "rpn_cls_loss": rpn_l["rpn_cls_loss"],
        "rpn_bbox_loss": rpn_l["rpn_bbox_loss"],
        "rcnn_cls_loss": rcnn_l["rcnn_cls_loss"],
        "rcnn_bbox_loss": rcnn_l["rcnn_bbox_loss"],
        "total_loss": total,
        # Metric auxiliaries (train/metrics.py — the reference's 6 metrics).
        "rpn_logits": rpn_logits_pairs,  # per-image rows (packed: gathered)
        "rpn_labels": rpn_t.labels,
        "rcnn_logits": cls_logits,
        "rcnn_labels": labels,
        "num_fg": jnp.sum(samples.fg_mask),
    }
    return total, aux


def forward_test(
    model: FasterRCNN,
    params,
    images: jnp.ndarray,
    im_info: jnp.ndarray,
    cfg: Config,
):
    """Test forward: images → (rois, roi_scores (B,R,C), pred_boxes (B,R,4C)).

    Reference: get_*_test symbol + rcnn/core/tester.py::im_detect. Box
    decoding (bbox_pred → clip) happens here on device; per-class NMS lives
    in ops/detection.py (the reference does all of it on host).
    """
    a = model.num_anchors
    stride = cfg.network.rpn_feat_stride
    feat, rpn_cls_logits, rpn_bbox_deltas, anchors = _backbone_rpn(
        model, params, images, cfg)
    rpn_prob = _rpn_softmax(rpn_cls_logits, a)
    rois, roi_valid, _ = generate_proposals(
        rpn_prob, rpn_bbox_deltas, im_info, anchors,
        pre_nms_top_n=cfg.test.rpn_pre_nms_top_n,
        post_nms_top_n=cfg.test.rpn_post_nms_top_n,
        nms_thresh=cfg.test.rpn_nms_thresh,
        min_size=cfg.test.rpn_min_size,
        feat_stride=stride,
        topk_impl=cfg.network.proposal_topk,
    )
    b, r = rois.shape[0], rois.shape[1]
    pooled = _pool_rois(feat, rois, roi_valid,
                        model.roi_pool_size, model.roi_pool_type)
    cls_logits, bbox_deltas = model.apply(
        params, pooled, True, method=FasterRCNN.box_head)
    scores = jax.nn.softmax(cls_logits, axis=-1).reshape(b, r, -1)
    # Un-normalize deltas (reference folds means/stds into saved weights at
    # checkpoint time — rcnn/core/callback.py do_checkpoint; we keep weights
    # normalized and decode explicitly, see train/checkpoint.py contract).
    stds = jnp.tile(island(jnp.asarray(cfg.train.bbox_stds)),
                    model.num_classes)
    means = jnp.tile(island(jnp.asarray(cfg.train.bbox_means)),
                     model.num_classes)
    deltas = bbox_deltas.reshape(b, r, -1) * stds + means
    boxes = jax.vmap(bbox_pred)(rois, deltas)  # (B, R, 4C)
    boxes = jax.vmap(lambda bx, ii: clip_boxes(bx, (ii[0], ii[1])))(boxes, im_info)
    scores = scores * roi_valid[..., None].astype(scores.dtype)
    return rois, roi_valid, scores, boxes


def forward_train_rpn(
    model: FasterRCNN,
    params,
    batch: Dict[str, jnp.ndarray],
    rng: jax.Array,
    cfg: Config,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """RPN-only training forward (alternate-optimization stages 1 and 4).

    Reference: the rpn-only symbols get_*_rpn + rcnn/tools/train_rpn.py.
    Same batch contract as forward_train; only the RPN pair of losses.
    """
    if batch["im_info"].ndim == 3:
        raise ValueError("canvas packing (image.canvas_pack) supports the "
                         "end2end forward only; the alternate-training "
                         "stages run bucketed")
    images = batch["image"]
    b = images.shape[0]
    a = model.num_anchors
    feat, rpn_cls_logits, rpn_bbox_deltas, anchors = _backbone_rpn(
        model, params, images, cfg)
    rpn_t = _assign_anchors_batch(anchors, batch["gt_boxes"],
                                  batch["gt_valid"], batch["im_info"],
                                  rng, cfg)
    rpn_l = rpn_losses(
        _pair_logits(rpn_cls_logits, a),
        rpn_bbox_deltas.reshape(b, -1, 4),
        rpn_t.labels, rpn_t.bbox_targets, rpn_t.bbox_weights,
        cfg.train.rpn_batch_size,
    )
    total = rpn_l["rpn_cls_loss"] + rpn_l["rpn_bbox_loss"]
    aux = {
        "rpn_cls_loss": rpn_l["rpn_cls_loss"],
        "rpn_bbox_loss": rpn_l["rpn_bbox_loss"],
        "total_loss": total,
        "rpn_logits": _pair_logits(rpn_cls_logits, a),
        "rpn_labels": rpn_t.labels,
    }
    return total, aux


def forward_train_rcnn(
    model: FasterRCNN,
    params,
    batch: Dict[str, jnp.ndarray],
    rng: jax.Array,
    cfg: Config,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Fast-R-CNN training forward over PRECOMPUTED proposals.

    Reference: get_*_rcnn symbols + rcnn/tools/train_rcnn.py over ROIIter
    (selective-search or stage-RPN proposals). Batch additionally carries
    proposals (B, P, 4) + proposal_valid (B, P).
    """
    if batch["im_info"].ndim == 3:
        raise ValueError("canvas packing (image.canvas_pack) supports the "
                         "end2end forward only; the alternate-training "
                         "stages run bucketed")
    images = batch["image"]
    b = images.shape[0]
    feat = model.apply(params, images, method=FasterRCNN.extract)
    k_sample, k_drop = jax.random.split(rng)
    samples = jax.vmap(
        partial(
            sample_rois,
            num_classes=model.num_classes,
            batch_rois=cfg.train.batch_rois,
            fg_fraction=cfg.train.fg_fraction,
            fg_thresh=cfg.train.fg_thresh,
            bg_thresh_hi=cfg.train.bg_thresh_hi,
            bg_thresh_lo=cfg.train.bg_thresh_lo_value,
            bbox_means=cfg.train.bbox_means,
            bbox_stds=cfg.train.bbox_stds,
        ),
    )(batch["proposals"], batch["proposal_valid"], batch["gt_boxes"],
      batch["gt_classes"], batch["gt_valid"], jax.random.split(k_sample, b))
    r = cfg.train.batch_rois
    pooled = _pool_rois(feat, samples.rois, samples.valid,
                        model.roi_pool_size, model.roi_pool_type)
    cls_logits, bbox_deltas = model.apply(
        params, pooled, False, method=FasterRCNN.box_head,
        rngs={"dropout": k_drop})
    labels = jnp.where(samples.valid.reshape(-1), samples.labels.reshape(-1), -1)
    rcnn_l = rcnn_losses(
        cls_logits, bbox_deltas, labels,
        samples.bbox_targets.reshape(b * r, -1),
        samples.bbox_weights.reshape(b * r, -1),
        cfg.train.batch_rois, b,
    )
    total = rcnn_l["rcnn_cls_loss"] + rcnn_l["rcnn_bbox_loss"]
    aux = {
        "rcnn_cls_loss": rcnn_l["rcnn_cls_loss"],
        "rcnn_bbox_loss": rcnn_l["rcnn_bbox_loss"],
        "total_loss": total,
        "rcnn_logits": cls_logits,
        "rcnn_labels": labels,
        "num_fg": jnp.sum(samples.fg_mask),
    }
    return total, aux


def forward_rpn(
    model: FasterRCNN,
    params,
    images: jnp.ndarray,
    im_info: jnp.ndarray,
    cfg: Config,
):
    """RPN-only forward → (rois, roi_valid, roi_scores).

    The proposal-generation path of the alternate-training pipeline
    (reference: tools/test_rpn.py → tester.py im_proposal), skipping the box
    head entirely — proposals cost only backbone + RPN.
    """
    a = model.num_anchors
    feat, rpn_cls_logits, rpn_bbox_deltas, anchors = _backbone_rpn(
        model, params, images, cfg)
    rpn_prob = _rpn_softmax(rpn_cls_logits, a)
    # PROPOSAL_* counts, not the detection-path RPN counts: the dump feeds
    # Fast-R-CNN training, which samples from ~2000 candidates per image
    # (reference TEST.PROPOSAL_PRE/POST_NMS_TOP_N).
    return generate_proposals(
        rpn_prob, rpn_bbox_deltas, im_info, anchors,
        pre_nms_top_n=cfg.test.proposal_pre_nms_top_n,
        post_nms_top_n=cfg.test.proposal_post_nms_top_n,
        nms_thresh=cfg.test.proposal_nms_thresh,
        min_size=cfg.test.rpn_min_size,
        feat_stride=cfg.network.rpn_feat_stride,
        topk_impl=cfg.network.proposal_topk,
    )


def build_model(cfg: Config) -> FasterRCNN:
    return FasterRCNN(
        backbone="vgg" if cfg.network.name == "vgg" else f"resnet{cfg.network.depth}",
        num_classes=cfg.dataset.num_classes,
        num_anchors=cfg.network.num_anchors,
        roi_pool_size=cfg.network.roi_pool_size,
        roi_pool_type=cfg.network.roi_pool_type,
        norm=cfg.network.norm,
        freeze_at=cfg.network.freeze_at,
        dtype=model_dtype(cfg),
        remat=cfg.network.remat,
    )


def init_params(model: FasterRCNN, cfg: Config, rng: jax.Array,
                image_shape=None):
    """Initialize the full parameter tree on tiny shapes (shape-polymorphic
    convs make the real padded shape unnecessary at init)."""
    h, w = image_shape or (64, 64)
    images = jnp.zeros((1, h, w, 3), jnp.float32)
    rois = jnp.asarray([[0.0, 0.0, 0.0, 31.0, 31.0]], jnp.float32)
    return model.init(rng, images, rois)
