"""FPN Faster R-CNN — neck, multi-level heads, functional forwards.

BASELINE.json config 3 ("ResNet-101 + FPN Faster R-CNN e2e, COCO"): the
reference repo itself never shipped FPN (its graphs are the C4 models of
rcnn/symbol/symbol_resnet.py), so this module follows Lin et al. (FPN,
CVPR'17) and the Detectron-lineage conventions the north star names, built
on the same TPU-first machinery as models/faster_rcnn.py: static shapes,
in-graph targets, batched Pallas NMS, matmul ROIAlign.

Level layout:
  backbone C2..C5 (strides 4..32) → lateral 1x1 (256ch) + top-down nearest
  ×2 + output 3x3 → P2..P5; P6 = stride-2 maxpool of P5 (RPN only).
  RPN head shared across levels; one anchor scale per level (cfg
  anchor_scales=(8,) → 32..512 px areas on P2..P6), 3 ratios.
  ROI features: level k = floor(k0 + log2(sqrt(area)/224)) clamped to
  [2, 5] (FPN Eq. 1), pooled 7x7 from the assigned level.

Static-shape strategy: proposals are decoded + top-k'd per level (a fixed
per-level budget), NMS'd within each level, and the top post_nms of the
score-ranked union is taken (Detectron-lineage semantics; the joint
union-NMS variant stays available via fpn_nms_per_level=False) — every
shape is compile-time fixed either way.
ROI-to-level assignment computes the cheap matmul pool on EVERY level and
selects by mask (4 levels × a 13 GFLOP/step op beats any dynamic gather).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models.backbones import ResNetStages
from mx_rcnn_tpu.models.losses import rcnn_losses, rpn_losses
from mx_rcnn_tpu.models.rpn import RPNHead
from mx_rcnn_tpu.ops.anchors import anchor_grid
from mx_rcnn_tpu.ops.boxes import bbox_pred, clip_boxes
from mx_rcnn_tpu.ops.nms import nms_dispatch
from mx_rcnn_tpu.ops.proposal import _decode_one_image
from mx_rcnn_tpu.ops.roi_align import roi_align
from mx_rcnn_tpu.targets.rcnn_targets import sample_rois
from mx_rcnn_tpu.targets.rpn_targets import assign_anchor
from mx_rcnn_tpu.train.precision import island, model_dtype

Dtype = Any

# RPN levels P2..P6; ROI pooling levels P2..P5 (FPN paper).
RPN_LEVELS = (2, 3, 4, 5, 6)
ROI_LEVELS = (2, 3, 4, 5)


class FPNNeck(nn.Module):
    """Lateral + top-down feature pyramid (Lin et al. §3).

    Input (C2, C3, C4, C5) NHWC; output dict {2: P2, ..., 5: P5, 6: P6}.
    """

    channels: int = 256
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, feats: Sequence[jnp.ndarray],
                 masks=None) -> Dict[int, jnp.ndarray]:
        """masks (graftcanvas): {stride: (B, H/s, W/s, 1)} placement
        masks re-zeroing packed-canvas gap cells after every biased conv
        (laterals and 3x3 outputs both carry biases, so a gap cell would
        otherwise turn into a bias halo the next conv — or the RPN head —
        reads where the bucketed path reads implicit zero padding). The
        nearest-neighbor upsample maps masked-zero cells onto masked-zero
        cells (offsets are max-stride aligned), and P6's kernel-1 pool
        subsamples masked P5, so those need no masks of their own."""
        m = masks or {}
        c2, c3, c4, c5 = [f.astype(self.dtype) for f in feats]
        laterals = []
        for i, c in enumerate((c2, c3, c4, c5)):
            lat = nn.Conv(self.channels, (1, 1), dtype=self.dtype,
                          param_dtype=jnp.float32, name=f"lateral{i + 2}")(c)
            stride = 2 ** (i + 2)
            if stride in m:
                lat = lat * m[stride].astype(lat.dtype)
            laterals.append(lat)
        # Top-down: nearest-neighbor x2 upsample, accumulate.
        merged = [None] * 4
        merged[3] = laterals[3]
        for i in (2, 1, 0):
            up = _upsample2x(merged[i + 1])
            merged[i] = laterals[i] + up
        out = {}
        for i in range(4):
            o = nn.Conv(self.channels, (3, 3),
                        padding=[(1, 1), (1, 1)], dtype=self.dtype,
                        param_dtype=jnp.float32,
                        name=f"output{i + 2}")(merged[i])
            stride = 2 ** (i + 2)
            if stride in m:
                o = o * m[stride].astype(o.dtype)
            out[i + 2] = o
        # P6: stride-2 subsample of P5 (FPN paper: max-pool, kernel 1).
        out[6] = nn.max_pool(out[5], (1, 1), strides=(2, 2))
        return out


def _upsample2x(x: jnp.ndarray) -> jnp.ndarray:
    """Nearest-neighbor 2x spatial upsample, NHWC."""
    b, h, w, c = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :], (b, h, 2, w, 2, c))
    return x.reshape(b, h * 2, w * 2, c)


class TwoFCHead(nn.Module):
    """2-FC box head (FPN paper §4.2; replaces the C4 stage-5 head)."""

    width: int = 1024
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, pooled: jnp.ndarray) -> jnp.ndarray:
        r = pooled.shape[0]
        x = pooled.astype(self.dtype).reshape(r, -1)
        x = nn.relu(nn.Dense(self.width, dtype=self.dtype,
                             param_dtype=jnp.float32, name="fc6")(x))
        x = nn.relu(nn.Dense(self.width, dtype=self.dtype,
                             param_dtype=jnp.float32, name="fc7")(x))
        return x


class MaskHead(nn.Module):
    """Mask branch (He et al., Mask R-CNN): 4x conv 3x3 → deconv x2 → 1x1.

    Input (R, 14, 14, 256) → per-class logits (R, 28, 28, num_classes).
    """

    num_classes: int = 81
    channels: int = 256
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, pooled: jnp.ndarray) -> jnp.ndarray:
        x = pooled.astype(self.dtype)
        for i in range(4):
            x = nn.relu(nn.Conv(self.channels, (3, 3),
                                padding=[(1, 1), (1, 1)], dtype=self.dtype,
                                param_dtype=jnp.float32,
                                name=f"mask_conv{i}")(x))
        x = nn.relu(nn.ConvTranspose(self.channels, (2, 2), strides=(2, 2),
                                     dtype=self.dtype,
                                     param_dtype=jnp.float32,
                                     name="mask_deconv")(x))
        logits = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype,
                         param_dtype=jnp.float32,
                         kernel_init=nn.initializers.normal(0.001),
                         name="mask_logits")(x)
        return island(logits)


class FPNFasterRCNN(nn.Module):
    """ResNet-FPN Faster/Mask R-CNN parameter tree.

    Mirrors models/faster_rcnn.py::FasterRCNN's method-based apply contract
    so the functional forwards wire the non-parametric middle differently for
    train/test while sharing parameters.
    """

    depth: int = 50
    num_classes: int = 81
    num_anchors: int = 3  # per level: 1 scale x 3 ratios
    fpn_channels: int = 256
    roi_pool_size: int = 7
    use_mask: bool = False
    mask_pool_size: int = 14
    norm: str = "frozen_bn"
    freeze_at: int = 2
    dtype: Dtype = jnp.bfloat16
    remat: bool = False

    def setup(self):
        self.features = ResNetStages(depth=self.depth,
                                     freeze_at=self.freeze_at,
                                     norm=self.norm, dtype=self.dtype,
                                     remat=self.remat)
        self.neck = FPNNeck(channels=self.fpn_channels, dtype=self.dtype)
        self.rpn = RPNHead(num_anchors=self.num_anchors,
                           channels=self.fpn_channels, dtype=self.dtype)
        self.head = TwoFCHead(dtype=self.dtype)
        self.cls_score = nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=nn.initializers.normal(0.01), name="cls_score")
        self.bbox_pred = nn.Dense(
            self.num_classes * 4, dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=nn.initializers.normal(0.001), name="bbox_pred")
        if self.use_mask:
            self.mask_head = MaskHead(num_classes=self.num_classes,
                                      dtype=self.dtype)

    def extract(self, images: jnp.ndarray,
                masks=None) -> Dict[int, jnp.ndarray]:
        """masks (graftcanvas): packed-canvas placement masks threaded
        through the backbone stages and the neck (see FPNNeck)."""
        return self.neck(self.features(images, masks), masks)

    def rpn_forward(self, pyramid: Dict[int, jnp.ndarray]):
        """Shared RPN over P2..P6 → per-level (cls_logits, bbox_deltas)."""
        return {lv: self.rpn(pyramid[lv]) for lv in RPN_LEVELS}

    def rpn_forward_packed(self, pyramid: Dict[int, jnp.ndarray]):
        """Shared RPN over P2..P6 as ONE head application.

        Five separate per-level head convs run at tiny grids (P5: 20x32,
        P6: 10x16) where the MXU idles behind launch/tiling floors —
        measured util 0.050 at 6.8 ms fwd (PERF.md r4 FPN roofline). The
        levels are packed into one zero-gapped canvas (~1.13x the real
        pixel count), the head runs once at a big grid, and the per-level
        outputs are sliced back out. A 3x3 SAME conv on the canvas equals
        per-level 3x3 SAME convs exactly: every level border sees zeros
        either way (gap rows/cols or the conv's own zero padding).
        """
        return apply_rpn_head_packed(self.rpn, pyramid)

    def box_head(self, pooled: jnp.ndarray):
        x = self.head(pooled)
        cls = island(self.cls_score(x))
        box = island(self.bbox_pred(x))
        return cls, box

    def mask_forward(self, pooled: jnp.ndarray):
        return self.mask_head(pooled)

    def __call__(self, images: jnp.ndarray, rois: jnp.ndarray):
        """Init-only path touching every submodule."""
        pyramid = self.extract(images)
        rpn_out = self.rpn_forward(pyramid)
        pooled = roi_align(pyramid[2], rois, self.roi_pool_size, 1.0 / 4.0)
        cls, box = self.box_head(pooled)
        outs = (pyramid, rpn_out, cls, box)
        if self.use_mask:
            mp = roi_align(pyramid[2], rois, self.mask_pool_size, 1.0 / 4.0)
            outs = outs + (self.mask_forward(mp),)
        return outs


# ---------------------------------------------------------------------------
# Level packing (fused shared-head application)
# ---------------------------------------------------------------------------


def pack_placements(shapes: Sequence[Tuple[int, int]], gap: int = 1
                    ) -> Tuple[Tuple[int, int], List[Tuple[int, int, int, int]]]:
    """Shelf-pack (h, w) rectangles into one canvas with `gap` px between
    any two rectangles (not at canvas edges — conv zero padding covers
    those). Returns ((Hc, Wc), [(y, x, h, w) per input, input order]).

    Greedy shelves in the given order; pyramid levels arrive tallest
    first, so P2 fills shelf 1 and P3..P6 share shelf 2 (canvas ~1.13x
    the real pixel count at the flagship shapes). Pure-Python on static
    shapes — runs at trace time.
    """
    canvas_w = max(w for _, w in shapes)
    places: List[Tuple[int, int, int, int]] = []
    shelf_y = 0      # top row of the current shelf
    shelf_h = 0      # height of the tallest rect on the current shelf
    cur_x = 0        # next free column on the current shelf
    for h, w in shapes:
        if cur_x > 0 and cur_x + w > canvas_w:  # start a new shelf
            shelf_y += shelf_h + gap
            shelf_h, cur_x = 0, 0
        places.append((shelf_y, cur_x, h, w))
        shelf_h = max(shelf_h, h)
        cur_x += w + gap
    return (shelf_y + shelf_h, canvas_w), places


def apply_rpn_head_packed(rpn_head, pyramid: Dict[int, jnp.ndarray]):
    """Apply a shared RPN head to all RPN_LEVELS as one packed-canvas
    call; shared by FPNFasterRCNN and ViTDetector.

    The inter-level gap is the head's declared spatial receptive radius
    (``SPATIAL_RADIUS`` — 1 for RPNHead's single 3x3 conv): any deeper
    head must declare its radius, and a head class that doesn't declare
    one fails loudly here rather than silently leaking activations
    across adjacent levels on the canvas."""
    radius = getattr(type(rpn_head), "SPATIAL_RADIUS", None)
    if radius is None:
        raise ValueError(
            f"{type(rpn_head).__name__} declares no SPATIAL_RADIUS: the "
            "packed-canvas RPN application needs the head's spatial "
            "receptive radius to size the inter-level gap (declare "
            "`SPATIAL_RADIUS: ClassVar[int]` on the head, or disable "
            "network.fpn_packed_rpn_head)")
    tensors = [pyramid[lv] for lv in RPN_LEVELS]
    canvas, places = pack_levels(tensors, gap=int(radius))
    cls_c, box_c = rpn_head(canvas)
    out = {}
    for lv, (y, x, h, w) in zip(RPN_LEVELS, places):
        out[lv] = (cls_c[:, y:y + h, x:x + w, :],
                   box_c[:, y:y + h, x:x + w, :])
    return out


def pack_levels(tensors: Sequence[jnp.ndarray], gap: int = 1):
    """Pack same-channel NHWC tensors into one zero-gapped canvas.

    Returns (canvas (B, Hc, Wc, C), placements [(y, x, h, w), ...]).
    Offsets are static, so placement lowers to cheap in-place updates and
    unpacking to slices; the backward pass of a slice is a zero-pad.
    """
    shapes = [(t.shape[1], t.shape[2]) for t in tensors]
    (hc, wc), places = pack_placements(shapes, gap)
    b, c = tensors[0].shape[0], tensors[0].shape[3]
    canvas = jnp.zeros((b, hc, wc, c), tensors[0].dtype)
    for t, (y, x, h, w) in zip(tensors, places):
        canvas = jax.lax.dynamic_update_slice(canvas, t, (0, y, x, 0))
    return canvas, places


# ---------------------------------------------------------------------------
# Anchors / proposals over the pyramid
# ---------------------------------------------------------------------------


def pyramid_anchors(pyramid_shapes: Dict[int, Tuple[int, int]],
                    cfg: Config) -> Dict[int, np.ndarray]:
    """Per-level anchor grids. Level k uses stride 2^k and scales scaled so
    cfg.network.anchor_scales (default (8,)) are relative to the stride —
    the FPN convention (scale 8 x stride 4..64 → 32..512 px anchors)."""
    out = {}
    for lv in RPN_LEVELS:
        h, w = pyramid_shapes[lv]
        stride = 2 ** lv
        out[lv] = anchor_grid(
            h, w,
            stride=stride,
            base_size=stride,
            ratios=cfg.network.anchor_ratios,
            scales=cfg.network.anchor_scales,
        )
    return out


def fpn_proposals(
    rpn_out: Dict[int, Tuple[jnp.ndarray, jnp.ndarray]],
    anchors: Dict[int, jnp.ndarray],
    im_info: jnp.ndarray,
    cfg: Config,
    *,
    train: bool,
):
    """Multi-level proposal generation: per-level decode + top-k, concat,
    NMS per level or jointly over the union (tc.fpn_nms_per_level), top
    post_nms_top_n.

    Returns rois (B, post, 4), roi_valid (B, post), roi_scores (B, post).
    """
    tc = cfg.train if train else cfg.test

    def decode(scores, dl, k, anch):
        return jax.vmap(
            partial(_decode_one_image, pre_nms_top_n=k,
                    min_size=tc.rpn_min_size,
                    topk_impl=cfg.network.proposal_topk),
            in_axes=(0, 0, 0, None),
        )(scores, dl, im_info, anch)

    return _select_level_proposals(
        *_decode_levels(rpn_out, anchors, cfg.network.num_anchors,
                        tc.fpn_rpn_pre_nms_per_level, lambda x: x, decode),
        tc.fpn_nms_per_level, tc.rpn_nms_thresh, tc.rpn_post_nms_top_n)


def fpn_proposals_packed(
    rpn_out: Dict[int, Tuple[jnp.ndarray, jnp.ndarray]],
    anchors: Dict[int, jnp.ndarray],
    im_info: jnp.ndarray,
    plane_of: jnp.ndarray,
    cfg: Config,
    *,
    train: bool,
):
    """fpn_proposals over a packed canvas (graftcanvas).

    rpn_out holds per-PLANE level maps; im_info (B, 5) packed rows and
    plane_of (B,) expand them to per-image candidate sets: each image
    reads its plane's scores/deltas over the canvas grid, keeps only
    anchors centered in its placement rect, and clips decoded boxes to
    the rect (ops/proposal.py::_decode_one_window) — so proposals never
    cross a placement border. Selection semantics (per-level NMS + union
    top-k, or joint) are fpn_proposals' unchanged.
    """
    from mx_rcnn_tpu.ops.canvas import plane_take
    from mx_rcnn_tpu.ops.proposal import _decode_one_window

    tc = cfg.train if train else cfg.test

    def decode(scores, dl, k, anch):
        return jax.vmap(
            partial(_decode_one_window, pre_nms_top_n=k,
                    min_size=tc.rpn_min_size,
                    topk_impl=cfg.network.proposal_topk),
            in_axes=(0, 0, 0, None),
        )(scores, dl, im_info, anch)

    return _select_level_proposals(
        *_decode_levels(rpn_out, anchors, cfg.network.num_anchors,
                        tc.fpn_rpn_pre_nms_per_level,
                        lambda x: plane_take(x, plane_of), decode),
        tc.fpn_nms_per_level, tc.rpn_nms_thresh, tc.rpn_post_nms_top_n)


def _decode_levels(rpn_out, anchors, num_anchors: int, per_level: int,
                   row_fn, decode_fn):
    """Shared per-level head of the (packed and bucketed) FPN proposal
    paths: fg softmax, row prep (`row_fn`: identity for bucketed rows,
    plane→image expansion for packed), per-level budgeted decode.

    decode_fn(scores (B, N_l), deltas (B, N_l, 4), k, anchors (N_l, 4))
    → (boxes, scores, valid) per image; returns the three per-level
    candidate lists _select_level_proposals consumes."""
    boxes_all: List[jnp.ndarray] = []
    scores_all: List[jnp.ndarray] = []
    valid_all: List[jnp.ndarray] = []
    for lv in RPN_LEVELS:
        cls_logits, deltas = rpn_out[lv]
        n = cls_logits.shape[0]
        prob = _rpn_softmax_fg(cls_logits, num_anchors)
        scores = island(row_fn(prob.reshape(n, -1)))
        dl = island(row_fn(deltas.reshape(n, -1, 4)))
        k = min(per_level, scores.shape[1])
        tb, ts, tv = decode_fn(scores, dl, k, jnp.asarray(anchors[lv]))
        boxes_all.append(tb)
        scores_all.append(ts)
        valid_all.append(tv)
    return boxes_all, scores_all, valid_all


def _select_level_proposals(boxes_all, scores_all, valid_all,
                            per_level_nms: bool, thresh: float, post: int):
    """Shared tail of the (packed and bucketed) FPN proposal paths."""
    if per_level_nms:
        return per_level_nms_union(boxes_all, scores_all, valid_all,
                                   thresh, post)

    boxes = jnp.concatenate(boxes_all, axis=1)
    scores = jnp.concatenate(scores_all, axis=1)
    valid = jnp.concatenate(valid_all, axis=1)

    keep_idx, keep_valid = nms_dispatch(boxes, scores, valid, thresh, post)
    rois = jnp.take_along_axis(boxes, keep_idx[..., None], axis=1)
    kept_scores = jnp.take_along_axis(scores, keep_idx, axis=1)
    roi_scores = jnp.where(keep_valid, kept_scores, 0.0)
    rois = jnp.where(keep_valid[..., None], rois, rois[:, :1, :])
    return rois, keep_valid, roi_scores


def per_level_nms_union(boxes_all, scores_all, valid_all,
                        thresh: float, post: int):
    """Detectron-lineage RPN selection: NMS WITHIN each level, then the
    top `post` of the union by score — no cross-level suppression.

    Inputs are per-level lists of (B, k_l, 4) boxes / (B, k_l) scores &
    validity. Returns (rois (B, post, 4), keep_valid, roi_scores)."""
    kept_boxes, kept_scores = [], []
    for bl, sl, vl in zip(boxes_all, scores_all, valid_all):
        idx, kv = nms_dispatch(bl, sl, vl, thresh, bl.shape[1])
        kept_boxes.append(jnp.take_along_axis(bl, idx[..., None], axis=1))
        sk = jnp.take_along_axis(sl, idx, axis=1)
        # -1 marks suppressed/invalid slots out of the union top-k
        # (valid RPN scores are softmax probs, strictly > 0)
        kept_scores.append(jnp.where(kv, sk, -1.0))
    boxes = jnp.concatenate(kept_boxes, axis=1)
    scores = jnp.concatenate(kept_scores, axis=1)
    top_s, top_i = jax.lax.top_k(scores, post)
    keep_valid = top_s >= 0.0
    rois = jnp.take_along_axis(boxes, top_i[..., None], axis=1)
    roi_scores = jnp.where(keep_valid, top_s, 0.0)
    rois = jnp.where(keep_valid[..., None], rois, rois[:, :1, :])
    return rois, keep_valid, roi_scores




def _rpn_softmax_fg(cls_logits: jnp.ndarray, num_anchors: int) -> jnp.ndarray:
    """(B,H,W,2A) [bg×A, fg×A] logits → (B,H,W,A) fg probability."""
    a = num_anchors
    bg, fg = cls_logits[..., :a], cls_logits[..., a:]
    return jax.nn.sigmoid(fg - bg)  # 2-way softmax fg prob == sigmoid(fg-bg)


# ---------------------------------------------------------------------------
# ROI-to-level assignment + pyramid pooling
# ---------------------------------------------------------------------------


def roi_levels(rois: jnp.ndarray, k0: int = 4, canonical: float = 224.0
               ) -> jnp.ndarray:
    """FPN Eq. 1: k = floor(k0 + log2(sqrt(wh)/224)), clamped to ROI_LEVELS.

    rois: (..., 4) image-coordinate boxes → (...,) int32 level ids.
    """
    w = rois[..., 2] - rois[..., 0] + 1.0
    h = rois[..., 3] - rois[..., 1] + 1.0
    scale = jnp.sqrt(jnp.maximum(w * h, 1e-6))
    k = jnp.floor(k0 + jnp.log2(scale / canonical))
    return jnp.clip(k, ROI_LEVELS[0], ROI_LEVELS[-1]).astype(jnp.int32)


def pyramid_roi_align(
    pyramid: Dict[int, jnp.ndarray],
    rois: jnp.ndarray,
    roi_valid: jnp.ndarray,
    pool_size: int,
    plane_of: jnp.ndarray = None,
    windows: jnp.ndarray = None,
) -> jnp.ndarray:
    """(B, R, 4) rois → (B·R, P, P, C) pooled from each roi's FPN level.

    Static-shape strategy: pool from every ROI level and mask-select — the
    matmul ROIAlign is cheap enough that 4x beats any data-dependent
    partition (see module docstring).

    graftcanvas: on a packed batch the pyramid holds PLANES, not images —
    `plane_of` (B,) maps each image row to its plane, and `windows`
    (B, 4) [y0, x0, h, w] placement rects clamp border samples to the
    image's own cells (ops/roi_align.py).
    """
    b, r = rois.shape[0], rois.shape[1]
    ids = (jnp.arange(b, dtype=jnp.float32) if plane_of is None
           else island(plane_of))
    batch_idx = jnp.repeat(ids, r)[:, None]
    flat = jnp.concatenate([batch_idx, rois.reshape(b * r, 4)], axis=1)
    win = (None if windows is None
           else jnp.repeat(windows, r, axis=0))  # (B·R, 4)
    levels = roi_levels(rois.reshape(b * r, 4))
    out = None
    for lv in ROI_LEVELS:
        pooled = roi_align(pyramid[lv], flat, pool_size, 1.0 / (2 ** lv),
                           windows=win)
        sel = (levels == lv)[:, None, None, None].astype(pooled.dtype)
        out = pooled * sel if out is None else out + pooled * sel
    return out * roi_valid.reshape(b * r, 1, 1, 1).astype(out.dtype)


# ---------------------------------------------------------------------------
# Functional forwards
# ---------------------------------------------------------------------------


def _pyramid_rpn(model: FPNFasterRCNN, params, images, cfg: Config,
                 masks=None):
    pyramid = model.apply(params, images, masks, method="extract")
    rpn_method = ("rpn_forward_packed" if cfg.network.fpn_packed_rpn_head
                  else "rpn_forward")
    rpn_out = model.apply(params, pyramid, method=rpn_method)
    shapes = {lv: (pyramid[lv].shape[1], pyramid[lv].shape[2])
              for lv in RPN_LEVELS}
    anchors = pyramid_anchors(shapes, cfg)
    return pyramid, rpn_out, anchors


def _concat_level_outputs(rpn_out, num_anchors: int):
    """Per-level (B,H,W,2A)/(B,H,W,4A) → (B, N, 2) logits + (B, N, 4) deltas
    concatenated in the same order as the concatenated anchor grid."""
    logits_all, deltas_all = [], []
    for lv in RPN_LEVELS:
        cls_logits, deltas = rpn_out[lv]
        b = cls_logits.shape[0]
        a = num_anchors
        bg = cls_logits[..., :a].reshape(b, -1)
        fg = cls_logits[..., a:].reshape(b, -1)
        logits_all.append(jnp.stack([bg, fg], axis=-1))
        deltas_all.append(deltas.reshape(b, -1, 4))
    return (jnp.concatenate(logits_all, axis=1),
            jnp.concatenate(deltas_all, axis=1))


def forward_train(
    model: FPNFasterRCNN,
    params,
    batch: Dict[str, jnp.ndarray],
    rng: jax.Array,
    cfg: Config,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """FPN end-to-end train forward. Same batch contract as
    models/faster_rcnn.py::forward_train; adds gt_masks (B, G, M, M) when
    cfg.network.use_mask (box-frame rasterized instance masks).

    graftcanvas: a PACKED batch (ops/canvas.py contract — planes of
    shelf-packed images, im_info (P, I, 5) placement rows) runs the
    backbone/neck once over the canvas planes with gap cells re-masked,
    then threads placements through anchors/targets, proposals and ROI
    pooling so per-image semantics match the bucketed path (gated in
    tests/test_canvas.py)."""
    from mx_rcnn_tpu.ops.canvas import (is_packed_batch, packed_views,
                                        placement_masks, plane_take)

    images = batch["image"]
    a = model.num_anchors
    packed = is_packed_batch(batch)
    if packed:
        from mx_rcnn_tpu.data.canvas import packed_strides

        v = packed_views(batch)
        im_info, plane_of = v["im_info"], v["plane_of"]
        gt_boxes, gt_classes = v["gt_boxes"], v["gt_classes"]
        gt_valid, gt_masks = v["gt_valid"], v.get("gt_masks")
        b = im_info.shape[0]
        windows = jnp.stack([im_info[:, 3], im_info[:, 4],
                             im_info[:, 0], im_info[:, 1]], axis=1)
        masks = placement_masks(batch["im_info"], images.shape[1:3],
                                packed_strides(cfg))
    else:
        im_info, plane_of, windows, masks = batch["im_info"], None, None, None
        gt_boxes, gt_classes = batch["gt_boxes"], batch["gt_classes"]
        gt_valid, gt_masks = batch["gt_valid"], batch.get("gt_masks")
        b = images.shape[0]

    pyramid, rpn_out, anchors = _pyramid_rpn(model, params, images, cfg,
                                             masks)
    anchors_cat = jnp.asarray(
        np.concatenate([anchors[lv] for lv in RPN_LEVELS], axis=0))

    k_anchor, k_sample, k_dummy = jax.random.split(rng, 3)
    rpn_t = jax.vmap(
        partial(
            assign_anchor,
            rpn_batch_size=cfg.train.rpn_batch_size,
            rpn_fg_fraction=cfg.train.rpn_fg_fraction,
            positive_overlap=cfg.train.rpn_positive_overlap,
            negative_overlap=cfg.train.rpn_negative_overlap,
            allowed_border=cfg.train.rpn_allowed_border,
            clobber_positives=cfg.train.rpn_clobber_positives,
        ),
        in_axes=(None, 0, 0, 0, 0),
    )(anchors_cat, gt_boxes, gt_valid, im_info,
      jax.random.split(k_anchor, b))

    rpn_logits, rpn_deltas = _concat_level_outputs(rpn_out, a)
    if packed:
        # Per-plane head outputs → per-image rows: each image reads ITS
        # plane's canvas grid; its labels ignore every out-of-rect anchor.
        rpn_logits = plane_take(rpn_logits, plane_of)
        rpn_deltas = plane_take(rpn_deltas, plane_of)
    rpn_l = rpn_losses(rpn_logits, rpn_deltas, rpn_t.labels,
                       rpn_t.bbox_targets, rpn_t.bbox_weights,
                       cfg.train.rpn_batch_size)

    rpn_sg = {lv: (jax.lax.stop_gradient(c), jax.lax.stop_gradient(d))
              for lv, (c, d) in rpn_out.items()}
    if packed:
        rois, roi_valid, _ = fpn_proposals_packed(
            rpn_sg, anchors, im_info, plane_of, cfg, train=True)
    else:
        rois, roi_valid, _ = fpn_proposals(rpn_sg, anchors, im_info, cfg,
                                           train=True)

    samples = jax.vmap(
        partial(
            sample_rois,
            num_classes=model.num_classes,
            batch_rois=cfg.train.batch_rois,
            fg_fraction=cfg.train.fg_fraction,
            fg_thresh=cfg.train.fg_thresh,
            bg_thresh_hi=cfg.train.bg_thresh_hi,
            bg_thresh_lo=cfg.train.bg_thresh_lo_value,
            bbox_means=cfg.train.bbox_means,
            bbox_stds=cfg.train.bbox_stds,
        ),
    )(rois, roi_valid, gt_boxes, gt_classes,
      gt_valid, jax.random.split(k_sample, b))

    r = cfg.train.batch_rois
    pooled = pyramid_roi_align(pyramid, samples.rois, samples.valid,
                               model.roi_pool_size, plane_of=plane_of,
                               windows=windows)
    cls_logits, bbox_deltas = model.apply(params, pooled,
                                          method="box_head")

    labels = jnp.where(samples.valid.reshape(-1),
                       samples.labels.reshape(-1), -1)
    rcnn_l = rcnn_losses(
        cls_logits, bbox_deltas, labels,
        samples.bbox_targets.reshape(b * r, -1),
        samples.bbox_weights.reshape(b * r, -1),
        cfg.train.batch_rois, b)

    total = (rpn_l["rpn_cls_loss"] + rpn_l["rpn_bbox_loss"]
             + rcnn_l["rcnn_cls_loss"] + rcnn_l["rcnn_bbox_loss"])

    aux = {
        "rpn_cls_loss": rpn_l["rpn_cls_loss"],
        "rpn_bbox_loss": rpn_l["rpn_bbox_loss"],
        "rcnn_cls_loss": rcnn_l["rcnn_cls_loss"],
        "rcnn_bbox_loss": rcnn_l["rcnn_bbox_loss"],
        "rpn_logits": rpn_logits,
        "rpn_labels": rpn_t.labels,
        "rcnn_logits": cls_logits,
        "rcnn_labels": labels,
        "num_fg": jnp.sum(samples.fg_mask),
    }

    if model.use_mask:
        from mx_rcnn_tpu.targets.mask_targets import mask_targets_for_rois

        mask_pooled = pyramid_roi_align(
            pyramid, samples.rois, samples.valid & samples.fg_mask,
            model.mask_pool_size, plane_of=plane_of, windows=windows)
        mask_logits = model.apply(params, mask_pooled,
                                  method="mask_forward")
        m_res = mask_logits.shape[1]
        # gt_masks are BOX-frame, so the canvas shift cancels: rois and
        # gt boxes are both canvas-coordinate on a packed batch.
        targets = jax.vmap(
            partial(mask_targets_for_rois, resolution=m_res)
        )(samples.rois, samples.matched_gt, gt_boxes,
          gt_masks)  # (B, R, m, m)
        targets = targets.reshape(b * r, m_res, m_res)
        fg = (samples.fg_mask & samples.valid).reshape(-1)
        cls_sel = jnp.maximum(labels, 0)
        per_roi = jnp.take_along_axis(
            mask_logits, cls_sel[:, None, None, None], axis=-1)[..., 0]
        bce = optax_sigmoid_bce(per_roi, targets)
        denom = jnp.maximum(jnp.sum(island(fg)), 1.0)
        mask_loss = jnp.sum(
            jnp.mean(bce, axis=(1, 2)) * island(fg)) / denom
        total = total + mask_loss
        aux["mask_loss"] = mask_loss

    aux["total_loss"] = total
    return total, aux


def optax_sigmoid_bce(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Elementwise sigmoid BCE (numerically stable)."""
    zeros = jnp.zeros_like(logits)
    return (jnp.maximum(logits, zeros) - logits * labels
            + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def forward_test(
    model: FPNFasterRCNN,
    params,
    images: jnp.ndarray,
    im_info: jnp.ndarray,
    cfg: Config,
):
    """FPN test forward → (rois, roi_valid, scores (B,R,C), boxes (B,R,4C)).

    Same output contract as models/faster_rcnn.py::forward_test so the
    Predictor/pred_eval stack is model-agnostic.
    """
    pyramid, rpn_out, anchors = _pyramid_rpn(model, params, images, cfg)
    rois, roi_valid, _ = fpn_proposals(rpn_out, anchors, im_info, cfg,
                                       train=False)
    b, r = rois.shape[0], rois.shape[1]
    pooled = pyramid_roi_align(pyramid, rois, roi_valid, model.roi_pool_size)
    cls_logits, bbox_deltas = model.apply(params, pooled,
                                          method="box_head")
    scores = jax.nn.softmax(cls_logits, axis=-1).reshape(b, r, -1)
    stds = jnp.tile(island(jnp.asarray(cfg.train.bbox_stds)),
                    model.num_classes)
    means = jnp.tile(island(jnp.asarray(cfg.train.bbox_means)),
                     model.num_classes)
    deltas = bbox_deltas.reshape(b, r, -1) * stds + means
    boxes = jax.vmap(bbox_pred)(rois, deltas)
    boxes = jax.vmap(lambda bx, ii: clip_boxes(bx, (ii[0], ii[1])))(
        boxes, im_info)
    scores = scores * roi_valid[..., None].astype(scores.dtype)
    return rois, roi_valid, scores, boxes


def forward_test_masks(
    model: FPNFasterRCNN,
    params,
    images: jnp.ndarray,
    det_boxes: jnp.ndarray,
    det_classes: jnp.ndarray,
    det_valid: jnp.ndarray,
):
    """Mask branch on final detections → (B, D, m, m) sigmoid probabilities.

    det_boxes: (B, D, 4); det_classes: (B, D) int32; det_valid: (B, D).
    Run AFTER detection post-processing (the Mask R-CNN inference recipe:
    masks are predicted on the post-NMS boxes, not the proposals).
    """
    pyramid = model.apply(params, images, method="extract")
    b, d = det_boxes.shape[0], det_boxes.shape[1]
    pooled = pyramid_roi_align(pyramid, det_boxes, det_valid,
                               model.mask_pool_size)
    logits = model.apply(params, pooled, method="mask_forward")
    m = logits.shape[1]
    cls_sel = jnp.maximum(det_classes.reshape(-1), 0)
    per_det = jnp.take_along_axis(
        logits, cls_sel[:, None, None, None], axis=-1)[..., 0]
    probs = jax.nn.sigmoid(per_det).reshape(b, d, m, m)
    return probs * det_valid[..., None, None].astype(probs.dtype)


def forward_rpn(
    model: FPNFasterRCNN,
    params,
    images: jnp.ndarray,
    im_info: jnp.ndarray,
    cfg: Config,
):
    """Proposal-only forward → (rois, roi_valid, roi_scores).

    The FPN analog of models/faster_rcnn.py::forward_rpn (proposal dumping);
    uses the test-time per-level budget with the PROPOSAL_* post count."""
    from dataclasses import replace as _replace

    pyramid, rpn_out, anchors = _pyramid_rpn(model, params, images, cfg)
    dump_cfg = cfg.with_updates(test=_replace(
        cfg.test,
        rpn_post_nms_top_n=cfg.test.proposal_post_nms_top_n,
        rpn_nms_thresh=cfg.test.proposal_nms_thresh))
    return fpn_proposals(rpn_out, anchors, im_info, dump_cfg, train=False)


def build_fpn_model(cfg: Config) -> FPNFasterRCNN:
    return FPNFasterRCNN(
        depth=cfg.network.depth,
        num_classes=cfg.dataset.num_classes,
        num_anchors=cfg.network.num_anchors,
        fpn_channels=cfg.network.fpn_channels,
        roi_pool_size=cfg.network.roi_pool_size,
        use_mask=cfg.network.use_mask,
        mask_pool_size=cfg.network.mask_pool_size,
        norm=cfg.network.norm,
        freeze_at=cfg.network.freeze_at,
        dtype=model_dtype(cfg),
        remat=cfg.network.remat,
    )


def init_fpn_params(model: FPNFasterRCNN, cfg: Config, rng: jax.Array,
                    image_shape=None):
    h, w = image_shape or (64, 64)
    images = jnp.zeros((1, h, w, 3), jnp.float32)
    rois = jnp.asarray([[0.0, 0.0, 0.0, 31.0, 31.0]], jnp.float32)
    return model.init(rng, images, rois)
