"""Loss functions with the reference's exact normalization semantics.

Reference: the loss wiring inside rcnn/symbol/symbol_vgg.py /
symbol_resnet.py get_*_train:

- RPN cls: ``SoftmaxOutput(..., use_ignore=True, ignore_label=-1,
  normalization='valid')`` — cross-entropy summed over non-ignored anchors,
  divided by the non-ignored count (≈ RPN_BATCH_SIZE).
- RPN bbox: ``smooth_l1(scalar=3.0)`` × rpn_bbox_weight, ``MakeLoss``
  grad_scale 1/RPN_BATCH_SIZE — i.e. a *fixed-constant* normalizer, not the
  live fg count (SURVEY.md §4.5 'key numeric gotchas').
- RCNN cls: ``SoftmaxOutput(normalization='batch')`` — mean over sampled
  rois.
- RCNN bbox: ``smooth_l1(scalar=1.0)`` × bbox_weight, grad_scale
  1/BATCH_ROIS.

At >1 image per device the fixed constants are multiplied by the image count
(equivalent to the reference's per-device B=1 recipe replicated).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.train.precision import island


def smooth_l1(x: jnp.ndarray, sigma: float) -> jnp.ndarray:
    """Elementwise smooth-L1 with the reference's sigma parameterization.

    f(x) = 0.5 (sigma x)^2        if |x| < 1/sigma^2
           |x| - 0.5/sigma^2      otherwise
    (mx.symbol.smooth_l1 semantics; sigma=3 for RPN, sigma=1 for RCNN.)
    """
    s2 = sigma * sigma
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0 / s2, 0.5 * s2 * x * x, ax - 0.5 / s2)


def softmax_ce_with_ignore(logits: jnp.ndarray, labels: jnp.ndarray) -> tuple:
    """Cross-entropy with ignore-label −1, 'valid' normalization.

    logits: (N, C); labels: (N,) int32, −1 = ignore.
    Returns (loss_scalar, per_example_ce, valid_mask) — the per-example terms
    feed the RPNLogLoss/RCNNLogLoss metrics.
    """
    valid = labels >= 0
    safe = jnp.maximum(labels, 0)
    logp = jax.nn.log_softmax(island(logits), axis=-1)
    ce = -jnp.take_along_axis(logp, safe[:, None], axis=-1)[:, 0]
    ce = jnp.where(valid, ce, 0.0)
    count = jnp.maximum(jnp.sum(island(valid)), 1.0)
    return jnp.sum(ce) / count, ce, valid


def rpn_losses(
    rpn_cls_logits: jnp.ndarray,
    rpn_bbox_deltas: jnp.ndarray,
    labels: jnp.ndarray,
    bbox_targets: jnp.ndarray,
    bbox_weights: jnp.ndarray,
    rpn_batch_size: int,
) -> dict:
    """RPN pair of losses.

    Args:
      rpn_cls_logits: (B, N, 2) per-anchor [bg, fg] logits.
      rpn_bbox_deltas: (B, N, 4).
      labels: (B, N) in {−1, 0, 1}; bbox_targets/weights: (B, N, 4).
    """
    b = rpn_cls_logits.shape[0]
    cls_loss, ce, valid = softmax_ce_with_ignore(
        rpn_cls_logits.reshape(-1, 2), labels.reshape(-1)
    )
    diff = island(rpn_bbox_deltas - bbox_targets)
    l1 = smooth_l1(diff, sigma=3.0) * bbox_weights
    bbox_loss = jnp.sum(l1) / (rpn_batch_size * b)
    return {
        "rpn_cls_loss": cls_loss,
        "rpn_bbox_loss": bbox_loss,
        "rpn_ce": ce,
        "rpn_valid": valid,
    }


def rcnn_losses(
    cls_logits: jnp.ndarray,
    bbox_pred: jnp.ndarray,
    labels: jnp.ndarray,
    bbox_targets: jnp.ndarray,
    bbox_weights: jnp.ndarray,
    batch_rois: int,
    batch_images: int,
) -> dict:
    """RCNN pair of losses.

    Args:
      cls_logits: (R, C); bbox_pred: (R, 4C); labels: (R,) int32 (−1 masks a
      degenerate slot); bbox_targets/weights: (R, 4C).
    """
    cls_loss, ce, valid = softmax_ce_with_ignore(cls_logits, labels)
    diff = island(bbox_pred - bbox_targets)
    l1 = smooth_l1(diff, sigma=1.0) * bbox_weights
    bbox_loss = jnp.sum(l1) / (batch_rois * batch_images)
    return {
        "rcnn_cls_loss": cls_loss,
        "rcnn_bbox_loss": bbox_loss,
        "rcnn_ce": ce,
        "rcnn_valid": valid,
    }
