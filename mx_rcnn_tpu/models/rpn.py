"""RPN head.

Reference: the rpn_conv/rpn_cls_score/rpn_bbox_pred trio built inline in
rcnn/symbol/symbol_vgg.py and symbol_resnet.py: 3x3 conv (512) + relu, then
two sibling 1x1 convs producing 2A objectness logits and 4A box deltas.

TPU delta: outputs are NHWC with channels last — (B, H, W, 2A) and
(B, H, W, 4A) — matching ops/proposal.py's expected layout. The per-pixel
channel order is [bg x A, fg x A] for scores (so ``[..., A:]`` is fg) and A
groups of 4 for deltas, consistent with ops/anchors.anchor_grid ordering.
"""

from __future__ import annotations

from typing import Any, ClassVar, Tuple

import jax.numpy as jnp
from flax import linen as nn

from mx_rcnn_tpu.train.precision import island


class RPNHead(nn.Module):
    #: Spatial receptive radius (px on the feature grid) of the head's
    #: conv stack — one 3x3 conv reaches 1 px; the 1x1 siblings add 0.
    #: models/fpn.py::apply_rpn_head_packed sizes its inter-level canvas
    #: gap from this so activations cannot leak across packed levels; a
    #: deeper head MUST raise it (and gets a loud failure if the
    #: attribute is missing entirely).
    SPATIAL_RADIUS: ClassVar[int] = 1

    num_anchors: int = 9
    channels: int = 512
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, feat: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        a = self.num_anchors
        x = nn.Conv(self.channels, (3, 3), padding=[(1, 1), (1, 1)],
                    dtype=self.dtype, param_dtype=jnp.float32,
                    name="rpn_conv")(feat.astype(self.dtype))
        x = nn.relu(x)
        cls_logits = nn.Conv(2 * a, (1, 1), dtype=self.dtype,
                             param_dtype=jnp.float32,
                             kernel_init=nn.initializers.normal(0.01),
                             name="rpn_cls_score")(x)
        bbox_deltas = nn.Conv(4 * a, (1, 1), dtype=self.dtype,
                              param_dtype=jnp.float32,
                              kernel_init=nn.initializers.normal(0.01),
                              name="rpn_bbox_pred")(x)
        return island(cls_logits), island(bbox_deltas)
