"""ViTDet — plain ViT backbone + simple feature pyramid detector.

BASELINE.json config 5 (the stretch config; the reference repo predates
transformers entirely — SURVEY.md §3.2). Follows Li et al., "Exploring
Plain Vision Transformer Backbones for Object Detection" (ViTDet):

- non-hierarchical ViT encoder at stride 16 (patch 16), windowed attention
  in most blocks with a few global-attention blocks spread evenly;
- a Simple Feature Pyramid built from the LAST feature map only (stride-16
  map → deconv x4 / deconv x2 / identity / maxpool → strides 4/8/16/32),
  then the SAME multi-level RPN + box/mask heads as models/fpn.py — the
  class deliberately mirrors FPNFasterRCNN's method surface so
  fpn.forward_train / forward_test / forward_rpn drive it unchanged
  (models/zoo.py dispatch).

Long-context: the global-attention blocks can run RING ATTENTION
(ops/ring_attention.py) with the token sequence sharded over a mesh axis —
`network.use_ring_attention` + a mesh passed at construction. Window blocks
are always local (windows never cross device shards; each image row-block
is self-contained), so only the few global blocks pay ICI traffic, exactly
the ViTDet compute structure.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models.fpn import MaskHead, RPNHead, TwoFCHead
from mx_rcnn_tpu.ops.ring_attention import dense_attention
from mx_rcnn_tpu.train.precision import island, model_dtype

Dtype = Any


class Attention(nn.Module):
    """Multi-head self-attention over (B, N, C) tokens."""

    dim: int
    heads: int
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, attn_fn=None) -> jnp.ndarray:
        b, n, c = x.shape
        h = self.heads
        d = self.dim // h
        qkv = nn.Dense(3 * self.dim, dtype=self.dtype,
                       param_dtype=jnp.float32, name="qkv")(x)
        q, k, v = jnp.split(qkv.reshape(b, n, 3, h, d), 3, axis=2)
        q, k, v = (t[:, :, 0] for t in (q, k, v))  # (B, N, H, D)
        attn = attn_fn or dense_attention
        out = attn(q, k, v)  # (B, N, H, D)
        out = out.reshape(b, n, self.dim)
        return nn.Dense(self.dim, dtype=self.dtype, param_dtype=jnp.float32,
                        name="proj")(out)


class Block(nn.Module):
    """Pre-LN transformer block, windowed or global spatial attention.

    Input/output (B, H, W, C). Window attention partitions the (H, W) grid
    into window x window tiles (padded if needed) and attends within each —
    the ViTDet local block. window == 0 → global attention over all H·W
    tokens (optionally ring attention when attn_fn is given).
    """

    dim: int
    heads: int
    window: int = 0
    mlp_ratio: float = 4.0
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, attn_fn=None) -> jnp.ndarray:
        b, h, w, c = x.shape
        shortcut = x
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="norm1")(x)
        if self.window > 0:
            ws = self.window
            ph = (-h) % ws
            pw = (-w) % ws
            y = jnp.pad(y, ((0, 0), (0, ph), (0, pw), (0, 0)))
            hh, ww = h + ph, w + pw
            y = y.reshape(b, hh // ws, ws, ww // ws, ws, c)
            y = y.transpose(0, 1, 3, 2, 4, 5).reshape(-1, ws * ws, c)
            y = Attention(self.dim, self.heads, dtype=self.dtype,
                          name="attn")(y)
            y = y.reshape(b, hh // ws, ww // ws, ws, ws, c)
            y = y.transpose(0, 1, 3, 2, 4, 5).reshape(b, hh, ww, c)
            y = y[:, :h, :w]
        else:
            y = Attention(self.dim, self.heads, dtype=self.dtype,
                          name="attn")(y.reshape(b, h * w, c), attn_fn)
            y = y.reshape(b, h, w, c)
        x = shortcut + y
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="norm2")(x)
        y = nn.Dense(int(self.dim * self.mlp_ratio), dtype=self.dtype,
                     param_dtype=jnp.float32, name="mlp1")(y)
        y = nn.gelu(y)
        y = nn.Dense(self.dim, dtype=self.dtype, param_dtype=jnp.float32,
                     name="mlp2")(y)
        return x + y


def _global_block_indices(depth: int) -> set:
    """ViTDet global-attention placement: the depth is split into 4
    subsets, each ENDING with a global block (ViT-B depth 12 → {2, 5, 8,
    11}); degenerate small depths (< 4) make every block global. Shared
    by ViTBackbone and the staged-layout checkpoint converters."""
    blocks = {depth * k // 4 - 1 for k in range(1, 5)}
    return {i for i in blocks if i >= 0} or {depth - 1}


def _stage_global_pattern(depth: int, stages_n: int):
    """In-stage indices of the global-attention blocks for a staged split
    of the sequential backbone — the SAME tuple for every stage, so the
    stages are identically structured (what nn.scan and the GPipe ring
    need), or ValueError when no such split exists.

    The sequential placement is periodic with period depth/4, so any
    stages_n dividing 4 preserves it exactly (depth 12, 2 stages → {2, 5}
    in both halves); degenerate all-global depths support any divisor.
    Splits that would change the architecture (e.g. depth 12 into 3
    stages) hard-error instead of silently training a different model."""
    if stages_n <= 0 or depth % stages_n:
        raise ValueError(
            f"vit_depth {depth} must divide into pp_stages {stages_n}")
    per = depth // stages_n
    g = _global_block_indices(depth)
    pats = [tuple(sorted(i - s * per for i in g
                         if s * per <= i < (s + 1) * per))
            for s in range(stages_n)]
    if any(p != pats[0] for p in pats[1:]):
        raise ValueError(
            f"pp_stages={stages_n} cannot preserve the ViTDet global-"
            f"attention placement at depth {depth}: the sequential globals "
            f"{sorted(g)} split into unequal per-stage patterns {pats}; "
            "pipeline stages must be identically structured. Use a stage "
            "count that divides 4 (the placement period is depth/4).")
    return pats[0]


def _embed_patches(mdl, x: jnp.ndarray) -> jnp.ndarray:
    """Shared embed surface: patch Conv + bilinearly-resized absolute
    pos-embed. Called from the compact bodies of BOTH backbones (same
    param names — `patch_embed`, `pos_embed` — so the checkpoint format is
    identical; static under jit: shapes are compile-time)."""
    x = nn.Conv(mdl.dim, (mdl.patch, mdl.patch),
                strides=(mdl.patch, mdl.patch), dtype=mdl.dtype,
                param_dtype=jnp.float32, name="patch_embed")(
                    x.astype(mdl.dtype))
    h, w = x.shape[1], x.shape[2]
    pos = mdl.param("pos_embed", nn.initializers.normal(0.02),
                    (1, mdl.pos_grid, mdl.pos_grid, mdl.dim), jnp.float32)
    pos = jax.image.resize(pos, (1, h, w, mdl.dim), "bilinear")
    return x + pos.astype(mdl.dtype)


def _final_norm(mdl, x: jnp.ndarray) -> jnp.ndarray:
    return nn.LayerNorm(dtype=mdl.dtype, param_dtype=jnp.float32,
                        name="norm")(x)


class ViTBackbone(nn.Module):
    """Plain ViT encoder → single stride-16 feature map (B, H/16, W/16, C).

    Global blocks at depth/4 spacing (ViTDet: 4 global blocks for ViT-B);
    the rest use `window`-sized local attention. Absolute position
    embeddings are bilinearly resized to the runtime grid (static under
    jit — shapes are compile-time).
    """

    patch: int = 16
    dim: int = 768
    depth: int = 12
    heads: int = 12
    window: int = 8
    dtype: Dtype = jnp.bfloat16
    # Pretraining grid for pos-embed params; resized to runtime grid.
    pos_grid: int = 32

    @nn.compact
    def __call__(self, x: jnp.ndarray, attn_fn=None) -> jnp.ndarray:
        x = _embed_patches(self, x)
        global_blocks = _global_block_indices(self.depth)
        for i in range(self.depth):
            is_global = i in global_blocks
            x = Block(self.dim, self.heads,
                      window=0 if is_global else self.window,
                      dtype=self.dtype, name=f"block{i}")(
                          x, attn_fn if is_global else None)
        return _final_norm(self, x)


class ViTStage(nn.Module):
    """One pipeline stage: ``blocks`` Blocks, global attention at the
    static in-stage indices ``globals_idx`` (windowed elsewhere).

    The ViTDet placement is periodic in the stage size for any supported
    stage count (_stage_global_pattern), so every stage carries the SAME
    globals_idx — the encoder is a stack of IDENTICALLY-STRUCTURED stages,
    which is exactly what pipeline parallelism needs (ring-homogeneous,
    shape-preserving). nn.scan-compatible signature: (carry, None) ->
    (carry, None). Blocks are named positionally (b0..b{blocks-1}):
    Block params are window-independent, so the name encodes position
    only and the checkpoint layout is placement-agnostic.
    """

    dim: int
    heads: int
    window: int
    blocks: int
    globals_idx: tuple = ()
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, _=None):
        for i in range(self.blocks):
            is_global = i in self.globals_idx
            x = Block(self.dim, self.heads,
                      window=0 if is_global else self.window,
                      dtype=self.dtype, name=f"b{i}")(x)
        return x, None


class ViTBackbonePP(nn.Module):
    """Plain-ViT encoder with a STAGED block stack for pipeline parallelism.

    Same embed/norm surface as ViTBackbone, but the depth is organized as
    ``stages_n`` scanned ViTStages (params stacked on a leading stage axis
    by nn.scan). Sequential execution (pipeline_fn=None) and pipelined
    execution (parallel/pipeline.py::pipeline_apply over the mesh `model`
    axis) share the SAME parameters and numerics. The global-attention
    placement matches ViTBackbone's ViTDet pattern EXACTLY for every
    supported stage count (_stage_global_pattern hard-errors on splits
    that cannot preserve it).
    """

    patch: int = 16
    dim: int = 768
    stages_n: int = 4
    blocks_per_stage: int = 3
    heads: int = 12
    window: int = 8
    dtype: Dtype = jnp.bfloat16
    pos_grid: int = 32

    @nn.compact
    def __call__(self, x: jnp.ndarray, pipeline_fn=None) -> jnp.ndarray:
        x = _embed_patches(self, x)
        stage_kw = dict(dim=self.dim, heads=self.heads, window=self.window,
                        blocks=self.blocks_per_stage,
                        globals_idx=_stage_global_pattern(
                            self.stages_n * self.blocks_per_stage,
                            self.stages_n),
                        dtype=self.dtype)
        ScanStages = nn.scan(
            ViTStage, variable_axes={"params": 0},
            split_rngs={"params": True}, length=self.stages_n)
        stages = ScanStages(**stage_kw, name="stages")
        if pipeline_fn is None or self.is_initializing():
            # Sequential nn.scan — also the init path (creates the stacked
            # params the pipeline slices per stage).
            x, _ = stages(x, None)
        else:
            stacked = self.variables["params"]["stages"]
            stage = ViTStage(**stage_kw)

            def stage_fn(p, h_act):
                y, _ = stage.apply({"params": p}, h_act)
                return y

            x = pipeline_fn(stage_fn, stacked, x)
        return _final_norm(self, x)


class SimpleFeaturePyramid(nn.Module):
    """ViTDet SFP: stride-16 map → {P2..P6} 256-channel pyramid."""

    channels: int = 256
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, feat: jnp.ndarray) -> Dict[int, jnp.ndarray]:
        def out_convs(y, lv):
            y = nn.Conv(self.channels, (1, 1), dtype=self.dtype,
                        param_dtype=jnp.float32, name=f"out{lv}_1")(y)
            y = nn.Conv(self.channels, (3, 3), padding=[(1, 1), (1, 1)],
                        dtype=self.dtype, param_dtype=jnp.float32,
                        name=f"out{lv}_3")(y)
            return y

        c = feat.shape[-1]
        # stride 4: two stride-2 deconvs (with an intermediate norm+gelu).
        y4 = nn.ConvTranspose(c // 2, (2, 2), strides=(2, 2),
                              dtype=self.dtype, param_dtype=jnp.float32,
                              name="up4_1")(feat)
        y4 = nn.gelu(nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                                  name="up4_ln")(y4))
        y4 = nn.ConvTranspose(c // 4, (2, 2), strides=(2, 2),
                              dtype=self.dtype, param_dtype=jnp.float32,
                              name="up4_2")(y4)
        y8 = nn.ConvTranspose(c // 2, (2, 2), strides=(2, 2),
                              dtype=self.dtype, param_dtype=jnp.float32,
                              name="up8")(feat)
        out = {
            2: out_convs(y4, 2),
            3: out_convs(y8, 3),
            4: out_convs(feat, 4),
            5: out_convs(nn.max_pool(feat, (2, 2), strides=(2, 2)), 5),
        }
        out[6] = nn.max_pool(out[5], (1, 1), strides=(2, 2))
        return out


class ViTDet(nn.Module):
    """ViT backbone + SFP + the FPN detection heads.

    Mirrors models/fpn.py::FPNFasterRCNN's method surface (extract /
    rpn_forward / box_head / mask_forward and the attrs the functional
    forwards read), so fpn.forward_train/forward_test/forward_rpn drive it
    via models/zoo.py without modification.
    """

    num_classes: int = 81
    num_anchors: int = 3
    fpn_channels: int = 256
    roi_pool_size: int = 7
    use_mask: bool = False
    mask_pool_size: int = 14
    patch: int = 16
    dim: int = 768
    depth: int = 12
    heads: int = 12
    window: int = 8
    dtype: Dtype = jnp.bfloat16
    # Optional ring-attention backend for the global blocks: a callable
    # (q, k, v) -> out, typically partial(ring_attention, mesh=mesh).
    # Static (non-pytree) module field.
    global_attn_fn: Optional[Any] = None
    # Pipeline parallelism (mutually exclusive with global_attn_fn — both
    # own the mesh `model` axis): number of encoder stages, and the
    # executor (stage_fn, stacked_params, x) -> x built over the mesh
    # (parallel/pipeline.py). pp_stages > 0 selects ViTBackbonePP.
    pp_stages: int = 0
    pipeline_fn: Optional[Any] = None

    def setup(self):
        if self.pp_stages:
            # Raises when depth doesn't divide OR the split can't preserve
            # the ViTDet global-attention placement (hard error, not a
            # warning — a silently different architecture is a trap).
            _stage_global_pattern(self.depth, self.pp_stages)
            self.features = ViTBackbonePP(
                patch=self.patch, dim=self.dim, stages_n=self.pp_stages,
                blocks_per_stage=self.depth // self.pp_stages,
                heads=self.heads, window=self.window, dtype=self.dtype)
        else:
            self.features = ViTBackbone(patch=self.patch, dim=self.dim,
                                        depth=self.depth, heads=self.heads,
                                        window=self.window, dtype=self.dtype)
        self.neck = SimpleFeaturePyramid(channels=self.fpn_channels,
                                         dtype=self.dtype)
        self.rpn = RPNHead(num_anchors=self.num_anchors,
                           channels=self.fpn_channels, dtype=self.dtype)
        self.head = TwoFCHead(dtype=self.dtype)
        self.cls_score = nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=nn.initializers.normal(0.01), name="cls_score")
        self.bbox_pred = nn.Dense(
            self.num_classes * 4, dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=nn.initializers.normal(0.001), name="bbox_pred")
        if self.use_mask:
            self.mask_head = MaskHead(num_classes=self.num_classes,
                                      dtype=self.dtype)

    def extract(self, images: jnp.ndarray,
                masks=None) -> Dict[int, jnp.ndarray]:
        """masks (graftcanvas): packed-canvas placement masks applied to
        the SFP pyramid outputs. The ViT encoder itself attends across
        the canvas (windowed/global blocks may span placements — a
        documented approximation, unlike the conv families' exact
        re-masking); masking the pyramid keeps the RPN/ROI inputs clean
        so the proposal path stays border-exact."""
        if self.pp_stages:
            feat = self.features(images, self.pipeline_fn)
        else:
            feat = self.features(images, self.global_attn_fn)
        pyramid = self.neck(feat)
        if masks:
            pyramid = {lv: (p * masks[2 ** lv].astype(p.dtype)
                            if 2 ** lv in masks else p)
                       for lv, p in pyramid.items()}
        return pyramid

    def rpn_forward(self, pyramid: Dict[int, jnp.ndarray]):
        from mx_rcnn_tpu.models.fpn import RPN_LEVELS

        return {lv: self.rpn(pyramid[lv]) for lv in RPN_LEVELS}

    def rpn_forward_packed(self, pyramid: Dict[int, jnp.ndarray]):
        """One fused head application over all levels (see
        models/fpn.py::FPNFasterRCNN.rpn_forward_packed)."""
        from mx_rcnn_tpu.models.fpn import apply_rpn_head_packed

        return apply_rpn_head_packed(self.rpn, pyramid)

    def box_head(self, pooled: jnp.ndarray):
        x = self.head(pooled)
        return (island(self.cls_score(x)),
                island(self.bbox_pred(x)))

    def mask_forward(self, pooled: jnp.ndarray):
        return self.mask_head(pooled)

    def __call__(self, images: jnp.ndarray, rois: jnp.ndarray):
        from mx_rcnn_tpu.ops.roi_align import roi_align

        pyramid = self.extract(images)
        rpn_out = self.rpn_forward(pyramid)
        pooled = roi_align(pyramid[2], rois, self.roi_pool_size, 1.0 / 4.0)
        cls, box = self.box_head(pooled)
        outs = (pyramid, rpn_out, cls, box)
        if self.use_mask:
            mp = roi_align(pyramid[2], rois, self.mask_pool_size, 1.0 / 4.0)
            outs = outs + (self.mask_forward(mp),)
        return outs


def build_vitdet_model(cfg: Config, global_attn_fn=None,
                       pipeline_fn=None) -> ViTDet:
    pp_stages = cfg.network.pp_stages
    if pp_stages and global_attn_fn is not None:
        raise ValueError(
            "pp_stages and sequence-parallel attention both claim the mesh "
            "'model' axis; enable one of network.pp_stages / "
            "network.use_ring_attention")
    if pp_stages and cfg.network.tensor_parallel:
        raise ValueError(
            "network.tensor_parallel and network.pp_stages both claim the "
            "mesh 'model' axis (TP rules would shard the stacked STAGE "
            "axis of the scanned stage params); enable only one")
    if pp_stages:
        # Fail fast (before init) on splits that would change the
        # architecture; every constructible staged model preserves the
        # sequential global placement exactly.
        _stage_global_pattern(cfg.network.vit_depth, pp_stages)
    return ViTDet(
        num_classes=cfg.dataset.num_classes,
        num_anchors=cfg.network.num_anchors,
        fpn_channels=cfg.network.fpn_channels,
        roi_pool_size=cfg.network.roi_pool_size,
        use_mask=cfg.network.use_mask,
        mask_pool_size=cfg.network.mask_pool_size,
        patch=cfg.network.vit_patch,
        dim=cfg.network.vit_dim,
        depth=cfg.network.vit_depth,
        heads=cfg.network.vit_heads,
        window=cfg.network.vit_window,
        dtype=model_dtype(cfg),
        global_attn_fn=global_attn_fn,
        pp_stages=pp_stages,
        pipeline_fn=pipeline_fn,
    )


def sequential_to_staged(params, stages_n: int):
    """Convert a ViTDet param tree from the sequential backbone layout
    (`features/block{i}` with globals at depth/4 tails) to the staged/PP
    layout (`features/stages` with leaves stacked on a leading stage axis).

    Enables the train-small → scale-out path: fit with the default
    backbone on one chip, then resume/continue under pp_stages. Valid for
    every stage count the staged backbone itself supports — i.e. whenever
    _stage_global_pattern(depth, stages_n) exists, the staged model runs
    the IDENTICAL architecture (ValueError otherwise). Non-backbone
    leaves pass through unchanged.
    """
    feats = params["params"]["features"]
    blocks = sorted((k for k in feats if k.startswith("block")),
                    key=lambda k: int(k[5:]))
    depth = len(blocks)
    if not depth:
        raise ValueError(
            "no features/block* leaves — not a sequential-backbone param "
            "tree (already staged?)")
    _stage_global_pattern(depth, stages_n)  # architecture must be preserved
    per = depth // stages_n

    # ViTStage names its blocks positionally: b0..b{per-1}.
    def stage_tree(s):
        return {f"b{j}": feats[blocks[s * per + j]] for j in range(per)}

    stages = jax.tree.map(lambda *leaves: jnp.stack(leaves),
                          *[stage_tree(s) for s in range(stages_n)])
    new_feats = {k: v for k, v in feats.items() if not k.startswith("block")}
    new_feats["stages"] = stages
    return {**params, "params": {**params["params"], "features": new_feats}}


def staged_to_sequential(params):
    """Inverse of sequential_to_staged (stacked stages → block{i}).

    Validates the same architecture constraint as the forward direction:
    a staged layout whose (stages_n, per) split cannot preserve the
    sequential backbone's global placement would convert into params that
    LOAD cleanly (Block shapes are window-independent) but run the wrong
    attention pattern — the architectures differ, so it is rejected.
    """
    feats = params["params"]["features"]
    if "stages" not in feats:
        raise ValueError(
            "no features/stages subtree — not a staged-backbone param tree")
    stages = feats["stages"]
    stages_n = jax.tree.leaves(stages)[0].shape[0]
    names = sorted((k for k in stages
                    if k.startswith("b") and k[1:].isdigit()),
                   key=lambda k: int(k[1:]))
    if not names or len(names) != len(stages):
        raise ValueError(
            f"stage blocks {sorted(stages)} are not the positional "
            "b0..b{n} layout — a pre-round-4 staged checkpoint "
            "(win{i}/glob names) must be converted by the round that "
            "wrote it; refusing to silently drop blocks")
    per = len(names)
    depth = stages_n * per
    try:
        _stage_global_pattern(depth, stages_n)
    except ValueError as e:
        raise ValueError(f"the architectures differ: {e}") from e
    new_feats = {k: v for k, v in feats.items() if k != "stages"}
    for s in range(stages_n):
        for j, name in enumerate(names):
            new_feats[f"block{s * per + j}"] = jax.tree.map(
                lambda a: a[s], stages[name])
    return {**params, "params": {**params["params"], "features": new_feats}}


def init_vitdet_params(model: ViTDet, cfg: Config, rng: jax.Array,
                       image_shape=None):
    h, w = image_shape or (64, 64)
    images = jnp.zeros((1, h, w, 3), jnp.float32)
    rois = jnp.asarray([[0.0, 0.0, 0.0, 31.0, 31.0]], jnp.float32)
    return model.init(rng, images, rois)
