"""Model zoo dispatch — config → (model, init, forwards).

The reference selects its graph builders by name
(train_end2end.py: ``eval('get_' + args.network + '_train')`` over
rcnn/symbol/symbol_vgg.py / symbol_resnet.py). Here the config's
``network.use_fpn`` flag routes between the two model families:

- classic C4 Faster R-CNN (models/faster_rcnn.py): VGG16 / ResNet-50/101
  stride-16 single-level models — the reference's actual graphs;
- FPN Faster/Mask R-CNN (models/fpn.py): BASELINE.json configs 3-4.

Every consumer (trainer, Predictor, bench, CLI) goes through these
functions so the two families stay drop-in interchangeable: the functional
forwards share their input/output contracts.
"""

from __future__ import annotations

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models import faster_rcnn as _c4
from mx_rcnn_tpu.models import fpn as _fpn


def build_model(cfg: Config):
    if cfg.network.use_fpn:
        return _fpn.build_fpn_model(cfg)
    return _c4.build_model(cfg)


def init_params(model, cfg: Config, rng, image_shape=None):
    if isinstance(model, _fpn.FPNFasterRCNN):
        return _fpn.init_fpn_params(model, cfg, rng, image_shape)
    return _c4.init_params(model, cfg, rng, image_shape)


def forward_train(model, params, batch, rng, cfg: Config):
    if isinstance(model, _fpn.FPNFasterRCNN):
        return _fpn.forward_train(model, params, batch, rng, cfg)
    return _c4.forward_train(model, params, batch, rng, cfg)


def forward_test(model, params, images, im_info, cfg: Config):
    if isinstance(model, _fpn.FPNFasterRCNN):
        return _fpn.forward_test(model, params, images, im_info, cfg)
    return _c4.forward_test(model, params, images, im_info, cfg)


def forward_rpn(model, params, images, im_info, cfg: Config):
    if isinstance(model, _fpn.FPNFasterRCNN):
        return _fpn.forward_rpn(model, params, images, im_info, cfg)
    return _c4.forward_rpn(model, params, images, im_info, cfg)
