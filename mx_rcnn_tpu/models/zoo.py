"""Model zoo dispatch — config → (model, init, forwards).

The reference selects its graph builders by name
(train_end2end.py: ``eval('get_' + args.network + '_train')`` over
rcnn/symbol/symbol_vgg.py / symbol_resnet.py). Here the config's
``network.use_fpn`` flag routes between the two model families:

- classic C4 Faster R-CNN (models/faster_rcnn.py): VGG16 / ResNet-50/101
  stride-16 single-level models — the reference's actual graphs;
- FPN Faster/Mask R-CNN (models/fpn.py): BASELINE.json configs 3-4.

Every consumer (trainer, Predictor, bench, CLI) goes through these
functions so the two families stay drop-in interchangeable: the functional
forwards share their input/output contracts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models import faster_rcnn as _c4
from mx_rcnn_tpu.models import fpn as _fpn


def param_flatten_spec(params):
    """Canonical flatten spec: ((path, shape, dtype), ...) for every leaf.

    THE one ordering contract between a model's param tree and flatcore's
    segment tables (train/flatcore.py): `jax.tree_util` flatten order over
    the flax param dict (keys sorted, depth-first), which is deterministic
    for a given tree structure. Every family goes through init_params →
    plain nested dicts, so the spec is derivable from any state that holds
    the tree — params, gradients, or optimizer slots — and two trees with
    the same spec are segment-compatible buffer-for-buffer.
    """
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        keys = [str(getattr(p, "key", getattr(p, "idx", p))) for p in path]
        out.append(("/".join(keys), tuple(leaf.shape),
                    jnp.dtype(leaf.dtype).name))
    return tuple(out)


def _is_pyramid_model(model) -> bool:
    """FPN and ViTDet share the pyramid method surface and the fpn.py
    functional forwards (duck-typed via string method names)."""
    from mx_rcnn_tpu.models import vit as _vit

    return isinstance(model, (_fpn.FPNFasterRCNN, _vit.ViTDet))


def build_model(cfg: Config, mesh=None):
    """Config → model. For ViTDet configs, `mesh` + an SP request turn on
    sequence-parallel global attention over the mesh's model axis: either
    network.use_ring_attention=True (ring by default) or
    network.sp_mode="ulysses" (all-to-all) alone enables it."""
    if cfg.network.sp_mode not in ("ring", "ulysses"):
        raise ValueError(
            f"network.sp_mode must be 'ring' or 'ulysses', got "
            f"{cfg.network.sp_mode!r}")
    if cfg.network.attn_impl not in ("dense", "streaming"):
        raise ValueError(
            f"network.attn_impl must be 'dense' or 'streaming', got "
            f"{cfg.network.attn_impl!r}")
    # SP is requested by use_ring_attention=True (legacy knob, ring by
    # default) or by naming a non-default sp_mode outright; only the ViT
    # global-attention blocks have a sequence to shard.
    wants_sp = (cfg.network.use_ring_attention
                or cfg.network.sp_mode != "ring")
    if wants_sp and not cfg.network.use_vit:
        from mx_rcnn_tpu.logger import logger

        logger.warning(
            "sequence parallelism (use_ring_attention=%s, sp_mode=%r) has "
            "no effect on %s: only the ViTDet global-attention blocks "
            "have a token sequence to shard",
            cfg.network.use_ring_attention, cfg.network.sp_mode,
            cfg.network.name)
    if cfg.network.pp_stages and not cfg.network.use_vit:
        from mx_rcnn_tpu.logger import logger

        logger.warning(
            "network.pp_stages=%d has no effect on %s: only the ViT "
            "encoder has the homogeneous staged structure to pipeline "
            "(parallel/pipeline.py)",
            cfg.network.pp_stages, cfg.network.name)
    if cfg.network.use_detr:
        from mx_rcnn_tpu.models import detr as _detr

        return _detr.build_detr_model(cfg)
    if cfg.network.use_vit:
        from functools import partial

        from mx_rcnn_tpu.models import vit as _vit
        from mx_rcnn_tpu.ops.ring_attention import (
            ring_attention, ulysses_attention)

        attn_fn = None
        if wants_sp and mesh is not None:
            if "model" not in mesh.axis_names:
                # attn_fn shards over axis='model'; without it the failure
                # would surface later as an opaque unbound-axis error inside
                # shard_map. Fail at build time with the real cause.
                raise ValueError(
                    f"sequence parallelism (sp_mode="
                    f"{cfg.network.sp_mode!r}) needs a 'model' axis in the "
                    f"mesh; got axes {mesh.axis_names}. Build the mesh as "
                    "'<data>x<model>' (e.g. --tpu-mesh 2x4) or disable SP")
            if (cfg.network.sp_mode == "ulysses"
                    and cfg.network.vit_heads % mesh.shape["model"] != 0):
                # Fail at build time, not at first trace.
                raise ValueError(
                    f"sp_mode='ulysses' needs vit_heads "
                    f"({cfg.network.vit_heads}) divisible by the mesh "
                    f"model axis ({mesh.shape['model']}); use the ring "
                    "formulation for head-indivisible layouts")
            if cfg.network.pp_stages:
                raise ValueError(
                    "network.pp_stages and sequence parallelism both claim "
                    "the mesh 'model' axis; enable only one")
            sp = (ulysses_attention if cfg.network.sp_mode == "ulysses"
                  else ring_attention)
            attn_fn = partial(sp, mesh=mesh, axis="model")
            if cfg.network.attn_impl == "streaming":
                # Mirrors the pp_stages warning below: the knob is
                # accepted but cannot take effect on this build.
                from mx_rcnn_tpu.logger import logger

                logger.warning(
                    "network.attn_impl='streaming' superseded by "
                    "sequence-parallel attention (sp_mode=%r): the SP "
                    "kernels manage their own attention internals "
                    "(numerics unchanged)", cfg.network.sp_mode)
        elif wants_sp:
            # Not an error: SP modes are exact, so a dense build (inference
            # on one chip — no mesh passed) is mathematically identical —
            # but flag it, since the config asked for a parallel layout.
            from mx_rcnn_tpu.logger import logger

            logger.warning(
                "sequence parallelism (use_ring_attention=%s, sp_mode=%r) "
                "ignored: build_model() was called without a mesh; using "
                "dense attention (same numerics, no SP)",
                cfg.network.use_ring_attention, cfg.network.sp_mode)
        if attn_fn is None and cfg.network.attn_impl == "streaming":
            if cfg.network.pp_stages:
                # The staged encoder manages its own attention internals;
                # the knob cannot be routed through pipeline_fn.
                from mx_rcnn_tpu.logger import logger

                logger.warning(
                    "network.attn_impl='streaming' ignored under "
                    "pp_stages=%d (the staged ViT encoder uses its own "
                    "dense attention; numerics unchanged)",
                    cfg.network.pp_stages)
            else:
                # Flash-style streaming softmax for the single-device
                # dense path: O(S·chunk) score memory instead of O(S²).
                # Exact (the kernel SP uses locally); a speed/memory
                # knob, not an approximation (PERF.md r5).
                from mx_rcnn_tpu.ops.ring_attention import (
                    streaming_attention)

                attn_fn = partial(streaming_attention,
                                  kv_chunk=cfg.network.attn_kv_chunk)
        pipeline_fn = None
        if cfg.network.pp_stages and mesh is not None:
            if "model" not in mesh.axis_names or (
                    mesh.shape["model"] != cfg.network.pp_stages):
                raise ValueError(
                    f"network.pp_stages={cfg.network.pp_stages} needs a "
                    f"mesh model axis of that size; got "
                    f"{dict(zip(mesh.axis_names, mesh.devices.shape))}. "
                    "Build the mesh as '<data>x<stages>' "
                    f"(e.g. --tpu-mesh 2x{cfg.network.pp_stages})")
            from mx_rcnn_tpu.parallel.pipeline import pipeline_apply

            def pipeline_fn(stage_fn, stacked, x, _mesh=mesh):
                return pipeline_apply(
                    stage_fn, stacked, x, _mesh, axis="model",
                    microbatches=cfg.network.pp_microbatches or None)
        elif cfg.network.pp_stages:
            from mx_rcnn_tpu.logger import logger

            logger.warning(
                "network.pp_stages=%d: no mesh at build time — running the "
                "staged backbone SEQUENTIALLY (same params and numerics, "
                "no pipelining)", cfg.network.pp_stages)
        return _vit.build_vitdet_model(cfg, global_attn_fn=attn_fn,
                                       pipeline_fn=pipeline_fn)
    if cfg.network.use_fpn:
        return _fpn.build_fpn_model(cfg)
    return _c4.build_model(cfg)


def init_params(model, cfg: Config, rng, image_shape=None):
    from mx_rcnn_tpu.models import detr as _detr
    from mx_rcnn_tpu.models import vit as _vit

    if isinstance(model, _detr.DETR):
        return _detr.init_detr_params(model, cfg, rng, image_shape)
    if isinstance(model, _vit.ViTDet):
        return _vit.init_vitdet_params(model, cfg, rng, image_shape)
    if isinstance(model, _fpn.FPNFasterRCNN):
        return _fpn.init_fpn_params(model, cfg, rng, image_shape)
    return _c4.init_params(model, cfg, rng, image_shape)


def _is_detr(model) -> bool:
    from mx_rcnn_tpu.models import detr as _detr

    return isinstance(model, _detr.DETR)


def forward_train(model, params, batch, rng, cfg: Config):
    if _is_detr(model):
        from mx_rcnn_tpu.models import detr as _detr

        return _detr.forward_train(model, params, batch, rng, cfg)
    if _is_pyramid_model(model):
        return _fpn.forward_train(model, params, batch, rng, cfg)
    return _c4.forward_train(model, params, batch, rng, cfg)


def forward_test(model, params, images, im_info, cfg: Config):
    if _is_detr(model):
        from mx_rcnn_tpu.models import detr as _detr

        return _detr.forward_test(model, params, images, im_info, cfg)
    if _is_pyramid_model(model):
        return _fpn.forward_test(model, params, images, im_info, cfg)
    return _c4.forward_test(model, params, images, im_info, cfg)


def forward_rpn(model, params, images, im_info, cfg: Config):
    if _is_detr(model):
        raise NotImplementedError("DETR has no RPN / proposal path")
    if _is_pyramid_model(model):
        return _fpn.forward_rpn(model, params, images, im_info, cfg)
    return _c4.forward_rpn(model, params, images, im_info, cfg)
