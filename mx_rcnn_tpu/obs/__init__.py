"""graftscope — structured runtime telemetry for train/eval/bench.

The reference repo's only runtime signal is the Speedometer log line;
graftscope adds the machine-readable layer underneath it:

- ``events``:        typed append-only JSONL event stream (EventLog /
                     NullEventLog; schema = EVENT_TYPES)
- ``timing``:        StepTimer — per-iteration data-wait / dispatch /
                     step split, no host syncs added
- ``compile_track``: every XLA compile becomes a ``compile`` event with
                     the triggering batch-shape signature
- ``watchdog``:      StallWatchdog — a hung run emits a ``stall`` event
                     with stack dumps instead of dying as a bare rc=124
- ``report``:        ``python -m mx_rcnn_tpu.obs.report`` folds a run's
                     JSONL into a human summary + BENCH-compatible JSON

graftprof (this layer's profiling/cost pass) adds:

- ``costs``:         XLA ``cost_analysis``/``memory_analysis`` per
                     compiled shape bucket → ``cost`` events, computed
                     MFU, HBM footprint, padding-waste accounting
- ``profile``:       programmatic jax.profiler capture windows
                     (``obs.trace_at_step``; stall-armed) + a coarse
                     trace summarizer → ``trace`` events
- ``ledger``:        ``python -m mx_rcnn_tpu.obs.ledger`` — append-only
                     cross-run perf history (PERF_LEDGER.jsonl) with a
                     regression-gating ``check`` subcommand

Enable on any training entry point with config overrides::

    --set obs.enabled=true --set obs.dir=runs/myrun

When disabled (the default) every surface degrades to a no-op sink and
the train hot path is unchanged. See the README's graftscope section for
the event schema.
"""

from __future__ import annotations

from mx_rcnn_tpu.obs.events import (
    EVENT_TYPES,
    EventLog,
    NullEventLog,
    env_fingerprint,
    event_log_path,
    open_event_log,
    run_meta_fields,
)
from mx_rcnn_tpu.obs.timing import StepTimer
from mx_rcnn_tpu.obs.watchdog import StallWatchdog

# NOTE: costs (CostTracker) and profile (TraceController) are NOT
# imported here — costs needs numpy, and the `python -m
# mx_rcnn_tpu.obs.report` / `...obs.ledger` CLIs promise a stdlib-only
# import chain (foldable on any machine the JSON is copied to). Import
# them from their submodules.

__all__ = [
    "EVENT_TYPES",
    "EventLog",
    "NullEventLog",
    "StallWatchdog",
    "StepTimer",
    "event_log_path",
    "obs_from_config",
    "open_event_log",
    "run_meta_fields",
]


def obs_from_config(cfg, default_dir: str = ""):
    """Config → sink: a real EventLog when ``cfg.obs.enabled`` (at
    ``cfg.obs.dir``, else ``default_dir``), the NullEventLog otherwise.
    The disabled path touches no filesystem and imports no jax."""
    if not cfg.obs.enabled:
        return NullEventLog()
    directory = cfg.obs.dir or default_dir
    if not directory:
        raise ValueError(
            "obs.enabled=true needs obs.dir (or a caller-provided run "
            "directory) to place this process's events_p<k>.jsonl")
    try:
        # Coordination identity, not raw jax: under the graftquorum
        # simulated-host tests each CPU process stamps (and names its
        # JSONL after) the host index it is standing in for, so the
        # report's per-host fold sees the fleet it would see on a pod.
        from mx_rcnn_tpu.parallel.distributed import process_index as _pi

        process_index = _pi()
    except (ImportError, RuntimeError):
        process_index = 0
    return open_event_log(directory, process_index=process_index,
                          flush_every=cfg.obs.flush_every)
