"""Compile-event accounting via jax.monitoring.

Steady-state recompiles are the silent throughput killer on TPU: a shape
that drifts (an unpadded tail batch, a new pad bucket, a donation-layout
mismatch) costs minutes of XLA time that shows up only as a mysteriously
slow step. jax emits per-compile durations on its monitoring bus
(``/jax/core/compile/{jaxpr_trace,jaxpr_to_mlir_module,backend_compile}
_duration``); this module forwards them to the active EventLog as
``compile`` records, labelled with the shape signature of the batch most
recently handed to the train loop (StepTimer calls ``note_batch``) — the
prime recompile suspect.

jax's listener registry is append-only (no unregister), so the listener
is installed once per process and routed through a module-level active
sink; ``deactivate()`` just clears the sink. With no active sink the
listener is a two-comparison no-op.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from mx_rcnn_tpu.obs.events import EventLog

_lock = threading.Lock()
_active: Optional[EventLog] = None
_installed = False
_batch = None  # the most recently dispatched batch (a dict of arrays)

#: monitoring keys forwarded as compile events; the last path segment
#: (minus "_duration") becomes the record's ``phase`` field. Only
#: backend_compile is a real XLA compile — report counts those.
_COMPILE_SUFFIX = "_duration"
_COMPILE_MARKER = "/compile/"


def note_batch(batch) -> None:
    """Remember the batch about to be dispatched (cheap: one ref store).
    Read back only if a compile event actually fires."""
    global _batch
    _batch = batch


def shape_signature() -> Optional[Dict[str, Any]]:
    """Shapes of the last noted batch, or None before the first step
    (init/first-trace compiles have no triggering batch)."""
    batch = _batch
    if batch is None:
        return None
    try:
        return {k: list(getattr(v, "shape", ())) for k, v in batch.items()}
    except AttributeError:  # not a mapping — stringify the type instead
        return {"batch": [repr(type(batch))]}


class CompileCounter:
    """Tally of real XLA compiles observed while registered — graftprof's
    per-bench-row compile accounting (``compile_s`` / ``n_executables``).
    A persistent-cache hit fires no backend_compile event, so a warm row
    honestly reports 0 executables built."""

    def __init__(self):
        self.n = 0
        self.seconds = 0.0


_counters: list = []


def _on_event_duration(event: str, duration_secs: float, **kwargs) -> None:
    if _COMPILE_MARKER not in event:
        return
    phase = event.rsplit("/", 1)[-1]
    if phase.endswith(_COMPILE_SUFFIX):
        phase = phase[: -len(_COMPILE_SUFFIX)]
    if phase == "backend_compile" and _counters:
        with _lock:
            for c in _counters:
                c.n += 1
                c.seconds += duration_secs
    log = _active
    if log is None:
        return
    log.emit("compile", phase=phase, event=event,
             duration_ms=round(duration_secs * 1e3, 3),
             shapes=shape_signature())


def _ensure_installed() -> bool:
    """Register the jax.monitoring listener once per process."""
    global _installed
    with _lock:
        if not _installed:
            try:
                import jax.monitoring

                jax.monitoring.register_event_duration_secs_listener(
                    _on_event_duration)
            except (ImportError, AttributeError):
                return False
            _installed = True
    return True


def count() -> "_CountContext":
    """Context manager tallying backend compiles in its window::

        with compile_track.count() as cc:
            ...  # compiles here
        row["compile_s"], row["n_executables"] = cc.seconds, cc.n

    Independent of any active EventLog (bench child processes count
    their own compiles with no sink attached); nested counters all see
    every compile in their window."""
    return _CountContext()


class _CountContext:
    def __enter__(self) -> CompileCounter:
        self.counter = CompileCounter()
        if _ensure_installed():
            with _lock:
                _counters.append(self.counter)
        return self.counter

    def __exit__(self, *exc):
        with _lock:
            if self.counter in _counters:
                _counters.remove(self.counter)
        return False


def activate(log: EventLog) -> bool:
    """Route compile events to ``log``. Returns False when jax (or its
    monitoring bus) is unavailable — telemetry degrades, never blocks."""
    global _active
    if not _ensure_installed():
        return False
    with _lock:
        _active = log
    return True


def deactivate() -> None:
    global _active, _batch
    with _lock:
        _active = None
        _batch = None
