"""graftprof cost accounting — FLOPs/HBM straight from XLA, per executable.

The ROADMAP's MFU push (0.28 → 0.45) was blocked on attribution: the
repo's best efficiency number was ONE hand-derived scalar (BENCH_r04's
0.2811), and nothing could say what a compiled step actually costs in
FLOPs or HBM, or how much of the conv work is burned on pad-bucket
padding. This module closes that gap at the only honest source — the
compiled executable itself:

- ``executable_costs``: wraps ``Compiled.cost_analysis()`` /
  ``Compiled.memory_analysis()`` into one flat dict
  (``flops``, ``bytes_accessed``, ``hbm_args/temps/output/alias``,
  ``hbm_bytes``) that works on every backend jax exposes the analyses on
  (CPU included — the tier-1 gate runs there).
- ``mfu_from``: measured step rate × analytic FLOPs ÷ chip peak — the
  computed MFU that replaces the hand model in bench rows and reports.
- ``batch_pad_waste``: real pixels ÷ canvas pixels for one batch, from
  ``im_info`` (the loader records the pre-pad size there) — the measured
  baseline for the canvas-packing lever (ROADMAP MFU item, lever 3).
- ``CostTracker``: the train-loop hook — one ``cost`` event per compiled
  shape bucket (FPN multi-scale runs compile one executable per pad
  bucket; their FLOPs differ, so per-bucket MFU needs per-bucket costs).

Everything here degrades, never blocks: a backend without cost analysis
yields partial dicts, and the tracker disarms itself on the first
failure (telemetry must not kill a training run).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

#: v5e per-chip peak FLOP/s by compute dtype (graftcast,
#: train.compute_dtype): the MXU's bf16 peak is ~2x its f32 peak, so an
#: MFU must divide by the peak of the dtype the step actually ran —
#: grading a bf16 step against the f32 peak would read ~2x inflated,
#: and an f32 step against the bf16 peak ~2x deflated. Keeping the
#: table here keeps report folding jax-free: cost events carry the peak
#: they were computed against.
PEAK_FLOPS = {
    "bfloat16": 197e12,
    "float32": 98.5e12,
}

#: legacy alias — the bf16 peak, the only dtype the repo ran before
#: graftcast (every pre-round-8 ledger/bench row is a bf16 row).
V5E_PEAK_FLOPS = PEAK_FLOPS["bfloat16"]


def peak_flops_for(compute_dtype: Optional[str]) -> float:
    """Per-chip peak for a compute dtype name (canonical or the "f32"/
    "bf16" short spellings); None/unknown falls back to the bf16 peak —
    the pre-graftcast convention every historical row used."""
    if not compute_dtype:
        return V5E_PEAK_FLOPS
    name = {"f32": "float32", "bf16": "bfloat16"}.get(
        str(compute_dtype), str(compute_dtype))
    return PEAK_FLOPS.get(name, V5E_PEAK_FLOPS)


def executable_costs(compiled) -> Dict[str, Any]:
    """XLA's analytic cost + memory accounting for ONE compiled executable.

    Returns a flat dict: ``flops`` / ``bytes_accessed`` from
    ``cost_analysis()`` (per-device numbers for SPMD programs — XLA
    analyzes the partitioned module), and the HBM footprint split from
    ``memory_analysis()``: ``hbm_args`` (live inputs), ``hbm_temps``
    (scratch), ``hbm_output``, ``hbm_alias`` (donated input/output
    aliasing), plus ``hbm_bytes`` = args + temps + output − alias (the
    peak working set; donated buffers must not double-count). Keys are
    omitted, not zeroed, when a backend lacks the analysis."""
    out: Dict[str, Any] = {}
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):  # older jax: one dict per device
            analysis = analysis[0] if analysis else {}
        if analysis:
            out["flops"] = float(analysis.get("flops", 0.0))
            out["bytes_accessed"] = float(
                analysis.get("bytes accessed", 0.0))
    except Exception:  # noqa: BLE001  # graftlint: disable=broad-except — backend-dependent API (unimplemented/runtime errors vary); cost accounting degrades to a partial dict, never raises into the run
        pass
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            args = float(getattr(mem, "argument_size_in_bytes", 0) or 0)
            temps = float(getattr(mem, "temp_size_in_bytes", 0) or 0)
            outb = float(getattr(mem, "output_size_in_bytes", 0) or 0)
            alias = float(getattr(mem, "alias_size_in_bytes", 0) or 0)
            out.update(hbm_args=args, hbm_temps=temps, hbm_output=outb,
                       hbm_alias=alias,
                       hbm_bytes=max(0.0, args + temps + outb - alias))
    except Exception:  # noqa: BLE001  # graftlint: disable=broad-except — same degradation contract as above
        pass
    return out


def mfu_from(flops: Optional[float], steps_per_sec: float,
             peak_flops: float = V5E_PEAK_FLOPS) -> Optional[float]:
    """Computed MFU: analytic per-step FLOPs × measured step rate ÷ peak.

    ``cost_analysis()`` counts the per-device (SPMD-partitioned) program,
    so per-device flops × steps/sec ÷ per-chip peak IS the per-chip MFU
    — no extra device_count factor (the bench.py convention)."""
    if not flops or steps_per_sec <= 0 or peak_flops <= 0:
        return None
    return (flops * steps_per_sec) / peak_flops


def batch_pad_waste(batch) -> Dict[str, Any]:
    """Padding waste of one batch: real pixels ÷ canvas pixels.

    ``im_info`` rows are ``[h, w, scale]`` (or graftcanvas packed
    ``[h, w, scale, y0, x0]``) with (h, w) the content size; the canvas
    is the image tensor's static (H, W) × its PLANE count — for a
    bucketed batch that is one canvas per im_info row, for a packed
    batch one per canvas plane holding several rows, so packed rows
    honestly report canvas utilization. Works on plain and multi-step-
    dispatch-stacked batches (leading-axes flattening). Returns {} when
    the batch lacks the train contract keys (custom loaders)."""
    try:
        image = batch["image"]
        info = np.asarray(batch["im_info"], np.float64)
    except (KeyError, TypeError):
        return {}
    shape = getattr(image, "shape", ())
    if len(shape) < 3 or info.ndim < 1:
        return {}
    canvas_h, canvas_w = int(shape[-3]), int(shape[-2])
    planes = int(np.prod(shape[:-3], dtype=np.int64)) if len(shape) > 3 else 1
    rows = info.reshape(-1, info.shape[-1])
    real = float(np.sum(rows[:, 0] * rows[:, 1]))
    canvas = float(planes * canvas_h * canvas_w)
    if canvas <= 0:
        return {}
    return {
        "canvas": [canvas_h, canvas_w],
        "real_px": int(real),
        "canvas_px": int(canvas),
        "pad_waste": round(1.0 - real / canvas, 4),
    }


def step_fields(batch) -> Dict[str, Any]:
    """The per-step enrichment StepTimer attaches to ``step`` events:
    the batch's canvas + pad-waste fraction (host-side numpy arithmetic
    over ``im_info`` — no device touch, no added sync)."""
    pw = batch_pad_waste(batch)
    if not pw:
        return {}
    return {"canvas": pw["canvas"], "pad_waste": pw["pad_waste"]}


class CostTracker:
    """One ``cost`` event per compiled shape bucket of the train step.

    ``observe(step_fn, state, batch, key)`` is called once per dispatch
    (host-side, before the call): on a batch-shape signature it has not
    seen it AOT-lowers the step (``step_fn.lower(...).compile()``) and
    emits the executable's cost/memory accounting. The AOT compile of an
    already-jitted program is a persistent-compile-cache hit — the extra
    cost is one tracing pass per bucket, paid only with obs enabled.
    Every other dispatch is one dict lookup.

    Self-disarming: any failure (TP pre-placement quirks, a backend
    without AOT) switches the tracker off for the rest of the run —
    attribution is telemetry, not a dependency of training."""

    def __init__(self, elog, label: str = "train_step",
                 peak_flops: Optional[float] = None,
                 compute_dtype: Optional[str] = None):
        """``compute_dtype`` (graftcast policy, canonical name) selects
        the dtype-correct peak when ``peak_flops`` is not given and is
        stamped on every ``cost`` event so report/ledger folding can
        split rows by dtype."""
        self.elog = elog
        self.label = label
        self.compute_dtype = compute_dtype
        self.peak_flops = float(peak_flops if peak_flops is not None
                                else peak_flops_for(compute_dtype))
        self._seen: set = set()
        self._disabled = False

    def reset(self):
        """Forget seen buckets — called when the session is rebuilt
        (graftheal): an elastic re-mesh changes the PER-DEVICE program
        behind the same global batch shape, so the old cost events no
        longer describe the running executable. Re-arms the tracker too
        (a heal is a new backend; a prior AOT failure may not recur)."""
        self._seen.clear()
        self._disabled = False

    def _bucket_key(self, batch):
        try:
            return tuple(sorted(
                (k, tuple(getattr(v, "shape", ()))) for k, v in batch.items()))
        except (AttributeError, TypeError):
            return None

    def observe(self, step_fn, state, batch, key) -> None:
        if self._disabled or not self.elog.enabled:
            return
        bucket = self._bucket_key(batch)
        if bucket is None or bucket in self._seen:
            return
        self._seen.add(bucket)
        try:
            compiled = step_fn.lower(state, batch, key).compile()
            costs = executable_costs(compiled)
        except Exception as exc:  # noqa: BLE001  # graftlint: disable=broad-except — AOT support varies by backend/sharding mode; the tracker disarms instead of killing the run
            from mx_rcnn_tpu.logger import logger

            logger.warning("graftprof cost tracking disabled: %r", exc)
            self._disabled = True
            return
        shapes = {k: list(getattr(v, "shape", ())) for k, v in batch.items()}
        extra = ({"compute_dtype": self.compute_dtype}
                 if self.compute_dtype else {})
        self.elog.emit("cost", label=self.label, shapes=shapes,
                       peak_flops=self.peak_flops, **extra, **costs)
