"""graftscope event stream — append-only JSONL telemetry records.

The repo's only runtime signal used to be the Speedometer samples/sec log
line; when a run stalled or died (BENCH_r05 rc=124) there was no artifact
saying which phase was at fault. This module is the sink every runtime
surface (train loop, eval, bench, profiler, watchdog) writes through:
one typed JSON record per line, machine-foldable by ``obs.report`` into
run summaries and BENCH-compatible blobs.

Design rules:

- **Typed records.** ``EVENT_TYPES`` is the closed schema; ``emit`` raises
  on anything else, and the graftlint rule ``obs-event-schema`` enforces
  literal, known type keys at lint time (new record kinds are a schema
  change, reviewed here, not ad-hoc strings at call sites).
- **No-op when disabled.** ``NullEventLog`` has the same surface and does
  nothing — the train hot path stays allocation-free when telemetry is
  off (``StepTimer.iterate`` degrades to ``enumerate``).
- **jax-free.** This module (and ``report``) imports only the stdlib, so
  a run's JSONL can be folded on any machine, including one without the
  accelerator stack.

Every record carries wall time (``t_wall``, epoch seconds — correlate
across hosts/logs), monotonic time (``t_mono`` — durations immune to NTP
steps), the emitting process index, and the global step counter at emit
time (``step`` — set by StepTimer; 0 before training starts).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import threading
import time
from typing import Any, Dict, Optional

#: The closed record schema. Adding a kind here is a schema change:
#: update the README table and obs/report.py's folding in the same PR
#: (the obs-event-schema lint rule reads this tuple from the AST).
EVENT_TYPES = (
    "run_meta",    # once per run: config digest, mesh, versions, git sha
    "step",        # per train iteration (StepTimer) / per timed profile row;
                   # Speedometer windows carry samples_per_sec instead
    "epoch",       # epoch boundary with the drained MetricBag means
    "compile",     # one XLA compile (jax.monitoring), with shape signature
    "checkpoint",  # checkpoint save enqueued/written
    "eval",        # one evaluation pass (pred_eval) with its result dict
    "stall",       # watchdog: no step completed within the stall threshold
    "crash",       # unhandled exception in the train loop (re-raised)
    "bench",       # one bench.py config measurement
    "backend_retry",  # graftguard: transient backend failure; sleeping
                      # sleep_s before attempt+1 (resilience/backend.py)
    "backend_up",  # graftguard: backend acquired (attempts, waited_s)
    "preempt",     # SIGTERM/SIGINT honored at a step boundary; emergency
                   # checkpoint state in `saved` (resilience/preempt.py)
    "heal",        # graftheal: step-time backend loss recovered in-process
                   # (capture mode, downtime_s, devices before/after —
                   # resilience/heal.py)
    "cost",        # graftprof: XLA cost/memory accounting for one
                   # compiled shape bucket (flops, hbm split — obs/costs.py)
    "trace",       # graftprof: one closed jax.profiler capture window
                   # (dir + coarse phase summary — obs/profile.py)
    "health",      # graftpulse: one cadenced numerics reading — loss (+
                   # z-score), per-buffer grad/param/update norms and
                   # nonfinite counts (obs/health.py HealthMonitor over
                   # train/health.py's in-graph reductions)
    "anomaly",     # graftpulse tripwire: a health reading crossed a
                   # tripwire (nonfinite, grad explosion, loss z-score)
                   # — reasons, emergency-checkpoint path, flight-dump
                   # path (obs/health.py)
    "quorum",      # graftquorum: one coordination round — kind
                   # (preempt/heal/excluded), hosts arrived/excluded,
                   # agreed boundary or topology (resilience/quorum.py
                   # via tools/train.py; the process stamp says which
                   # host's view this record is)
    "heartbeat",   # grafttower: cadenced liveness beacon from the
                   # watchdog thread (obs.heartbeat_every_s) — beat_age_s
                   # since the last completed step, stall count, and
                   # final=True exactly once at clean shutdown; a host
                   # whose stream ends with a STALE non-final heartbeat
                   # was killed, not slow (obs/watchdog.py, obs/fleet.py)
    "barrier",     # grafttower: one quorum barrier from THIS host's
                   # view — name, per-host wait_s, arrival order, who
                   # arrived last, timed_out (resilience/quorum.py; the
                   # fleet fold attributes everyone's wait to the last
                   # arriver)
    "data",        # graftfeed: one input-plane incident — kind
                   # quarantine (record id + reason + deterministic
                   # replacement), retry (transient IO flake ridden out
                   # under data.record_deadline_s), quarantine_applied
                   # (a resume re-armed a prior run's quarantine.jsonl),
                   # quarantine_cap (fraction cap tripped — the abort),
                   # stall (next() blew data.wait_deadline_s —
                   # DataStallError) (data/feedguard.py, data/loader.py)
    "data_worker", # graftfeed: one prefetch-worker death — worker name,
                   # the queue position its claim was requeued at,
                   # deaths so far vs data.worker_restart_max, and
                   # whether a replacement thread was spawned
                   # (data/loader.py worker supervision)
)

#: Buffered kinds — everything else flushes to disk immediately, so the
#: record survives the very hang/crash it is diagnosing.
_BUFFERED_TYPES = frozenset({"step", "compile"})


def _json_default(value: Any):
    """Last-resort JSON coercion: numpy scalars/arrays (via item/tolist)
    without importing numpy; everything else degrades to repr."""
    for attr in ("tolist", "item"):
        fn = getattr(value, attr, None)
        if callable(fn):
            try:
                return fn()
            except (TypeError, ValueError):
                continue
    return repr(value)


class NullEventLog:
    """The disabled sink: same surface as EventLog, does nothing.

    ``enabled`` is the branch guard consumers use to keep even kwargs
    construction off the hot path when telemetry is off.
    """

    enabled = False
    path: Optional[str] = None
    step = 0

    def emit(self, type_: str, **fields):
        return None

    def attach_ring(self, ring):
        return None

    def set_step(self, step: int):
        return None

    def flush(self):
        return None

    def close(self):
        return None


class EventLog:
    """Append-only JSONL sink with typed records.

    Thread-safe (the stall watchdog emits from its own thread). ``step``
    and ``compile`` records buffer up to ``flush_every`` lines; every
    other kind flushes immediately (see _BUFFERED_TYPES).
    """

    enabled = True

    def __init__(self, path: str, process_index: int = 0,
                 flush_every: int = 64):
        self.path = path
        self.process_index = int(process_index)
        self.flush_every = max(1, int(flush_every))
        self.step = 0
        self._lock = threading.Lock()
        self._buf: list = []
        self._ring = None
        self._fh: Optional[io.TextIOBase] = open(path, "a", encoding="utf-8")

    def attach_ring(self, ring):
        """graftpulse flight recorder (obs/health.py FlightRecorder):
        every emitted record is ALSO appended to ``ring`` — at emit time,
        before any disk buffering, so the crash-time dump holds the step/
        compile records the flush cadence has not written yet."""
        self._ring = ring

    def set_step(self, step: int):
        """Update the global step counter stamped on subsequent records
        (called by StepTimer after each completed iteration)."""
        self.step = int(step)

    def emit(self, type_: str, **fields):
        """Append one typed record. Raises ValueError on a type outside
        EVENT_TYPES — the schema is closed (see module docstring)."""
        if type_ not in EVENT_TYPES:
            raise ValueError(
                f"unknown event type {type_!r}; the graftscope schema is "
                f"{EVENT_TYPES} (extend obs/events.py::EVENT_TYPES to add "
                "a record kind)")
        record: Dict[str, Any] = {
            "type": type_,
            "t_wall": time.time(),
            "t_mono": time.monotonic(),
            "process": self.process_index,
            "step": self.step,
        }
        record.update(fields)
        if self._ring is not None:
            self._ring.append(record)
        line = json.dumps(record, default=_json_default)
        with self._lock:
            if self._fh is None:
                return
            self._buf.append(line)
            if (type_ not in _BUFFERED_TYPES
                    or len(self._buf) >= self.flush_every):
                self._flush_locked()

    def _flush_locked(self):
        if self._buf and self._fh is not None:
            self._fh.write("\n".join(self._buf) + "\n")
            self._fh.flush()
            self._buf.clear()

    def flush(self):
        with self._lock:
            self._flush_locked()

    def close(self):
        with self._lock:
            self._flush_locked()
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def event_log_path(directory: str, process_index: int = 0) -> str:
    """events_p<k>.jsonl — one stream per process (JSONL appends from
    multiple writers interleave), including process 0: on a fleet every
    host's stream is a peer input to the grafttower merge, not a special
    case. report.py::load_events also folds the pre-grafttower names
    (events.jsonl / events.<i>.jsonl) so old run dirs stay readable."""
    return os.path.join(directory, f"events_p{process_index}.jsonl")


def open_event_log(directory: str, process_index: int = 0,
                   flush_every: int = 64, fresh: bool = False) -> EventLog:
    """Create ``directory`` and open this process's event log in it.

    ``fresh=True`` truncates an existing stream first — for per-run
    artifacts in a fixed directory (bench, profiler), where appending a
    second run would silently fold both runs into one report. Training
    keeps the append default: a resumed run IS the same run.
    """
    os.makedirs(directory, exist_ok=True)
    path = event_log_path(directory, process_index)
    if fresh and os.path.exists(path):
        os.remove(path)
    return EventLog(path, process_index=process_index,
                    flush_every=flush_every)


def _git_sha(start: str) -> Optional[str]:
    """Best-effort HEAD sha by reading .git directly (no subprocess)."""
    cur = os.path.abspath(start)
    while True:
        git = os.path.join(cur, ".git")
        if os.path.isdir(git):
            try:
                with open(os.path.join(git, "HEAD"), encoding="utf-8") as fh:
                    head = fh.read().strip()
                if head.startswith("ref: "):
                    ref = os.path.join(git, *head[5:].split("/"))
                    with open(ref, encoding="utf-8") as fh:
                        return fh.read().strip()
                return head or None
            except OSError:
                return None
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return None
        cur = nxt


def _git_dirty(start: str) -> Optional[bool]:
    """Best-effort working-tree dirtiness: one ``git status --porcelain``
    capped at 5 s (the only subprocess in this module — the sha reader
    stays file-based). None when git is unavailable, times out, or the
    path is not a work tree: run_meta omits unknowns rather than guess."""
    import subprocess

    try:
        proc = subprocess.run(
            ["git", "-C", start, "status", "--porcelain"],
            capture_output=True, timeout=5)
    except (OSError, subprocess.SubprocessError):
        return None
    if proc.returncode != 0:
        return None
    return bool(proc.stdout.strip())


def env_fingerprint() -> Dict[str, Any]:
    """The environment-drift fields (graftpulse satellite): jax/jaxlib
    versions plus a ``git_dirty`` flag, so a cross-run regression in the
    perf ledger is attributable to environment change — an upgraded
    jaxlib or an uncommitted local patch — not just the git sha. Stamped
    into ``run_meta`` and into every bench/ledger row (bench.py)."""
    fields: Dict[str, Any] = {}
    try:
        import jax

        fields["jax_version"] = jax.__version__
    except ImportError:
        pass  # jax-free caller — the fingerprint stays partial
    try:
        import jaxlib

        fields["jaxlib_version"] = jaxlib.__version__
    except (ImportError, AttributeError):
        pass
    dirty = _git_dirty(os.path.dirname(os.path.abspath(__file__)))
    if dirty is not None:
        fields["git_dirty"] = dirty
    return fields


def run_meta_fields(cfg=None, mesh=None, **extra) -> Dict[str, Any]:
    """The ``run_meta`` payload: config digest, mesh shape, jax/jaxlib
    versions, git sha + dirtiness. ``cfg``/``mesh`` are optional so
    jax-free tools (report) and config-free tools (bench across many
    configs) can still stamp a run."""
    fields: Dict[str, Any] = {}
    if cfg is not None:
        # repr of the frozen dataclass tree is a stable, total rendering
        # of every field — the digest changes iff the config does.
        fields["config_digest"] = hashlib.sha256(
            repr(cfg).encode("utf-8")).hexdigest()[:16]
        fields["network"] = cfg.network.name
        fields["dataset"] = cfg.dataset.name
    if mesh is not None:
        fields["mesh"] = dict(
            zip(mesh.axis_names, (int(s) for s in mesh.devices.shape)))
    fields.update(env_fingerprint())
    try:
        import jax

        fields["backend"] = jax.default_backend()
        fields["device_count"] = jax.device_count()
    except (ImportError, RuntimeError):
        pass  # jax-free caller (report tooling) — meta stays partial
    sha = _git_sha(os.path.dirname(os.path.abspath(__file__)))
    if sha:
        fields["git_sha"] = sha
    fields.update(extra)
    return fields
