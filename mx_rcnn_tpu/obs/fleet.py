"""grafttower — fleet-scope fold over per-host graftscope streams.

Every host of a fleet writes its own ``events_p<k>.jsonl`` (obs/events.py)
stamped with both wall time (``t_wall``) and monotonic time (``t_mono``).
Wall clocks across hosts drift (NTP steps of whole seconds are routine on
preemptible fleets), so sorting the union by ``t_wall`` interleaves the
streams in the order the *clocks* claim, not the order the fleet ran.
This module rebuilds one trustworthy fleet timeline and folds it into the
numbers OUTAGES triage needs: who is slow, who is hung, and whose tail
everyone else's barrier wait is paying for.

Alignment is two-stage:

1. **Clock anchor.** Each stream's ``run_meta`` record carries the pair
   (t_wall, t_mono) sampled in one emit — the host's own anchor. Every
   record is projected onto ``t_fleet = anchor_wall + (t_mono -
   anchor_mono)``: durations come from the monotonic clock (immune to NTP
   steps *during* the run), the anchor only places the origin.
2. **Residual skew.** The anchors themselves inherit each host's wall
   offset. ``barrier`` events are the correction signal: a quorum barrier
   releases every host within one poll interval of the same true instant,
   so the per-host median of (own barrier t_fleet − reference host's
   barrier t_fleet) over shared barriers IS the residual offset, and is
   subtracted out. No shared barriers → anchors stand as-is.

stdlib-only, like the rest of the report chain: a run dir scp'd off a pod
folds on any machine.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

#: A heartbeat older than this many emission intervals at end-of-stream
#: (with no ``final`` beat) reads as a killed host, not a slow one. Two
#: intervals tolerate one missed emission under scheduler pressure.
STALE_HEARTBEATS = 2.0


def split_hosts(events: Iterable[Dict[str, Any]]
                ) -> Dict[int, List[Dict[str, Any]]]:
    """Group a folded event list back into per-host streams by the
    ``process`` stamp every record carries."""
    hosts: Dict[int, List[Dict[str, Any]]] = {}
    for e in events:
        hosts.setdefault(int(e.get("process", 0)), []).append(e)
    return hosts


def _anchor(stream: List[Dict[str, Any]]
            ) -> Optional[Tuple[float, float]]:
    """The stream's (t_wall, t_mono) clock anchor: its first record with
    both stamps — normally ``run_meta``, but any record works (the pair
    is sampled in one emit either way)."""
    for e in stream:
        if "t_wall" in e and "t_mono" in e:
            return float(e["t_wall"]), float(e["t_mono"])
    return None


def _project(stream: List[Dict[str, Any]]) -> None:
    """Stamp ``t_fleet`` onto every record of one host stream (in place):
    the anchor's wall origin plus the record's monotonic offset from the
    anchor. Records missing ``t_mono`` (foreign/hand-edited lines) fall
    back to their wall stamp."""
    anchor = _anchor(stream)
    for e in stream:
        if anchor is not None and "t_mono" in e:
            anchor_wall, anchor_mono = anchor
            e["t_fleet"] = anchor_wall + (float(e["t_mono"]) - anchor_mono)
        else:
            e["t_fleet"] = float(e.get("t_wall", 0.0))


def _barrier_marks(stream: List[Dict[str, Any]]) -> Dict[str, float]:
    """name → this host's ``t_fleet`` at each barrier release (the
    residual-skew correction signal; first release wins per name)."""
    marks: Dict[str, float] = {}
    for e in stream:
        if e.get("type") == "barrier" and e.get("name"):
            marks.setdefault(str(e["name"]), float(e["t_fleet"]))
    return marks


def _median(vals: List[float]) -> float:
    vals = sorted(vals)
    mid = len(vals) // 2
    if len(vals) % 2:
        return vals[mid]
    return (vals[mid - 1] + vals[mid]) / 2.0


def merge_streams(hosts: Dict[int, List[Dict[str, Any]]]
                  ) -> List[Dict[str, Any]]:
    """Align every host stream onto one fleet timeline (module docstring)
    and return the union sorted by ``t_fleet``. Mutates the records:
    each gains ``t_fleet``; the per-host corrections applied are left in
    ``fleet_offsets`` on the (lowest-host) ``run_meta`` record so reports
    can say how skewed the clocks were."""
    if not hosts:
        return []
    for stream in hosts.values():
        _project(stream)
    ref = min(hosts)
    ref_marks = _barrier_marks(hosts[ref])
    offsets: Dict[int, float] = {ref: 0.0}
    for idx, stream in hosts.items():
        if idx == ref:
            continue
        deltas = [marks_tf - ref_marks[name]
                  for name, marks_tf in _barrier_marks(stream).items()
                  if name in ref_marks]
        offsets[idx] = _median(deltas) if deltas else 0.0
        if offsets[idx]:
            for e in stream:
                e["t_fleet"] -= offsets[idx]
    merged = [e for stream in hosts.values() for e in stream]
    merged.sort(key=lambda e: e.get("t_fleet", 0.0))
    for e in merged:
        if e.get("type") == "run_meta" and int(e.get("process", 0)) == ref:
            e["fleet_offsets"] = {str(i): round(off, 3)
                                  for i, off in sorted(offsets.items())}
            break
    return merged


# ---------------------------------------------------------------------------
# the fold
# ---------------------------------------------------------------------------

def _percentile(sorted_vals: List[float], pct: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, round(pct / 100.0 * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _step_skew(hosts: Dict[int, List[Dict[str, Any]]]
               ) -> Tuple[List[float], Dict[int, float]]:
    """Per-dispatch cross-host completion skew from StepTimer events.

    Every host dispatches the same (epoch, batch) sequence (SPMD), so the
    spread of ``t_fleet`` at which the hosts complete one dispatch is the
    fleet's lockstep error — and each host's lateness (its completion
    minus the earliest host's) summed over shared dispatches is the
    straggler metric: seconds of fleet time this host ran behind."""
    marks: Dict[Tuple[int, int], Dict[int, float]] = {}
    for idx, stream in hosts.items():
        for e in stream:
            if e.get("type") != "step" or "step_ms" not in e:
                continue
            key = (int(e.get("epoch", -1)), int(e.get("batch", -1)))
            marks.setdefault(key, {})[idx] = float(e["t_fleet"])
    skews: List[float] = []
    lateness: Dict[int, float] = {idx: 0.0 for idx in hosts}
    for per_host in marks.values():
        if len(per_host) < 2:
            continue
        first = min(per_host.values())
        skews.append(max(per_host.values()) - first)
        for idx, tf in per_host.items():
            lateness[idx] += tf - first
    return sorted(skews), lateness


def _fold_barriers(hosts: Dict[int, List[Dict[str, Any]]]
                   ) -> Dict[str, Any]:
    """Barrier accounting with wait attribution: at each barrier the
    waiters' wait_s is owed by the LAST arriver (every barrier event
    names it from the shared arrival stamps, so all host views agree)."""
    rounds: Dict[str, Dict[str, Any]] = {}
    for idx, stream in hosts.items():
        for e in stream:
            if e.get("type") != "barrier":
                continue
            name = str(e.get("name"))
            r = rounds.setdefault(name, {"name": name, "wait_s": {},
                                         "last": None, "timed_out": False})
            r["wait_s"][idx] = float(e.get("wait_s", 0.0))
            if e.get("last") is not None:
                r["last"] = int(e["last"])
            r["timed_out"] = r["timed_out"] or bool(e.get("timed_out"))
    owed: Dict[int, float] = {idx: 0.0 for idx in hosts}
    total_wait = 0.0
    for r in rounds.values():
        total_wait += sum(r["wait_s"].values())
        last = r["last"]
        if last is None:
            continue
        r["owed_s"] = round(sum(w for idx, w in r["wait_s"].items()
                                if idx != last), 3)
        owed.setdefault(last, 0.0)
        owed[last] += r["owed_s"]
    return {
        "rounds": len(rounds),
        "timed_out": sorted(n for n, r in rounds.items() if r["timed_out"]),
        "total_wait_s": round(total_wait, 3),
        "owed_s": {idx: round(s, 3) for idx, s in owed.items()},
        "worst": max(rounds.values(),
                     key=lambda r: r.get("owed_s", 0.0))["name"]
                 if rounds else None,
    }


def _fold_heartbeats(hosts: Dict[int, List[Dict[str, Any]]],
                     fleet_end: float) -> Dict[int, Dict[str, Any]]:
    """Per-host liveness verdict from the heartbeat trail (module
    docstring of obs/watchdog.py): ``clean`` = a final beat was emitted
    (orderly shutdown), ``hung`` = the trail goes stale before the fleet
    ended with no final beat (SIGKILL skips every finally), ``live`` =
    fresh beats to the end (slow-but-alive reads as live + a fat step
    tail in the straggler ranking), ``no-heartbeats`` = the knob was off."""
    out: Dict[int, Dict[str, Any]] = {}
    for idx, stream in hosts.items():
        beats = [e for e in stream if e.get("type") == "heartbeat"]
        if not beats:
            out[idx] = {"status": "no-heartbeats", "beats": 0,
                        "age_s": None, "final": False}
            continue
        last = beats[-1]
        final = any(e.get("final") for e in beats)
        age = fleet_end - float(last["t_fleet"])
        every = float(last.get("every_s") or 0.0)
        if final:
            status = "clean"
        elif every and age > STALE_HEARTBEATS * every:
            status = "hung"
        else:
            status = "live"
        out[idx] = {"status": status, "beats": len(beats),
                    "age_s": round(age, 3), "final": final,
                    "every_s": every,
                    "last_beat_age_s": last.get("beat_age_s")}
    return out


def _timeline(merged: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The coordination-plane narrative: every quorum / heal / preempt /
    barrier / stall / anomaly / crash record on the fleet clock, relative
    to the first merged record."""
    kinds = ("quorum", "heal", "preempt", "barrier", "stall", "anomaly",
             "crash", "backend_retry", "backend_up")
    t0 = merged[0]["t_fleet"] if merged else 0.0
    rows = []
    for e in merged:
        if e.get("type") not in kinds:
            continue
        rows.append({
            "t_s": round(float(e["t_fleet"]) - t0, 3),
            "host": int(e.get("process", 0)),
            "type": e.get("type"),
            "what": e.get("name") or e.get("kind") or e.get("reason")
                    or e.get("error") or "",
        })
    return rows


def fleet_summary(hosts: Dict[int, List[Dict[str, Any]]]
                  ) -> Dict[str, Any]:
    """Fold per-host streams into the fleet report dict. Calls
    merge_streams itself (idempotent when already merged): the summary
    always speaks fleet time."""
    merged = merge_streams(hosts)
    fleet_end = merged[-1]["t_fleet"] if merged else 0.0
    skews, lateness = _step_skew(hosts)
    barriers = _fold_barriers(hosts)
    heartbeats = _fold_heartbeats(hosts, fleet_end)

    per_host: Dict[int, Dict[str, Any]] = {}
    for idx, stream in sorted(hosts.items()):
        step_ms = sorted(float(e["step_ms"]) for e in stream
                         if e.get("type") == "step" and "step_ms" in e)
        wait_ms = sorted(float(e.get("data_wait_ms", 0.0)) for e in stream
                         if e.get("type") == "step" and "step_ms" in e)
        per_host[idx] = {
            "steps": len(step_ms),
            "step_ms_p50": round(_percentile(step_ms, 50), 3),
            "step_ms_p90": round(_percentile(step_ms, 90), 3),
            "data_wait_ms_p50": round(_percentile(wait_ms, 50), 3),
            "lateness_s": round(lateness.get(idx, 0.0), 3),
            "barrier_wait_owed_s": barriers["owed_s"].get(idx, 0.0),
            "heartbeat": heartbeats.get(idx),
        }

    # Straggler ranking: accumulated lateness first (the direct "who ran
    # behind" signal), barrier wait owed as the tie-breaker — a host can
    # be late without a barrier in sight, but owing barrier wait without
    # lateness means the skew hid between step events.
    ranking = sorted(
        per_host,
        key=lambda i: (per_host[i]["lateness_s"],
                       per_host[i]["barrier_wait_owed_s"]),
        reverse=True)
    anchor_meta = next((e for e in merged if "fleet_offsets" in e), None)
    return {
        "hosts": sorted(hosts),
        "offsets_s": (anchor_meta or {}).get("fleet_offsets", {}),
        "per_host": per_host,
        "skew": {
            "dispatches": len(skews),
            "p50_s": round(_percentile(skews, 50), 4),
            "p90_s": round(_percentile(skews, 90), 4),
            "max_s": round(skews[-1], 4) if skews else 0.0,
        },
        "straggler_ranking": ranking,
        "straggler": ranking[0] if len(ranking) > 1 else None,
        "barriers": barriers,
        "hung": sorted(i for i, h in heartbeats.items()
                       if h["status"] == "hung"),
        "timeline": _timeline(merged),
    }


def render_fleet(fs: Dict[str, Any]) -> str:
    """Human rendering of a fleet summary — the straggler table OUTAGES'
    "which host is the problem?" runbook reads top to bottom."""
    sk = fs["skew"]
    lines = [
        "grafttower fleet report",
        f"  hosts:      {len(fs['hosts'])} stream(s) merged"
        + (f" | clock offsets(s) {fs['offsets_s']}" if fs["offsets_s"]
           else ""),
        f"  step skew:  p50 {sk['p50_s']}s, p90 {sk['p90_s']}s, max "
        f"{sk['max_s']}s over {sk['dispatches']} shared dispatch(es)",
        f"  barriers:   {fs['barriers']['rounds']} round(s), "
        f"{fs['barriers']['total_wait_s']}s total wait"
        + (f", worst: {fs['barriers']['worst']}"
           if fs["barriers"]["worst"] else "")
        + (f", TIMED OUT: {fs['barriers']['timed_out']}"
           if fs["barriers"]["timed_out"] else ""),
        "  straggler table (worst first):",
        "    host  steps  step_ms_p50  lateness_s  barrier_owed_s  "
        "heartbeat",
    ]
    for idx in fs["straggler_ranking"]:
        h = fs["per_host"][idx]
        hb = h["heartbeat"] or {}
        hb_txt = hb.get("status", "-")
        if hb.get("age_s") is not None:
            hb_txt += f" (age {hb['age_s']}s)"
        lines.append(
            f"    {idx:<4}  {h['steps']:<5}  {h['step_ms_p50']:<11}  "
            f"{h['lateness_s']:<10}  {h['barrier_wait_owed_s']:<14}  "
            f"{hb_txt}")
    if fs["straggler"] is not None:
        lines.append(f"  straggler:  host {fs['straggler']}")
    if fs["hung"]:
        lines.append(f"  HUNG:       host(s) {fs['hung']} — stale "
                     "heartbeat with no final beat (killed, not slow)")
    for row in fs["timeline"]:
        lines.append(f"    +{row['t_s']:>8.3f}s [h{row['host']}] "
                     f"{row['type']}: {row['what']}")
    return "\n".join(lines)
