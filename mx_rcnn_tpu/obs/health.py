"""graftpulse host layer — health folding, anomaly tripwires, flight recorder.

train/health.py computes the numerics signal INSIDE the compiled step
(per-buffer nonfinite counts + squared norms of grads/params/update,
plus the pooled loss, returned as extra step outputs). This module is
the host half:

- ``HealthMonitor`` stores the latest device-side health dict per
  dispatch (a reference — no sync) and, every ``obs.health_every``
  dispatches, pulls it to host, folds it into one ``health`` event
  (norms, nonfinite counts, loss z-score vs a trailing window) and runs
  the tripwires: any nonfinite count, a grad-norm explosion past
  ``obs.health_grad_factor`` × the trailing median, or a loss z-score
  beyond ``obs.health_loss_z``.
- A tripped wire becomes ACTION, not just a log line: an ``anomaly``
  event, a ``jax.profiler`` window (TraceController.anomaly_window), a
  graftguard-style emergency checkpoint of the last KNOWN-GOOD state
  (refreshed after each clean check — resumable with ``--resume auto``),
  and a flight-recorder dump; then ``obs.health_action="abort"`` raises
  :class:`NumericsAnomaly` (training on NaNs is worse than stopping)
  while ``"warn"`` keeps going.
- ``FlightRecorder`` is the last-K-events in-memory ring every EventLog
  record passes through (``EventLog.attach_ring``): on anomaly, stall,
  heal, preemption or crash the ring is dumped to
  ``<obs dir>/flight_<reason>.json`` — so every rc!=0 artifact says what
  the numbers were doing when it died, including buffered step/compile
  records the JSONL flush cadence had not written yet.

stdlib-only, like events/report: the monitor touches device values only
through ``float()`` at the cadence — no jax import, no numpy.
"""

from __future__ import annotations

import json
import math
import os
import statistics
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.obs.events import _json_default

#: the train/health.py key suffixes/prefixes (kept literal here so this
#: module stays importable without jax — the contract is pinned by tests)
_NF = "/nf"
_SQ = "/sq"
#: train/health.py PIN_PREFIX — full device buffers riding the health
#: dict purely as program-output pins (CPU XLA schedule quirk); NEVER
#: pulled to host, skipped by the cadenced read below.
_PIN = "_pin/"


class NumericsAnomaly(Exception):
    """Raised by HealthMonitor under ``obs.health_action="abort"`` AFTER
    the tripwire actions (anomaly event, trace window, emergency
    checkpoint, flight dump) have run. Deliberately NOT a RuntimeError:
    the graftheal session loop catches RuntimeError to classify backend
    loss, and a numerics anomaly must never enter that path — there is
    no backend to heal, only state to roll back."""


class FlightRecorder:
    """Last-K in-memory ring of emitted event records + crash-time dump.

    ``append`` is the EventLog hook (called on EVERY emit, under no
    lock contention worth caring about — one deque append); ``dump``
    writes the ring as ``<directory>/flight_<reason>.json`` (atomic
    tmp+rename — the dump itself can race the kill it is diagnosing).
    Repeat dumps for the same reason overwrite: the event log keeps the
    full history, the flight file is the "last moments" convenience."""

    def __init__(self, directory: str, capacity: int = 256):
        self.directory = directory
        self.capacity = max(1, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    def append(self, record: Dict[str, Any]):
        with self._lock:
            self._ring.append(record)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def path_for(self, reason: str) -> str:
        return os.path.join(self.directory, f"flight_{reason}.json")

    def dump(self, reason: str) -> Optional[str]:
        """Write the ring (possibly empty — an early crash is still a
        crash) and return the file path. Best-effort BY CONTRACT: every
        caller sits on a failure path (watchdog thread, heal recovery,
        the crash handler's re-raise, the anomaly abort) where an
        OSError from a full disk or unwritable obs dir must not replace
        the error being diagnosed, kill the watchdog thread, or crash a
        healed run — a failed dump logs and returns None."""
        events = self.snapshot()
        path = self.path_for(reason)
        payload = {
            "reason": reason,
            "t_wall": time.time(),
            "last_step": events[-1].get("step", 0) if events else 0,
            "events": events,
        }
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            os.makedirs(self.directory, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(payload, fh, default=_json_default)
            os.replace(tmp, path)
        except OSError as exc:
            logger.warning("graftpulse: flight dump %r failed: %r",
                           reason, exc)
            return None
        return path


class HealthMonitor:
    """Folds the step's in-graph health outputs into ``health`` events
    and turns anomalies into action (see module docstring).

    ``capture`` (optional, ``() -> carry``) refreshes the known-good
    snapshot after each CLEAN check — one device_get per health interval,
    the documented cost of a resumable tripwire; ``save`` (optional,
    ``carry -> path``) writes it as the emergency checkpoint when a wire
    trips. ``observe`` returns the tripped reasons (a list) or None, so
    "warn" callers can see what fired."""

    #: minimum clean history before the relative tripwires arm (a cold
    #: window has no meaningful median/std)
    MIN_GRAD_HISTORY = 5
    MIN_LOSS_HISTORY = 8

    def __init__(self, elog, every: int = 50, window: int = 64,
                 grad_factor: float = 100.0, loss_z: float = 10.0,
                 action: str = "abort", tracer=None, recorder=None,
                 capture: Optional[Callable[[], Any]] = None,
                 save: Optional[Callable[[Any], Optional[str]]] = None):
        if action not in ("abort", "warn"):
            raise ValueError(
                f"obs.health_action must be 'abort' or 'warn', "
                f"got {action!r}")
        self.elog = elog
        self.every = max(1, int(every))
        self.grad_factor = float(grad_factor)
        self.loss_z = float(loss_z)
        self.action = action
        self.tracer = tracer
        self.recorder = recorder
        self._capture = capture
        self._save = save
        self._latest: Optional[Dict[str, Any]] = None
        self._pos = (0, 0)
        self._since = 0
        window = max(8, int(window))
        self._losses: deque = deque(maxlen=window)
        self._grad_norms: deque = deque(maxlen=window)
        self.good = None  # last known-good carry (HealCarry shape)
        self.checks = 0
        self.anomalies = 0

    # -- the per-dispatch surface -------------------------------------------

    def observe(self, health: Dict[str, Any], epoch: int,
                dispatch: int) -> Optional[List[str]]:
        """Store the latest device-side health dict (a reference — no
        host sync) and, at the ``obs.health_every`` cadence, pull and
        check it. Returns the tripped reasons when a check fired one."""
        self._latest = health
        self._pos = (int(epoch), int(dispatch))
        self._since += 1
        if self._since < self.every:
            return None
        self._since = 0
        return self.check()

    # -- folding + tripwires -------------------------------------------------

    def check(self) -> Optional[List[str]]:
        """Pull the stored reading to host (the ONE cadenced device→host
        read — it piggybacks on outputs the step already returned), fold
        it into a ``health`` event and run the tripwires."""
        if self._latest is None:
            return None
        vals = {k: float(v) for k, v in self._latest.items()
                if not k.startswith(_PIN)}
        self._latest = None
        loss = vals.pop("loss", None)
        nonfinite = {k[:-len(_NF)]: int(v) for k, v in vals.items()
                     if k.endswith(_NF)}
        norms = {k[:-len(_SQ)]: (math.sqrt(v) if math.isfinite(v) and v >= 0
                                 else v)
                 for k, v in vals.items() if k.endswith(_SQ)}
        grad_sq = [v for k, v in vals.items()
                   if k.startswith("grad/") and k.endswith(_SQ)]
        grad_norm = None
        if grad_sq:
            total = sum(grad_sq)
            grad_norm = (math.sqrt(total)
                         if math.isfinite(total) and total >= 0 else total)

        reasons: List[str] = []
        bad_nf = {k: n for k, n in nonfinite.items() if n}
        if bad_nf:
            reasons.append("nonfinite:" + ",".join(
                f"{k}={n}" for k, n in sorted(bad_nf.items())))
        if loss is not None and not math.isfinite(loss):
            reasons.append(f"loss_nonfinite:{loss}")

        grad_median = (statistics.median(self._grad_norms)
                       if self._grad_norms else None)
        if grad_norm is not None and not math.isfinite(grad_norm):
            if not bad_nf:
                # every element finite but the f32 squared sum overflowed
                # — a blowup the count alone cannot see
                reasons.append("grad_norm_overflow")
        elif (grad_norm is not None and grad_median is not None
                and len(self._grad_norms) >= self.MIN_GRAD_HISTORY
                and grad_median > 0
                and grad_norm > self.grad_factor * grad_median):
            reasons.append(
                f"grad_explode:{grad_norm:.3g}>"
                f"{self.grad_factor:g}x median {grad_median:.3g}")

        z = None
        if (loss is not None and math.isfinite(loss)
                and len(self._losses) >= self.MIN_LOSS_HISTORY):
            mean = statistics.fmean(self._losses)
            std = statistics.pstdev(self._losses)
            if std > 1e-12:
                z = (loss - mean) / std
                if abs(z) > self.loss_z:
                    reasons.append(
                        f"loss_z:{z:.1f} (loss {loss:.4g} vs trailing "
                        f"{mean:.4g}±{std:.3g})")

        if self.elog.enabled:
            self.elog.emit(
                "health", epoch=self._pos[0], dispatch=self._pos[1],
                loss=loss,
                loss_z=round(z, 3) if z is not None else None,
                grad_norm=grad_norm, grad_median=grad_median,
                nonfinite=nonfinite,
                norm={k: round(v, 6) if math.isfinite(v) else v
                      for k, v in norms.items()})
        self.checks += 1

        if not reasons:
            # Only CLEAN readings extend the trailing windows — an
            # anomalous value folded into the median/std would drag the
            # baseline toward the fault and mask the next one.
            if loss is not None and math.isfinite(loss):
                self._losses.append(loss)
            if grad_norm is not None and math.isfinite(grad_norm):
                self._grad_norms.append(grad_norm)
            if self._capture is not None:
                self.good = self._capture()
            return None
        return self._trip(reasons, loss, nonfinite)

    def _trip(self, reasons: List[str], loss, nonfinite) -> List[str]:
        """Anomaly → action: trace window first (capture whatever the
        run does next), then the emergency save of the known-good state,
        then the ``anomaly`` event and the flight dump (the dump follows
        the emit so the ring includes the anomaly record itself)."""
        self.anomalies += 1
        if self.tracer is not None:
            self.tracer.anomaly_window()
        saved = None
        if self.good is not None and self._save is not None:
            try:
                saved = self._save(self.good)
            except Exception as exc:  # noqa: BLE001  # graftlint: disable=broad-except — the emergency save is best-effort inside an already-failing run; the anomaly event/abort below must not be masked by a save failure
                logger.warning(
                    "graftpulse: emergency save of the known-good state "
                    "failed: %r", exc)
        flight = None
        if self.elog.enabled:
            self.elog.emit(
                "anomaly", epoch=self._pos[0], dispatch=self._pos[1],
                reasons=reasons, loss=loss, nonfinite=nonfinite,
                saved=saved,
                good_epoch=getattr(self.good, "epoch", None),
                good_dispatch=getattr(self.good, "dispatch", None),
                flight=(self.recorder.path_for("anomaly")
                        if self.recorder is not None else None))
        if self.recorder is not None:
            flight = self.recorder.dump("anomaly")
        logger.error(
            "graftpulse ANOMALY at epoch %d dispatch %d: %s (emergency "
            "checkpoint: %s, flight dump: %s)", self._pos[0], self._pos[1],
            "; ".join(reasons), saved, flight)
        if self.action == "abort":
            raise NumericsAnomaly(
                f"numerics anomaly at epoch {self._pos[0]} dispatch "
                f"{self._pos[1]}: {'; '.join(reasons)} — last known-good "
                f"checkpoint: {saved or 'none'}; resume with --resume auto "
                "(runbook: OUTAGES.md, 'run went nonfinite')")
        return reasons
