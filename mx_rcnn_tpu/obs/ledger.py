"""graftprof perf ledger — append-only cross-run performance history.

    python -m mx_rcnn_tpu.obs.ledger add FILE [--round N]
    python -m mx_rcnn_tpu.obs.ledger backfill BENCH_r01.json BENCH_r02.json ...
    python -m mx_rcnn_tpu.obs.ledger show [--config NAME]
    python -m mx_rcnn_tpu.obs.ledger check [--candidate FILE] [--threshold 0.1]

Every bench round so far lived in a loose ``BENCH_r0N.json`` — useful
per round, invisible as a trajectory, and nothing ever FAILED when a
number regressed (BENCH_r03's c4 drop vs r02 was prose, not a gate).
The ledger is the tracked, diffable record: one JSONL row per measured
config per round, keyed by (config, git sha, round), appended by
``bench.py`` as each row completes and committed to the repo
(``PERF_LEDGER.jsonl``; ``MX_RCNN_PERF_LEDGER`` overrides).

- ``add`` appends rows from any bench artifact: a ``partial.json``
  detail dict, the printed bench JSON line, or a driver
  ``BENCH_r0N.json`` wrapper — all three shapes are normalized.
- ``backfill`` seeds history from the committed BENCH_r01–r05 wrappers
  (rounds and rc are taken from the wrapper; r05's rc=124 lands as an
  error row so the outage stays visible in the trajectory).
- ``show`` renders the per-config trajectory (round, img/s, MFU,
  step ms, HBM, pad waste, compile cost).
- ``check`` diffs candidate rows against the BEST prior row per config
  and exits non-zero on a throughput or MFU regression past the
  threshold (default 10%) — the regression gate the next chip window's
  flatcore A/B lands under.

stdlib-only, like ``obs.report`` — a ledger can be appended/folded on
any machine the JSON can be copied to.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

#: row fields copied verbatim from bench rows when present (everything
#: else a recipe emits stays in the source artifact, not the ledger).
_METRIC_FIELDS = (
    "img_s_per_chip", "mfu", "step_ms", "hbm_bytes", "pad_waste",
    "compile_s", "n_executables", "tree_ms", "flat_ms", "speedup",
    "ms_per_img", "error", "timeout_s", "compute_dtype",
    # environment-drift attribution (graftpulse satellite): a cross-run
    # regression should be pinnable to an env change — jaxlib upgrade,
    # uncommitted local patch — not just the git sha. bench.py stamps
    # these into every live row (events.env_fingerprint); blob-level
    # values propagate to rows in rows_from_artifact.
    "jax_version", "jaxlib_version", "git_dirty",
)
#: blob-level env fields copied down onto every row they wrap
_ENV_FIELDS = ("jax_version", "jaxlib_version", "git_dirty")
#: the two regression-gated metrics (higher is better for both)
_GATED = ("img_s_per_chip", "mfu")


def row_dtype(row: Dict[str, Any]) -> str:
    """A row's compute dtype for comparison purposes. Rows predating
    graftcast carry no field — they all ran the bf16 default (the only
    compute dtype the repo had), so missing means "bf16"."""
    return str(row.get("compute_dtype") or "bf16")


def default_path() -> str:
    """MX_RCNN_PERF_LEDGER, else PERF_LEDGER.jsonl at the repo root
    (resolved from this file — cwd-independent, like the lint settings)."""
    env = os.environ.get("MX_RCNN_PERF_LEDGER")
    if env:
        return env
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "PERF_LEDGER.jsonl")


def load_rows(path: str) -> List[Dict[str, Any]]:
    """Parse the ledger JSONL; a torn tail line — SIGKILL mid-append —
    is skipped WITH a warning, never fatal (the shared
    obs.report.load_jsonl_tolerant contract)."""
    if not os.path.exists(path):
        return []
    from mx_rcnn_tpu.obs.report import load_jsonl_tolerant

    return load_jsonl_tolerant(path, hint="append")


def append_rows(path: str, rows: Iterable[Dict[str, Any]]) -> int:
    """Append rows as JSONL lines. Append-only by design: history is
    never rewritten, corrections are new rows."""
    rows = [r for r in rows if r]
    if not rows:
        return 0
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "a", encoding="utf-8") as fh:
        for r in rows:
            fh.write(json.dumps(r, sort_keys=True) + "\n")
    return len(rows)


def _git_sha() -> Optional[str]:
    from mx_rcnn_tpu.obs.events import _git_sha as sha_of

    return sha_of(os.path.dirname(os.path.abspath(__file__)))


def normalize_row(config: str, row: Dict[str, Any],
                  round_: Optional[int] = None, sha: Optional[str] = None,
                  source: Optional[str] = None) -> Dict[str, Any]:
    out: Dict[str, Any] = {"config": config, "round": round_,
                           "git_sha": sha, "t_wall": round(time.time(), 3)}
    if source:
        out["source"] = source
    for k in _METRIC_FIELDS:
        if k in row and row[k] is not None:
            out[k] = row[k]
    return out


def rows_from_artifact(blob: Any, round_: Optional[int] = None,
                       sha: Optional[str] = None,
                       source: Optional[str] = None) -> List[Dict[str, Any]]:
    """Normalize any bench artifact shape into ledger rows.

    Accepted: a driver wrapper ({n, rc, parsed}), the printed bench line
    ({metric, value, detail}), or a bare detail dict ({config: row}).
    A wrapper with no parsed payload (rc!=0 — the BENCH_r05 shape) lands
    as one error row so failed rounds stay on the trajectory."""
    if not isinstance(blob, dict):
        raise ValueError("bench artifact must be a JSON object")
    if "parsed" in blob or "rc" in blob:  # driver wrapper
        round_ = blob.get("n", round_)
        parsed = blob.get("parsed")
        if not parsed:
            return [dict(normalize_row("headline", {}, round_, sha, source),
                         error=f"rc={blob.get('rc')} (no parsed output)")]
        blob = parsed
    rows: List[Dict[str, Any]] = []
    env: Dict[str, Any] = {}
    if "value" in blob and "metric" in blob:  # printed bench line
        # blob-level env fingerprint (report.bench_blob): applies to
        # every row the blob wraps — copied down after normalization.
        env = {k: blob[k] for k in _ENV_FIELDS if k in blob}
        rows.append(normalize_row(
            "headline",
            {"img_s_per_chip": blob.get("value"), "mfu": blob.get("mfu")},
            round_, sha, source))
        if blob.get("headline_config"):
            rows[-1]["headline_config"] = blob["headline_config"]
        blob = blob.get("detail") or {}
    for config, row in blob.items():
        if isinstance(row, dict):
            rows.append(normalize_row(config, row, round_, sha, source))
    for r in rows:
        for k, v in env.items():
            r.setdefault(k, v)
    return rows


# ---------------------------------------------------------------------------
# show / check
# ---------------------------------------------------------------------------

def _fmt(v, width=9, prec=3):
    if v is None:
        return " " * (width - 1) + "-"
    if isinstance(v, float):
        return f"{v:{width}.{prec}f}"
    return f"{v!s:>{width}}"


def render_show(rows: List[Dict[str, Any]],
                config: Optional[str] = None) -> str:
    """The trajectory, grouped by config, rounds in order — read it
    top-to-bottom per config; the gated metrics are the first two
    numeric columns (see PERF.md's graftprof section)."""
    if config:
        rows = [r for r in rows if r.get("config") == config]
    if not rows:
        return "perf ledger: no rows" + (f" for config {config!r}"
                                         if config else "")
    by_cfg: Dict[str, List[Dict[str, Any]]] = {}
    for r in rows:
        by_cfg.setdefault(r.get("config", "?"), []).append(r)
    lines = [f"perf ledger — {len(rows)} row(s), "
             f"{len(by_cfg)} config(s)",
             f"{'config':22s} {'round':>5} {'dtype':>5} "
             f"{'img/s/chip':>10} {'mfu':>7} "
             f"{'step_ms':>8} {'hbm_GB':>7} {'pad_waste':>9} "
             f"{'compile_s':>9} {'sha':>8}"]
    for cfg in sorted(by_cfg):
        hist = sorted(by_cfg[cfg],
                      key=lambda r: (r.get("round") is None,
                                     r.get("round") or 0,
                                     r.get("t_wall") or 0))
        for r in hist:
            hbm = r.get("hbm_bytes")
            lines.append(
                f"{cfg:22s} {_fmt(r.get('round'), 5)} "
                f"{row_dtype(r):>5} "
                f"{_fmt(r.get('img_s_per_chip'), 10)} "
                f"{_fmt(r.get('mfu'), 7, 4)} {_fmt(r.get('step_ms'), 8, 2)} "
                f"{_fmt(hbm / 1e9 if hbm else None, 7, 2)} "
                f"{_fmt(r.get('pad_waste'), 9, 4)} "
                f"{_fmt(r.get('compile_s'), 9, 1)} "
                f"{(r.get('git_sha') or '-')[:8]:>8}"
                + (f"  ! {r['error']}" if r.get("error") else ""))
    return "\n".join(lines)


def best_prior(history: List[Dict[str, Any]], config: str,
               before_round: Optional[int] = None,
               dtype: Optional[str] = None
               ) -> Dict[str, Optional[Tuple[float, Dict[str, Any]]]]:
    """Best prior value per gated metric for ``config`` (optionally only
    rounds strictly before ``before_round``). 'Best' is per-metric: the
    throughput best and the MFU best may be different rows (b1 vs b2
    recipes trade them off). ``dtype`` restricts to rows of that compute
    dtype (graftcast): a bf16 row's ~2x throughput must not become the
    bar an f32 row is graded against, and an f32 row must not hide a
    bf16 regression — cross-dtype rows are simply not comparable."""
    out: Dict[str, Optional[Tuple[float, Dict[str, Any]]]] = {
        m: None for m in _GATED}
    for r in history:
        if r.get("config") != config or r.get("error"):
            continue
        if dtype is not None and row_dtype(r) != dtype:
            continue
        if (before_round is not None and r.get("round") is not None
                and r["round"] >= before_round):
            continue
        for m in _GATED:
            v = r.get(m)
            if isinstance(v, (int, float)) and (
                    out[m] is None or v > out[m][0]):
                out[m] = (float(v), r)
    return out


def check_rows(history: List[Dict[str, Any]],
               candidates: List[Dict[str, Any]],
               threshold: float = 0.10) -> List[str]:
    """Regression messages for every candidate metric more than
    ``threshold`` below the best prior row of the same config AND the
    same compute dtype (graftcast: a bf16 win must not mask an f32
    regression, and vice versa). Configs with no same-dtype prior
    history pass (first measurement IS the baseline)."""
    problems = []
    for cand in candidates:
        cfg = cand.get("config")
        if not cfg or cand.get("error"):
            continue
        prior = best_prior(history, cfg, before_round=cand.get("round"),
                           dtype=row_dtype(cand))
        for metric in _GATED:
            v = cand.get(metric)
            best = prior.get(metric)
            if best is None or not isinstance(v, (int, float)):
                continue
            best_v, best_row = best
            if best_v > 0 and v < (1.0 - threshold) * best_v:
                problems.append(
                    f"{cfg}: {metric} {v:g} is "
                    f"{100.0 * (1 - v / best_v):.1f}% below best prior "
                    f"{best_v:g} (round {best_row.get('round')}, "
                    f"sha {(best_row.get('git_sha') or '?')[:8]})")
    return problems


def latest_round(rows: List[Dict[str, Any]]) -> Optional[int]:
    rounds = [r["round"] for r in rows
              if isinstance(r.get("round"), int)]
    return max(rounds) if rounds else None


def _latest_round_split(rows: List[Dict[str, Any]]
                        ) -> Tuple[List[Dict[str, Any]],
                                   List[Dict[str, Any]]]:
    """History vs candidates for the no-`--candidate` check mode.
    Rows with ``round: null`` are UNKEYED appends (a bench run outside
    the driver) — they are the newest measurements and must be graded,
    not silently skipped; when present they are the candidate set and
    every keyed row is history. Otherwise the latest integer round is
    the candidate set (bench.py auto-derives the next round when
    MX_RCNN_BENCH_ROUND is unset, so this is the normal path)."""
    unkeyed = [r for r in rows if r.get("round") is None]
    if unkeyed:
        return [r for r in rows if r.get("round") is not None], unkeyed
    latest = latest_round(rows)
    if latest is None:
        return rows, []
    return ([r for r in rows if r.get("round") != latest],
            [r for r in rows if r.get("round") == latest])


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _load_artifact(path: str):
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mx_rcnn_tpu.obs.ledger",
        description=__doc__.splitlines()[0])
    ap.add_argument("--ledger", default=None,
                    help="ledger path (default: MX_RCNN_PERF_LEDGER or "
                         "PERF_LEDGER.jsonl at the repo root)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_add = sub.add_parser("add", help="append rows from a bench artifact")
    p_add.add_argument("source", help="partial.json / printed bench line / "
                                      "driver BENCH_r0N.json wrapper")
    p_add.add_argument("--round", type=int, default=None)
    p_back = sub.add_parser("backfill",
                            help="seed history from driver wrappers")
    p_back.add_argument("sources", nargs="+")
    p_show = sub.add_parser("show", help="render the trajectory")
    p_show.add_argument("--config", default=None)
    p_check = sub.add_parser("check", help="regression-gate candidate rows")
    p_check.add_argument("--candidate", default=None,
                         help="bench artifact to gate; default: the "
                              "ledger's latest round vs everything before")
    p_check.add_argument("--threshold", type=float, default=0.10,
                         help="allowed fractional drop (default 0.10)")
    args = ap.parse_args(argv)
    path = args.ledger or default_path()

    if args.cmd == "add":
        rows = rows_from_artifact(_load_artifact(args.source),
                                  round_=args.round, sha=_git_sha(),
                                  source=os.path.basename(args.source))
        n = append_rows(path, rows)
        print(f"appended {n} row(s) to {path}")
        return 0
    if args.cmd == "backfill":
        total = 0
        for src in args.sources:
            rows = rows_from_artifact(_load_artifact(src),
                                      source=os.path.basename(src))
            total += append_rows(path, rows)
        print(f"backfilled {total} row(s) from {len(args.sources)} "
              f"artifact(s) into {path}")
        return 0
    if args.cmd == "show":
        print(render_show(load_rows(path), config=args.config))
        return 0
    # check
    history = load_rows(path)
    if args.candidate:
        candidates = rows_from_artifact(_load_artifact(args.candidate),
                                        sha=_git_sha(),
                                        source=os.path.basename(
                                            args.candidate))
    else:
        history, candidates = _latest_round_split(history)
    gradable = [c for c in candidates if not c.get("error")
                and any(isinstance(c.get(m), (int, float)) for m in _GATED)]
    if not gradable:
        # An all-error/empty candidate set must not read as a green gate
        # (the r05 rc=124 shape: error rows are skipped by check_rows).
        print("perf ledger check: no gradable candidate rows "
              f"({len(candidates)} candidate(s), all error/metric-free)",
              file=sys.stderr)
        return 2
    problems = check_rows(history, candidates, threshold=args.threshold)
    if problems:
        print(f"perf ledger check: {len(problems)} regression(s) past "
              f"{args.threshold:.0%}:")
        for p in problems:
            print(f"  REGRESSION {p}")
        return 1
    print(f"perf ledger check: OK ({len(candidates)} candidate row(s) "
          f"within {args.threshold:.0%} of best prior)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
