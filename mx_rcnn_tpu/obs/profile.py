"""graftprof trace windows — programmatic jax.profiler capture + folding.

``tools/profile.py`` can capture a trace of a synthetic step, but the
numbers that matter come from REAL runs — and nobody restarts a 12-hour
train job under TensorBoard. This module arms a capture window inside
the run itself:

- ``--set obs.trace_at_step=K`` (with ``obs.trace_steps=N``, default 3)
  starts a ``jax.profiler`` trace just before global step K and stops it
  N completed steps later, saving under ``<obs dir>/trace``;
- the stall watchdog auto-arms ONE window when it fires (before the
  stack dump), so a mysteriously slow/hung run leaves a trace of what
  the host was doing during the stall — closed at the next completed
  step or at teardown;
- every closed window emits a ``trace`` event carrying the capture dir
  and a coarse folded summary, so ``obs.report`` shows the breakdown
  without TensorBoard.

``summarize_trace`` folds the profiler's Chrome-trace JSON
(``*.trace.json.gz`` — written alongside the xplane protobuf, stdlib-
parseable) into a phase breakdown: ``forward`` / ``backward`` /
``update`` / ``host`` / ``infra``. The split is a NAME HEURISTIC over
trace events (XLA op/fusion names and host-side TraceMe labels) — good
for "where does the time go" at the granularity the MFU levers need,
not a replacement for the full TensorBoard view (the trace dir keeps
the xplane for that).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import threading
from typing import Any, Dict, List, Optional

#: phase classification, first match wins (order matters: an op named
#: "transpose.fusion.adam" is an update op). Host/infra events are
#: runtime machinery and python frames; the remainder — actual compute
#: ops without a backward/update marker — folds into forward.
_PHASE_PATTERNS = (
    ("update", re.compile(
        r"(adamw?|sgd|apply_grad|optimizer|flat_(sgd|adamw)|momentum)",
        re.IGNORECASE)),
    ("backward", re.compile(
        r"(backward|bwd|grad|vjp|transpose)", re.IGNORECASE)),
    ("host", re.compile(
        r"^\$|python|PyCall|callback|PjitFunction|ParseArguments|"
        r"CopyToDevice|TransferTo|BufferFromHost", re.IGNORECASE)),
    ("infra", re.compile(
        r"Tfrt|Thunk|Threadpool|Stream|Listener|profiler|XlaModule|"
        r"Await|Execute", re.IGNORECASE)),
)


def _classify(name: str) -> str:
    for phase, pat in _PHASE_PATTERNS:
        if pat.search(name):
            return phase
    return "forward"


def summarize_trace(trace_dir: str,
                    top_n: int = 8) -> Optional[Dict[str, Any]]:
    """Fold the NEWEST ``*.trace.json.gz`` under ``trace_dir`` into
    ``{phases: {phase: ms}, total_ms, events, top_ops, file}``.
    Returns None when no trace JSON exists (capture failed or a jax
    build that writes only xplane)."""
    hits = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                     recursive=True)
    if not hits:
        return None
    path = max(hits, key=os.path.getmtime)
    try:
        with gzip.open(path, "rt", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    phases: Dict[str, float] = {}
    per_op: Dict[str, float] = {}
    n = 0
    for ev in data.get("traceEvents", ()):
        if ev.get("ph") != "X":
            continue
        dur_ms = float(ev.get("dur", 0.0)) / 1e3  # trace units are µs
        name = str(ev.get("name", "?"))
        phases[_classify(name)] = phases.get(_classify(name), 0.0) + dur_ms
        per_op[name] = per_op.get(name, 0.0) + dur_ms
        n += 1
    top = sorted(per_op.items(), key=lambda kv: -kv[1])[:top_n]
    return {
        "file": os.path.relpath(path, trace_dir),
        "events": n,
        "total_ms": round(sum(phases.values()), 3),
        "phases": {k: round(v, 3) for k, v in sorted(phases.items())},
        "top_ops": [{"name": k, "ms": round(v, 3)} for k, v in top],
    }


class TraceController:
    """Arms/collects jax.profiler windows inside a run.

    Hot-path surface is ``step_completed(total_steps)``: one int compare
    when nothing is armed. ``stall_window()`` is the watchdog's hook
    (called from its thread — jax's profiler state is process-global, so
    cross-thread start/stop is fine); at most one stall window per run.
    ``close()`` force-stops an open window so the artifact survives the
    crash/teardown path."""

    def __init__(self, elog, out_dir: str, trace_at_step: int = 0,
                 trace_steps: int = 3):
        self.elog = elog
        self.out_dir = out_dir
        self.trace_steps = max(1, int(trace_steps))
        self._arm_at = int(trace_at_step)  # 0 = nothing armed
        self._lock = threading.Lock()
        self._active_dir: Optional[str] = None
        self._active_reason: Optional[str] = None
        self._stop_after: Optional[int] = None
        self._stall_used = False
        self._anomaly_used = False

    # -- capture plumbing ---------------------------------------------------

    def _start(self, sub: str, reason: str) -> bool:
        target = os.path.join(self.out_dir, sub)
        try:
            import jax.profiler

            os.makedirs(target, exist_ok=True)
            jax.profiler.start_trace(target)
        except Exception as exc:  # noqa: BLE001  # graftlint: disable=broad-except — a profiler that cannot start (already active elsewhere, unsupported build) must not take the run down
            from mx_rcnn_tpu.logger import logger

            logger.warning("graftprof: trace start failed: %r", exc)
            return False
        self._active_dir = target
        self._active_reason = reason
        return True

    def _stop_and_emit(self):
        target, reason = self._active_dir, self._active_reason
        self._active_dir = self._active_reason = None
        self._stop_after = None
        try:
            import jax.profiler

            jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001  # graftlint: disable=broad-except — same survival contract as _start
            from mx_rcnn_tpu.logger import logger

            logger.warning("graftprof: trace stop failed: %r", exc)
            return
        if self.elog.enabled:
            self.elog.emit("trace", dir=target, reason=reason,
                           summary=summarize_trace(target))

    # -- public surface -----------------------------------------------------

    def before_step(self, step: int):
        """Called just before dispatching global step ``step``: opens the
        armed window so the capture INCLUDES step ``trace_at_step`` —
        step 1 (the compile-heavy first dispatch) is capturable too."""
        with self._lock:
            if self._active_dir is not None:
                return
            if self._arm_at and step >= self._arm_at:
                at = self._arm_at
                self._arm_at = 0  # one window per arming
                if self._start(f"step{at}", reason=f"step {at}"):
                    # window spans steps at..at+N-1 (N = trace_steps)
                    self._stop_after = step + self.trace_steps - 1

    def step_completed(self, step: int):
        """Called once per completed dispatch: closes the open window
        when its step budget is spent (a stall window, which has no
        budget, closes on the first completed step after it)."""
        with self._lock:
            if self._active_dir is not None and (
                    self._stop_after is None or step >= self._stop_after):
                self._stop_and_emit()

    def anomaly_window(self):
        """graftpulse tripwire hook (obs/health.py): like stall_window,
        at most ONE anomaly window per run — armed before the anomaly
        event is written so the capture brackets whatever the diverging
        run does next; closed at the next completed step or at close()."""
        with self._lock:
            if self._anomaly_used or self._active_dir is not None:
                return
            self._anomaly_used = True
            self._start("anomaly", reason="anomaly")

    def stall_window(self):
        """Watchdog hook: open ONE trace window for the stall in flight.
        Closed at the next completed step (if the run recovers) or at
        close() (if it dies) — either way the capture lands on disk."""
        with self._lock:
            if self._stall_used or self._active_dir is not None:
                return
            self._stall_used = True
            self._start("stall", reason="stall")
            # no step budget: the next heartbeat (or teardown) closes it

    def close(self):
        with self._lock:
            if self._active_dir is not None:
                self._stop_and_emit()
