"""Fold a graftscope JSONL event stream into a run report.

    python -m mx_rcnn_tpu.obs.report RUN_DIR_OR_JSONL [--json OUT.json]

Prints a human summary (phase timing, throughput percentiles, compile
accounting, data-wait fraction, stalls/crashes) and optionally writes a
BENCH-compatible JSON blob (top-level metric/value/unit plus the full
summary as detail) that BENCH_*.json tooling and regression gates can
consume. stdlib-only — runs anywhere the JSONL can be copied to.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional


def load_jsonl_tolerant(path: str, hint: str = "run") -> List[Dict[str, Any]]:
    """Parse a JSONL file whose appends can race a kill: an unparseable
    line — the normal signature of SIGKILL mid-append — is skipped WITH
    a stderr warning naming the file and the byte offset of each torn
    line (a silently half-read stream would fold a killed run into a
    clean-looking artifact, and "somewhere in some stream" is useless
    when a fleet dir holds one JSONL per host), never fatal. Shared by
    this module's event streams and obs/ledger.py's perf rows (``hint``
    names what was being appended, for the warning). Binary read: byte
    offsets must be file offsets usable with ``tail -c``, not decoded
    character counts."""
    records = []
    torn_at: List[int] = []
    offset = 0
    with open(path, "rb") as fh:
        for raw in fh:
            line = raw.strip()
            if line:
                try:
                    records.append(json.loads(line.decode("utf-8")))
                except (json.JSONDecodeError, UnicodeDecodeError):
                    torn_at.append(offset)  # torn tail of a killed write
            offset += len(raw)
    if torn_at:
        where = ", ".join(f"byte {o}" for o in torn_at[:4])
        if len(torn_at) > 4:
            where += f", … ({len(torn_at)} total)"
        print(f"warning: {path}: skipped {len(torn_at)} unparseable "
              f"JSONL line(s) at {where} — torn tail of a killed "
              f"{hint}?", file=sys.stderr)
    return records


def event_streams(path: str) -> Dict[int, str]:
    """The per-host streams in a run dir: host index → file path.
    Discovers the grafttower names (``events_p<k>.jsonl``) and the
    pre-grafttower ones (``events.jsonl`` = host 0, ``events.<i>.jsonl``)
    so old run dirs keep folding."""
    streams: Dict[int, str] = {}
    for name in sorted(os.listdir(path)):
        idx = None
        if name.startswith("events_p") and name.endswith(".jsonl"):
            mid = name[len("events_p"):-len(".jsonl")]
            idx = int(mid) if mid.isdigit() else None
        elif name == "events.jsonl":
            idx = 0
        elif name.startswith("events.") and name.endswith(".jsonl"):
            mid = name[len("events."):-len(".jsonl")]
            idx = int(mid) if mid.isdigit() else None
        if idx is not None and idx not in streams:
            streams[idx] = os.path.join(path, name)
    return streams


def load_events(path: str) -> List[Dict[str, Any]]:
    """Parse one JSONL event file, or a run dir — folding EVERY per-host
    stream it holds (``events_p<k>.jsonl``, plus the legacy
    ``events.jsonl``/``events.<i>.jsonl`` names; see event_streams) into
    one list ordered by wall time, so a multi-host run's quorum/heal/
    preempt records interleave the way the fleet experienced them. Each
    record already carries its ``process`` stamp. Tolerates a torn tail
    line per stream (load_jsonl_tolerant). For the skew-corrected fleet
    timeline use ``--fleet`` / obs/fleet.py — wall order is only as
    honest as the hosts' clocks."""
    if not os.path.isdir(path):
        return load_jsonl_tolerant(path, hint="run")
    records: List[Dict[str, Any]] = []
    for _, stream_path in sorted(event_streams(path).items()):
        records.extend(load_jsonl_tolerant(stream_path, hint="run"))
    records.sort(key=lambda e: e.get("t_wall", 0.0))
    return records


def _percentile(sorted_vals: List[float], pct: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, round(pct / 100.0 * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _fold_costs(cost_events, timed, all_step_ms: List[float],
                multi: int) -> Dict[str, Any]:
    """Join per-bucket XLA cost accounting (graftprof `cost` events) with
    the measured step times of that bucket's canvas → per-bucket and
    aggregate MFU. ``multi`` (train.multi_step_dispatch) converts a
    dispatch's wall time into per-optimizer-step time — cost_analysis
    counts a scan body once, so flops are already per step."""
    buckets = []
    agg_flops = agg_time_s = 0.0
    for c in cost_events:
        shapes = c.get("shapes") or {}
        img = shapes.get("image") or ()
        canvas = list(img[-3:-1]) if len(img) >= 3 else None
        in_bucket = sorted(
            e["step_ms"] for e in timed
            if canvas is None or e.get("canvas") == canvas) or all_step_ms
        p50 = _percentile(in_bucket, 50)
        flops = c.get("flops")
        peak = c.get("peak_flops") or 0.0
        step_s = (p50 / 1e3) / max(1, multi)
        mfu = (flops / step_s / peak
               if flops and step_s > 0 and peak > 0 else None)
        if flops and in_bucket and p50 > 0:
            agg_flops += flops * len(in_bucket) * max(1, multi)
            agg_time_s += (p50 / 1e3) * len(in_bucket)
        buckets.append({
            "canvas": canvas,
            # graftcast: the dtype this bucket's peak was chosen for —
            # MFUs from different compute dtypes must not be compared
            "compute_dtype": c.get("compute_dtype"),
            "flops": flops,
            "bytes_accessed": c.get("bytes_accessed"),
            "hbm_bytes": c.get("hbm_bytes"),
            "steps": len(in_bucket),
            "step_ms_p50": round(p50, 3),
            "mfu": round(mfu, 4) if mfu is not None else None,
        })
    peak = next((c.get("peak_flops") for c in cost_events
                 if c.get("peak_flops")), None)
    overall = (round(agg_flops / agg_time_s / peak, 4)
               if peak and agg_time_s > 0 and agg_flops > 0 else None)
    hbm = [b["hbm_bytes"] for b in buckets if b.get("hbm_bytes")]
    return {"buckets": buckets, "mfu": overall,
            "hbm_bytes": max(hbm) if hbm else None}


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold an event list into the run summary dict (the --json payload's
    ``detail``). Keys are stable — BENCH tooling reads them."""
    by_type: Dict[str, List[Dict[str, Any]]] = {}
    for e in events:
        by_type.setdefault(e.get("type", "?"), []).append(e)

    run_meta = (by_type.get("run_meta") or [{}])[0]
    timed = [e for e in by_type.get("step", ()) if "step_ms" in e]
    speed = [e["samples_per_sec"] for e in by_type.get("step", ())
             if "samples_per_sec" in e]

    step_ms = sorted(e["step_ms"] for e in timed)
    total_step_ms = sum(step_ms)
    data_wait_ms = sorted(e.get("data_wait_ms", 0.0) for e in timed)
    total_wait_ms = sum(data_wait_ms)

    batch_size = run_meta.get("batch_size")
    # Throughput: prefer the Speedometer's measured windows (they bracket
    # the MetricBag drain, i.e. real end-to-end time); else derive from
    # the per-step median and the run_meta batch size.
    p50 = _percentile(step_ms, 50)
    if speed:
        img_s = _percentile(sorted(speed), 50)
    elif batch_size and p50 > 0:
        img_s = batch_size * 1000.0 / p50
    else:
        img_s = None

    compiles = [e for e in by_type.get("compile", ())
                if e.get("phase") == "backend_compile"]
    # A compile after the first completed step is a steady-state
    # recompile — the silent throughput killer the tracker exists for.
    recompiles = [e for e in compiles if e.get("step", 0) >= 1]

    # graftprof: per-bucket cost accounting joined with measured step
    # time → computed MFU (obs/costs.py emits one `cost` event per
    # compiled shape bucket; step events carry the batch canvas).
    multi = run_meta.get("multi_step_dispatch") or 1
    cost = _fold_costs(by_type.get("cost", ()), timed, step_ms, multi)
    pad_vals = sorted(e["pad_waste"] for e in timed if "pad_waste" in e)
    pad_waste = (round(_percentile(pad_vals, 50), 4) if pad_vals else None)

    crash = (by_type.get("crash") or [None])[-1]
    # graftpulse: cadenced numerics readings + tripped anomalies
    health_evs = by_type.get("health", ())
    last_health = health_evs[-1] if health_evs else None
    summary: Dict[str, Any] = {
        "run": {k: run_meta.get(k) for k in
                ("config_digest", "network", "dataset", "mesh",
                 "jax_version", "jaxlib_version", "git_dirty", "backend",
                 "device_count", "git_sha", "batch_size",
                 "steps_per_epoch", "prefix", "tool", "compute_dtype")
                if k in run_meta},
        "events": len(events),
        "steps": len(timed),
        "epochs": len(by_type.get("epoch", ())),
        "throughput": {
            "img_s": round(img_s, 3) if img_s is not None else None,
            "step_ms_p50": round(p50, 3),
            "step_ms_p90": round(_percentile(step_ms, 90), 3),
            "step_ms_max": round(step_ms[-1], 3) if step_ms else 0.0,
        },
        "data_wait": {
            "ms_p50": round(_percentile(data_wait_ms, 50), 3),
            "fraction": (round(total_wait_ms / total_step_ms, 4)
                         if total_step_ms else 0.0),
        },
        "compile": {
            "count": len(compiles),
            "total_ms": round(sum(e.get("duration_ms", 0.0)
                                  for e in compiles), 3),
            "steady_state_count": len(recompiles),
            "steady_state_shapes": [e.get("shapes") for e in recompiles],
        },
        "cost": cost,
        "pad_waste": pad_waste,
        "traces": [{"dir": e.get("dir"), "reason": e.get("reason"),
                    "summary": e.get("summary")}
                   for e in by_type.get("trace", ())],
        "checkpoints": len(by_type.get("checkpoint", ())),
        "evals": [e.get("results") for e in by_type.get("eval", ())],
        "bench": {e.get("config", f"cfg{i}"):
                  {k: v for k, v in e.items()
                   if k not in ("type", "t_wall", "t_mono", "process",
                                "step", "config")}
                  for i, e in enumerate(by_type.get("bench", ()))},
        "stalls": len(by_type.get("stall", ())),
        # graftpulse: how many health readings the run folded, how many
        # saw a nonfinite count, and the last reading's numbers (the
        # first thing the "run went nonfinite" runbook reads).
        "health": {
            "checks": len(health_evs),
            "nonfinite_checks": sum(
                1 for e in health_evs
                if any((e.get("nonfinite") or {}).values())),
            "last": ({k: last_health.get(k) for k in
                      ("loss", "loss_z", "grad_norm", "nonfinite")}
                     if last_health else None),
        },
        "anomalies": [{"step": e.get("step"), "epoch": e.get("epoch"),
                       "dispatch": e.get("dispatch"),
                       "reasons": e.get("reasons"),
                       "saved": e.get("saved"), "flight": e.get("flight")}
                      for e in by_type.get("anomaly", ())],
        # graftguard: how hard the backend fought acquisition, and whether
        # the run was preempted (OUTAGES.md reads these three lines first).
        "backend": {
            "retries": len(by_type.get("backend_retry", ())),
            "retry_wait_s": round(sum(
                e.get("sleep_s", 0.0)
                for e in by_type.get("backend_retry", ())), 3),
            "last_error": (by_type["backend_retry"][-1].get("error")
                           if by_type.get("backend_retry") else None),
        },
        "preempts": [{"signal": e.get("signal"), "step": e.get("step"),
                      "saved": e.get("saved")}
                     for e in by_type.get("preempt", ())],
        # graftheal: in-run recoveries — how often the backend was lost
        # mid-run, how long the run was down for it, and any elastic
        # shrink transitions (device count before -> after).
        "heals": {
            "count": len(by_type.get("heal", ())),
            "downtime_s": round(sum(e.get("downtime_s", 0.0)
                                    for e in by_type.get("heal", ())), 3),
            "shrinks": [f"{e.get('devices_before')}->"
                        f"{e.get('devices_after')}"
                        for e in by_type.get("heal", ())
                        if e.get("devices_before") is not None
                        and e.get("devices_after") is not None
                        and e["devices_before"] != e["devices_after"]],
            "last_error": (by_type["heal"][-1].get("error")
                           if by_type.get("heal") else None),
        },
        # graftquorum: multi-host coordination rounds — per-host records
        # interleaved by load_events, so `hosts` is how many distinct
        # process stamps the fold saw and `excluded` collects every host
        # any round sealed out (the "who got dropped" runbook line).
        "quorum": {
            "rounds": len(by_type.get("quorum", ())),
            "hosts": len({e.get("process", 0) for e in events}),
            "excluded": sorted({h for e in by_type.get("quorum", ())
                                for h in (e.get("excluded") or ())}),
            "last": ({k: by_type["quorum"][-1].get(k) for k in
                      ("kind", "hosts", "excluded", "agreed", "spec")}
                     if by_type.get("quorum") else None),
        },
        # graftfeed: input-plane fault accounting — which records were
        # quarantined (and what replaced them), how often transient IO
        # was retried, and whether any prefetch workers died mid-run.
        # OUTAGES.md's "the data plane broke" runbook reads this fold.
        "data": {
            "quarantined": [
                {"record": e.get("record"), "epoch": e.get("epoch"),
                 "replacement": e.get("replacement"),
                 "reason": e.get("reason")}
                for e in by_type.get("data", ())
                if e.get("kind") == "quarantine"],
            "retries": sum(1 for e in by_type.get("data", ())
                           if e.get("kind") == "retry"),
            "retry_wait_s": round(sum(
                e.get("sleep_s", 0.0) for e in by_type.get("data", ())
                if e.get("kind") == "retry"), 3),
            "reapplied": sum(e.get("count", 0)
                             for e in by_type.get("data", ())
                             if e.get("kind") == "quarantine_applied"),
            "cap_trips": sum(1 for e in by_type.get("data", ())
                             if e.get("kind") == "quarantine_cap"),
            "worker_deaths": len(by_type.get("data_worker", ())),
            "worker_resurrections": sum(
                1 for e in by_type.get("data_worker", ())
                if e.get("resurrected")),
        },
        "crash": ({"error": crash.get("error"), "step": crash.get("step")}
                  if crash else None),
    }
    return summary


def bench_blob(summary: Dict[str, Any]) -> Dict[str, Any]:
    """BENCH-compatible wrapper: one headline metric line + full detail."""
    img_s = summary["throughput"]["img_s"]
    return {
        "metric": "graftscope_train_img_per_sec",
        "value": img_s if img_s is not None else 0.0,
        "unit": "img/s",
        "steps": summary["steps"],
        "compile_count": summary["compile"]["count"],
        "compile_total_ms": summary["compile"]["total_ms"],
        "data_wait_fraction": summary["data_wait"]["fraction"],
        "stall_count": summary["stalls"],
        "backend_retries": summary["backend"]["retries"],
        "heal_count": summary["heals"]["count"],
        # graftfeed: quarantine pressure and worker churn belong on the
        # same ledger row — a throughput regression with nonzero
        # data_retries is a storage problem, not a model problem.
        "data_quarantined": len(summary["data"]["quarantined"]),
        "data_retries": summary["data"]["retries"],
        "data_worker_deaths": summary["data"]["worker_deaths"],
        # graftprof: the computed-MFU / HBM / padding numbers regression
        # gates (obs/ledger.py) track alongside throughput.
        "mfu": summary["cost"]["mfu"],
        "hbm_bytes": summary["cost"]["hbm_bytes"],
        "pad_waste": summary["pad_waste"],
        # graftpulse: anomaly accounting + the environment-drift fields
        # (jax/jaxlib/git_dirty ride into ledger rows via this blob, so
        # a cross-run regression is attributable to env change too).
        "anomaly_count": len(summary["anomalies"]),
        "health_checks": summary["health"]["checks"],
        # grafttower (--fleet folds only): the skew/wait aggregate, so
        # multi-host ledger rows carry "how lockstep was the fleet"
        # next to throughput (obs/fleet.py).
        **({"fleet_skew_p50_s": summary["fleet"]["skew"]["p50_s"],
            "fleet_skew_p90_s": summary["fleet"]["skew"]["p90_s"],
            "fleet_barrier_wait_s":
                summary["fleet"]["barriers"]["total_wait_s"],
            "fleet_straggler": summary["fleet"]["straggler"],
            "fleet_hung_hosts": summary["fleet"]["hung"]}
           if "fleet" in summary else {}),
        **{k: summary["run"][k]
           for k in ("jax_version", "jaxlib_version", "git_dirty")
           if k in summary["run"]},
        "detail": summary,
    }


def render(summary: Dict[str, Any]) -> str:
    run = summary["run"]
    tp = summary["throughput"]
    dw = summary["data_wait"]
    co = summary["compile"]
    lines = [
        "graftscope run report",
        "  run:        " + ", ".join(
            f"{k}={v}" for k, v in run.items()) if run else "  run:        -",
        f"  events:     {summary['events']} "
        f"({summary['steps']} steps, {summary['epochs']} epochs, "
        f"{summary['checkpoints']} checkpoints, "
        f"{len(summary['evals'])} evals)",
        f"  throughput: {tp['img_s']} img/s | step p50 {tp['step_ms_p50']} "
        f"ms, p90 {tp['step_ms_p90']} ms, max {tp['step_ms_max']} ms",
        f"  data wait:  p50 {dw['ms_p50']} ms ({dw['fraction']:.1%} of "
        "step time)",
        f"  compiles:   {co['count']} ({co['total_ms']:.0f} ms total), "
        f"{co['steady_state_count']} in steady state",
        f"  stalls:     {summary['stalls']}",
    ]
    cost = summary.get("cost") or {}
    if cost.get("buckets"):
        hbm = cost.get("hbm_bytes")
        lines.append(
            f"  cost:       mfu {cost.get('mfu')} | hbm "
            f"{hbm / 1e9:.2f} GB | {len(cost['buckets'])} bucket(s): "
            + ", ".join(
                f"{b.get('canvas')} mfu={b.get('mfu')}"
                for b in cost["buckets"])
            if hbm else
            f"  cost:       mfu {cost.get('mfu')} | "
            f"{len(cost['buckets'])} bucket(s)")
    if summary.get("pad_waste") is not None:
        # Canvas utilization (real/canvas px) rides next to MFU above:
        # graftcanvas packed-vs-bucketed runs grade both in one report.
        lines.append(f"  pad waste:  {summary['pad_waste']:.1%} of canvas "
                     f"pixels (p50) | canvas util "
                     f"{1.0 - summary['pad_waste']:.1%}")
    for t in summary.get("traces", ()):
        ph = (t.get("summary") or {}).get("phases")
        lines.append(f"  trace:      [{t.get('reason')}] {t.get('dir')}"
                     + (f" phases(ms)={ph}" if ph else ""))
    be = summary.get("backend", {})
    if be.get("retries"):
        lines.append(
            f"  backend:    {be['retries']} transient failure(s), "
            f"{be['retry_wait_s']:.0f}s backing off | last: "
            f"{be['last_error']}")
    for p in summary.get("preempts", ()):
        lines.append(f"  preempt:    signal {p['signal']} at step "
                     f"{p['step']} (emergency save: {p['saved']})")
    hl = summary.get("health", {})
    if hl.get("checks"):
        last = hl.get("last") or {}
        z = last.get("loss_z")
        lines.append(
            f"  health:     {hl['checks']} reading(s), "
            f"{hl['nonfinite_checks']} with nonfinites | last: loss "
            f"{last.get('loss')}"
            + (f" (z {z})" if z is not None else "")
            + f", grad norm {last.get('grad_norm')}")
    for a in summary.get("anomalies", ()):
        lines.append(
            f"  ANOMALY:    epoch {a.get('epoch')} dispatch "
            f"{a.get('dispatch')}: {'; '.join(a.get('reasons') or ())} | "
            f"checkpoint {a.get('saved')} | flight {a.get('flight')}")
    he = summary.get("heals", {})
    if he.get("count"):
        shrink = (", shrink " + ", ".join(he["shrinks"])
                  if he.get("shrinks") else "")
        lines.append(
            f"  heal:       {he['count']} in-run recover(ies), "
            f"{he['downtime_s']:.0f}s down{shrink} | last: "
            f"{he['last_error']}")
    da = summary.get("data", {})
    if (da.get("quarantined") or da.get("retries")
            or da.get("worker_deaths") or da.get("cap_trips")):
        recs = ", ".join(str(q["record"]) for q in da["quarantined"][:8])
        more = (f" (+{len(da['quarantined']) - 8} more)"
                if len(da["quarantined"]) > 8 else "")
        lines.append(
            f"  data:       {len(da['quarantined'])} record(s) "
            f"quarantined{': ' + recs + more if recs else ''} | "
            f"{da['retries']} IO retr(ies), {da['retry_wait_s']:.0f}s "
            f"backing off | {da['worker_deaths']} worker death(s), "
            f"{da['worker_resurrections']} resurrected"
            + (f" | CAP TRIPPED x{da['cap_trips']}"
               if da.get("cap_trips") else ""))
    qu = summary.get("quorum", {})
    if qu.get("rounds"):
        last = qu.get("last") or {}
        excl = (f", excluded hosts {qu['excluded']}" if qu.get("excluded")
                else "")
        lines.append(
            f"  quorum:     {qu['rounds']} coordination round(s) across "
            f"{qu['hosts']} host stream(s){excl} | last: "
            f"kind={last.get('kind')} hosts={last.get('hosts')}")
    for name, row in summary["bench"].items():
        lines.append(f"  bench:      {name}: {row}")
    if summary["crash"]:
        lines.append(f"  CRASH:      step {summary['crash']['step']}: "
                     f"{summary['crash']['error']}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mx_rcnn_tpu.obs.report",
        description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run directory (holding per-host "
                                 "events_p<k>.jsonl streams) or a JSONL "
                                 "file")
    ap.add_argument("--fleet", action="store_true",
                    help="grafttower fold: merge every host stream onto "
                         "one skew-corrected fleet timeline and append "
                         "the straggler/barrier/heartbeat report "
                         "(obs/fleet.py; path must be a run dir)")
    ap.add_argument("--json", dest="json_out", default=None,
                    metavar="OUT.json",
                    help="also write the BENCH-compatible JSON blob here")
    args = ap.parse_args(argv)
    if args.fleet:
        from mx_rcnn_tpu.obs import fleet

        if not os.path.isdir(args.path):
            print(f"error: --fleet needs a run directory of per-host "
                  f"streams, got {args.path}", file=sys.stderr)
            return 2
        try:
            hosts = {idx: load_jsonl_tolerant(p, hint="run")
                     for idx, p in event_streams(args.path).items()}
        except OSError as exc:
            print(f"error: cannot read {args.path}: {exc}",
                  file=sys.stderr)
            return 2
        if not hosts:
            print(f"error: no event streams in {args.path}",
                  file=sys.stderr)
            return 2
        events = fleet.merge_streams(hosts)
        summary = summarize(events)
        summary["fleet"] = fleet.fleet_summary(hosts)
        print(render(summary))
        print(fleet.render_fleet(summary["fleet"]))
    else:
        try:
            events = load_events(args.path)
        except OSError as exc:
            print(f"error: cannot read {args.path}: {exc}",
                  file=sys.stderr)
            return 2
        summary = summarize(events)
        print(render(summary))
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            json.dump(bench_blob(summary), fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"wrote {args.json_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
