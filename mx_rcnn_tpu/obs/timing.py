"""Per-iteration phase timing for the train loop — no host syncs added.

The split follows the loop's own structure (tools/train.py::fit_detector):

  data_wait_ms  — time blocked in the loader's ``next()`` (host input
                  pipeline: decode/augment/stack; includes the
                  multi-step-dispatch group stacking).
  dispatch_ms   — from batch-in-hand to the train step's RETURN. The step
                  is an async dispatch, so in steady state this is the
                  host-side enqueue cost — UNLESS the device queue is
                  full, in which case dispatch blocks and absorbs device
                  time (backpressure).
  step_ms       — the full iteration wall time (data wait + dispatch +
                  callback/bookkeeping). In steady state the device is
                  the bottleneck iff step_ms ≈ device step time: device
                  time is never measured directly because that would
                  take a per-step host sync, which is exactly the
                  overhead this repo's lazy-drain discipline
                  (train/metrics.py::MetricBag) exists to avoid. The
                  drain still happens — at Speedometer log boundaries —
                  so windowed step_ms is honest end-to-end time.

When the sink is disabled, ``iterate`` degrades to ``enumerate`` and
``dispatched()`` to one attribute check: zero events, zero allocations.
"""

from __future__ import annotations

import time

from mx_rcnn_tpu.obs.events import EventLog


class StepTimer:
    """Times each train iteration and emits one ``step`` event for it.

    Usage (the fit_detector wiring)::

        timer = StepTimer(event_log, watchdog=watchdog)
        for i, batch in timer.iterate(epoch, batches):
            state, metrics = step_fn(state, batch, key)
            timer.dispatched()          # marks the dispatch boundary
            ...                          # metrics/callbacks

    Also drives the stall watchdog (one ``beat`` per completed iteration,
    carrying the iteration duration for the trailing-median threshold)
    and refreshes the compile tracker's shape signature so a recompile
    event can name the batch shapes that triggered it.
    """

    def __init__(self, log: EventLog, watchdog=None, track_shapes=True,
                 enrich=None):
        """``enrich``: optional ``batch -> dict`` of extra fields for each
        step event (graftprof attaches the canvas + pad-waste fraction —
        host-side numpy over im_info, no device touch). Only called when
        the sink is enabled; must never raise for a well-formed batch."""
        self.log = log
        self.watchdog = watchdog
        self.track_shapes = track_shapes
        self.enrich = enrich
        self.total_steps = 0
        self._t_dispatch = None

    def dispatched(self):
        """Record the train-step return time (the dispatch boundary)."""
        if self.log.enabled:
            self._t_dispatch = time.perf_counter()

    def iterate(self, epoch: int, batches, start: int = 0):
        """Yield ``(i, batch)`` like ``enumerate(batches, start)``, timing
        each iteration. Pass-through when the sink is disabled. ``start``
        offsets the index for a mid-epoch resume, so logged/emitted batch
        numbers continue where the interrupted run stopped instead of
        double-using the indices it already recorded."""
        if not self.log.enabled:
            yield from enumerate(batches, start)
            return
        from mx_rcnn_tpu.obs import compile_track

        it = iter(batches)
        i = start
        while True:
            t0 = time.perf_counter()
            if self.watchdog is not None:
                # Phase marks bracket the blocking next(): a stall event
                # fired while we sit here is attributed to data-wait (the
                # input plane), not dispatch (the device queue).
                self.watchdog.note_phase("data_wait")
            try:
                batch = next(it)
            except StopIteration:
                return
            t1 = time.perf_counter()
            if self.watchdog is not None:
                self.watchdog.note_phase("dispatch")
            if self.track_shapes:
                compile_track.note_batch(batch)
            self._t_dispatch = None
            yield i, batch
            t2 = time.perf_counter()
            self.total_steps += 1
            self.log.set_step(self.total_steps)
            step_s = t2 - t0
            fields = {
                "epoch": epoch,
                "batch": i,
                "data_wait_ms": round((t1 - t0) * 1e3, 3),
                "step_ms": round(step_s * 1e3, 3),
            }
            if self._t_dispatch is not None:
                fields["dispatch_ms"] = round(
                    (self._t_dispatch - t1) * 1e3, 3)
            if self.enrich is not None:
                fields.update(self.enrich(batch) or {})
            self.log.emit("step", **fields)
            if self.watchdog is not None:
                self.watchdog.beat(step_s)
            i += 1
