"""Heartbeat/stall watchdog — turns a hung run into a diagnosable artifact.

BENCH_r05 died as rc=124 (external timeout) with no artifact saying which
phase stalled. This watchdog is the in-process tripwire: the train loop
beats once per completed iteration (StepTimer); a daemon thread checks
the heartbeat on a poll interval and, when no step has completed within
``max(min_stall_s, stall_factor × trailing-median step time)``, emits one
``stall`` event carrying every thread's current stack — flushed to disk
immediately, so the evidence survives the SIGKILL that usually follows.

The threshold adapts to the run: before the FIRST completed step the
floor is ``COLD_GRACE × min_stall_s`` (cold-start XLA compiles cost
minutes — a healthy first trace must not read as a stall, but a truly
hung compile still surfaces); once steps flow, the trailing median makes the
factor meaningful for fast and slow configs alike. One event per stall
episode — the tripwire re-arms on the next heartbeat.
"""

from __future__ import annotations

import statistics
import sys
import threading
import time
import traceback
from collections import deque
from typing import Dict, Optional

from mx_rcnn_tpu.obs.events import EventLog


def _stack_dump(skip_ident: Optional[int] = None) -> Dict[str, str]:
    """Current stacks of all threads (except ``skip_ident``, the watchdog
    itself), keyed by thread name — the stall event's payload."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        if ident == skip_ident:
            continue
        name = names.get(ident, f"thread-{ident}")
        out[name] = "".join(traceback.format_stack(frame))
    return out


class StallWatchdog:
    """Daemon thread emitting a ``stall`` event when the heartbeat stops.

    ``beat(duration_s)`` is the only hot-path call: one lock, one deque
    append. ``check(now)`` is separated from the thread loop so tests can
    drive the stall logic synchronously.
    """

    #: pre-first-step threshold multiplier on min_stall_s (see module
    #: docstring: cold compiles are slow but a hung compile must still
    #: eventually fire).
    COLD_GRACE = 10.0

    def __init__(self, log: EventLog, stall_factor: float = 10.0,
                 min_stall_s: float = 60.0, poll_s: float = 5.0,
                 window: int = 101, tracer=None, recorder=None,
                 heartbeat_every_s: float = 0.0):
        """``tracer``: optional graftprof TraceController — when a stall
        fires, ONE jax.profiler window is auto-armed before the stack
        dump (``tracer.stall_window()``), so a hung run leaves a trace
        of the stall alongside the stacks (obs/profile.py).
        ``recorder``: optional graftpulse FlightRecorder — the stall dump
        also flushes the last-K-events ring (obs/health.py), so the
        artifact says what the numbers were doing when the run hung.
        ``heartbeat_every_s``: grafttower liveness beacon cadence (0 =
        off) — this thread additionally emits a ``heartbeat`` event at
        that cadence (flushed immediately, and into the flight ring via
        the log's attach_ring), plus one final=True beat from stop(). A
        SIGKILL skips stop(), so a stream whose heartbeat trail goes
        stale with no final beat was KILLED; a slow host keeps beating
        (obs/fleet.py folds the distinction)."""
        self.log = log
        self.tracer = tracer
        self.recorder = recorder
        self.stall_factor = float(stall_factor)
        self.min_stall_s = float(min_stall_s)
        self.poll_s = float(poll_s)
        self.heartbeat_every_s = float(heartbeat_every_s)
        self._durations = deque(maxlen=window)
        self._last_beat = time.monotonic()
        self._last_heartbeat: Optional[float] = None
        self._final_sent = False
        self._fired = False
        self._paused = False
        self._stalls = 0
        self._phase = ""
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="graftscope-watchdog", daemon=True)

    def start(self):
        with self._lock:
            self._last_beat = time.monotonic()
        self._thread.start()

    def beat(self, duration_s: Optional[float] = None):
        """One completed step: refresh the heartbeat, extend the trailing
        window, re-arm the tripwire."""
        with self._lock:
            self._last_beat = time.monotonic()
            if duration_s is not None:
                self._durations.append(float(duration_s))
            self._fired = False
            self._paused = False

    def note_phase(self, name: str):
        """Name the loop phase now running (``data_wait`` before the
        loader's blocking ``next()``, ``dispatch`` once the batch is in
        hand — StepTimer.iterate sets both). A later ``stall`` event
        carries the last-noted phase, so the dump says WHERE the loop
        was wedged — graftfeed's runbook (OUTAGES.md) splits "storage is
        stuck" from "device queue is stuck" on this one field."""
        with self._lock:
            self._phase = name

    def threshold_s(self) -> float:
        with self._lock:
            if not self._durations:
                return self.COLD_GRACE * self.min_stall_s
            median = statistics.median(self._durations)
        return max(self.min_stall_s, self.stall_factor * median)

    def pause(self):
        """Silence the tripwire while a KNOWN no-heartbeat window runs —
        graftheal calls this at the very start of a recovery, before the
        (possibly hours-long) backend re-acquisition backoff: the outage
        is already being handled and will be reported as a ``heal``
        event; a ``stall`` dump for it would be noise. Re-armed by
        reset() (the heal's epilogue) or the next beat()."""
        with self._lock:
            self._paused = True

    def reset(self):
        """Forget the trailing window and re-arm with cold-start grace —
        called after a graftheal recovery (in-process resume): the first
        post-heal step pays backend re-acquisition plus a fresh XLA
        compile, and judging it by the pre-loss median would emit a
        false ``stall`` event (with stack dump) for a healthy recovery.
        Also lifts pause()."""
        with self._lock:
            self._durations.clear()
            self._last_beat = time.monotonic()
            self._fired = False
            self._paused = False

    def check(self, now: Optional[float] = None) -> bool:
        """Evaluate the stall condition once; emit at most one event per
        episode. Returns True when a stall event was emitted."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._paused:
                return False
            waited = now - self._last_beat
            fired = self._fired
            phase = self._phase
            median = (statistics.median(self._durations)
                      if self._durations else None)
        threshold = self.threshold_s()
        if fired or waited <= threshold:
            return False
        with self._lock:
            self._fired = True
            self._stalls += 1
        if self.tracer is not None:
            # Arm the stall trace BEFORE dumping: the capture brackets
            # whatever the stalled threads do next (profile.py bounds it
            # to one window per run and closes it at teardown).
            self.tracer.stall_window()
        self.log.emit(
            "stall",
            waited_s=round(waited, 3),
            threshold_s=round(threshold, 3),
            phase=phase or None,
            median_step_s=round(median, 4) if median is not None else None,
            stacks=_stack_dump(skip_ident=self._thread.ident))
        if self.recorder is not None:
            # after the emit: the flight ring then includes the stall
            # record itself alongside the recent step/health events
            self.recorder.dump("stall")
        return True

    def maybe_heartbeat(self, now: Optional[float] = None) -> bool:
        """Emit a ``heartbeat`` event when the cadence is due (at most
        one per heartbeat_every_s; the first call always emits, so even
        a seconds-long run leaves one beacon). Separated from the thread
        loop so tests drive the cadence synchronously. Returns True when
        a beat was emitted."""
        if not self.heartbeat_every_s:
            return False
        now = time.monotonic() if now is None else now
        with self._lock:
            if (self._last_heartbeat is not None
                    and now - self._last_heartbeat < self.heartbeat_every_s):
                return False
            self._last_heartbeat = now
        self._emit_heartbeat(now=now)
        return True

    def _emit_heartbeat(self, final: bool = False,
                        now: Optional[float] = None):
        now = time.monotonic() if now is None else now
        with self._lock:
            beat_age = now - self._last_beat
            stalls = self._stalls
        self.log.emit(
            "heartbeat",
            every_s=self.heartbeat_every_s,
            beat_age_s=round(max(0.0, beat_age), 3),
            stalls=stalls,
            final=final)

    def _run(self):
        # The heartbeat shares this thread (one daemon thread per run):
        # wake at whichever cadence is shorter so neither starves.
        wait_s = (min(self.poll_s, self.heartbeat_every_s)
                  if self.heartbeat_every_s else self.poll_s)
        self.maybe_heartbeat()
        while not self._stop.wait(wait_s):
            self.check()
            self.maybe_heartbeat()

    def stop(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=self.poll_s + 1.0)
        if self.heartbeat_every_s and not self._final_sent:
            # The clean-shutdown marker: its absence at end-of-stream is
            # how the fleet fold tells a killed host from a finished one.
            self._final_sent = True
            self._emit_heartbeat(final=True)
