"""Pure, static-shape, jit-traceable detection ops.

TPU-native replacements for the reference's host-side box math
(rcnn/processing/bbox_transform.py, generate_anchor.py, nms.py), Cython/CUDA
kernels (rcnn/cython/bbox.pyx, cpu_nms.pyx, nms_kernel.cu) and in-graph
custom ops (rcnn/symbol/proposal.py, MXNet's C++ ROIPooling/ROIAlign).
"""

from mx_rcnn_tpu.ops.boxes import (
    bbox_transform,
    bbox_pred,
    clip_boxes,
    bbox_overlaps,
)
from mx_rcnn_tpu.ops.anchors import generate_anchors, anchor_grid
from mx_rcnn_tpu.ops.nms import nms
from mx_rcnn_tpu.ops.roi_align import roi_align, roi_pool
from mx_rcnn_tpu.ops.proposal import generate_proposals
