"""Anchor generation.

Replaces rcnn/processing/generate_anchor.py (the classic Girshick
``generate_anchors``) plus the feature-map shift enumeration that the
reference repeats inside assign_anchor (rcnn/io/rpn.py) and the Proposal op
(rcnn/symbol/proposal.py). Anchors are compile-time constants under jit:
``anchor_grid`` is pure numpy on static shapes, so XLA folds it.
"""

from __future__ import annotations

import numpy as np


def _whctrs(anchor: np.ndarray):
    w = anchor[2] - anchor[0] + 1.0
    h = anchor[3] - anchor[1] + 1.0
    cx = anchor[0] + 0.5 * (w - 1.0)
    cy = anchor[1] + 0.5 * (h - 1.0)
    return w, h, cx, cy


def _mkanchors(ws, hs, cx, cy):
    ws = ws[:, None]
    hs = hs[:, None]
    return np.hstack(
        [
            cx - 0.5 * (ws - 1.0),
            cy - 0.5 * (hs - 1.0),
            cx + 0.5 * (ws - 1.0),
            cy + 0.5 * (hs - 1.0),
        ]
    )


def _ratio_enum(anchor, ratios):
    w, h, cx, cy = _whctrs(anchor)
    size = w * h
    size_ratios = size / ratios
    ws = np.round(np.sqrt(size_ratios))
    hs = np.round(ws * ratios)
    return _mkanchors(ws, hs, cx, cy)


def _scale_enum(anchor, scales):
    w, h, cx, cy = _whctrs(anchor)
    ws = w * scales
    hs = h * scales
    return _mkanchors(ws, hs, cx, cy)


def generate_anchors(
    base_size: int = 16,
    ratios=(0.5, 1.0, 2.0),
    scales=(8, 16, 32),
) -> np.ndarray:
    """(A, 4) base anchors centred on the (0,0) stride cell.

    Bit-exact port of the classic algorithm's semantics (ratio enumeration
    with rounding, then scale enumeration) — the rounding matters for parity
    with reference-trained checkpoints.
    """
    ratios = np.asarray(ratios, dtype=np.float64)
    scales = np.asarray(scales, dtype=np.float64)
    base_anchor = np.array([0, 0, base_size - 1, base_size - 1], dtype=np.float64)
    ratio_anchors = _ratio_enum(base_anchor, ratios)
    anchors = np.vstack(
        [_scale_enum(ratio_anchors[i], scales) for i in range(ratio_anchors.shape[0])]
    )
    return anchors.astype(np.float32)


def anchor_grid(
    feat_height: int,
    feat_width: int,
    stride: int = 16,
    base_size: int = 16,
    ratios=(0.5, 1.0, 2.0),
    scales=(8, 16, 32),
) -> np.ndarray:
    """All anchors for an HxW feature map, shape (H*W*A, 4).

    Enumeration order matches the reference (rcnn/io/rpn.py assign_anchor /
    rcnn/symbol/proposal.py): shifts vary fastest over W, then H; the A base
    anchors are the innermost group, i.e. reshape of
    (1,H,W,A,4) -> (H*W*A, 4). This ordering must match the (A·4, H, W)
    layout of the RPN conv outputs after transpose/reshape.
    """
    base = generate_anchors(base_size, ratios, scales)  # (A,4)
    shift_x = np.arange(feat_width, dtype=np.float32) * stride
    shift_y = np.arange(feat_height, dtype=np.float32) * stride
    sx, sy = np.meshgrid(shift_x, shift_y)  # (H,W)
    shifts = np.stack([sx, sy, sx, sy], axis=-1)  # (H,W,4)
    all_anchors = shifts[:, :, None, :] + base[None, None, :, :]  # (H,W,A,4)
    return all_anchors.reshape(-1, 4)
