"""Box geometry: transforms, decoding, clipping, IoU.

Replaces the reference's rcnn/processing/bbox_transform.py (bbox_transform,
bbox_pred, clip_boxes, numpy bbox_overlaps) and rcnn/cython/bbox.pyx
(bbox_overlaps_cython). Everything is pure jnp, differentiable where it makes
sense, and shape-polymorphic in the leading box count (which is always static
under jit).

Numeric contract (silent-mAP-killer territory, see SURVEY.md §8): the
reference uses *inclusive* pixel coordinates, so a box (x1,y1,x2,y2) has
width x2-x1+1. That +1.0 is preserved everywhere here.
"""

from __future__ import annotations

import jax.numpy as jnp

# Matches the reference's clamp on dw/dh before exp (py-faster-rcnn lineage
# clamps at log(1000/16); the classic mx-rcnn relies on training stability —
# we clamp for TPU-safety, it is a no-op for in-range deltas).
_BBOX_XFORM_CLIP = jnp.log(1000.0 / 16.0)


def _whctrs(boxes: jnp.ndarray):
    """(x1,y1,x2,y2) -> (w, h, cx, cy) with the +1 inclusive convention."""
    w = boxes[..., 2] - boxes[..., 0] + 1.0
    h = boxes[..., 3] - boxes[..., 1] + 1.0
    cx = boxes[..., 0] + 0.5 * (w - 1.0)
    cy = boxes[..., 1] + 0.5 * (h - 1.0)
    return w, h, cx, cy


def bbox_transform(ex_rois: jnp.ndarray, gt_rois: jnp.ndarray) -> jnp.ndarray:
    """Regression targets (dx,dy,dw,dh) taking ex_rois onto gt_rois.

    Reference: rcnn/processing/bbox_transform.py::bbox_transform.
    ex_rois, gt_rois: (..., 4). Returns (..., 4).
    """
    ew, eh, ecx, ecy = _whctrs(ex_rois)
    gw, gh, gcx, gcy = _whctrs(gt_rois)
    # 1e-14 guards the padded/degenerate rows; real boxes have w,h >= 1.
    dx = (gcx - ecx) / (ew + 1e-14)
    dy = (gcy - ecy) / (eh + 1e-14)
    dw = jnp.log(gw / (ew + 1e-14) + 1e-14)
    dh = jnp.log(gh / (eh + 1e-14) + 1e-14)
    return jnp.stack([dx, dy, dw, dh], axis=-1)


def bbox_pred(boxes: jnp.ndarray, deltas: jnp.ndarray) -> jnp.ndarray:
    """Decode deltas on top of boxes (inverse of bbox_transform).

    Reference: rcnn/processing/bbox_transform.py::bbox_pred.
    boxes: (..., N, 4); deltas: (..., N, 4*K) for K classes (K=1 for RPN).
    Returns (..., N, 4*K).
    """
    w, h, cx, cy = _whctrs(boxes)
    # Broadcast the box geometry over the K per-class delta groups.
    shape = deltas.shape[:-1] + (deltas.shape[-1] // 4, 4)
    d = deltas.reshape(shape)
    dx, dy = d[..., 0], d[..., 1]
    dw = jnp.clip(d[..., 2], max=_BBOX_XFORM_CLIP)
    dh = jnp.clip(d[..., 3], max=_BBOX_XFORM_CLIP)
    w_ = w[..., None]
    h_ = h[..., None]
    pcx = dx * w_ + cx[..., None]
    pcy = dy * h_ + cy[..., None]
    pw = jnp.exp(dw) * w_
    ph = jnp.exp(dh) * h_
    out = jnp.stack(
        [
            pcx - 0.5 * (pw - 1.0),
            pcy - 0.5 * (ph - 1.0),
            pcx + 0.5 * (pw - 1.0),
            pcy + 0.5 * (ph - 1.0),
        ],
        axis=-1,
    )
    return out.reshape(deltas.shape)


def clip_boxes(boxes: jnp.ndarray, im_shape) -> jnp.ndarray:
    """Clip (..., 4*K) boxes to [0, W-1] x [0, H-1].

    Reference: rcnn/processing/bbox_transform.py::clip_boxes.
    im_shape: (H, W) scalars or arrays broadcastable over the leading dims.
    """
    h, w = im_shape[0], im_shape[1]
    shape = boxes.shape[:-1] + (boxes.shape[-1] // 4, 4)
    b = boxes.reshape(shape)
    x1 = jnp.clip(b[..., 0], 0.0, w - 1.0)
    y1 = jnp.clip(b[..., 1], 0.0, h - 1.0)
    x2 = jnp.clip(b[..., 2], 0.0, w - 1.0)
    y2 = jnp.clip(b[..., 3], 0.0, h - 1.0)
    return jnp.stack([x1, y1, x2, y2], axis=-1).reshape(boxes.shape)


def bbox_overlaps(boxes: jnp.ndarray, query_boxes: jnp.ndarray) -> jnp.ndarray:
    """Dense IoU matrix, (N,4) x (K,4) -> (N,K).

    Replaces rcnn/cython/bbox.pyx::bbox_overlaps_cython — on TPU the O(N·K)
    matrix is a vectorized broadcast, no kernel needed. Inclusive (+1) widths
    as in the reference. Degenerate/padded query rows (area <= 0 after the +1
    convention requires x2>=x1) yield overlap 0 via the max(0, ...) clamps and
    a non-negative union, so callers can pad with zero boxes safely *if* they
    also mask; a (0,0,0,0) pad box has area 1 and can produce tiny IoUs —
    always mask padded rows downstream.
    """
    b = boxes[:, None, :]
    q = query_boxes[None, :, :]
    iw = jnp.minimum(b[..., 2], q[..., 2]) - jnp.maximum(b[..., 0], q[..., 0]) + 1.0
    ih = jnp.minimum(b[..., 3], q[..., 3]) - jnp.maximum(b[..., 1], q[..., 1]) + 1.0
    iw = jnp.maximum(iw, 0.0)
    ih = jnp.maximum(ih, 0.0)
    inter = iw * ih
    area_b = (b[..., 2] - b[..., 0] + 1.0) * (b[..., 3] - b[..., 1] + 1.0)
    area_q = (q[..., 2] - q[..., 0] + 1.0) * (q[..., 3] - q[..., 1] + 1.0)
    union = area_b + area_q - inter
    return inter / jnp.maximum(union, 1e-14)


def generalized_iou_xyxy(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Pairwise generalized IoU, EXCLUSIVE (x1, y1, x2, y2) convention.

    (N, 4) x (M, 4) -> (N, M). Used by the DETR matcher/loss
    (models/detr.py) — gIoU = IoU − |hull − union| / |hull| (Rezatofighi
    et al.). Exclusive widths (x2 − x1), unlike the classic +1-inclusive
    ops above, because DETR boxes are continuous normalized coordinates.
    """
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0) * jnp.clip(a[:, 3] - a[:, 1], 0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0) * jnp.clip(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    iou = inter / jnp.maximum(union, 1e-9)
    hlt = jnp.minimum(a[:, None, :2], b[None, :, :2])
    hrb = jnp.maximum(a[:, None, 2:], b[None, :, 2:])
    hwh = jnp.clip(hrb - hlt, 0)
    hull = hwh[..., 0] * hwh[..., 1]
    return iou - (hull - union) / jnp.maximum(hull, 1e-9)
