"""graftcanvas — in-graph placement machinery for packed batches.

Host side (planning, assembly, config contract) lives in data/canvas.py;
this module is the traced half: placement masks the backbone re-zeros its
gap cells with, and the packed-batch view helpers every forward shares.

Packed batch contract (data/loader.py::AnchorLoader under
image.canvas_pack):

  image       (P, Hc, Wc, 3)    one fixed canvas per plane
  im_info     (P, I, 5)         rows [h, w, scale, y0, x0] per image
  gt_boxes    (P, I, G, 4)      CANVAS coordinates (offset-shifted)
  gt_classes  (P, I, G)         int32
  gt_valid    (P, I, G)         bool
  gt_masks    (P, I, G, m, m)   box-frame (shift-invariant), when used

P = planes (one per data shard x accum chunk), I = images per plane.
Forwards flatten (P, I) -> B images; `plane_of` maps image -> plane for
per-image reads of per-plane tensors (RPN outputs, ROI pooling).
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax.numpy as jnp


def is_packed_batch(batch) -> bool:
    """Packed batches carry (P, I, 5) im_info; bucketed ones (B, 3)."""
    info = batch.get("im_info") if hasattr(batch, "get") else None
    return info is not None and getattr(info, "ndim", 0) == 3


def packed_views(batch):
    """(im_info (B,5), plane_of (B,), gt views flattened to (B, ...)).

    The packed forward's common preamble: flatten the (P, I) image grid
    to B = P*I rows while remembering each image's plane."""
    info = batch["im_info"]
    p, ipp = info.shape[0], info.shape[1]
    b = p * ipp
    plane_of = jnp.repeat(jnp.arange(p, dtype=jnp.int32), ipp)
    views = {"im_info": info.reshape(b, info.shape[-1]),
             "plane_of": plane_of}
    for key in ("gt_boxes", "gt_classes", "gt_valid", "gt_masks"):
        if key in batch:
            v = batch[key]
            views[key] = v.reshape(b, *v.shape[2:])
    return views


def plane_take(per_plane: jnp.ndarray, plane_of: jnp.ndarray) -> jnp.ndarray:
    """Per-plane tensor (P, ...) -> per-image rows (B, ...)."""
    return jnp.take(per_plane, plane_of, axis=0)


def placement_masks(im_info: jnp.ndarray, canvas_hw: Tuple[int, int],
                    strides: Sequence[int]) -> Dict[int, jnp.ndarray]:
    """{stride: (P, Hc/s, Wc/s, 1) float32} content masks of the canvas.

    A cell is 1 iff it overlaps ANY placement's content rect — the
    backbone multiplies activations by these after every residual block
    so gap cells stay exactly zero (the per-level analog of the
    rpn_forward_packed zero-gap argument: each conv then sees zeros
    beyond the content boundary, identical to the bucketed path's
    implicit SAME padding at its canvas edge). Offsets are aligned to
    the max stride (data/canvas.py), so start cells are exact; trailing
    partial cells count as content, matching the bucketed map's
    ceil-extent. Pure broadcasted comparisons — a few comparisons per
    canvas cell, folded by XLA."""
    ch, cw = canvas_hw
    h = im_info[..., 0]   # (P, I)
    w = im_info[..., 1]
    y0 = im_info[..., 3]
    x0 = im_info[..., 4]
    out = {}
    for s in strides:
        ys = (jnp.arange(ch // s, dtype=jnp.float32) * s)[None, None, :]
        xs = (jnp.arange(cw // s, dtype=jnp.float32) * s)[None, None, :]
        row_in = (ys + s > y0[..., None]) & (ys < (y0 + h)[..., None])
        col_in = (xs + s > x0[..., None]) & (xs < (x0 + w)[..., None])
        # (P, I, hs, ws) any-image union -> (P, hs, ws, 1)
        cell = jnp.any(row_in[..., :, None] & col_in[..., None, :], axis=1)
        out[s] = cell.astype(jnp.float32)[..., None]
    return out


def anchors_in_window(anchors: jnp.ndarray, info: jnp.ndarray) -> jnp.ndarray:
    """(N,) bool: anchor CENTER inside the image's placement rect.

    The packed analog of "this anchor belongs to this image's grid":
    center-inside keeps the border-straddling anchors the bucketed grid
    also has (they get clipped), and excludes every anchor over a gap or
    a neighboring placement. info row = [h, w, scale, y0, x0]."""
    cy = (anchors[:, 1] + anchors[:, 3]) * 0.5
    cx = (anchors[:, 0] + anchors[:, 2]) * 0.5
    return ((cy >= info[3]) & (cy < info[3] + info[0])
            & (cx >= info[4]) & (cx < info[4] + info[1]))
