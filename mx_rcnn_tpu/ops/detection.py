"""Final detection post-processing — per-class NMS, on device.

Reference: the host-side loop in rcnn/core/tester.py::pred_eval (per class:
score threshold → NMS(0.3) → all_boxes, then a max_per_image cap across
classes). On TPU this is a vmapped static-shape op inside jit, so the whole
test forward produces ready detections and only one small tensor crosses to
the host per batch.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.nms import nms_bitmask


class Detections(NamedTuple):
    boxes: jnp.ndarray    # (B, M, 4)
    scores: jnp.ndarray   # (B, M)
    classes: jnp.ndarray  # (B, M) int32 (1..C-1)
    valid: jnp.ndarray    # (B, M) bool


def multiclass_nms(
    scores: jnp.ndarray,
    boxes: jnp.ndarray,
    roi_valid: jnp.ndarray,
    *,
    score_thresh: float = 0.05,
    nms_thresh: float = 0.3,
    max_per_image: int = 100,
) -> Detections:
    """Batched multi-class NMS.

    Args:
      scores: (B, R, C) softmax class probabilities (col 0 = background).
      boxes: (B, R, 4C) per-class decoded boxes.
      roi_valid: (B, R) bool.

    Returns top max_per_image detections across classes per image.
    """
    b, r, c = scores.shape

    def one_image(sc, bx, rv):
        # per-class arrays, skipping background (class 0).
        sc_t = sc[:, 1:].T  # (C-1, R)
        bx_t = bx.reshape(r, c, 4).transpose(1, 0, 2)[1:]  # (C-1, R, 4)
        valid = (sc_t >= score_thresh) & rv[None, :]

        def per_class(s, bxs, v):
            keep_idx, keep_valid = nms_bitmask(bxs, s, v, nms_thresh,
                                               max_per_image)
            return s[keep_idx] * keep_valid, bxs[keep_idx], keep_valid

        ks, kb, kv = jax.vmap(per_class)(sc_t, bx_t, valid)  # (C-1, M, ...)
        cls_ids = jnp.broadcast_to(
            jnp.arange(1, c, dtype=jnp.int32)[:, None], ks.shape)
        flat_s = ks.reshape(-1)
        flat_b = kb.reshape(-1, 4)
        flat_c = cls_ids.reshape(-1)
        flat_v = kv.reshape(-1)
        # max_per_image cap ACROSS classes (reference: the image_scores sort
        # + threshold in pred_eval).
        top_s, top_i = jax.lax.top_k(
            jnp.where(flat_v, flat_s, -1.0), max_per_image)
        return Detections(
            boxes=flat_b[top_i],
            scores=top_s,
            classes=flat_c[top_i],
            valid=top_s > 0,
        )

    return jax.vmap(one_image)(scores, boxes, roi_valid)
