"""Bipartite matching, in-graph — the DETR Hungarian-matcher analog.

Torch DETR implementations match on the host with
scipy.optimize.linear_sum_assignment inside the loss — a per-step
device→host bounce, the same anti-pattern as the reference's Python
CustomOps (SURVEY.md §4.1). Here assignment runs INSIDE the jitted step as
a Bertsekas auction (forward auction, fixed epsilon): the VALID COLUMNS
(gt objects — the scarce side) bid simultaneously for rows (queries), each
bid computed with dense (M, N) tensor ops on the VPU; rows take the
highest bidder and prices rise — a `lax.while_loop` with no
data-dependent shapes.

With epsilon < gap/M the auction is exactly optimal; eps here bounds each
agent's suboptimality, keeping the result within M·eps (~1e-2 of the cost
scale at defaults) of the optimum — differential tests against
scipy.optimize.linear_sum_assignment (tests/test_matching.py) check
exact-optimal total cost on random instances at test tolerances.

Rectangular problems (more rows than valid columns — DETR's 100 queries
vs ≤ max_gt objects) terminate naturally: every valid column ends up owning
a distinct row.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def auction_assign(cost: jnp.ndarray, col_valid: jnp.ndarray,
                   eps: float = 1e-3, max_iters: int = 10000):
    """Minimize sum over matched pairs of cost[i, j], each valid column j
    matched to a distinct row i (auction algorithm; columns bid for rows).

    Args:
      cost: (N, M) float32; rows = DETR queries, cols = gt objects.
        Requires N >= number of valid columns.
      col_valid: (M,) bool.
      eps: bid increment (per-agent suboptimality bound).
      max_iters: while_loop safety bound.

    Returns:
      row_to_col: (N,) int32 — the matched column per row (0 where
        unmatched; check row_matched).
      row_matched: (N,) bool — True iff the row is matched to a valid
        column; each valid column is matched to exactly one row.
    """
    n, m = cost.shape
    # Agents = columns, objects = rows: benefit[a, o] = -cost[o, a].
    benefit = -cost.T.astype(jnp.float32)  # (M, N)

    prices = jnp.zeros((n,), jnp.float32)      # row prices
    col_to_row = jnp.full((m,), -1, jnp.int32)  # agent -> object
    row_owner = jnp.full((n,), -1, jnp.int32)   # object -> agent

    def cond(state):
        it, prices, col_to_row, row_owner = state
        unassigned = (col_to_row < 0) & col_valid
        return jnp.any(unassigned) & (it < max_iters)

    def body(state):
        it, prices, col_to_row, row_owner = state
        bidding = (col_to_row < 0) & col_valid  # (M,)
        value = benefit - prices[None, :]  # (M, N)
        best_row = jnp.argmax(value, axis=1)  # (M,)
        best_val = jnp.max(value, axis=1)
        masked = value.at[jnp.arange(m), best_row].set(-jnp.inf)
        second_val = jnp.max(masked, axis=1)
        second_val = jnp.where(jnp.isfinite(second_val), second_val,
                               best_val - 1.0)
        bid = jnp.where(bidding, best_val - second_val + eps, -jnp.inf)
        # Highest bid per row; ties broken toward the lowest column index.
        row_bid = jnp.full((n,), -jnp.inf).at[best_row].max(bid)
        cols = jnp.arange(m, dtype=jnp.int32)
        is_top = bidding & (bid == row_bid[best_row]) & jnp.isfinite(bid)
        winner_col = jnp.full((n,), m, jnp.int32).at[best_row].min(
            jnp.where(is_top, cols, m))
        takes = is_top & (winner_col[best_row] == cols)  # (M,)
        target = jnp.where(takes, best_row, n)  # scatter target (drop OOB)
        # Displace previous owners of the taken rows.
        row_taken = jnp.zeros((n,), bool).at[target].set(True, mode="drop")
        col_to_row = jnp.where(
            (col_to_row >= 0) & row_taken[jnp.maximum(col_to_row, 0)]
            & ~takes, -1, col_to_row)
        col_to_row = jnp.where(takes, best_row, col_to_row)
        row_owner = row_owner.at[target].set(
            jnp.where(takes, cols, 0), mode="drop")
        prices = prices.at[target].add(jnp.where(takes, bid, 0.0),
                                       mode="drop")
        return it + 1, prices, col_to_row, row_owner

    _, prices, col_to_row, row_owner = lax.while_loop(
        cond, body,
        (jnp.zeros((), jnp.int32), prices, col_to_row, row_owner))

    row_matched = (row_owner >= 0) & col_valid[jnp.maximum(row_owner, 0)]
    row_to_col = jnp.where(row_matched, jnp.maximum(row_owner, 0), 0)
    return row_to_col.astype(jnp.int32), row_matched
