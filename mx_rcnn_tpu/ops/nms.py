"""Static-shape greedy NMS, jit-traceable.

Replaces the reference's three NMS paths (rcnn/processing/nms.py wrappers over
rcnn/cython/cpu_nms.pyx, rcnn/cython/gpu_nms.pyx + nms_kernel.cu, and the pure
python reference) with a single TPU formulation:

- input is a fixed-size padded set of boxes + scores + validity mask;
- output is the top `max_output` surviving indices, padded, plus a validity
  mask — shapes are static, so the op lives inside jit (the reference's GPU
  NMS requires a device->host sync for the host-side bitmask scan).

Algorithm: exact greedy NMS. Iteratively select the highest-scoring live box,
emit it, suppress all boxes with IoU > thresh against it. `max_output`
iterations of an O(N) step inside `lax.fori_loop`. This matches the
sequential-suppression semantics of the Cython/CUDA kernels exactly
(including the strict `>` threshold comparison).

A blockwise-bitmask Pallas kernel (the nms_kernel.cu formulation on MXU-sized
tiles) is the planned fast path for the 12000-box training case; this jnp
version is the reference implementation and the correctness oracle for it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from mx_rcnn_tpu.ops.boxes import bbox_overlaps

_NEG = -1e10


def nms(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    valid: jnp.ndarray,
    iou_threshold: float,
    max_output: int,
):
    """Greedy NMS over a padded box set.

    Args:
      boxes: (N, 4) float, (x1,y1,x2,y2) inclusive coords.
      scores: (N,) float.
      valid: (N,) bool — padded rows must be False.
      iou_threshold: suppress IoU strictly greater than this (reference
        cpu_nms.pyx uses `ovr >= thresh` suppression? No — classic uses
        `ovr > thresh` kept check via np.where(ovr <= thresh); we keep
        boxes with IoU <= thresh, i.e. suppress strictly-greater).
      max_output: static number of survivors to emit.

    Returns:
      keep_idx: (max_output,) int32 indices into boxes (0-padded),
      keep_valid: (max_output,) bool.
    """
    n = boxes.shape[0]
    live_scores = jnp.where(valid, scores.astype(jnp.float32), _NEG)

    def body(i, carry):
        live, keep_idx, keep_valid = carry
        best = jnp.argmax(live)
        best_ok = live[best] > _NEG / 2
        keep_idx = keep_idx.at[i].set(jnp.where(best_ok, best, 0).astype(jnp.int32))
        keep_valid = keep_valid.at[i].set(best_ok)
        best_box = boxes[best]
        iou = _iou_one_to_many(best_box, boxes)
        suppress = (iou > iou_threshold) & best_ok
        live = jnp.where(suppress, _NEG, live)
        live = live.at[best].set(_NEG)
        return live, keep_idx, keep_valid

    keep_idx = jnp.zeros((max_output,), jnp.int32)
    keep_valid = jnp.zeros((max_output,), bool)
    _, keep_idx, keep_valid = lax.fori_loop(
        0, max_output, body, (live_scores, keep_idx, keep_valid)
    )
    return keep_idx, keep_valid


def _iou_one_to_many(box: jnp.ndarray, boxes: jnp.ndarray) -> jnp.ndarray:
    iw = jnp.minimum(box[2], boxes[:, 2]) - jnp.maximum(box[0], boxes[:, 0]) + 1.0
    ih = jnp.minimum(box[3], boxes[:, 3]) - jnp.maximum(box[1], boxes[:, 1]) + 1.0
    inter = jnp.maximum(iw, 0.0) * jnp.maximum(ih, 0.0)
    area = (box[2] - box[0] + 1.0) * (box[3] - box[1] + 1.0)
    areas = (boxes[:, 2] - boxes[:, 0] + 1.0) * (boxes[:, 3] - boxes[:, 1] + 1.0)
    return inter / jnp.maximum(area + areas - inter, 1e-14)


def nms_bitmask(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    valid: jnp.ndarray,
    iou_threshold: float,
    max_output: int,
):
    """Bitmask-formulation greedy NMS (the nms_kernel.cu algorithm, XLA-side).

    Phase 1 (parallel, MXU-friendly): sort boxes by score, compute the full
    N×N suppression matrix in one shot. Phase 2 (sequential scan over N):
    box i survives iff it is not suppressed by any earlier survivor. The scan
    is O(N) steps of O(N) vector work — much fewer sequential steps than
    `nms` when max_output << N is false (e.g. 12000→2000 training proposals).

    Memory: N×N bool matrix. Fine for N ≤ ~8k on one v5e core; the training
    12k case is handled by pre-trimming to pre_nms_top_n first (as the
    reference also does) or by the future Pallas blocked kernel.

    Returns indices into the ORIGINAL (unsorted) box array, padded, + mask.
    """
    n = boxes.shape[0]
    neg_scores = jnp.where(valid, scores.astype(jnp.float32), _NEG)
    order = jnp.argsort(-neg_scores)  # descending
    sboxes = boxes[order]
    svalid = valid[order]
    iou = bbox_overlaps(sboxes, sboxes)
    sup = (iou > iou_threshold) & svalid[None, :] & svalid[:, None]
    # Keep lower triangle: sup[j, i] = True iff earlier box i (higher score)
    # would suppress later box j, for i < j.
    sup = jnp.tril(sup, k=-1)

    def body(carry, j):
        kept = carry
        suppressed = jnp.any(sup[j] & kept)
        keep_j = svalid[j] & ~suppressed
        kept = kept.at[j].set(keep_j)
        return kept, keep_j

    kept0 = jnp.zeros((n,), bool)
    _, keep_flags = lax.scan(body, kept0, jnp.arange(n))
    # Select the first max_output kept boxes in score order.
    rank = jnp.cumsum(keep_flags) - 1
    take = keep_flags & (rank < max_output)
    # Scatter sorted positions into output slots.
    out_idx = jnp.zeros((max_output,), jnp.int32)
    out_valid = jnp.zeros((max_output,), bool)
    slot = jnp.where(take, rank, max_output)  # invalid rows -> OOB slot
    out_idx = out_idx.at[slot].set(order.astype(jnp.int32), mode="drop")
    out_valid = out_valid.at[slot].set(True, mode="drop")
    return out_idx, out_valid


# Past this size the (N, N) bitmask suppression matrix costs more than the
# O(N·max_output) iterative formulation (measured crossover region on CPU;
# the Pallas kernel owns the TPU path regardless).
BITMASK_NMS_MAX_BOXES = 6144


def nms_dispatch(boxes, scores, valid, iou_threshold: float,
                 max_output: int, impl: str = "auto"):
    """THE batched NMS policy, shared by every proposal path
    (ops/proposal.py, models/fpn.py): Pallas on TPU, jnp elsewhere with
    the bitmask-vs-iterative size guard.

    boxes (B, N, 4), scores (B, N), valid (B, N) → (keep_idx (B, max_output),
    keep_valid (B, max_output)).
    impl: "auto" | "pallas" | "xla".
    """
    from functools import partial

    from mx_rcnn_tpu.ops.nms_pallas import batched_nms

    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas":
        return batched_nms(boxes, scores, valid, iou_threshold, max_output)
    if impl == "xla":
        nms_fn = (nms_bitmask if boxes.shape[1] <= BITMASK_NMS_MAX_BOXES
                  else nms)
        return jax.vmap(
            partial(nms_fn, iou_threshold=iou_threshold,
                    max_output=max_output))(boxes, scores, valid)
    raise ValueError(f"unknown nms impl {impl!r}")
