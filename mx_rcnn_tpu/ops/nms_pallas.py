"""Pallas TPU NMS — the blocked-bitmask greedy NMS kernel.

This is the TPU replacement for the reference's CUDA NMS
(rcnn/cython/nms_kernel.cu + gpu_nms.pyx): same algorithm family — compute
pairwise suppression in score order, then a sequential survivor scan — but
restructured for the TPU memory hierarchy instead of 64-thread warps:

- boxes are pre-sorted by score (descending) and padded to a multiple of the
  128-lane block size;
- the grid walks (set, block): for each 128-box block the kernel computes the
  IoU of the block's boxes against ALL boxes in one (128, N) VPU tile
  (recomputed per block — cheaper than materializing the N×N matrix in HBM,
  which is what caps the XLA `nms_bitmask` variant at ~6k boxes);
- suppression *within* the block is resolved by a 128-step `fori_loop` on
  (1, 128) vectors (the only inherently sequential part of greedy NMS);
- suppression of *later* blocks is propagated with one (1,128)·(128,N) MXU
  matmul into a persistent (1, N) VMEM accumulator.

Semantics match ops/nms.py exactly (strict `>` threshold, +1 inclusive box
widths, score-descending greedy order). This kernel is the production NMS for
proposal generation on TPU (ops/proposal.py dispatches to ``batched_nms``
when the backend is TPU); tests/test_nms.py::TestBatchedNMSPallas checks
equivalence against both jnp oracles (interpret mode off-TPU).

Mosaic lowering notes: dynamic_slice on computed VALUES is unsupported — all
dynamic indexing here happens either through BlockSpec index maps (the
per-block column views) or through `pl.ds` on refs (the in-block suppression
matrix staged via VMEM scratch, the suppression-accumulator prefix).

The kernel runs in interpreter mode off-TPU so the CPU test mesh exercises
the same code path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK = 128


def _iou_tile(x1i, y1i, x2i, y2i, cols):
    """IoU of column-vector boxes (B,1 each) vs a (4, M) transposed box set."""
    x1j, y1j = cols[0:1, :], cols[1:2, :]
    x2j, y2j = cols[2:3, :], cols[3:4, :]
    iw = jnp.minimum(x2i, x2j) - jnp.maximum(x1i, x1j) + 1.0
    ih = jnp.minimum(y2i, y2j) - jnp.maximum(y1i, y1j) + 1.0
    inter = jnp.maximum(iw, 0.0) * jnp.maximum(ih, 0.0)
    area_i = (x2i - x1i + 1.0) * (y2i - y1i + 1.0)
    area_j = (x2j - x1j + 1.0) * (y2j - y1j + 1.0)
    return inter / jnp.maximum(area_i + area_j - inter, 1e-14)


def _nms_kernel(rows_ref, cols_ref, cols_blk_ref, valid_ref, valid_blk_ref,
                out_ref, supp_ref, mkk_ref, *, iou_threshold: float):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        supp_ref[...] = jnp.zeros_like(supp_ref)

    blk = rows_ref[0]  # (BLOCK, 4) — this block's boxes, score-desc order
    x1i, y1i = blk[:, 0:1], blk[:, 1:2]
    x2i, y2i = blk[:, 2:3], blk[:, 3:4]

    vj = valid_ref[0]  # (1, N)
    # mask[i, j] = 1 iff box i (this block), if kept, suppresses box j.
    iou_all = _iou_tile(x1i, y1i, x2i, y2i, cols_ref[0])
    mask = ((iou_all > iou_threshold) & (vj > 0.0)).astype(jnp.float32)

    vblk = valid_blk_ref[0]  # (1, BLOCK)
    iou_kk = _iou_tile(x1i, y1i, x2i, y2i, cols_blk_ref[0])
    mkk_ref[...] = ((iou_kk > iou_threshold) & (vblk > 0.0)).astype(jnp.float32)

    base = pl.multiple_of(k * BLOCK, BLOCK)
    prefix = supp_ref[0:1, pl.ds(base, BLOCK)]  # (1, BLOCK)
    lane = lax.broadcasted_iota(jnp.int32, (1, BLOCK), 1)

    def inner(j, carry):
        kept_row, sup_row = carry  # (1, BLOCK) each
        onehot = (lane == j).astype(jnp.float32)
        supped = jnp.sum(onehot * (sup_row + prefix))
        v_j = jnp.sum(onehot * vblk)
        keep_j = (v_j > 0.0) & (supped == 0.0)
        # Row j of the in-block mask: boxes j would suppress if kept.
        mrow = mkk_ref[pl.ds(j, 1), :]
        sup_row = sup_row + jnp.where(keep_j, mrow, 0.0)
        kept_row = kept_row + jnp.where(keep_j, onehot, 0.0)
        return kept_row, sup_row

    zeros = jnp.zeros((1, BLOCK), jnp.float32)
    kept_row, _ = lax.fori_loop(0, BLOCK, inner, (zeros, zeros))

    out_ref[0] = kept_row
    # Propagate this block's survivors to every later column (earlier columns
    # are never read again, so polluting them is harmless).
    supp_ref[...] += lax.dot_general(
        kept_row, mask, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def nms_keep_sorted(boxes: jnp.ndarray, valid: jnp.ndarray,
                    iou_threshold: float) -> jnp.ndarray:
    """Greedy-NMS survivor mask over score-DESC-sorted boxes.

    Args:
      boxes: (S, N, 4) float32, sorted by descending score within each set.
      valid: (S, N) bool.
    Returns: keep (S, N) bool.
    """
    s, n = boxes.shape[0], boxes.shape[1]
    n_pad = -(-n // BLOCK) * BLOCK
    if n_pad != n:
        boxes = jnp.pad(boxes, ((0, 0), (0, n_pad - n), (0, 0)))
        valid = jnp.pad(valid, ((0, 0), (0, n_pad - n)))
    rows = boxes.astype(jnp.float32)
    cols = jnp.transpose(rows, (0, 2, 1))  # (S, 4, N)
    vmask = valid.astype(jnp.float32)[:, None, :]  # (S, 1, N)

    grid = (s, n_pad // BLOCK)
    keep = pl.pallas_call(
        partial(_nms_kernel, iou_threshold=float(iou_threshold)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, BLOCK, 4), lambda si, ki: (si, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 4, n_pad), lambda si, ki: (si, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 4, BLOCK), lambda si, ki: (si, 0, ki),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, n_pad), lambda si, ki: (si, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, BLOCK), lambda si, ki: (si, 0, ki),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, BLOCK), lambda si, ki: (si, 0, ki),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((s, 1, n_pad), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((1, n_pad), jnp.float32),
            pltpu.VMEM((BLOCK, BLOCK), jnp.float32),
        ],
        interpret=jax.default_backend() != "tpu",
    )(rows, cols, cols, vmask, vmask)
    return keep[:, 0, :n] > 0.0


def batched_nms(boxes: jnp.ndarray, scores: jnp.ndarray, valid: jnp.ndarray,
                iou_threshold: float, max_output: int):
    """Batched greedy NMS: sort → Pallas survivor mask → top-k selection.

    Args:
      boxes: (S, N, 4); scores: (S, N); valid: (S, N) bool.
    Returns:
      keep_idx: (S, max_output) int32 indices into the ORIGINAL box order
        (0-padded), keep_valid: (S, max_output) bool.

    Same output contract as ops/nms.py::nms/nms_bitmask (score-descending
    emission order, stable ties by original index).
    """
    s, n = scores.shape
    neg = jnp.where(valid, scores.astype(jnp.float32), -jnp.inf)
    order = jnp.argsort(-neg, axis=1)  # stable: ties keep original order
    sboxes = jnp.take_along_axis(boxes, order[..., None], axis=1)
    svalid = jnp.take_along_axis(valid, order, axis=1)
    keep = nms_keep_sorted(sboxes, svalid, iou_threshold)  # (S, N)

    rank = jnp.cumsum(keep, axis=1) - 1
    take = keep & (rank < max_output)
    slot = jnp.where(take, rank, max_output)  # OOB slot drops padding rows
    out_idx = jnp.zeros((s, max_output), jnp.int32)
    out_valid = jnp.zeros((s, max_output), bool)
    out_idx = out_idx.at[jnp.arange(s)[:, None], slot].set(
        order.astype(jnp.int32), mode="drop")
    out_valid = out_valid.at[jnp.arange(s)[:, None], slot].set(
        True, mode="drop")
    return out_idx, out_valid
