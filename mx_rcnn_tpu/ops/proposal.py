"""Proposal generation — in-graph, static-shape.

Replaces the reference's Proposal custom op (rcnn/symbol/proposal.py
ProposalOperator, and the C++/CUDA ``mx.contrib.sym.Proposal`` selected by
config.CXX_PROPOSAL). In the reference this op is a host round-trip when the
Python version is used (GPU→CPU→GPU per step); here it is a pure traced
function inside the jitted train step.

Pipeline (reference semantics, static shapes):
  anchors (compile-time const) + rpn deltas → bbox_pred → clip to image
  → min-size filter (mask, not drop) → top pre_nms_top_n by score
  → greedy NMS(thresh) → top post_nms_top_n, padded + validity mask.

NMS dispatch (``nms_impl``):
  - "pallas": the blocked-bitmask Pallas TPU kernel
    (ops/nms_pallas.py::batched_nms — the nms_kernel.cu analog), one batched
    call over all images.
  - "xla": the jnp formulations (ops/nms.py) — bitmask for small candidate
    sets, iterative otherwise — vmapped per image.
  - "auto" (default): "pallas" on the TPU backend, "xla" elsewhere (the
    Pallas kernel still runs off-TPU via the interpreter, but the XLA
    formulations are much faster under CPU testing).

The reference pads a short post-NMS set by *re-sampling kept rois*
(proposal.py pads with random duplicates) so downstream shapes hold; we pad
with the first kept roi and carry an explicit validity mask — downstream
sampling (ProposalTarget analog) respects the mask, which the reference's
duplicate-padding only approximates.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from mx_rcnn_tpu.ops.boxes import bbox_pred, clip_boxes
from mx_rcnn_tpu.ops.nms import BITMASK_NMS_MAX_BOXES, nms_dispatch

# Backwards-compat alias; the policy (and the guard rationale) lives in
# ops/nms.py::nms_dispatch now.
_BITMASK_NMS_MAX_BOXES = BITMASK_NMS_MAX_BOXES


def generate_proposals(
    rpn_cls_prob: jnp.ndarray,
    rpn_bbox_pred: jnp.ndarray,
    im_info: jnp.ndarray,
    anchors: jnp.ndarray,
    *,
    pre_nms_top_n: int,
    post_nms_top_n: int,
    nms_thresh: float,
    min_size: float,
    feat_stride: int = 16,
    nms_impl: str = "auto",
    topk_impl: str = "exact",
):
    """Batched proposal generation.

    Args:
      rpn_cls_prob: (B, H, W, 2A) — softmaxed scores, channel layout
        [bg*A, fg*A] along the last dim (matching the reference's
        (2A, H, W) NCHW layout transposed to NHWC).
      rpn_bbox_pred: (B, H, W, 4A) deltas.
      im_info: (B, 3) rows (im_height, im_width, im_scale) — the true
        (unpadded) image extent, as in the reference.
      anchors: (H*W*A, 4) from ops.anchors.anchor_grid (static const).
      min_size: min box side at the ORIGINAL scale; scaled by im_scale as in
        the reference (proposal.py: min_size * im_info[2]).
      nms_impl: "auto" | "pallas" | "xla" (see module docstring).
      topk_impl: "exact" (lax.top_k) | "approx" (lax.approx_max_k,
        recall_target 0.95 — the TPU PartialReduce op; ~1.2 ms faster at
        the 245k-score C4 size, identical on backends without the op).

    Returns:
      rois: (B, post_nms_top_n, 4) image-coordinate boxes,
      roi_valid: (B, post_nms_top_n) bool,
      roi_scores: (B, post_nms_top_n) float32 RPN fg scores (0 on padding) —
        the reference's Proposal op drops scores in-graph but the alternate
        -training proposal dump (tester.py::generate_proposals) saves them.
    """
    b, h, w, twice_a = rpn_cls_prob.shape
    a = twice_a // 2
    # fg scores: channels [A:2A). Reshape to (B, H*W*A) matching anchor order
    # (H, then W, then A fastest).
    fg = rpn_cls_prob[..., a:]  # (B, H, W, A)
    scores = fg.reshape(b, -1).astype(jnp.float32)
    deltas = rpn_bbox_pred.reshape(b, -1, 4).astype(jnp.float32)

    k = min(pre_nms_top_n, scores.shape[1])
    top_boxes, top_scores, top_valid = jax.vmap(
        partial(_decode_one_image, pre_nms_top_n=k, min_size=min_size,
                topk_impl=topk_impl),
        in_axes=(0, 0, 0, None),
    )(scores, deltas, im_info, anchors)

    return _nms_select(top_boxes, top_scores, top_valid, nms_thresh,
                       post_nms_top_n, nms_impl)


def generate_proposals_packed(
    fg_scores: jnp.ndarray,
    deltas: jnp.ndarray,
    im_info: jnp.ndarray,
    anchors: jnp.ndarray,
    *,
    pre_nms_top_n: int,
    post_nms_top_n: int,
    nms_thresh: float,
    min_size: float,
    nms_impl: str = "auto",
    topk_impl: str = "exact",
):
    """generate_proposals over a packed canvas (graftcanvas).

    Args:
      fg_scores: (B, N) per-IMAGE rows of the image's PLANE's fg scores
        over the full canvas anchor grid (ops/canvas.py::plane_take).
      deltas: (B, N, 4) likewise.
      im_info: (B, 5) packed rows [h, w, scale, y0, x0].
      anchors: (N, 4) canvas anchor grid (static const).

    Per image, only anchors whose center lies inside the placement rect
    participate, decoded boxes clip to the RECT (not the canvas), and
    min-size uses the image's own scale — so no proposal ever crosses a
    placement border (tests/test_canvas.py border-isolation gate) and
    each image reproduces the bucketed per-image decode exactly.
    Returns (rois, roi_valid, roi_scores) in CANVAS coordinates, same
    shapes/padding as generate_proposals.
    """
    k = min(pre_nms_top_n, fg_scores.shape[1])
    top_boxes, top_scores, top_valid = jax.vmap(
        partial(_decode_one_window, pre_nms_top_n=k, min_size=min_size,
                topk_impl=topk_impl),
        in_axes=(0, 0, 0, None),
    )(fg_scores.astype(jnp.float32), deltas.astype(jnp.float32), im_info,
      anchors)
    return _nms_select(top_boxes, top_scores, top_valid, nms_thresh,
                       post_nms_top_n, nms_impl)


def _nms_select(top_boxes, top_scores, top_valid, nms_thresh: float,
                post_nms_top_n: int, nms_impl: str):
    """Shared post-decode tail of the bucketed and packed proposal
    paths: NMS, gather, score zeroing, pad-with-first-kept-roi."""
    keep_idx, keep_valid = nms_dispatch(
        top_boxes, top_scores, top_valid, nms_thresh, post_nms_top_n,
        impl=nms_impl)
    rois = jnp.take_along_axis(top_boxes, keep_idx[..., None], axis=1)
    kept_scores = jnp.take_along_axis(top_scores, keep_idx, axis=1)
    roi_scores = jnp.where(keep_valid, kept_scores, 0.0)
    # Pad invalid slots with the first (highest-score) kept roi so
    # downstream pooling reads a real box; validity masks them out.
    rois = jnp.where(keep_valid[..., None], rois, rois[:, :1, :])
    return rois, keep_valid, roi_scores


def _decode_one_image(scores, deltas, im_info, anchors, *, pre_nms_top_n,
                      min_size, topk_impl: str = "exact"):
    """Per-image decode: deltas → boxes → clip → min-size mask → top-k."""
    boxes = bbox_pred(anchors, deltas)  # (N, 4)
    boxes = clip_boxes(boxes, (im_info[0], im_info[1]))
    # min-size filter (reference: _filter_boxes with min_size * im_scale).
    ws = boxes[:, 2] - boxes[:, 0] + 1.0
    hs = boxes[:, 3] - boxes[:, 1] + 1.0
    min_sz = min_size * im_info[2]
    size_ok = (ws >= min_sz) & (hs >= min_sz)
    scores = jnp.where(size_ok, scores, -1e10)
    # top-k pre-NMS trim. "approx" keeps score ORDER within the returned
    # set (approx_max_k returns sorted results; only membership at the
    # tail is approximate), so downstream NMS semantics are unchanged.
    if topk_impl == "approx":
        top_scores, top_idx = lax.approx_max_k(
            scores, pre_nms_top_n, recall_target=0.95)
    elif topk_impl == "exact":
        top_scores, top_idx = lax.top_k(scores, pre_nms_top_n)
    else:
        raise ValueError(
            f"topk_impl must be 'exact' or 'approx', got {topk_impl!r}")
    top_boxes = boxes[top_idx]
    top_valid = top_scores > -1e9
    return top_boxes, top_scores, top_valid


def _decode_one_window(scores, deltas, info, anchors, *, pre_nms_top_n,
                       min_size, topk_impl: str = "exact"):
    """_decode_one_image against a placement WINDOW of a packed canvas.

    info = [h, w, scale, y0, x0]. Same pipeline and ordering as the
    bucketed decode — decode, clip, min-size, top-k — with two deltas:
    anchors outside the window (center test) are masked out of the score
    race, and clipping happens in window-local coordinates (shift, clip
    to (h, w), shift back — identical arithmetic to the bucketed clip,
    so a canvas placement reproduces its bucketed image bit-for-bit).
    """
    from mx_rcnn_tpu.ops.canvas import anchors_in_window

    boxes = bbox_pred(anchors, deltas)  # (N, 4) canvas coords
    shift = jnp.stack([info[4], info[3], info[4], info[3]])
    boxes = clip_boxes(boxes - shift, (info[0], info[1])) + shift
    ws = boxes[:, 2] - boxes[:, 0] + 1.0
    hs = boxes[:, 3] - boxes[:, 1] + 1.0
    min_sz = min_size * info[2]
    keep = (ws >= min_sz) & (hs >= min_sz) & anchors_in_window(anchors, info)
    scores = jnp.where(keep, scores, -1e10)
    if topk_impl == "approx":
        top_scores, top_idx = lax.approx_max_k(
            scores, pre_nms_top_n, recall_target=0.95)
    elif topk_impl == "exact":
        top_scores, top_idx = lax.top_k(scores, pre_nms_top_n)
    else:
        raise ValueError(
            f"topk_impl must be 'exact' or 'approx', got {topk_impl!r}")
    top_boxes = boxes[top_idx]
    top_valid = top_scores > -1e9
    return top_boxes, top_scores, top_valid
