"""Ring attention — sequence-parallel exact attention over a mesh axis.

The reference has no attention at all (SURVEY.md §3.2 / §6: "no reference
parity needed ... if the ViTDet/DETR stretch config lands, sequence = image
patches — plan a shard_map ring-attention option over the ICI mesh"). This
module is that option: exact (non-approximate) attention where the sequence
axis is sharded across devices and key/value blocks rotate around the ring
with `jax.lax.ppermute`, overlapping compute with ICI transfers. Memory per
device is O(S/P · d) instead of O(S · d), so context length scales linearly
with the ring size.

Algorithm (Liu et al., Ring Attention; numerics = flash attention's
streaming softmax): each device keeps its query shard fixed and accumulates

    m_new = max(m, rowmax(q k_blk^T))
    acc   = acc · e^{m−m_new} + e^{s−m_new} v_blk
    l     = l · e^{m−m_new} + rowsum(e^{s−m_new})

over all P key/value blocks, permuting (k, v) one step around the ring per
iteration. The final output acc / l is bitwise-independent of the block
order up to float addition reordering, so it matches dense softmax
attention to numerical tolerance (tests/test_ring_attention.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_attn_update(carry, kv, q, scale):
    """One streaming-softmax update with a (k, v) block."""
    acc, m, l = carry
    k, v = kv
    s = jnp.einsum("...qhd,...khd->...hqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    m_blk = jnp.max(s, axis=-1)  # (..., h, q)
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(s - m_new[..., None])  # (..., h, q, k)
    corr = jnp.exp(m - m_new)
    acc = acc * corr[..., None] + jnp.einsum(
        "...hqk,...khd->...hqd", p, v, preferred_element_type=jnp.float32)
    l = l * corr + jnp.sum(p, axis=-1)
    return acc, m_new, l


def _mark_varying(x, axes):
    """Mark x as varying over the given mesh axes (shard_map manual-axes
    type tracking). pvary is deprecated in favor of pcast in jax >= 0.9."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    return lax.pvary(x, axes)


def ring_attention_sharded(q, k, v, axis_name: str, scale=None,
                           vary_axes=None):
    """Attention with the SEQUENCE axis sharded over `axis_name`.

    To be called inside shard_map (or pmapped code): q/k/v are the LOCAL
    shards, shape (..., s_local, h, d). Returns the local output shard,
    (..., s_local, h, d), float32 accumulation cast back to q.dtype.

    vary_axes: all mesh axes the q/k/v shards vary over (defaults to just
    the ring axis). When the caller also shards the batch dim over another
    axis (DP×SP), that axis must be included so the fori_loop carry's
    varying-axes type matches the loop body's output.
    """
    p_size = lax.psum(1, axis_name)
    vary = tuple(vary_axes) if vary_axes is not None else (axis_name,)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    h, d = q.shape[-2], q.shape[-1]
    q_len = q.shape[-3]
    batch_shape = q.shape[:-3]

    acc = jnp.zeros(batch_shape + (h, q_len, d), jnp.float32)
    m = jnp.full(batch_shape + (h, q_len), -jnp.inf, jnp.float32)
    l = jnp.zeros(batch_shape + (h, q_len), jnp.float32)
    # Mark the carry as varying over every sharded operand axis (the body
    # mixes it with sharded operands; shard_map's manual-axes tracking
    # requires the fori_loop carry types to agree).
    acc, m, l = (_mark_varying(x, vary) for x in (acc, m, l))

    def body(i, carry):
        acc, m, l, k_cur, v_cur = carry
        acc, m, l = _block_attn_update((acc, m, l), (k_cur, v_cur), q, scale)
        perm = [(j, (j + 1) % p_size) for j in range(p_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    # p_size - 1 rotate-and-update steps, then the final block WITHOUT the
    # trailing ppermute (its result would be discarded — one full k/v shard
    # of ICI traffic saved per call).
    acc, m, l, k_last, v_last = lax.fori_loop(
        0, p_size - 1, body, (acc, m, l, k, v))
    acc, m, l = _block_attn_update((acc, m, l), (k_last, v_last), q, scale)
    out = acc / jnp.maximum(l[..., None], 1e-30)
    # (..., h, q, d) -> (..., q, h, d)
    out = jnp.moveaxis(out, -3, -2)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "data", scale=None):
    """Full-array entry point: shards the sequence axis over `mesh[axis]`.

    q/k/v: (B, S, H, D) with S divisible by the axis size. Output (B, S, H,
    D). This is the module attention backend for long-context configs
    (models/vit.py global blocks with network.use_ring_attention).

    The BATCH axis stays sharded over the mesh's data axis when one exists
    (and isn't the ring axis itself) — in the DP×SP layout the batch must
    not be allgathered onto every data-axis device.
    """
    batch_axis = None
    if "data" in mesh.axis_names and axis != "data" \
            and mesh.shape["data"] > 1 \
            and q.shape[0] % mesh.shape["data"] == 0:
        # Skip batch sharding when the batch doesn't tile the data axis —
        # notably the batch-1 dummy of init_vitdet_params; the real train
        # step always passes a data-divisible global batch.
        batch_axis = "data"
    spec = P(batch_axis, axis, None, None)
    vary = (axis,) if batch_axis is None else (axis, batch_axis)
    fn = jax.shard_map(
        partial(ring_attention_sharded, axis_name=axis, scale=scale,
                vary_axes=vary),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    sh = NamedSharding(mesh, spec)
    return fn(jax.device_put(q, sh), jax.device_put(k, sh),
              jax.device_put(v, sh))


def dense_attention(q, k, v, scale=None):
    """Reference dense softmax attention, (B, S, H, D) layout — the oracle
    for the ring formulation and the single-device fallback."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("...qhd,...khd->...hqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("...hqk,...khd->...qhd", p, v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
