"""Sequence-parallel exact attention over a mesh axis — ring and all-to-all.

The reference has no attention at all (SURVEY.md §3.2 / §6: "no reference
parity needed ... if the ViTDet/DETR stretch config lands, sequence = image
patches — plan a shard_map ring-attention option over the ICI mesh"). This
module provides BOTH standard sequence-parallel formulations:

- **Ring** (`ring_attention`, Liu et al.): key/value blocks rotate around
  the ring with `jax.lax.ppermute`, overlapping compute with ICI
  transfers; streaming-softmax accumulation. Memory per device is
  O(S/P · d) instead of O(S · d), so context length scales linearly with
  the ring size. No constraint on head count.
- **All-to-all** (`ulysses_attention`, DeepSpeed-Ulysses): one
  re-partition step before attention (an `all_to_all` on each of q/k/v)
  and one after (on the output) — 4 tensor collectives per call —
  exchange sequence sharding for head sharding; streaming-softmax
  (flash-style) attention runs locally without materializing the (S, S)
  score matrix. Constant collective count instead of the ring's P−1
  hops per tensor; requires heads divisible by the axis size.

Algorithm (Liu et al., Ring Attention; numerics = flash attention's
streaming softmax): each device keeps its query shard fixed and accumulates

    m_new = max(m, rowmax(q k_blk^T))
    acc   = acc · e^{m−m_new} + e^{s−m_new} v_blk
    l     = l · e^{m−m_new} + rowsum(e^{s−m_new})

over all P key/value blocks, permuting (k, v) one step around the ring per
iteration. The final output acc / l is bitwise-independent of the block
order up to float addition reordering, so it matches dense softmax
attention to numerical tolerance (tests/test_ring_attention.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover - version-dependent import
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_attn_update(carry, kv, q, scale, key_mask=None):
    """One streaming-softmax update with a (k, v) block.

    key_mask: optional (block,) bool — False keys are excluded (their
    scores forced to −inf before the max/exp), used for the padded tail
    block of streaming_attention.
    """
    acc, m, l = carry
    k, v = kv
    s = jnp.einsum("...qhd,...khd->...hqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if key_mask is not None:
        s = jnp.where(key_mask, s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)  # (..., h, q)
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(s - m_new[..., None])  # (..., h, q, k)
    corr = jnp.exp(m - m_new)
    acc = acc * corr[..., None] + jnp.einsum(
        "...hqk,...khd->...hqd", p, v, preferred_element_type=jnp.float32)
    l = l * corr + jnp.sum(p, axis=-1)
    return acc, m_new, l


def _mark_varying(x, axes):
    """Mark x as varying over the given mesh axes (shard_map manual-axes
    type tracking). pvary is deprecated in favor of pcast in jax >= 0.9;
    jax lines OLD enough to predate varying types (< 0.5, no pvary at
    all) need no marking — their shard_map mixes the values freely."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)
    return x


def _streaming_init(q, vary_axes=()):
    """(acc, m, l) carry for the streaming softmax, (..., h, q_len, d/·),
    marked varying over `vary_axes` (the carry mixes with sharded operands
    inside shard_map loops, so the types must agree)."""
    h, d, q_len = q.shape[-2], q.shape[-1], q.shape[-3]
    batch_shape = q.shape[:-3]
    acc = jnp.zeros(batch_shape + (h, q_len, d), jnp.float32)
    m = jnp.full(batch_shape + (h, q_len), -jnp.inf, jnp.float32)
    l = jnp.zeros(batch_shape + (h, q_len), jnp.float32)
    if vary_axes:
        acc, m, l = (_mark_varying(x, tuple(vary_axes))
                     for x in (acc, m, l))
    return acc, m, l


def _streaming_finalize(acc, l, dtype):
    """acc / l with the (..., h, q, d) -> (..., q, h, d) layout restore."""
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.moveaxis(out, -3, -2).astype(dtype)


def ring_attention_sharded(q, k, v, axis_name: str, scale=None,
                           vary_axes=None):
    """Attention with the SEQUENCE axis sharded over `axis_name`.

    To be called inside shard_map (or pmapped code): q/k/v are the LOCAL
    shards, shape (..., s_local, h, d). Returns the local output shard,
    (..., s_local, h, d), float32 accumulation cast back to q.dtype.

    vary_axes: all mesh axes the q/k/v shards vary over (defaults to just
    the ring axis). When the caller also shards the batch dim over another
    axis (DP×SP), that axis must be included so the fori_loop carry's
    varying-axes type matches the loop body's output.
    """
    p_size = lax.psum(1, axis_name)
    vary = tuple(vary_axes) if vary_axes is not None else (axis_name,)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    acc, m, l = _streaming_init(q, vary)

    def body(i, carry):
        acc, m, l, k_cur, v_cur = carry
        acc, m, l = _block_attn_update((acc, m, l), (k_cur, v_cur), q, scale)
        perm = [(j, (j + 1) % p_size) for j in range(p_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    # p_size - 1 rotate-and-update steps, then the final block WITHOUT the
    # trailing ppermute (its result would be discarded — one full k/v shard
    # of ICI traffic saved per call).
    acc, m, l, k_last, v_last = lax.fori_loop(
        0, p_size - 1, body, (acc, m, l, k, v))
    acc, m, l = _block_attn_update((acc, m, l), (k_last, v_last), q, scale)
    return _streaming_finalize(acc, l, q.dtype)


def _sp_layout(q, mesh: Mesh, axis: str):
    """(spec, vary) for a (B, S, H, D) array with S sharded over `axis`.

    The BATCH axis stays sharded over the mesh's data axis when one exists
    (and isn't the sequence axis itself) — in the DP×SP layout the batch
    must not be allgathered onto every data-axis device. Batch sharding is
    skipped when the batch doesn't tile the data axis — notably the
    batch-1 dummy of init_vitdet_params; the real train step always passes
    a data-divisible global batch.
    """
    batch_axis = None
    if "data" in mesh.axis_names and axis != "data" \
            and mesh.shape["data"] > 1 \
            and q.shape[0] % mesh.shape["data"] == 0:
        batch_axis = "data"
    spec = P(batch_axis, axis, None, None)
    vary = (axis,) if batch_axis is None else (axis, batch_axis)
    return spec, vary


def _sp_entry(make_sharded_fn, q, k, v, mesh: Mesh, axis: str):
    """Shared full-array entry: shard the sequence axis over `mesh[axis]`,
    run the per-shard attention under shard_map, return the full array.

    make_sharded_fn(vary) -> the per-shard callable; the layout (spec and
    varying axes) is computed ONCE here so the two can't diverge."""
    spec, vary = _sp_layout(q, mesh, axis)
    fn = shard_map(
        make_sharded_fn(vary),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    sh = NamedSharding(mesh, spec)
    return fn(jax.device_put(q, sh), jax.device_put(k, sh),
              jax.device_put(v, sh))


def ring_attention(q, k, v, mesh: Mesh, axis: str = "data", scale=None):
    """Full-array entry point: shards the sequence axis over `mesh[axis]`.

    q/k/v: (B, S, H, D) with S divisible by the axis size. Output (B, S, H,
    D). This is the module attention backend for long-context configs
    (models/vit.py global blocks with network.use_ring_attention).
    """
    return _sp_entry(
        lambda vary: partial(ring_attention_sharded, axis_name=axis,
                             scale=scale, vary_axes=vary),
        q, k, v, mesh, axis)


def streaming_attention(q, k, v, scale=None, kv_chunk=1024, vary_axes=()):
    """Exact attention with flash-style streaming softmax over key blocks.

    (B, S, H, D) layout, same contract as dense_attention, but the score
    buffer is (..., H, S, chunk) instead of (..., H, S, S) — O(S·chunk)
    memory, so long sequences never materialize a quadratic tensor. A
    non-divisible S is padded up to a whole number of chunks with the
    padded keys masked to −inf, so the bound holds for every length. Used
    as the LOCAL attention inside ulysses_attention (which would otherwise
    undercut the module's long-context memory claim) and usable standalone.

    vary_axes: mesh axes the operands vary over when called inside
    shard_map (the scan carry must carry the same varying-axes type).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = k.shape[-3]
    c = min(kv_chunk, s)
    n = -(-s // c)
    if n <= 1:
        # One block: the streaming pass degenerates to a single (S, S)
        # score buffer anyway — dense is the same memory, fewer ops.
        return dense_attention(q, k, v, scale=scale)
    h, d = q.shape[-2], q.shape[-1]
    batch_shape = q.shape[:-3]
    pad = n * c - s
    if pad:
        widths = [(0, 0)] * (k.ndim - 3) + [(0, pad), (0, 0), (0, 0)]
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    acc, m, l = _streaming_init(q, vary_axes)
    # (..., n·c, h, d) -> (n, ..., c, h, d): chunk axis leading for scan.
    nd = k.ndim
    km = jnp.moveaxis(k.reshape(batch_shape + (n, c, h, d)), nd - 3, 0)
    vm = jnp.moveaxis(v.reshape(batch_shape + (n, c, h, d)), nd - 3, 0)

    def body(carry, xs):
        return _block_attn_update(carry, xs, q, scale), None

    if pad:
        # Only the final block holds padded keys: scan the full blocks
        # unmasked (no per-block where in the hot path), then one masked
        # tail update.
        (acc, m, l), _ = lax.scan(body, (acc, m, l), (km[:-1], vm[:-1]))
        tail_mask = jnp.arange(c) < (c - pad)
        acc, m, l = _block_attn_update((acc, m, l), (km[-1], vm[-1]), q,
                                       scale, key_mask=tail_mask)
    else:
        (acc, m, l), _ = lax.scan(body, (acc, m, l), (km, vm))
    return _streaming_finalize(acc, l, q.dtype)


def ulysses_attention_sharded(q, k, v, axis_name: str, scale=None,
                              vary_axes=None, kv_chunk=1024):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses layout).

    Local shards (..., s_local, h, d) with the SEQUENCE sharded over
    `axis_name`. all_to_alls on q/k/v re-partition to full-sequence ×
    h/P heads (3 collectives), exact streaming-softmax attention runs
    locally (no (S, S) buffer), and one all_to_all re-partitions the
    output back — 4 tensor collectives per call, independent of P, vs
    the ring's P−1 ppermutes each for k and v; cheaper when h ≥ P and
    the per-step latency of the ring hops would dominate. Requires h
    divisible by the axis size (ring has no such constraint).
    """
    p_size = lax.psum(1, axis_name)
    h = q.shape[-2]
    # h % p_size == 0 is a static requirement; jit-traced shapes make this
    # checkable at trace time.
    if h % p_size != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({h}) divisible by the "
            f"sequence-parallel axis size ({p_size}); use ring_attention "
            "for head-indivisible layouts")
    # (..., s_local, h, d) -> (..., s_full, h/P, d): split heads, gather seq.
    q, k, v = (
        lax.all_to_all(x, axis_name, split_axis=x.ndim - 2,
                       concat_axis=x.ndim - 3, tiled=True)
        for x in (q, k, v))
    vary = tuple(vary_axes) if vary_axes is not None else (axis_name,)
    out = streaming_attention(q, k, v, scale=scale, vary_axes=vary,
                              kv_chunk=kv_chunk)
    # (..., s_full, h/P, d) -> (..., s_local, h, d).
    return lax.all_to_all(out, axis_name, split_axis=out.ndim - 3,
                          concat_axis=out.ndim - 2, tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "data", scale=None,
                      kv_chunk=1024):
    """Full-array entry point for the all-to-all SP formulation; same
    contract as ring_attention (q/k/v (B, S, H, D), S divisible by the
    axis size, plus H divisible by the axis size). kv_chunk sets the local
    streaming-softmax key-block size (the (S, S/chunks) memory knob)."""
    return _sp_entry(
        lambda vary: partial(ulysses_attention_sharded, axis_name=axis,
                             scale=scale, vary_axes=vary,
                             kv_chunk=kv_chunk),
        q, k, v, mesh, axis)


def dense_attention(q, k, v, scale=None):
    """Reference dense softmax attention, (B, S, H, D) layout — the oracle
    for the ring formulation and the single-device fallback."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("...qhd,...khd->...hqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("...hqk,...khd->...qhd", p, v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
