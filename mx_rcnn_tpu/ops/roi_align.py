"""ROIAlign / ROIPooling as traceable JAX ops.

Replaces MXNet's C++/CUDA builtins ``mx.symbol.ROIPooling`` and
``mx.contrib.sym.ROIAlign`` that the reference wires into its graphs
(rcnn/symbol/symbol_vgg.py 7x7 pool, rcnn/symbol/symbol_resnet.py 14x14 pool,
spatial_scale 1/16).

Formulation: both ops are expressed as dense gather + weighted reduction over
a static sampling grid, vmapped over ROIs — XLA lowers the gathers well and
there are no dynamic shapes. A Pallas fused-gather kernel is the planned fast
path; this is the semantic reference for it.

- ``roi_align``: bilinear sampling, ``sampling_ratio`` points per bin axis,
  average-pooled (He et al. Mask R-CNN semantics; ``aligned=True`` applies the
  -0.5 half-pixel correction of Detectron2, default False matches the classic
  MXNet contrib op).
- ``roi_pool``: quantized max pooling (classic Fast R-CNN semantics used by
  the reference's training graphs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _bilinear_gather(feat: jnp.ndarray, y: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Sample feat (H, W, C) at continuous points y, x (...,) -> (..., C).

    Out-of-bounds points clamp to the border (matching the CUDA kernels'
    behavior of clipping sample coords to the feature extent).
    """
    h, w = feat.shape[0], feat.shape[1]
    y = jnp.clip(y, 0.0, h - 1.0)
    x = jnp.clip(x, 0.0, w - 1.0)
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1 = jnp.minimum(y0 + 1.0, h - 1.0)
    x1 = jnp.minimum(x0 + 1.0, w - 1.0)
    ly = y - y0
    lx = x - x0
    hy = 1.0 - ly
    hx = 1.0 - lx
    y0i, x0i, y1i, x1i = (a.astype(jnp.int32) for a in (y0, x0, y1, x1))
    v00 = feat[y0i, x0i]
    v01 = feat[y0i, x1i]
    v10 = feat[y1i, x0i]
    v11 = feat[y1i, x1i]
    wdt = feat.dtype
    return (
        v00 * (hy * hx)[..., None].astype(wdt)
        + v01 * (hy * lx)[..., None].astype(wdt)
        + v10 * (ly * hx)[..., None].astype(wdt)
        + v11 * (ly * lx)[..., None].astype(wdt)
    )


def roi_align(
    features: jnp.ndarray,
    rois: jnp.ndarray,
    output_size: int,
    spatial_scale: float,
    sampling_ratio: int = 2,
    aligned: bool = False,
) -> jnp.ndarray:
    """ROIAlign.

    Args:
      features: (B, H, W, C) feature maps (NHWC — TPU-native layout; the
        reference's graphs are NCHW because cuDNN prefers it).
      rois: (R, 5) rows of (batch_idx, x1, y1, x2, y2) in image coords —
        same layout as the reference's Proposal op output.
      output_size: pooled grid side P.
      spatial_scale: e.g. 1/16 for C4.
      sampling_ratio: sample points per bin axis.
      aligned: half-pixel correction.

    Returns: (R, P, P, C).
    """
    p = output_size
    s = sampling_ratio
    offset = 0.5 if aligned else 0.0

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0) if not aligned else (x2 - x1)
        rh = jnp.maximum(y2 - y1, 1.0) if not aligned else (y2 - y1)
        bin_w = rw / p
        bin_h = rh / p
        # Sample grid: for bin (i,j), points at
        # y1 + (i + (k+0.5)/s) * bin_h, k in [0,s)
        grid = (jnp.arange(p * s, dtype=features.dtype) + 0.5) / s
        ys = y1 + grid * bin_h  # (p*s,)
        xs = x1 + grid * bin_w
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")  # (p*s, p*s)
        vals = _bilinear_gather(features[b], yy, xx)  # (p*s, p*s, C)
        # Average the s*s samples per bin.
        c = vals.shape[-1]
        vals = vals.reshape(p, s, p, s, c)
        return vals.mean(axis=(1, 3))

    return jax.vmap(one_roi)(rois)


def roi_pool(
    features: jnp.ndarray,
    rois: jnp.ndarray,
    output_size: int,
    spatial_scale: float,
) -> jnp.ndarray:
    """Classic quantized max ROIPooling (mx.symbol.ROIPooling semantics).

    Bin boundaries are computed by integer quantization (round of scaled
    coords, floor/ceil of bin edges); empty bins yield 0 (the CUDA kernel
    emits 0 for empty bins). Implemented densely: for each bin, a max over a
    masked window of the (static) feature map — O(P²·H·W) per ROI is fine at
    C4 sizes (64×64 feature map) and keeps shapes static.
    """
    p = output_size
    h, w = features.shape[1], features.shape[2]
    fy = jnp.arange(h, dtype=jnp.float32)
    fx = jnp.arange(w, dtype=jnp.float32)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        # Reference quantizes roi coords with round().
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        bin_w = rw / p
        bin_h = rh / p
        i = jnp.arange(p, dtype=jnp.float32)
        ys_lo = jnp.floor(y1 + i * bin_h)  # (p,)
        ys_hi = jnp.ceil(y1 + (i + 1.0) * bin_h)
        xs_lo = jnp.floor(x1 + i * bin_w)
        xs_hi = jnp.ceil(x1 + (i + 1.0) * bin_w)
        # Mask (p, H): feature row r in bin i iff ys_lo[i] <= r < ys_hi[i].
        row_in = (fy[None, :] >= ys_lo[:, None]) & (fy[None, :] < ys_hi[:, None])
        col_in = (fx[None, :] >= xs_lo[:, None]) & (fx[None, :] < xs_hi[:, None])
        feat = features[b]  # (H, W, C)
        neg = jnp.asarray(-jnp.inf, feat.dtype)
        # (p, 1, H, 1, 1) & (1, p, 1, W, 1) -> mask (p,p,H,W,1)
        mask = row_in[:, None, :, None, None] & col_in[None, :, None, :, None]
        masked = jnp.where(mask, feat[None, None], neg)
        out = masked.max(axis=(2, 3))  # (p, p, C)
        return jnp.where(jnp.isfinite(out), out, 0.0).astype(feat.dtype)

    return jax.vmap(one_roi)(rois)
