"""ROIAlign / ROIPooling as traceable JAX ops, MXU-formulated.

Replaces MXNet's C++/CUDA builtins ``mx.symbol.ROIPooling`` and
``mx.contrib.sym.ROIAlign`` that the reference wires into its graphs
(rcnn/symbol/symbol_vgg.py 7x7 pool, rcnn/symbol/symbol_resnet.py 14x14 pool,
spatial_scale 1/16).

TPU formulation — this is the "Pallas-or-provably-fast" design decision:
bilinear interpolation is SEPARABLE, so ROIAlign is exactly two small
matmuls per ROI,

    pooled[i, j, c] = sum_h sum_w  Wy[i, h] * feat[h, w, c] * Wx[j, w]

where ``Wy (P, H)`` / ``Wx (P, W)`` hold the tent-function (hat) bilinear
weights of each bin's sample points, bin-averaging folded in. That maps the
op onto the MXU as a batched (R·P, H) x (H, W·C) contraction instead of the
CUDA kernels' per-point gathers — gathers lower to slow scalar loads on TPU,
while these matmuls run at MXU rate and their transposes ARE the backward
pass. A custom Pallas kernel would only re-derive this same schedule, so the
einsum form is the intended final design, not a stopgap (profiled: the pool
is <5% of the train step, see tools/profile.py).

- ``roi_align``: bilinear sampling, ``sampling_ratio`` points per bin axis,
  average-pooled (He et al. Mask R-CNN semantics; ``aligned=True`` applies the
  -0.5 half-pixel correction of Detectron2, default False matches the classic
  MXNet contrib op). Border behavior matches the CUDA kernels: sample coords
  clamp to the feature extent.
- ``roi_pool``: quantized max pooling (classic Fast R-CNN semantics used by
  the reference's training graphs). Max over a rectangular bin is separable
  too (max over rows, then cols), giving an O(P·H·W·C)/ROI masked reduction
  instead of the O(P²·H·W·C) dense mask this module used to carry.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _tent_weights(lo, bin_size, p: int, s: int, extent: int,
                  clip_lo=0.0, clip_hi=None):
    """Per-bin averaged bilinear sample weights along one axis.

    For bin i, the s sample points sit at ``lo + (i + (k+0.5)/s) * bin_size``;
    each contributes tent-function (hat) weights to its two integer
    neighbors. Points are clamped to [clip_lo, clip_hi] — by default the
    full feature extent [0, extent-1] (CUDA-kernel border semantics);
    packed canvases pass the ROI's placement window instead (graftcanvas),
    so a border sample clamps to the IMAGE's last cell exactly as the
    bucketed per-image map would, rather than drifting into the zero gap.
    Returns (P, extent) float32 with the 1/s bin average folded in, so
    ``W @ feat`` directly yields bin-averaged bilinear samples.
    """
    grid = (jnp.arange(p * s, dtype=jnp.float32) + 0.5) / s  # (p*s,)
    pts = lo + grid * bin_size
    pts = jnp.clip(pts, clip_lo, extent - 1.0 if clip_hi is None else clip_hi)
    idx = jnp.arange(extent, dtype=jnp.float32)
    tent = jnp.maximum(0.0, 1.0 - jnp.abs(pts[:, None] - idx[None, :]))
    return tent.reshape(p, s, extent).mean(axis=1)  # (p, extent)


def roi_align(
    features: jnp.ndarray,
    rois: jnp.ndarray,
    output_size: int,
    spatial_scale: float,
    sampling_ratio: int = 2,
    aligned: bool = False,
    windows: jnp.ndarray = None,
) -> jnp.ndarray:
    """ROIAlign.

    Args:
      features: (B, H, W, C) feature maps (NHWC — TPU-native layout; the
        reference's graphs are NCHW because cuDNN prefers it).
      rois: (R, 5) rows of (batch_idx, x1, y1, x2, y2) in image coords —
        same layout as the reference's Proposal op output.
      output_size: pooled grid side P.
      spatial_scale: e.g. 1/16 for C4.
      sampling_ratio: sample points per bin axis.
      aligned: half-pixel correction.
      windows: optional (R, 4) rows [y0, x0, h, w] in image coords — the
        ROI's placement rect on a packed canvas (graftcanvas). Sample
        points then clamp to the rect's feature cells instead of the
        whole map, reproducing the bucketed per-image border behavior.

    Returns: (R, P, P, C), features.dtype.
    """
    b, h, w, _ = features.shape
    p = output_size
    s = sampling_ratio
    offset = 0.5 if aligned else 0.0

    def one_roi_weights(roi, win):
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0) if not aligned else (x2 - x1)
        rh = jnp.maximum(y2 - y1, 1.0) if not aligned else (y2 - y1)
        cy = cx = (0.0, None)
        if win is not None:
            wy0 = win[0] * spatial_scale
            wx0 = win[1] * spatial_scale
            cy = (wy0, wy0 + jnp.ceil(win[2] * spatial_scale) - 1.0)
            cx = (wx0, wx0 + jnp.ceil(win[3] * spatial_scale) - 1.0)
        wy = _tent_weights(y1, rh / p, p, s, h, *cy)  # (P, H)
        wx = _tent_weights(x1, rw / p, p, s, w, *cx)  # (P, W)
        return wy, wx

    wy, wx = jax.vmap(one_roi_weights, in_axes=(0, None if windows is None
                                                else 0))(rois, windows)
    batch_idx = rois[:, 0].astype(jnp.int32)
    dt = features.dtype
    wy = wy.astype(dt)
    wx = wx.astype(dt)

    # Contract against each image's features with the ROI→image assignment
    # folded into the weights (zeroing non-matching ROIs), summing the per-
    # image contributions — exactly one image contributes per ROI. This keeps
    # the contraction a clean (R·P, H) x (H, W·C) matmul per image instead of
    # a per-ROI feature-map gather (which would materialize (R, H, W, C)).
    tmp = None
    for bi in range(b):
        wy_b = jnp.where((batch_idx == bi)[:, None, None], wy, 0)
        t = jnp.einsum("rph,hwc->rpwc", wy_b, features[bi],
                       preferred_element_type=jnp.float32)
        tmp = t if tmp is None else tmp + t
    out = jnp.einsum("rqw,rpwc->rpqc", wx, tmp.astype(dt),
                     preferred_element_type=jnp.float32)
    return out.astype(dt)


def roi_pool(
    features: jnp.ndarray,
    rois: jnp.ndarray,
    output_size: int,
    spatial_scale: float,
) -> jnp.ndarray:
    """Classic quantized max ROIPooling (mx.symbol.ROIPooling semantics).

    Bin boundaries are computed by integer quantization (round of scaled
    coords, floor/ceil of bin edges); empty bins yield 0 (the CUDA kernel
    emits 0 for empty bins). Max over a rectangular bin separates into a
    row-max then a col-max, each a masked reduction over one spatial axis.
    """
    p = output_size
    h, w = features.shape[1], features.shape[2]
    fy = jnp.arange(h, dtype=jnp.float32)
    fx = jnp.arange(w, dtype=jnp.float32)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        # Reference quantizes roi coords with round().
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        bin_w = rw / p
        bin_h = rh / p
        i = jnp.arange(p, dtype=jnp.float32)
        ys_lo = jnp.floor(y1 + i * bin_h)  # (p,)
        ys_hi = jnp.ceil(y1 + (i + 1.0) * bin_h)
        xs_lo = jnp.floor(x1 + i * bin_w)
        xs_hi = jnp.ceil(x1 + (i + 1.0) * bin_w)
        row_in = (fy[None, :] >= ys_lo[:, None]) & (fy[None, :] < ys_hi[:, None])
        col_in = (fx[None, :] >= xs_lo[:, None]) & (fx[None, :] < xs_hi[:, None])
        feat = features[b]  # (H, W, C)
        neg = jnp.asarray(-jnp.inf, feat.dtype)
        # Row reduction: (p, H, 1, 1) mask over (H, W, C) -> (p, W, C).
        rowmax = jnp.where(row_in[:, :, None, None], feat[None], neg).max(axis=1)
        # Col reduction: (p, W, 1) mask over (p, W, C) -> (p, p, C).
        out = jnp.where(col_in[None, :, :, None], rowmax[:, None], neg).max(axis=2)
        return jnp.where(jnp.isfinite(out), out, 0.0).astype(feat.dtype)

    return jax.vmap(one_roi)(rois)


def roi_align_gather(
    features: jnp.ndarray,
    rois: jnp.ndarray,
    output_size: int,
    spatial_scale: float,
    sampling_ratio: int = 2,
    aligned: bool = False,
) -> jnp.ndarray:
    """Point-gather ROIAlign — the semantic oracle for ``roi_align``.

    Direct transcription of the CUDA kernel's per-sample-point bilinear
    gather. Kept for differential testing only; the matmul formulation above
    is the production path (gathers lower poorly on TPU).
    """
    p = output_size
    s = sampling_ratio
    offset = 0.5 if aligned else 0.0

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0) if not aligned else (x2 - x1)
        rh = jnp.maximum(y2 - y1, 1.0) if not aligned else (y2 - y1)
        grid = (jnp.arange(p * s, dtype=features.dtype) + 0.5) / s
        ys = y1 + grid * (rh / p)
        xs = x1 + grid * (rw / p)
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
        vals = _bilinear_gather(features[b], yy, xx)  # (p*s, p*s, C)
        c = vals.shape[-1]
        return vals.reshape(p, s, p, s, c).mean(axis=(1, 3))

    return jax.vmap(one_roi)(rois)


def _bilinear_gather(feat: jnp.ndarray, y: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Sample feat (H, W, C) at continuous points y, x (...,) -> (..., C).

    Out-of-bounds points clamp to the border (matching the CUDA kernels'
    behavior of clipping sample coords to the feature extent).
    """
    h, w = feat.shape[0], feat.shape[1]
    y = jnp.clip(y, 0.0, h - 1.0)
    x = jnp.clip(x, 0.0, w - 1.0)
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1 = jnp.minimum(y0 + 1.0, h - 1.0)
    x1 = jnp.minimum(x0 + 1.0, w - 1.0)
    ly = y - y0
    lx = x - x0
    hy = 1.0 - ly
    hx = 1.0 - lx
    y0i, x0i, y1i, x1i = (a.astype(jnp.int32) for a in (y0, x0, y1, x1))
    v00 = feat[y0i, x0i]
    v01 = feat[y0i, x1i]
    v10 = feat[y1i, x0i]
    v11 = feat[y1i, x1i]
    wdt = feat.dtype
    return (
        v00 * (hy * hx)[..., None].astype(wdt)
        + v01 * (hy * lx)[..., None].astype(wdt)
        + v10 * (ly * hx)[..., None].astype(wdt)
        + v11 * (ly * lx)[..., None].astype(wdt)
    )
