"""Distributed backend — the KVStore replacement.

Reference: MXNet KVStore (`local`/`device`/`dist_sync` — C++ ps-lite) plus
DataParallelExecutorGroup batch slicing (SURVEY.md §2 L0, §3 'KVStore / comm
backend'). Here: one `jax.sharding.Mesh`, batch sharded on the `data` axis,
parameters replicated, gradient allreduce inserted by XLA over ICI/DCN.
"""

from mx_rcnn_tpu.parallel.mesh import (
    batch_sharding,
    create_mesh,
    parse_mesh_shape,
    replicated,
    shard_batch,
)
from mx_rcnn_tpu.parallel.partition import (
    TP_RULES,
    shard_params,
    shard_train_state,
    tp_param_specs,
)
from mx_rcnn_tpu.parallel.pipeline import pipeline_apply

__all__ = [
    "create_mesh",
    "parse_mesh_shape",
    "batch_sharding",
    "replicated",
    "shard_batch",
    "TP_RULES",
    "tp_param_specs",
    "shard_params",
    "shard_train_state",
    "pipeline_apply",
]
