"""Multi-host (multi-process) wiring — the `dist_sync` KVStore analog.

Reference: MXNet KVStore's `dist_sync`/`dist_async` modes run a ps-lite
C++ parameter server over TCP and every worker pushes/pulls gradients
(SURVEY.md §6 'distributed communication backend'). The TPU equivalent has
no server at all: `jax.distributed.initialize` connects the processes,
every process sees the GLOBAL device set, and the same pjit train step
compiles to per-host programs whose gradient all-reduce rides ICI within a
host/slice and DCN across them (mesh data axis leading = cross-host).

Environment contract (the `--kvstore dist_sync` analog):
  MXRCNN_COORDINATOR   host:port of process 0 (jax coordinator)
  MXRCNN_NUM_PROCESSES world size
  MXRCNN_PROCESS_ID    this process's rank

All three set and NUM_PROCESSES > 1 → `maybe_initialize_distributed()`
connects; otherwise it is a no-op (single-process path unchanged). On TPU
pods where the cluster environment is auto-detectable, call
`jax.distributed.initialize()` directly before the entry point instead —
this helper intentionally only handles the explicit MXRCNN_* contract.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import numpy as np

from mx_rcnn_tpu.logger import logger


def maybe_initialize_distributed(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Connect this process to the jax distributed runtime if configured.

    MUST run before the first jax device/backend use. Returns True when a
    multi-process runtime was initialized.
    """
    coordinator = coordinator or os.environ.get("MXRCNN_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("MXRCNN_NUM_PROCESSES", "0") or 0)
    if process_id is None:
        pid_env = os.environ.get("MXRCNN_PROCESS_ID")
        process_id = int(pid_env) if pid_env is not None else None

    if coordinator and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        logger.info(
            "distributed: process %d/%d via %s (%d global devices)",
            jax.process_index(), jax.process_count(), coordinator,
            jax.device_count())
        return True
    return False


def process_index() -> int:
    """This host's coordination identity.

    Normally ``jax.process_index()``. Under the graftquorum CPU tests the
    MXRCNN_SIM_* pair overrides it: N separate single-process CPU runs
    each execute the FULL replicated computation (identical deterministic
    trajectories — no collectives cross the processes) while believing
    they are host i of N for everything COORDINATION-shaped: quorum
    membership, barrier arrival, who publishes checkpoints, the process
    stamp on obs events. Data sharding deliberately keeps using the raw
    jax calls (each simulated host must load the full global batch to
    stay bit-identical), so the override lives here and not in
    local_data_shards/make_global_batch.
    """
    sim = os.environ.get("MXRCNN_SIM_PROCESS_ID")
    if sim is not None:
        return int(sim)
    return jax.process_index()


def process_count() -> int:
    """World size for coordination (see process_index for the simulated-
    host override contract)."""
    sim = os.environ.get("MXRCNN_SIM_NUM_PROCESSES")
    if sim is not None:
        return int(sim)
    return jax.process_count()


def is_primary() -> bool:
    """True on the process that owns logging/checkpoint writes."""
    return process_index() == 0


def local_data_shards(mesh) -> int:
    """How many data-axis shards THIS process feeds.

    The loader produces `batch_images × local_data_shards` images per step;
    `shard_batch` assembles them into the global array. Mesh data axis is
    laid out process-major (create_mesh uses jax.devices() order), so the
    division is exact.
    """
    n_data = mesh.shape["data"]
    procs = jax.process_count()
    if n_data % procs:
        raise ValueError(
            f"data axis {n_data} not divisible by process count {procs}")
    return n_data // procs


def make_global_batch(batch: Dict[str, np.ndarray], mesh, sharding) -> Dict:
    """Per-process local batch → global jax.Arrays (multi-host shard_batch).

    Single-process: plain device_put. Multi-process: every process
    contributes its local leading-axis slice via
    `jax.make_array_from_process_local_data` — the global batch never
    materializes on any one host (the ps-lite analog would have shipped it
    through the server).
    """
    if jax.process_count() == 1:
        return {k: jax.device_put(v, sharding) for k, v in batch.items()}
    return {
        k: jax.make_array_from_process_local_data(sharding, np.asarray(v))
        for k, v in batch.items()
    }
