"""Device mesh construction + sharding helpers.

Replaces the reference's device plumbing: ``ctx = [mx.gpu(i) for i in
--gpus]`` + ``mx.kvstore.create(args.kvstore)`` (train_end2end.py) and the
batch slicing of ``DataParallelExecutorGroup``. The ``--tpu-mesh`` CLI flag
("8", "4x2", "4x4") maps to a Mesh with axes ``(data, model)``:

- ``data``: the DP axis — per-device batch shards, gradients reduced by XLA
  ``psum`` over ICI (the KVStore 'device' mode analog).
- ``model``: reserved for tensor/spatial sharding of the later large configs
  (the reference has no model parallelism — SURVEY.md §3.2 — so default 1).

Multi-host: `jax.distributed.initialize` + the same mesh over all processes
covers the reference's `dist_sync` ps-lite mode; the DCN axis is the leading
mesh dim so gradient collectives ride ICI within a slice.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def parse_mesh_shape(spec: str) -> Tuple[int, int]:
    """'8' → (8, 1); '4x2' → (4, 2) as (data, model)."""
    parts = [int(p) for p in str(spec).lower().split("x") if p]
    if len(parts) == 1:
        return parts[0], 1
    if len(parts) == 2:
        return parts[0], parts[1]
    raise ValueError(f"bad mesh spec {spec!r}; expected 'N' or 'NxM'")


def create_mesh(spec: str = "", devices=None) -> Mesh:
    """Create the (data, model) mesh. Empty spec → all available devices DP."""
    devices = devices if devices is not None else jax.devices()
    if not spec:
        d, m = len(devices), 1
    else:
        d, m = parse_mesh_shape(spec)
    if d * m > len(devices):
        raise ValueError(
            f"mesh {d}x{m} needs {d*m} devices, have {len(devices)}")
    arr = np.asarray(devices[: d * m]).reshape(d, m)
    return Mesh(arr, ("data", "model"))


def batch_sharding(mesh: Mesh, stacked: bool = False) -> NamedSharding:
    """Leading-axis (batch) sharding over the data axis; ``stacked`` for
    multi-step-dispatch batches whose leading axis is the step index
    (leaves (K, B, ...) — batch axis 1 shards, step axis replicates)."""
    return NamedSharding(mesh, P(None, "data") if stacked else P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch: dict, mesh: Mesh, stacked: bool = False) -> dict:
    """Place a host batch dict onto the mesh, sharded along the batch axis
    (axis 0, or axis 1 of a ``stacked`` multi-step batch).

    The analog of DataParallelExecutorGroup slicing a batch across contexts
    (reference: mxnet executor_group via work_load_list) — here one
    device_put with a NamedSharding; the batch's sharded dim must divide by
    the data-axis size. Under a multi-process runtime each process passes
    its LOCAL slice and the global array is assembled across hosts
    (parallel/distributed.py).
    """
    from mx_rcnn_tpu.parallel.distributed import make_global_batch

    return make_global_batch(batch, mesh, batch_sharding(mesh, stacked))
