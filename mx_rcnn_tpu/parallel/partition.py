"""Tensor-parallel parameter partitioning over the mesh ``model`` axis.

The reference has NO model parallelism (SURVEY.md §3.2 — KVStore data
parallelism is its only strategy), so this module is pure TPU-native
surface: Megatron-style weight sharding for the dense/transformer layers,
expressed as PartitionSpec rules and realized by GSPMD. The recipe is the
scaling-book one: assign shardings to the weights, place the arrays, and
let XLA insert the collectives — no hand-written all-reduces.

What gets sharded (the ``model`` axis):

- transformer MLPs (ViTDet ``mlp1``/``mlp2``, DETR ``ffn1``/``ffn2``) and
  the paired detection FC heads (``fc6``/``fc7`` in TwoFCHead/VGGHead):
  the classic column-parallel → row-parallel split — the up-projection's
  output dim and the down-projection's input dim are sharded, so the
  pointwise nonlinearity runs on shards and XLA places ONE all-reduce at
  the row-parallel output;
- attention projections (ViTDet fused ``qkv``, DETR ``q``/``k``/``v``,
  and both families' ``proj``): column-parallel in, row-parallel out.
  The head-split reshape between them may cost GSPMD a resharding —
  semantics are guaranteed either way; the head-aligned fast path for
  long sequences is the Ulysses/ring SP formulation
  (ops/ring_attention.py), which composes with this module on the same
  axis.

Everything unmatched (convs, norms, small output heads) stays replicated:
for a detector the conv trunk dominates FLOPs but its weights are tiny —
DP handles it; TP pays off exactly where weight matrices are large
(VGG's 25088x4096 fc6 is the classic case, and the transformer families).

Optimizer slots mirror the params tree inside the optax state, so each is
matched to its param by path suffix and placed on that param's sharding —
momentum/Adam slots co-locate with their shards, including restored
(resume) opt_states.
"""

from __future__ import annotations

from fnmatch import fnmatchcase
from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mx_rcnn_tpu.logger import logger

# (path glob, spec) — first match wins; paths are "/"-joined tree keys,
# e.g. "params/features/block0/attn/qkv/kernel".
TP_RULES: Tuple[Tuple[str, P], ...] = (
    # ViTDet encoder blocks (models/vit.py).
    ("*/attn/qkv/kernel", P(None, "model")),
    ("*/attn/qkv/bias", P("model")),
    ("*/attn/proj/kernel", P("model", None)),
    ("*/mlp1/kernel", P(None, "model")),
    ("*/mlp1/bias", P("model")),
    ("*/mlp2/kernel", P("model", None)),
    # DETR encoder/decoder (models/detr.py): separate q/k/v Dense modules
    # under self_attn/cross_attn, paired ffn1/ffn2.
    ("*_attn/q/kernel", P(None, "model")),
    ("*_attn/q/bias", P("model")),
    ("*_attn/k/kernel", P(None, "model")),
    ("*_attn/k/bias", P("model")),
    ("*_attn/v/kernel", P(None, "model")),
    ("*_attn/v/bias", P("model")),
    ("*_attn/proj/kernel", P("model", None)),
    ("*/ffn1/kernel", P(None, "model")),
    ("*/ffn1/bias", P("model")),
    ("*/ffn2/kernel", P("model", None)),
    # Paired FC detection heads: TwoFCHead (models/fpn.py) and VGGHead
    # (models/backbones.py fc6/fc7 — reference symbol_vgg.py's 4096-wide
    # pair, the one genuinely large dense matrix in the classic family).
    ("*/fc6/kernel", P(None, "model")),
    ("*/fc6/bias", P("model")),
    ("*/fc7/kernel", P("model", None)),
)


def flat_segment_specs(params, specs):
    """Map per-leaf PartitionSpecs onto flatcore buffer segments.

    The flat path (train/flatcore.py) concatenates leaves into one
    replicated buffer per dtype, so it is only sound when EVERY leaf is
    replicated — then each buffer takes ``P()`` and the DP gradient
    allreduce is ONE psum per buffer. Any sharded leaf (the TP/PP rules
    above) has no contiguous image inside a flat buffer: return None and
    the caller keeps the per-leaf tree path for the whole state (mixing
    per-segment layouts inside one buffer would force GSPMD to reshard
    every step — worse than the many-buffer floor it replaces).

    graftcast: the compute shadow (``FlatTrainState.compute``, one
    buffer per float dtype group under ``train.compute_dtype=bf16``)
    inherits its MASTER buffer's placement by construction — it is
    derived state keyed by the same dtype-group names, so the ``P()``
    verdict here covers it, and the future ZeRO-1 flat shards (ROADMAP)
    shard master and shadow along the same segment boundaries with the
    cast running shard-local.
    """
    import jax.numpy as jnp

    flat_specs = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    for spec in flat_specs:
        if isinstance(spec, P) and any(ax is not None for ax in spec):
            return None
    dtypes = {jnp.dtype(leaf.dtype).name
              for leaf in jax.tree_util.tree_leaves(params)}
    return {d: P() for d in sorted(dtypes)}


def elastic_mesh_spec(data: int, model: int, n_devices: int,
                      micro_batch: int, mode: str = "shrink") -> str:
    """Re-derive a mesh spec when the backend comes back with a different
    device count (graftheal shrink / elastic resume / elastic phase 2).

    The default contract is GLOBAL-BATCH INVARIANCE: the run's
    hyperparameters (batch, LR schedule, epoch order) describe the run,
    not the hardware, so a (data, model) mesh re-cut onto fewer devices
    keeps the model axis intact (a TP/PP-sharded weight cannot change
    its partition count mid-run without a resharding story) and shrinks
    the DATA axis to the largest size that still divides ``micro_batch``
    (the per-micro-step global image count) — each surviving device
    simply carries more batch rows, and the loss trajectory continues up
    to psum reassociation.

    ``mode`` is elastic phase 2 (``resilience.elastic_mode``):

    - ``"shrink"`` — the phase 1 behavior above; with ``n_devices`` at
      or above the original footprint the original shape is kept (extra
      devices idle; growth stays a scheduling decision).
    - ``"grow"`` — additionally GROW the data axis onto devices beyond
      the nominal footprint when the re-acquire returns more, to the
      largest micro-batch divisor that fits (still batch-invariant:
      each device carries FEWER rows).
    - ``"rescale"`` — grow, and when a shrink cannot hold the global
      batch the caller rescales it instead: the data axis takes ALL
      available slots (no divisor constraint) and the caller keeps
      rows-per-device constant, shrinking the global batch and rebasing
      the LR schedule in images-seen terms (rebase_schedule_count).
      This function only picks the axis size; the batch/schedule math
      lives in the trainer.
    """
    if mode not in ("shrink", "grow", "rescale"):
        raise ValueError(f"unknown elastic mode {mode!r}; expected "
                         "shrink | grow | rescale")
    if n_devices >= data * model:
        if mode == "shrink":
            grown = data
        else:
            # GROW: the largest micro-batch divisor the returned devices
            # can seat (>= the nominal data axis; falls back to nominal
            # when no larger divisor fits).
            avail = n_devices // model
            grown = next((k for k in range(avail, data, -1)
                          if micro_batch % k == 0), data)
        if grown != data:
            logger.warning(
                "elastic mesh: backend returned %d device(s) above the "
                "%dx%d footprint; growing data axis %d -> %d "
                "(global micro-batch %d invariant, fewer rows per device)",
                n_devices, data, model, data, grown, micro_batch)
        return f"{grown}x{model}"
    if n_devices < model:
        raise ValueError(
            f"backend came back with {n_devices} device(s), fewer than the "
            f"model axis ({model}) — a model-sharded run cannot shrink "
            "below one data shard; resume from checkpoint on a matching "
            "topology instead")
    avail = n_devices // model
    if mode == "rescale" and micro_batch % avail:
        # Too deep for a batch-invariant shrink: take every slot and let
        # the trainer rescale the global batch instead of idling devices.
        logger.warning(
            "elastic mesh: %dx%d does not fit %d device(s) and %d does "
            "not divide the micro-batch %d; rescale mode takes all %d "
            "data slots (rows-per-device constant, global batch scales)",
            data, model, n_devices, avail, micro_batch, avail)
        return f"{avail}x{model}"
    new_data = next(k for k in range(min(avail, data), 0, -1)
                    if micro_batch % k == 0)
    logger.warning(
        "elastic mesh: %dx%d does not fit %d device(s); re-sharding data "
        "axis %d -> %d (model axis kept, global micro-batch %d invariant)",
        data, model, n_devices, data, new_data, micro_batch)
    return f"{new_data}x{model}"


def _path_str(path) -> str:
    parts = []
    for entry in path:
        key = getattr(entry, "key", None)
        if key is None:
            key = getattr(entry, "idx", entry)
        parts.append(str(key))
    return "/".join(parts)


def tp_param_specs(params, rules: Sequence[Tuple[str, P]] = TP_RULES):
    """Params pytree → PartitionSpec pytree (unmatched leaves → P())."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = []
    for path, _ in flat:
        name = _path_str(path)
        spec = P()
        for pattern, rule_spec in rules:
            if fnmatchcase(name, pattern):
                spec = rule_spec
                break
        specs.append(spec)
    return jax.tree_util.tree_unflatten(treedef, specs)


def _validated_sharding(mesh: Mesh, spec: P, shape,
                        name: str = "?") -> NamedSharding:
    """Spec → NamedSharding; drop to replicated if a sharded dim is not
    divisible by its mesh-axis size (GSPMD would pad, but for the small
    test/head dims an even split either exists or the layer is too small
    for TP to matter — replicate and say so)."""
    for dim, axes in enumerate(spec):
        if axes is None:
            continue
        size = mesh.shape[axes] if isinstance(axes, str) else 1
        if dim >= len(shape) or shape[dim] % size != 0:
            logger.warning(
                "TP rule %s for param %s (shape %s) dropped: dim %d not "
                "divisible by mesh axis %r (size %d) — replicating",
                spec, name, tuple(shape), dim, axes, size)
            return NamedSharding(mesh, P())
    return NamedSharding(mesh, spec)


def shard_params(params, mesh: Mesh, specs=None):
    """Place a (host or replicated) params tree per the spec tree."""
    specs = specs if specs is not None else tp_param_specs(params)
    flat_p, treedef = jax.tree_util.tree_flatten_with_path(params)
    flat_s, spec_def = jax.tree_util.tree_flatten(
        specs, is_leaf=lambda x: isinstance(x, P))
    if spec_def != treedef:  # stale/mismatched spec tree must not
        raise ValueError(    # silently misalign shardings
            f"spec tree does not match params tree: {spec_def} vs {treedef}")
    shardings = jax.tree_util.tree_unflatten(treedef, [
        _validated_sharding(mesh, spec, leaf.shape, _path_str(path))
        for (path, leaf), spec in zip(flat_p, flat_s)])
    return jax.device_put(params, shardings), shardings


def shard_train_state(state, mesh: Mesh, specs=None):
    """Place a TrainState for tensor-parallel training.

    Params go to their rule shardings; step is replicated; opt_state leaves
    (fresh OR restored-from-checkpoint) are suffix-matched to their params
    and placed on the same shardings, so Adam/momentum slots always
    co-locate with their param shards.
    """
    params, shardings = shard_params(state.params, mesh, specs)
    n_sharded = sum(
        1 for s in jax.tree.leaves(
            shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
        if not s.is_fully_replicated)
    logger.info("tensor parallel: %d param leaves sharded over model axis "
                "(size %d)", n_sharded, mesh.shape["model"])
    # Optimizer slots (momentum/Adam moments, fresh OR restored) mirror the
    # params tree inside the optax state — e.g. ...mu/params/head/fc6/kernel.
    # Match each opt leaf to its param by path suffix and co-locate it on
    # that param's sharding; everything else (schedule counts, scalars) is
    # replicated. (Running tx.init over sharded params does NOT work:
    # zeros_like has no data dependence on the params, so GSPMD has nothing
    # to propagate and XLA picks arbitrary single-device placements.)
    p_flat = jax.tree_util.tree_flatten_with_path(params)[0]
    s_flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    param_info = [
        (_path_str(pp), leaf.shape, sh)
        for (pp, leaf), (_, sh) in zip(p_flat, s_flat)
        if not sh.is_fully_replicated]
    repl = NamedSharding(mesh, P())

    def _opt_sharding(path, leaf):
        name = _path_str(path)
        for pname, pshape, sh in param_info:
            if ((name == pname or name.endswith("/" + pname))
                    and getattr(leaf, "shape", None) == pshape):
                return sh
        return repl

    flat, treedef = jax.tree_util.tree_flatten_with_path(state.opt_state)
    opt_shardings = jax.tree_util.tree_unflatten(
        treedef, [_opt_sharding(p, leaf) for p, leaf in flat])
    opt_state = jax.device_put(state.opt_state, opt_shardings)
    step = jax.device_put(state.step, NamedSharding(mesh, P()))
    return state.replace(step=step, params=params, opt_state=opt_state)
