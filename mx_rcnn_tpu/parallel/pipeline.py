"""Pipeline parallelism — GPipe-style microbatched stage pipeline.

The reference has no model parallelism at all (SURVEY.md §3.2); like
parallel/partition.py (TP) this is TPU-native surface, built the idiomatic
JAX way: the mesh axis IS the pipeline, stages talk over ICI with
``lax.ppermute`` ring hops inside one ``shard_map``-ped program, and the
whole schedule is a ``lax.scan`` — fully traceable, differentiable (the
ppermute/where transpose is its own reverse schedule), and jit-compiled
once.

Schedule (classic GPipe, S stages, M microbatches, T = M + S - 1 ticks)::

    tick t: stage 0 injects microbatch t (t < M); every stage applies its
            block to the activation it holds; activations hop one stage
            down the ring; stage S-1 emits microbatch t-(S-1) (t >= S-1).

Stages run on *every* tick (devices compute on zero/stale buffers during
fill/drain) — the standard bubble; efficiency is M / (M + S - 1).

``stage_params`` carries a leading stage axis (leaf shape (S, ...)), the
layout produced by ``flax.linen.scan`` over a homogeneous stage module
(models/vit.py builds exactly that), so the same params run EITHER
sequentially (nn.scan) or pipelined (here) with identical numerics.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover - version-dependent import
    from jax.experimental.shard_map import shard_map


def _mark_varying(x, axes):
    """shard_map manual-axes type tracking (see ops/ring_attention.py);
    identity on jax lines without varying types (< 0.5)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)
    return x


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x: jnp.ndarray,
    mesh: Mesh,
    axis: str = "model",
    microbatches: Optional[int] = None,
    batch_axis: Optional[str] = "data",
):
    """Run ``y = stage_{S-1}(...stage_1(stage_0(x)))`` as a pipeline.

    Args:
      stage_fn: (params_one_stage, activation) -> activation, identical
        structure for every stage (the activation shape must be preserved
        — stages are ring-connected).
      stage_params: pytree with a leading stage axis of size S = mesh
        axis size on every leaf.
      x: (B, ...) batch; split into ``microbatches`` chunks along axis 0
        (B must divide). Default: one microbatch per stage (the smallest
        sensible choice; more microbatches shrink the bubble).
      mesh/axis: the mesh axis acting as the pipeline.
      batch_axis: mesh axis the batch dim is data-sharded over (composes
        DP x PP: each data shard keeps its slice while activations ring
        over `axis`). Ignored if absent from the mesh.

    Returns:
      (B, ...) output, replicated over the pipeline axis (still sharded
      over `batch_axis`).
    """
    s = mesh.shape[axis]
    bad = [tuple(leaf.shape) for leaf in jax.tree.leaves(stage_params)
           if leaf.shape[:1] != (s,)]
    if bad:
        # A larger multiple would pass shard_map's divisibility check and
        # silently compose only every (S/s)-th stage — hard error instead.
        raise ValueError(
            f"stage_params leading axis must equal the {s}-way '{axis}' "
            f"mesh axis; got leaf shapes {bad[:3]}")
    m = microbatches or s
    if x.shape[0] % m:
        raise ValueError(
            f"batch {x.shape[0]} must divide into {m} microbatches")
    xs = x.reshape(m, x.shape[0] // m, *x.shape[1:])
    t_total = m + s - 1
    shift_down = [(i, (i + 1) % s) for i in range(s)]
    b_ax = batch_axis if (batch_axis and batch_axis != axis
                          and batch_axis in mesh.axis_names) else None
    if b_ax and xs.shape[1] % mesh.shape[b_ax]:
        raise ValueError(
            f"microbatch size {xs.shape[1]} (batch {x.shape[0]} / "
            f"{m} microbatches) must divide over the {mesh.shape[b_ax]}-way "
            f"'{b_ax}' data axis")

    def pipelined(params, xs):
        # Inside shard_map: params leaves arrive as (1, ...) slices of the
        # stage axis — drop it to get MY stage's params.
        params = jax.tree.map(lambda p: p[0], params)
        idx = lax.axis_index(axis)
        zero = _mark_varying(jnp.zeros_like(xs[0]), (axis,))

        def tick(buf, t):
            inject = _mark_varying(xs[jnp.clip(t, 0, m - 1)], (axis,))
            buf = jnp.where(idx == 0, inject, buf)
            y = stage_fn(params, buf)
            recv = lax.ppermute(y, axis, shift_down)
            return recv, y

        _, ys = lax.scan(tick, zero, jnp.arange(t_total))
        # ys[t] on the LAST stage is microbatch t-(s-1) for t >= s-1.
        outs = lax.dynamic_slice_in_dim(ys, s - 1, m, axis=0)
        # Replicate the last stage's outputs to every device in the ring.
        return lax.psum(jnp.where(idx == s - 1, outs, 0.0), axis)

    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    xs_spec = P(None, b_ax) if b_ax else P()
    out = shard_map(
        pipelined, mesh=mesh,
        in_specs=(param_specs, xs_spec), out_specs=xs_spec,
    )(stage_params, xs)
    return out.reshape(x.shape[0], *out.shape[2:])
