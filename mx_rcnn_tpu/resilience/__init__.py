"""graftguard — fault tolerance for a flaky accelerator relay.

Round 5 (TPU_OUTAGE_r5.log / VERDICT.md) established the failure taxonomy
this package answers: the backend vanishes for hours (UNAVAILABLE from the
axon relay), the scheduler preempts multi-hour runs mid-epoch, and a single
hung compile can eat an entire bench timeout (BENCH_r05 rc=124). graftscope
(mx_rcnn_tpu/obs) made those failures *visible*; graftguard makes them
*survivable*:

- ``backend``  — classified backend acquisition: transient errors
  (UNAVAILABLE — the outage signature) retry with exponential backoff +
  jitter under a deadline; permanent errors fail fast. Emits
  ``backend_retry`` / ``backend_up`` graftscope events.
- ``preempt``  — SIGTERM/SIGINT handlers that request a checkpoint at the
  next step boundary and exit with ``RESUMABLE_RC`` so a supervisor knows
  to restart with ``--resume auto``.
- ``isolate``  — run a callable in a child process under a deadline (the
  bench's per-config jail: a hung compile forfeits one row, not the sweep).
- ``heal``     — graftheal: a step-time backend loss (mid-run, the part
  graftguard's startup acquisition could not reach) is recovered
  IN-PROCESS — emergency capture of the last known-good host state,
  teardown + re-acquisition under the same deadline, elastic re-shard
  when the backend returns with fewer devices. No crash, no operator.
- ``chaos``    — deterministic fault injection (raise UNAVAILABLE on the
  first N probes or mid-run at step K, SIGTERM at step K, hang one bench
  config, SIGKILL at a named site, shrink the re-acquired device list,
  kill one host of a simulated fleet, skip a quorum barrier) so every
  guarantee above is exercised by tier-1 CPU tests instead of by the
  next real outage.
- ``quorum``   — graftquorum: multi-host coordination (deadline-bounded
  barriers, propose/agree, generation-numbered heal rounds with
  exclusion) over jax.distributed's KV client or a filesystem store, so
  preemption commits ONE consistent fleet-wide save and a backend loss
  heals in lockstep across hosts instead of deadlocking the survivors.

Config: the ``resilience`` section of config.py; runbook: OUTAGES.md.
"""

from mx_rcnn_tpu.resilience.backend import (
    BackendUnavailableError,
    acquire_backend,
    classify_backend_error,
)
from mx_rcnn_tpu.resilience.heal import (
    HealCarry,
    Healer,
    host_tree_copy,
)
from mx_rcnn_tpu.resilience.preempt import (
    RESUMABLE_RC,
    PreemptionExit,
    PreemptionGuard,
)
from mx_rcnn_tpu.resilience.quorum import (
    CoordinatedStop,
    FileKVStore,
    Quorum,
    QuorumError,
    QuorumExcludedError,
    QuorumOutcome,
)

__all__ = [
    "BackendUnavailableError",
    "acquire_backend",
    "classify_backend_error",
    "HealCarry",
    "Healer",
    "host_tree_copy",
    "RESUMABLE_RC",
    "PreemptionExit",
    "PreemptionGuard",
    "CoordinatedStop",
    "FileKVStore",
    "Quorum",
    "QuorumError",
    "QuorumExcludedError",
    "QuorumOutcome",
]
