"""Classified backend acquisition — the answer to TPU_OUTAGE_r5.log.

The round-5 outage was survived by a hand-rolled watcher: 25+ blind
retries at a fixed 9-minute cadence, no backoff, no deadline, no error
classification, and the only artifact a scratch log. This module is the
structural replacement: one call that classifies backend initialization
failures into transient vs permanent, retries transients with exponential
backoff + jitter under a configurable deadline, and emits
``backend_retry`` / ``backend_up`` graftscope events so the next outage
leaves a machine-foldable record (``obs.report`` counts the retries and
keeps the last error).

Classification is by gRPC status name in the message — the relay's
signature failure is ``UNAVAILABLE: TPU backend setup/compile error``
(both as ``jax.errors.JaxRuntimeError`` and as the ``RuntimeError`` that
``Unable to initialize backend`` wraps it in; both are RuntimeError
subclasses). Anything not carrying a transient marker fails fast:
retrying an INVALID_ARGUMENT for eleven hours is how a misconfigured run
burns a deadline.

Wired through train (tools/train.py::fit_detector), eval (test.py) and
bench (bench.py) behind ``resilience.backend_acquire``; knobs live in the
``resilience`` config section. Fault injection: chaos.py's
``backend_unavailable`` / ``backend_permanent``.
"""

from __future__ import annotations

import os
import random
import time
from typing import Callable, Optional

from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.resilience import chaos

#: gRPC status names that mark a failure as transient (retry): the relay
#: outage signature plus the codes the relay surfaces while flapping.
TRANSIENT_MARKERS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED")


class BackendUnavailableError(RuntimeError):
    """The backend stayed transiently unavailable past the deadline."""


def classify_backend_error(exc: BaseException) -> str:
    """'transient' (retry) or 'permanent' (fail fast) for a backend
    initialization error, by gRPC status name in the message."""
    msg = str(exc)
    return ("transient" if any(m in msg for m in TRANSIENT_MARKERS)
            else "permanent")


def _default_probe():
    """One acquisition attempt: the chaos hook first (so injected outages
    work even on an already-initialized backend), then the real device
    query — the call that raised throughout the round-5 outage."""
    chaos.from_env().maybe_fail_backend()
    import jax

    return jax.devices()


def _check_platform(devices, want: str):
    """jax can SILENTLY fall back to CPU when the relay is down — the
    probe then 'succeeds' on attempt 1 and a multi-hour 'TPU' run
    proceeds at CPU speed. With ``resilience.backend_platform`` set, a
    device list without the expected platform is a transient failure
    like any other (classified UNAVAILABLE, retried under the
    deadline)."""
    if any(getattr(d, "platform", "").lower() == want for d in devices):
        return
    got = sorted({getattr(d, "platform", "?") for d in devices})
    raise RuntimeError(
        f"UNAVAILABLE: backend came up without a {want!r} device "
        f"(got {got}) — jax silently fell back; treating as outage")


def _clear_backend_cache():
    """Drop jax's cached backend set so the next probe re-initializes —
    after a silent CPU fallback the wrong backend is CACHED and no
    amount of retrying would ever observe the recovered relay without
    this. Two callers, both of which have made live arrays expendable
    first: the platform-mismatch retry path here (before the first real
    device touch), and graftheal's teardown (resilience/heal.py — after
    the emergency capture copied everything worth keeping to host-owned
    numpy). Anywhere else, clearing would invalidate live arrays."""
    try:
        import jax.extend.backend

        jax.extend.backend.clear_backends()
    except Exception:  # noqa: BLE001  # graftlint: disable=broad-except — best-effort across jax versions; the retry proceeds either way
        pass


def acquire_backend(rcfg, elog=None, probe: Optional[Callable] = None,
                    sleep: Callable[[float], None] = time.sleep,
                    clock: Callable[[], float] = time.monotonic,
                    rng: Optional[random.Random] = None):
    """Acquire the accelerator backend, riding out transient failures.

    Returns the device list. ``rcfg`` is the ``resilience`` config section
    (backend_deadline_s / backend_backoff_base_s / backend_backoff_max_s /
    backend_backoff_jitter). ``elog`` is an optional graftscope EventLog.
    ``probe``/``sleep``/``clock``/``rng`` are injectable for tests — the
    defaults are the real thing.

    Raises the original error immediately when it classifies permanent,
    and BackendUnavailableError when transient failures outlast
    ``backend_deadline_s``.
    """
    probe = probe or _default_probe
    # Jitter decorrelates a fleet of hosts re-probing a recovering relay;
    # seeding by pid keeps one process's schedule reproducible.
    rng = rng or random.Random(os.getpid())
    start = clock()
    deadline = start + max(0.0, rcfg.backend_deadline_s)
    delay = max(0.001, rcfg.backend_backoff_base_s)
    attempt = 0
    while True:
        attempt += 1
        try:
            devices = probe()
            want = getattr(rcfg, "backend_platform", "")
            if want:
                try:
                    _check_platform(devices, want.lower())
                except RuntimeError:
                    _clear_backend_cache()  # else retries see the cached
                    raise                   # fallback backend forever
        except RuntimeError as exc:
            waited = clock() - start
            if classify_backend_error(exc) == "permanent":
                logger.error(
                    "backend acquisition failed PERMANENTLY on attempt %d "
                    "(%s) — not retrying; fix the config/driver, the "
                    "deadline is for outages", attempt, exc)
                raise
            remaining = deadline - clock()
            if remaining <= 0:
                raise BackendUnavailableError(
                    f"backend still transiently unavailable after "
                    f"{attempt} attempts / {waited:.0f}s (deadline "
                    f"{rcfg.backend_deadline_s:.0f}s); last error: {exc}"
                ) from exc
            pause = min(delay, rcfg.backend_backoff_max_s)
            pause *= 1.0 + max(0.0, rcfg.backend_backoff_jitter) * rng.random()
            pause = min(pause, remaining)
            if elog is not None and elog.enabled:
                elog.emit("backend_retry", attempt=attempt,
                          sleep_s=round(pause, 3),
                          waited_s=round(waited, 3), error=str(exc)[:500])
            logger.warning(
                "backend unavailable (attempt %d, waited %.0fs): %s — "
                "retrying in %.1fs", attempt, waited, exc, pause)
            sleep(pause)
            delay = min(delay * 2.0, rcfg.backend_backoff_max_s)
        else:
            if elog is not None and elog.enabled:
                elog.emit("backend_up", attempts=attempt,
                          waited_s=round(clock() - start, 3),
                          device_count=len(devices))
            if attempt > 1:
                logger.info("backend up after %d attempts (%.0fs)",
                            attempt, clock() - start)
            return devices
