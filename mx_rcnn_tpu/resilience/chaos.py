"""graftguard fault injection — deterministic, env-carried faults.

Every resilience guarantee in this package is gated by a tier-1 CPU test,
which means the faults themselves must be injectable on demand: raise the
round-5 UNAVAILABLE signature on the first N backend probes, deliver
SIGTERM once the optimizer step count reaches K, hang one named bench
config, or SIGKILL the process at a named crash-window site. The spec
travels in the ``MX_RCNN_CHAOS`` environment variable so subprocess tests
(and operators reproducing an incident) can inject without code changes::

    MX_RCNN_CHAOS="backend_unavailable=3"          # 3 probes fail, then up
    MX_RCNN_CHAOS="sigterm_at_step=5"              # preempt mid-training
    MX_RCNN_CHAOS="hang_bench=c4_r101 hang_s=60"   # hang one sweep config
    MX_RCNN_CHAOS="die_at=checkpoint_finalize"     # SIGKILL mid-save

Pairs are space- or comma-separated ``key=value``; unknown keys raise (a
typo'd injection silently doing nothing would un-test the gate it was
written for). With the variable unset every hook is a no-op costing one
attribute check. stdlib-only — importable without jax.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time
from dataclasses import dataclass

ENV_VAR = "MX_RCNN_CHAOS"

#: Per-process injection state (e.g. how many backend probes have already
#: been failed) — module-level so repeated ``from_env()`` parses share it.
_counters: dict = {}


def reset():
    """Clear injection state (tests re-arming a spec within one process)."""
    _counters.clear()


@dataclass(frozen=True)
class ChaosSpec:
    """One parsed injection spec. All-defaults == no injection."""

    #: Fail the first N backend probes with the r5 UNAVAILABLE signature.
    backend_unavailable: int = 0
    #: Fail every backend probe with a PERMANENT (non-retryable) error.
    backend_permanent: bool = False
    #: Deliver SIGTERM (once) when the optimizer step count reaches K.
    sigterm_at_step: int = 0
    #: Hang for ``hang_s`` inside the isolated bench child whose config
    #: name equals ``hang_bench`` (resilience/isolate.py).
    hang_bench: str = ""
    hang_s: float = 30.0
    #: SIGKILL the process at a named site ("checkpoint_finalize" /
    #: "checkpoint_swap" — the save's crash windows, train/checkpoint.py).
    die_at: str = ""

    @property
    def active(self) -> bool:
        return self != ChaosSpec()

    # -- injection hooks (each a no-op unless its field is armed) ----------

    def maybe_fail_backend(self):
        """Raise the injected backend failure, if armed. Called by the
        default acquisition probe BEFORE touching jax (backend.py)."""
        if self.backend_permanent:
            raise RuntimeError(
                "INVALID_ARGUMENT: injected permanent backend failure "
                "(chaos)")
        n = self.backend_unavailable
        if n:
            done = _counters.get("backend", 0)
            if done < n:
                _counters["backend"] = done + 1
                raise RuntimeError(
                    "UNAVAILABLE: TPU backend setup/compile error "
                    f"(Unavailable). [injected outage {done + 1}/{n}, chaos]")

    def maybe_sigterm(self, step: int):
        """Deliver SIGTERM to this process once ``step`` reaches the armed
        threshold (tools/train.py calls this after every dispatch)."""
        if (self.sigterm_at_step and step >= self.sigterm_at_step
                and not _counters.get("sigterm")):
            _counters["sigterm"] = 1
            os.kill(os.getpid(), signal.SIGTERM)

    def maybe_hang(self, label: str):
        """Sleep ``hang_s`` when ``label`` matches the armed bench config
        — the BENCH_r05 hung-compile stand-in (resilience/isolate.py)."""
        if self.hang_bench and label == self.hang_bench:
            time.sleep(self.hang_s)

    def maybe_die(self, site: str):
        """SIGKILL this process at a named site — no atexit, no finally:
        the honest crash-window probe (train/checkpoint.py)."""
        if self.die_at and site == self.die_at:
            os.kill(os.getpid(), signal.SIGKILL)


_FIELDS = {f.name: f for f in dataclasses.fields(ChaosSpec)}


def parse(text: str) -> ChaosSpec:
    """Parse a spec string (see module docstring). Raises on unknown keys
    and unparseable values — a silently-ignored injection is worse than a
    loud one."""
    kw: dict = {}
    for pair in text.replace(",", " ").split():
        key, sep, raw = pair.partition("=")
        if not sep or key not in _FIELDS:
            raise ValueError(
                f"bad {ENV_VAR} entry {pair!r}; known keys: "
                f"{sorted(_FIELDS)}")
        ftype = _FIELDS[key].type
        if ftype in ("int", int):
            kw[key] = int(raw)
        elif ftype in ("float", float):
            kw[key] = float(raw)
        elif ftype in ("bool", bool):
            v = raw.strip().lower()
            if v in ("1", "true", "yes", "on"):
                kw[key] = True
            elif v in ("0", "false", "no", "off"):
                kw[key] = False
            else:
                raise ValueError(
                    f"bad {ENV_VAR} boolean {raw!r} for {key}")
        else:
            kw[key] = raw
    return ChaosSpec(**kw)


def from_env(environ=os.environ) -> ChaosSpec:
    """The armed spec for this process (inactive when the var is unset)."""
    text = environ.get(ENV_VAR, "")
    return parse(text) if text else ChaosSpec()
