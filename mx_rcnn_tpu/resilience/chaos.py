"""graftguard fault injection — deterministic, env-carried faults.

Every resilience guarantee in this package is gated by a tier-1 CPU test,
which means the faults themselves must be injectable on demand: raise the
round-5 UNAVAILABLE signature on the first N backend probes, deliver
SIGTERM once the optimizer step count reaches K, hang one named bench
config, or SIGKILL the process at a named crash-window site. The spec
travels in the ``MX_RCNN_CHAOS`` environment variable so subprocess tests
(and operators reproducing an incident) can inject without code changes::

    MX_RCNN_CHAOS="backend_unavailable=3"          # 3 probes fail, then up
    MX_RCNN_CHAOS="sigterm_at_step=5"              # preempt mid-training
    MX_RCNN_CHAOS="hang_bench=c4_r101 hang_s=60"   # hang one sweep config
    MX_RCNN_CHAOS="die_at=checkpoint_finalize"     # SIGKILL mid-save
    MX_RCNN_CHAOS="device_lost_at_step=4"          # backend dies mid-run
    MX_RCNN_CHAOS="device_lost_at_step=4 shrink_on_reacquire=4"  # ...and
                                                   # returns with 4 devices
    MX_RCNN_CHAOS="nan_at_step=5"                  # poison step 5's grads
                                                   # in-graph (graftpulse)
    MX_RCNN_CHAOS="slow_step_at=1:2:250"           # host 1 drags a 250 ms
                                                   # tail from step 2 on
                                                   # (grafttower straggler)
    MX_RCNN_CHAOS="data_corrupt_at=0:3"            # record 3 is rotten in
                                                   # epoch 0 (graftfeed
                                                   # quarantine)
    MX_RCNN_CHAOS="data_io_error_at=0:3:2"         # record 3 flakes twice,
                                                   # then reads fine
    MX_RCNN_CHAOS="data_hang_at=0:3 hang_s=60"     # record 3's read hangs
    MX_RCNN_CHAOS="data_worker_die_at=1"           # prefetch worker 1 dies

Pairs are space- or comma-separated ``key=value``; unknown keys raise (a
typo'd injection silently doing nothing would un-test the gate it was
written for). With the variable unset every hook is a no-op costing one
attribute check. stdlib-only — importable without jax.

Named injection points funnel through ``site(name, ...)`` / the
pre-parsed ``ChaosSpec.fire(name, ...)``: every site name is registered
in ``SITES`` and validated both at runtime (an unregistered name raises)
and at lint time (the ``chaos-site-name`` graftlint rule) — a typo'd
site string silently never firing is how a "tested" guarantee goes
untested.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time
from dataclasses import dataclass

ENV_VAR = "MX_RCNN_CHAOS"

#: The registered injection sites — the ONLY names ``site()``/``fire()``
#: accept, and the set the ``chaos-site-name`` lint rule resolves call
#: sites against (it reads this assignment from the AST; keep it a plain
#: tuple/set literal of string literals).
SITES = frozenset({
    "checkpoint_finalize",   # after the full checkpoint write, before the
                             # publishing rename (train/checkpoint.py)
    "checkpoint_swap",       # previous checkpoint set aside, new one not
                             # yet published — the narrowest crash window
    "train_dispatch",        # just before a train-step dispatch: the
                             # device_lost_at_step loss fires here
    "backend_reacquire",     # heal re-acquisition: shrink_on_reacquire
                             # truncates the recovered device list here
    "grad_inject",           # train-step build: nan_at_step's IN-GRAPH
                             # gradient poisoning is traced in here
                             # (train/step.py; fires once, at build time)
    "quorum_barrier",        # graftquorum barrier arrival: the
                             # barrier_timeout_at injection makes THIS
                             # host skip arriving (a hang past the
                             # deadline), driving the exclusion path
    "data_record_load",      # one roidb record load inside a prefetch
                             # worker: data_corrupt_at / data_io_error_at
                             # / data_hang_at fire here (data/feedguard.py)
    "data_worker_loop",      # top of a prefetch worker's claim loop:
                             # data_worker_die_at kills the thread here
                             # (data/loader.py worker supervision)
})

#: Per-process injection state (e.g. how many backend probes have already
#: been failed) — module-level so repeated ``from_env()`` parses share it.
_counters: dict = {}


def reset():
    """Clear injection state (tests re-arming a spec within one process)."""
    _counters.clear()


def _host_index(environ=os.environ) -> int:
    """This process's host index for per-host injections — the simulated
    identity under test (MXRCNN_SIM_PROCESS_ID, parallel/distributed.py)
    or the real distributed rank, 0 otherwise. Env-read keeps this
    module stdlib-only (no jax import)."""
    for var in ("MXRCNN_SIM_PROCESS_ID", "MXRCNN_PROCESS_ID"):
        value = environ.get(var)
        if value is not None:
            return int(value)
    return 0


@dataclass(frozen=True)
class ChaosSpec:
    """One parsed injection spec. All-defaults == no injection."""

    #: Fail the first N backend probes with the r5 UNAVAILABLE signature.
    backend_unavailable: int = 0
    #: Fail every backend probe with a PERMANENT (non-retryable) error.
    backend_permanent: bool = False
    #: Deliver SIGTERM (once) when the optimizer step count reaches K.
    sigterm_at_step: int = 0
    #: Hang for ``hang_s`` inside the isolated bench child whose config
    #: name equals ``hang_bench`` (resilience/isolate.py).
    hang_bench: str = ""
    hang_s: float = 30.0
    #: SIGKILL the process at a named site — any member of ``SITES``
    #: (the save's crash windows "checkpoint_finalize"/"checkpoint_swap",
    #: the pre-dispatch "train_dispatch", the heal "backend_reacquire").
    die_at: str = ""
    #: Raise the step-time device-loss signature (transient UNAVAILABLE)
    #: at the "train_dispatch" site, before the dispatch that would
    #: complete optimizer step K — the graftheal trigger.
    device_lost_at_step: int = 0
    #: How many times the device loss fires (consecutive re-dispatches
    #: keep failing until this count is spent — the double-loss-inside-
    #: one-heal-window scenario is device_lost_count=2).
    device_lost_count: int = 1
    #: On heal re-acquisition ("backend_reacquire" site) hand back only
    #: the first N devices — the backend "returns smaller" (spot reclaim
    #: / partial slice), forcing the elastic re-shard path.
    shrink_on_reacquire: int = 0
    #: Poison the gradients of optimizer step K with NaN, IN-GRAPH (the
    #: bf16-overflow stand-in the graftpulse tripwire must catch). The
    #: injection is baked into the traced step at build time
    #: ("grad_inject" site, train/step.py + poison_grads below) and
    #: fires every time the traced step counter reaches K while armed —
    #: disarm (unset the env var) before a --resume auto continuation.
    nan_at_step: int = 0
    #: Per-host death: ``H:K`` SIGKILLs the process whose host index is
    #: H (simulated-host identity, parallel/distributed.py) once the
    #: optimizer step count reaches K — the spot-reclaim-takes-a-whole-
    #: host scenario the quorum exclusion path must survive. Fires at
    #: the "train_dispatch" site; every other host parses the same spec
    #: and no-ops.
    host_die_at_step: str = ""
    #: Deterministic straggler: ``H:K:ms`` sleeps ``ms`` milliseconds at
    #: the "train_dispatch" site on the host whose index is H
    #: (simulated-host identity, parallel/distributed.py), at EVERY
    #: optimizer step >= K — a persistent per-dispatch tail, so the
    #: grafttower fleet fold sees one host consistently late and must
    #: rank it straggler / attribute the barrier wait to it. Every other
    #: host parses the same spec and no-ops.
    slow_step_at: str = ""
    #: Make THIS host (optionally scoped ``H:site``) skip arriving at
    #: the named barrier site — the others see a partial arrival set at
    #: the deadline, which is the deterministic way to drive the
    #: quorum exclusion / min-fraction paths. The only barrier site
    #: today is "quorum_barrier".
    barrier_timeout_at: str = ""
    #: Permanently corrupt one record: ``E:I`` makes every load of roidb
    #: record index I during epoch E raise the bad-JPEG signature — the
    #: graftfeed quarantine trigger ("data_record_load" site,
    #: data/feedguard.py). Keyed by record identity, not stream
    #: position, so a --resume auto replay of the epoch prefix observes
    #: the same fault (or finds the record already quarantined).
    data_corrupt_at: str = ""
    #: Transient IO flake on one record: ``E:I:N`` fails the first N
    #: load attempts of record I during epoch E with an EIO signature,
    #: then lets the load through — the graftfeed retry path must ride
    #: it out under data.record_deadline_s.
    data_io_error_at: str = ""
    #: Hang one record's load: ``E:I`` makes the load of record I during
    #: epoch E sleep ``hang_s`` (cancel-aware) inside its prefetch
    #: worker — the stuck-storage stand-in that must surface as
    #: DataStallError within data.wait_deadline_s, not a silent hang.
    data_hang_at: str = ""
    #: Kill prefetch worker thread index K (once, "data_worker_loop"
    #: site): the thread dies abruptly mid-claim — no error result, no
    #: slot release — and graftfeed's supervision must resurrect it at
    #: its queue position. -1 = disarmed (0 is a real worker index).
    data_worker_die_at: int = -1

    @property
    def active(self) -> bool:
        return self != ChaosSpec()

    # -- injection hooks (each a no-op unless its field is armed) ----------

    def maybe_fail_backend(self):
        """Raise the injected backend failure, if armed. Called by the
        default acquisition probe BEFORE touching jax (backend.py)."""
        if self.backend_permanent:
            raise RuntimeError(
                "INVALID_ARGUMENT: injected permanent backend failure "
                "(chaos)")
        n = self.backend_unavailable
        if n:
            done = _counters.get("backend", 0)
            if done < n:
                _counters["backend"] = done + 1
                raise RuntimeError(
                    "UNAVAILABLE: TPU backend setup/compile error "
                    f"(Unavailable). [injected outage {done + 1}/{n}, chaos]")

    def maybe_sigterm(self, step: int):
        """Deliver SIGTERM to this process once ``step`` reaches the armed
        threshold (tools/train.py calls this after every dispatch)."""
        if (self.sigterm_at_step and step >= self.sigterm_at_step
                and not _counters.get("sigterm")):
            _counters["sigterm"] = 1
            os.kill(os.getpid(), signal.SIGTERM)

    def maybe_hang(self, label: str):
        """Sleep ``hang_s`` when ``label`` matches the armed bench config
        — the BENCH_r05 hung-compile stand-in (resilience/isolate.py)."""
        if self.hang_bench and label == self.hang_bench:
            time.sleep(self.hang_s)

    def maybe_die(self, site: str):
        """SIGKILL this process at a named site — no atexit, no finally:
        the honest crash-window probe (train/checkpoint.py)."""
        if self.die_at and site == self.die_at:
            os.kill(os.getpid(), signal.SIGKILL)

    def maybe_device_loss(self, step: int):
        """Raise the transient device-loss signature when the dispatch
        about to complete optimizer step ``step`` reaches the armed
        threshold — fires ``device_lost_count`` times total, so a healed
        re-dispatch can be made to fail again (double loss inside one
        heal window)."""
        n = self.device_lost_at_step
        if n and step >= n:
            done = _counters.get("device_lost", 0)
            if done < max(1, self.device_lost_count):
                _counters["device_lost"] = done + 1
                raise RuntimeError(
                    "UNAVAILABLE: TPU backend lost mid-run (Unavailable). "
                    f"[injected device loss {done + 1}/"
                    f"{max(1, self.device_lost_count)} at step {step}, "
                    "chaos]")

    def maybe_shrink(self, devices):
        """Truncate a re-acquired device list to ``shrink_on_reacquire``
        devices, if armed — the backend came back smaller."""
        n = self.shrink_on_reacquire
        if n and devices is not None and len(devices) > n:
            return devices[:n]
        return devices

    def maybe_host_die(self, step: int):
        """SIGKILL this process when its host index matches an armed
        ``host_die_at_step=H:K`` and the step count reaches K — one
        whole simulated host drops out of the fleet, mid-run."""
        if not self.host_die_at_step:
            return
        host, _, at = self.host_die_at_step.partition(":")
        if (_host_index() == int(host) and step >= int(at)
                and not _counters.get("host_die")):
            _counters["host_die"] = 1
            os.kill(os.getpid(), signal.SIGKILL)

    def maybe_slow_step(self, step: int):
        """Sleep the armed ``slow_step_at=H:K:ms`` tail when this host's
        index is H and the optimizer step about to dispatch is >= K —
        host-side only (the sleep sits before the dispatch, it adds no
        device sync)."""
        if not self.slow_step_at:
            return
        host, at, ms = self.slow_step_at.split(":")
        if _host_index() == int(host) and step >= int(at):
            time.sleep(float(ms) / 1e3)

    @staticmethod
    def _at_match(armed: str, epoch: int, index: int):
        """Split an armed ``E:I[:N]`` key; (None, None) unless E/I match."""
        parts = armed.split(":")
        if int(parts[0]) != epoch or int(parts[1]) != index:
            return None, None
        return parts, f"{epoch}:{index}"

    def maybe_data_corrupt(self, epoch: int, index: int):
        """Raise the permanently-corrupt-record signature when loading
        roidb record ``index`` during ``epoch`` matches the armed
        ``data_corrupt_at=E:I`` — fires on EVERY attempt (a rotten JPEG
        does not heal on retry); quarantine is what stops the re-reads."""
        if not self.data_corrupt_at:
            return
        parts, _ = self._at_match(self.data_corrupt_at, epoch, index)
        if parts is not None:
            raise ValueError(
                f"corrupt JPEG data: premature end of data segment "
                f"[injected corruption, record {index} epoch {epoch}, "
                "chaos]")

    def maybe_data_io_error(self, epoch: int, index: int):
        """Fail the first N load attempts of record ``index`` during
        ``epoch`` with a transient EIO signature, per the armed
        ``data_io_error_at=E:I:N`` — then let the load through."""
        if not self.data_io_error_at:
            return
        parts, key = self._at_match(self.data_io_error_at, epoch, index)
        if parts is None:
            return
        n = int(parts[2])
        done = _counters.get(f"data_io:{key}", 0)
        if done < n:
            _counters[f"data_io:{key}"] = done + 1
            raise OSError(
                5, "Input/output error (EIO) reading record "
                   f"{index} [injected IO flake {done + 1}/{n}, chaos]")

    def maybe_data_hang(self, epoch: int, index: int, cancel=None):
        """Sleep ``hang_s`` (in cancel-aware 50 ms slices) when loading
        record ``index`` during ``epoch`` matches the armed
        ``data_hang_at=E:I`` — the stuck-storage read. ``cancel`` is a
        nullary predicate (the prefetcher's stop flag) so a consumer
        that already gave up (DataStallError) releases the worker."""
        if not self.data_hang_at:
            return
        parts, _ = self._at_match(self.data_hang_at, epoch, index)
        if parts is None:
            return
        deadline = time.monotonic() + self.hang_s
        while time.monotonic() < deadline:
            if cancel is not None and cancel():
                return
            time.sleep(0.05)

    def maybe_worker_die(self, worker_index: int) -> bool:
        """True exactly once when prefetch worker ``worker_index`` should
        die abruptly (armed ``data_worker_die_at=K``) — the loader turns
        this into a silent thread exit with its claim still pending."""
        if (self.data_worker_die_at >= 0
                and worker_index == self.data_worker_die_at
                and not _counters.get("data_worker_die")):
            _counters["data_worker_die"] = 1
            return True
        return False

    def maybe_barrier_timeout(self, site_name: str) -> bool:
        """True when this host should SKIP arriving at ``site_name`` —
        the quorum barrier then sees a partial set at its deadline.
        ``barrier_timeout_at`` is either a bare site (this host) or
        ``H:site`` (only host index H skips)."""
        armed = self.barrier_timeout_at
        if not armed:
            return False
        host, sep, target = armed.partition(":")
        if sep:
            return _host_index() == int(host) and site_name == target
        return site_name == armed

    def fire(self, name: str, step: int = 0, devices=None):
        """Dispatch one registered injection site on a PRE-PARSED spec
        (the hot train loop parses MX_RCNN_CHAOS once and calls this
        behind an ``active`` check). Returns ``devices`` — possibly
        truncated — for value sites, True from "quorum_barrier" when
        the arrival should be skipped; None otherwise. Unregistered
        names raise: see ``SITES``."""
        if name not in SITES:
            raise ValueError(
                f"unregistered chaos site {name!r}; the registered sites "
                f"are {sorted(SITES)} (add new ones to chaos.SITES)")
        # EVERY registered site is a valid die_at target (parse validates
        # die_at against SITES — routing only some of them here would
        # re-open the armed-but-never-fires hole that check closes).
        self.maybe_die(name)
        if name == "train_dispatch":
            self.maybe_host_die(step)
            self.maybe_slow_step(step)
            self.maybe_device_loss(step)
        elif name == "backend_reacquire":
            devices = self.maybe_shrink(devices)
        elif name == "quorum_barrier":
            return self.maybe_barrier_timeout(name)
        return devices


_FIELDS = {f.name: f for f in dataclasses.fields(ChaosSpec)}


def parse(text: str) -> ChaosSpec:
    """Parse a spec string (see module docstring). Raises on unknown keys
    and unparseable values — a silently-ignored injection is worse than a
    loud one."""
    kw: dict = {}
    for pair in text.replace(",", " ").split():
        key, sep, raw = pair.partition("=")
        if not sep or key not in _FIELDS:
            raise ValueError(
                f"bad {ENV_VAR} entry {pair!r}; known keys: "
                f"{sorted(_FIELDS)}")
        ftype = _FIELDS[key].type
        if ftype in ("int", int):
            kw[key] = int(raw)
        elif ftype in ("float", float):
            kw[key] = float(raw)
        elif ftype in ("bool", bool):
            v = raw.strip().lower()
            if v in ("1", "true", "yes", "on"):
                kw[key] = True
            elif v in ("0", "false", "no", "off"):
                kw[key] = False
            else:
                raise ValueError(
                    f"bad {ENV_VAR} boolean {raw!r} for {key}")
        else:
            kw[key] = raw
    if kw.get("die_at") and kw["die_at"] not in SITES:
        # Same hazard class as an unknown key: a typo'd site would arm an
        # injection that can never fire, silently un-testing its gate.
        raise ValueError(
            f"bad {ENV_VAR} die_at site {kw['die_at']!r}; registered "
            f"sites: {sorted(SITES)}")
    if kw.get("host_die_at_step"):
        host, sep, at = kw["host_die_at_step"].partition(":")
        if not sep or not host.isdigit() or not at.isdigit():
            raise ValueError(
                f"bad {ENV_VAR} host_die_at_step "
                f"{kw['host_die_at_step']!r}; expected H:K (host index, "
                "step)")
    if kw.get("slow_step_at"):
        parts = kw["slow_step_at"].split(":")
        ok = len(parts) == 3 and parts[0].isdigit() and parts[1].isdigit()
        if ok:
            try:
                float(parts[2])
            except ValueError:
                ok = False
        if not ok:
            raise ValueError(
                f"bad {ENV_VAR} slow_step_at {kw['slow_step_at']!r}; "
                "expected H:K:ms (host index, step, sleep milliseconds)")
    if kw.get("barrier_timeout_at"):
        _, sep, target = kw["barrier_timeout_at"].partition(":")
        site_name = target if sep else kw["barrier_timeout_at"]
        if site_name not in SITES:
            raise ValueError(
                f"bad {ENV_VAR} barrier_timeout_at site {site_name!r}; "
                f"registered sites: {sorted(SITES)}")
    for key, want in (("data_corrupt_at", 2), ("data_hang_at", 2),
                      ("data_io_error_at", 3)):
        if kw.get(key):
            parts = kw[key].split(":")
            if len(parts) != want or not all(p.isdigit() for p in parts):
                shape = "E:I:N (epoch, record index, fail count)" \
                    if want == 3 else "E:I (epoch, record index)"
                raise ValueError(
                    f"bad {ENV_VAR} {key} {kw[key]!r}; expected {shape}")
    return ChaosSpec(**kw)


def poison_grads(grads, step, at_step: int):
    """nan_at_step's IN-GRAPH injection: multiply every floating gradient
    leaf by a factor that is NaN exactly when the optimizer step being
    produced (``step + 1``, a TRACED counter) equals ``at_step`` and 1.0
    otherwise — so the poisoned program is numerically identical to the
    clean one on every other step, and the nonfinite values flow through
    the same fused update/health reductions a real bf16 overflow would.
    Trace-time helper (jax imported lazily: this module stays importable
    without it); non-float leaves (int dtype groups) pass through."""
    import jax
    import jax.numpy as jnp

    factor = jnp.where(step + 1 == at_step, jnp.nan, 1.0)
    return jax.tree_util.tree_map(
        lambda g: (g * factor.astype(g.dtype)
                   if jnp.issubdtype(g.dtype, jnp.floating) else g),
        grads)


def site(name: str, step: int = 0, devices=None):
    """Module-level injection point for COLD paths (checkpoint saves,
    heal re-acquisition): parses the env spec on every call. Hot paths
    pre-parse with ``from_env()`` and call ``spec.fire`` directly —
    which validates the name against ``SITES`` even when no spec is
    armed."""
    return from_env().fire(name, step=step, devices=devices)


def from_env(environ=os.environ) -> ChaosSpec:
    """The armed spec for this process (inactive when the var is unset)."""
    text = environ.get(ENV_VAR, "")
    return parse(text) if text else ChaosSpec()
