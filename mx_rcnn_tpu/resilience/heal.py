"""graftheal — in-run backend-loss recovery and elastic-topology resume.

graftguard (resilience/backend.py, preempt.py) made *startup* fault-
tolerant and preemption survivable; this module closes the remaining gap
in the ROADMAP taxonomy: the backend dying **mid-step**. Before graftheal
a step-time ``UNAVAILABLE`` (the TPU_OUTAGE_r5.log shape, hours into a
run) was an uncaught RuntimeError — every step since the last checkpoint
lost, an operator required. Now the train loop's dispatch is wrapped in a
recovery loop (tools/train.py::fit_detector):

1. **Classify.** A step-time RuntimeError is classified with the PR 5
   taxonomy (``classify_backend_error``): transient gRPC markers
   (UNAVAILABLE / DEADLINE_EXCEEDED / ABORTED) heal; anything else — a
   shape error, an INVALID_ARGUMENT — propagates untouched.
2. **Capture.** An in-memory emergency capture of the last known-good
   state: first a *live* capture (``jax.device_get`` of the current
   train state into host-OWNED numpy copies — tree form even from flat
   buffers, via ``FlatCore.tree_state``); if the post-loss state is
   unreadable (donated buffers on a dead backend poison the read), fall
   back to the standing host snapshot the loop refreshes every
   ``resilience.heal_snapshot_dispatches`` dispatches — the replayed
   dispatches are re-derived deterministically (epoch order is
   f(seed, epoch), per-dispatch keys fold the global index), so the
   resumed trajectory is the one the uninterrupted run would have taken.
3. **Re-acquire.** Tear the cached backend down (the clear used for the
   silent-CPU-fallback path) and re-acquire through the classified
   retry-with-backoff of ``acquire_backend`` under the SAME
   ``resilience.backend_deadline_s`` that guards startup.
4. **Re-shard.** The backend may come back with a DIFFERENT device
   count (spot reclaim, partial slice): the caller rebuilds the mesh via
   ``parallel.partition.elastic_mesh_spec`` (model axis preserved, data
   axis re-cut to the largest batch-divisible size), re-derives
   partition specs and re-cuts flatcore buffers against the new mesh —
   the GLOBAL batch is invariant, so the loader, the LR schedule and the
   loss trajectory carry straight across the shrink.

Each recovery emits one ``heal`` graftscope event (epoch/dispatch,
classified error, capture mode, downtime, device counts before/after)
and resets the stall watchdog's trailing median — the first post-heal
step pays a fresh compile and must not read as a stall.

Consecutive heals with no completed dispatch in between are capped
(``resilience.heal_consecutive_max``): a fault that recurs instantly is
not an outage, and re-raising beats looping. Fault injection:
``MX_RCNN_CHAOS="device_lost_at_step=K"`` raises the loss signature
before the dispatch that would complete optimizer step K;
``shrink_on_reacquire=N`` hands recovery only the first N devices
(resilience/chaos.py). Runbook: OUTAGES.md "mid-run backend loss".
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.resilience import chaos
from mx_rcnn_tpu.resilience.backend import (
    _clear_backend_cache,
    acquire_backend,
    classify_backend_error,
)


def host_tree_copy(tree):
    """Host-OWNED numpy copies of a pytree — THE heal-carry invariant:
    ``np.array`` of every leaf, never zero-copy views of runtime buffers
    (the backend they came from is about to be torn down, and on the CPU
    client ``device_get`` can alias the live buffer). Every capture/
    fallback site goes through here so the invariant lives in one place.
    jax imported lazily — this module stays importable without it."""
    import jax
    import numpy as np

    return jax.tree_util.tree_map(
        lambda x: np.array(jax.device_get(x)), tree)


@dataclass
class HealCarry:
    """Host-owned training state at a known-good point — what a session
    is (re)built from. ``params``/``opt_state`` are TREE-form numpy
    copies (never device views: the backend they came from is about to
    be torn down); ``opt_state`` is None only for a fresh run's initial
    carry. ``dispatch`` counts completed dispatches of ``epoch`` —
    ``(epoch, 0)`` is the epoch boundary. ``bag`` is the drained
    MetricBag snapshot at the same point, so the resumed epoch's metrics
    keep accounting for the pre-loss dispatches."""

    params: Any
    opt_state: Any = None
    epoch: int = 0
    dispatch: int = 0
    bag: Optional[Tuple[Dict[str, float], Dict[str, int]]] = None


class Healer:
    """The in-run recovery engine fit_detector leans on.

    Holds the standing fallback snapshot, the consecutive-failure cap,
    and the re-acquired device list (``devices`` — None until a heal
    changed the backend; the session builder re-derives the mesh from it
    when set). ``rcfg`` is the ``resilience`` config section; ``elog``
    an optional graftscope EventLog; ``watchdog`` an optional
    StallWatchdog whose trailing median is reset after each heal.
    """

    def __init__(self, rcfg, elog=None, watchdog=None, recorder=None,
                 clock: Callable[[], float] = time.monotonic):
        self.rcfg = rcfg
        self.elog = elog
        self.watchdog = watchdog
        # graftpulse flight recorder (obs/health.py): each heal flushes
        # the last-K-events ring, so the recovery artifact shows the
        # numerics around the loss, not just the heal event.
        self.recorder = recorder
        self._clock = clock
        self.heals = 0
        self.devices = None
        self._consecutive = 0
        self._fallback: Optional[HealCarry] = None
        self._since_snapshot = 0
        self._n_devices: Optional[int] = None
        self._footprint: Optional[int] = None
        # graftquorum (resilience/quorum.py): multi-host runs install a
        # hook called with the re-acquired device list; it runs one
        # generation of the heal quorum (barrier, topology agreement,
        # exclusion) and returns the QuorumOutcome — the agreed mesh
        # spec the session rebuild must adopt. Raises QuorumExcludedError
        # on the host the quorum moved on without. None = single-host
        # behavior (the session derives the spec locally).
        self.quorum_hook: Optional[Callable] = None
        #: The last heal's QuorumOutcome (None single-host) — the session
        #: rebuild reads the agreed spec from here.
        self.outcome = None

    # -- bookkeeping the train loop drives ---------------------------------

    def note_devices(self, n: int):
        """Record the session's device count (the heal event's 'before').
        The largest session ever seen is the run's FOOTPRINT — the cap
        for reporting re-acquired capacity (a re-grow back toward it
        after an earlier shrink is a real transition; spare devices
        beyond it are not)."""
        self._n_devices = int(n)
        self._footprint = max(self._footprint or 0, int(n))

    def note_progress(self):
        """A dispatch completed — the backend is live again; re-arm the
        consecutive-heal cap."""
        self._consecutive = 0

    def set_fallback(self, carry: HealCarry):
        """Install/refresh the standing host snapshot (initial carry,
        post-heal carry, or a periodic snapshot)."""
        self._fallback = carry

    def snapshot_due(self) -> bool:
        """True every ``heal_snapshot_dispatches`` completed dispatches
        (0 disables periodic snapshots — live capture only)."""
        every = int(getattr(self.rcfg, "heal_snapshot_dispatches", 0))
        if every <= 0:
            return False
        self._since_snapshot += 1
        if self._since_snapshot >= every:
            self._since_snapshot = 0
            return True
        return False

    # -- the recovery itself ------------------------------------------------

    def healable(self, exc: BaseException) -> bool:
        """Should this step-time error be healed in-process? Transient by
        the PR 5 taxonomy, under the consecutive cap, and heal enabled."""
        if not getattr(self.rcfg, "heal", False):
            return False
        if not isinstance(exc, RuntimeError):
            return False
        if self._consecutive >= max(1, int(self.rcfg.heal_consecutive_max)):
            logger.error(
                "graftheal: %d consecutive heals without a completed "
                "dispatch — the fault recurs instantly, giving up",
                self._consecutive)
            return False
        return classify_backend_error(exc) == "transient"

    def recover(self, exc: BaseException,
                capture: Callable[[], HealCarry]) -> HealCarry:
        """Capture → teardown → re-acquire. Returns the carry to rebuild
        the session from; raises ``exc`` (chained) when no state can be
        captured, and whatever ``acquire_backend`` raises when the
        backend stays down past the deadline.
        """
        t0 = self._clock()
        if self.watchdog is not None:
            # The heal window is a KNOWN no-heartbeat stretch (capture +
            # a possibly hours-long re-acquisition backoff): silence the
            # stall tripwire for its duration — the outage is reported
            # as a `heal` event, not a stall dump (reset() below
            # re-arms).
            self.watchdog.pause()
        mode = "live"
        try:
            carry = capture()
        except Exception as cap_exc:  # noqa: BLE001  # graftlint: disable=broad-except — the post-loss state may be unreadable in arbitrary ways (poisoned futures, donated buffers); ANY capture failure routes to the snapshot fallback
            if self._fallback is None:
                logger.error(
                    "graftheal: live capture failed (%s) and no snapshot "
                    "fallback exists — cannot heal", cap_exc)
                raise exc from cap_exc
            carry = self._fallback
            mode = "snapshot"
            logger.warning(
                "graftheal: live capture failed (%s); rolling back to the "
                "snapshot at epoch %d dispatch %d — the gap replays "
                "deterministically", cap_exc, carry.epoch, carry.dispatch)
        # Teardown: drop jax's cached backend so re-acquisition actually
        # re-initializes (the same clear the silent-CPU-fallback retry
        # path uses) — probing a dead cached client would fail forever.
        _clear_backend_cache()
        devices = acquire_backend(self.rcfg, elog=self.elog)
        devices = chaos.site("backend_reacquire", devices=devices)
        # Multi-host: every surviving host reaches the heal quorum with
        # its re-acquired capacity and adopts the agreed topology; a
        # host that missed the deadline gets QuorumExcludedError here
        # (propagates — the survivors sealed the round without it and
        # its only correct move is a resumable exit). Inside the
        # watchdog-paused window: a quorum wait is not a stall.
        self.outcome = None
        if self.quorum_hook is not None:
            self.outcome = self.quorum_hook(devices)
        downtime = self._clock() - t0
        before = self._n_devices
        # The event's "after" is the recovered capacity CAPPED at the
        # run's FOOTPRINT — not at the previous session's (possibly
        # shrunken) size, so a re-grow after an earlier shrink reports
        # as the 4->8 transition it is, while a backend with spare
        # devices beyond the footprint is not called a grow. The exact
        # re-cut mesh is logged by the session rebuild.
        after = (min(len(devices), self._footprint)
                 if self._footprint else len(devices))
        self.heals += 1
        self._consecutive += 1
        self.devices = devices
        self.set_fallback(carry)
        if self.watchdog is not None:
            # The pre-loss trailing median must not judge the first
            # post-heal step (re-acquire + fresh compile): cold grace.
            self.watchdog.reset()
        if self.elog is not None and self.elog.enabled:
            quorum_fields = {}
            if self.outcome is not None:
                # The agreed round, folded into the heal record so the
                # report can show WHO healed together and what topology
                # they agreed on (the event also carries this process's
                # index via the EventLog process stamp).
                quorum_fields = dict(
                    quorum_generation=self.outcome.generation,
                    quorum_hosts=self.outcome.arrived,
                    quorum_excluded=self.outcome.excluded,
                    quorum_devices=self.outcome.devices,
                    quorum_spec=self.outcome.spec)
            self.elog.emit("heal", epoch=carry.epoch, dispatch=carry.dispatch,
                           error=str(exc)[:500], mode=mode,
                           downtime_s=round(downtime, 3),
                           devices_before=before,
                           devices_after=after, **quorum_fields)
        if self.recorder is not None:
            self.recorder.dump("heal")
        logger.warning(
            "graftheal: healed step-time backend loss at epoch %d dispatch "
            "%d (%s capture, %.1fs down, devices %s -> %d): %s",
            carry.epoch, carry.dispatch, mode, downtime, before, after, exc)
        return carry
