"""Deadline isolation — run one measurement in a killable child process.

BENCH_r05 is ``rc=124, parsed: null``: one hung compile consumed the
whole bench timeout and every completed config's number died with the
parent. A deadline can only be enforced against work you can kill, and a
hung XLA compile holds the GIL-adjacent native stack — in-process timers
can't interrupt it. So each config runs in a ``spawn`` child (fresh
process, fresh backend handle — a wedged relay connection dies with it);
the parent waits at most ``timeout_s``, then kills the child and records
a structured timeout row instead of losing the sweep. bench.py::run_sweep
is the consumer; the rc=124 failure mode is structurally impossible.

stdlib-only (multiprocessing) — the child pays the jax import, not this
module. The callable and its argument must be picklable (module-level
functions + the frozen Config tree both are). Chaos hook: the child calls
``maybe_hang(label)`` before the work, so a tier-1 test can hang one
named config and watch the sweep survive (chaos.py ``hang_bench``).
"""

from __future__ import annotations

import multiprocessing as mp
from typing import Any, Callable, Dict


def deadline_row(timeout_s: float) -> Dict[str, Any]:
    """The structured row recorded for a config that outlived its
    deadline. ``timeout_s``'s presence IS the marker consumers test for
    (run_sweep retries relay errors but never retries a timeout — a hung
    compile would just hang again)."""
    return {"error": f"timeout: config exceeded the {timeout_s:g}s "
                     "per-config deadline (child killed)",
            "timeout_s": timeout_s}


def _child_entry(fn: Callable, label: str, arg, conn):
    """Child body: chaos hook, the work, one row through the pipe. Every
    failure becomes a row — the parent must always learn SOMETHING."""
    from mx_rcnn_tpu.resilience import chaos

    try:
        chaos.from_env().maybe_hang(label)
        row = fn(arg)
    except BaseException as e:  # noqa: BLE001  # graftlint: disable=broad-except — the child's last act is reporting the error as a row
        row = {"error": f"{type(e).__name__}: {e}"}
    try:
        conn.send(row)
    finally:
        conn.close()


def run_with_deadline(fn: Callable, arg, timeout_s: float,
                      label: str = "", grace_s: float = 10.0) -> Dict[str, Any]:
    """Run ``fn(arg)`` in a spawn child; return its row dict, or the
    ``deadline_row`` if it doesn't report within ``timeout_s`` seconds.

    The deadline covers the child end-to-end (interpreter start + jax
    import + compile + measurement) — exactly the budget a sweep config
    gets. A child that dies without reporting (OOM kill, crash) yields an
    error row carrying its exit code.
    """
    ctx = mp.get_context("spawn")  # no fork: the parent's jax state and
    parent_conn, child_conn = ctx.Pipe(duplex=False)  # relay fds stay out
    proc = ctx.Process(target=_child_entry,
                       args=(fn, label, arg, child_conn), daemon=True)
    proc.start()
    child_conn.close()  # parent's copy; EOF detection needs it closed
    row = None
    try:
        if parent_conn.poll(timeout_s):
            try:
                row = parent_conn.recv()
            except EOFError:
                row = {"error": "child died without reporting "
                                f"(exitcode {proc.exitcode})"}
    finally:
        parent_conn.close()
    if row is None:
        row = deadline_row(timeout_s)
        proc.terminate()  # SIGTERM first: lets the child's runtime unwind
        proc.join(grace_s)
        if proc.is_alive():
            proc.kill()  # the BENCH_r05 case: wedged in native code
            proc.join(grace_s)
        if proc.is_alive():  # unkillable (D-state): abandon, don't hang
            row["error"] += " [child unkillable; abandoned]"
        return row
    proc.join(grace_s)  # reported: normal exit is imminent
    if proc.is_alive():
        proc.kill()
        proc.join(grace_s)
    return row
