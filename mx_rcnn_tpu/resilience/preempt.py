"""Preemption handling — turn SIGTERM into a checkpoint, not a corpse.

A shared v5e slice preempts: the scheduler sends SIGTERM, waits a grace
period, then SIGKILLs. Before this module a SIGTERM mid-epoch lost every
step since the last epoch checkpoint. Now the train loop installs a
``PreemptionGuard``: the handler only RECORDS the signal (nothing unsafe
happens in signal context), the loop checks ``guard.requested`` at each
step boundary, saves a step-granular emergency checkpoint
(``resilience.preempt_save``, train/checkpoint.py dispatch-tagged dirs)
and raises ``PreemptionExit`` — a SystemExit carrying ``RESUMABLE_RC`` so
a supervisor can distinguish "restart me with --resume auto" (rc 75) from
a real failure. The kill→resume parity gate (tests/test_resilience.py)
proves the restart reaches bit-exact final params vs an uninterrupted run.

SIGINT is handled the same way (a Ctrl-C during a multi-hour run deserves
a checkpoint too) — but a SECOND Ctrl-C raises KeyboardInterrupt
immediately: the user means *now*.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional

#: BSD EX_TEMPFAIL: "temporary failure, retry later" — the contract with
#: supervisors/wrapper scripts: this rc means re-run with ``--resume auto``
#: and nothing was lost.
RESUMABLE_RC = 75


class PreemptionExit(SystemExit):
    """Orderly preemption exit (code RESUMABLE_RC). A SystemExit subclass
    so a plain CLI run exits with the resumable rc with no extra wiring,
    while library callers (tests) can still catch it."""

    def __init__(self, signum: Optional[int] = signal.SIGTERM):
        # None = no local signal: this host exits resumably because the
        # FLEET is stopping (coordinated preemption / quorum exclusion —
        # resilience/quorum.py), not because it was signaled itself.
        super().__init__(RESUMABLE_RC)
        self.signum = int(signum) if signum is not None else None


class PreemptionGuard:
    """Deferred-signal latch for the train loop.

    ``install()`` replaces the SIGTERM/SIGINT handlers with a recorder;
    the loop polls ``requested`` at step boundaries and performs the
    orderly save/exit itself. ``uninstall()`` (or context-manager exit)
    restores the previous handlers. Signal handlers only exist in the
    main thread — ``install()`` returns False elsewhere and the guard
    stays inert (e.g. fit_detector driven from a test worker thread).
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, signals=SIGNALS):
        self._signals = tuple(signals)
        self._prev: dict = {}
        self._installed = False
        self.signum: Optional[int] = None

    @property
    def requested(self) -> bool:
        return self.signum is not None

    def install(self) -> bool:
        if threading.current_thread() is not threading.main_thread():
            return False
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._handle)
        self._installed = True
        return True

    def _handle(self, signum, frame):
        if self.requested and signum == signal.SIGINT:
            # second Ctrl-C while the first is still being honored: the
            # user wants out NOW, not after the next step's save.
            raise KeyboardInterrupt
        self.signum = signum

    def uninstall(self):
        if self._installed:
            for s, prev in self._prev.items():
                signal.signal(s, prev)
            self._prev.clear()
            self._installed = False

    def __enter__(self):
        self.install()
        return self

    def __exit__(self, *exc):
        self.uninstall()
        return False
