"""graftquorum — multi-host coordination for the resilience layer.

Every resilience feature before this module (graftguard preemption,
graftheal backend re-acquisition) was gated to single-process runtimes:
emergency saves had no all-host barrier, and a backend loss on one host
left the others deadlocked in a collective. This module supplies the
missing coordination primitives:

- a **KV store** abstraction with two backends: `jax.distributed`'s
  coordination-service client (real pods) and a filesystem directory
  (`FileKVStore`) so N-process CPU tests exercise the REAL protocol —
  atomicity comes from `O_EXCL` create (propose) and `os.replace` (set);
- a deadline-bounded **all-host barrier** that returns the set of hosts
  that arrived (possibly partial — the caller decides whether a partial
  quorum survives via `min_fraction`);
- a first-writer-wins **propose/agree** protocol (the SIGTERM'd host
  proposes the stop boundary; the heal leader proposes the post-heal
  topology) with generation-numbered heal rounds so a host that sleeps
  through round g and wakes in round g+1 discovers it was excluded
  instead of corrupting the new session.

Protocol notes (why two phases for a coordinated stop): hosts in real
SPMD are collective-synchronized and drift by at most one dispatch, but
the CPU simulation runs N fully replicated processes with NO collectives
between them, so drift is unbounded. `CoordinatedStop` therefore agrees
on `max(requested, every host's current boundary)` — phase 1 publishes
each host's floor, phase 2 drains everyone to the max — which is exact
under lockstep and correct under drift.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set

from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.resilience import chaos


class QuorumError(RuntimeError):
    """The quorum could not be reached (below min fraction / no store)."""


class QuorumExcludedError(QuorumError):
    """THIS host missed a quorum deadline and the round was sealed
    without it. The correct reaction is a resumable exit (rc 75): the
    surviving quorum carried the run forward and this host's session
    state is stale; it rejoins via ``--resume auto``."""


# ---------------------------------------------------------------------------
# KV stores
# ---------------------------------------------------------------------------

class KVStore:
    """Minimal KV interface the quorum protocol needs.

    ``set`` is last-writer-wins, ``propose`` is first-writer-wins and
    returns the winning value either way. ``get`` is a non-blocking
    peek; blocking waits are built in Quorum via polling so deadline
    handling lives in one place.
    """

    def set(self, key: str, value: str) -> None:
        raise NotImplementedError

    def get(self, key: str) -> Optional[str]:
        raise NotImplementedError

    def propose(self, key: str, value: str) -> str:
        raise NotImplementedError


class FileKVStore(KVStore):
    """Filesystem-backed store: one file per key under ``root``.

    set = write-to-temp + os.replace (atomic on POSIX), propose =
    ``O_CREAT|O_EXCL`` (atomic first-writer-wins), get = read-or-None.
    Keys may contain ``/`` — mapped to subdirectories, so a run's
    namespace is just a directory tree that ``--resume`` debugging can
    inspect with ``cat``.
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        path = os.path.normpath(os.path.join(self.root, key))
        if not path.startswith(os.path.normpath(self.root) + os.sep):
            raise ValueError(f"quorum key escapes store root: {key!r}")
        return path

    def set(self, key: str, value: str) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            os.write(fd, value.encode("utf-8"))
        finally:
            os.close(fd)
        os.replace(tmp, path)

    def get(self, key: str) -> Optional[str]:
        try:
            with open(self._path(key), "r", encoding="utf-8") as f:
                return f.read()
        except (FileNotFoundError, NotADirectoryError, IsADirectoryError):
            return None

    def propose(self, key: str, value: str) -> str:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            won = self.get(key)
            if won is None:  # writer crashed between create and write:
                return value  # treat our value as accepted
            return won
        try:
            os.write(fd, value.encode("utf-8"))
        finally:
            os.close(fd)
        return value


class JaxKVStore(KVStore):
    """KV over jax's distributed coordination service client.

    Only reachable after ``jax.distributed.initialize``; constructed via
    :func:`jax_kv_client` which returns None when the runtime is not up
    (callers then fall back to FileKVStore or disable coordination).
    propose() leans on the service rejecting duplicate keys; where the
    installed jax only offers overwrite semantics we emulate
    first-writer-wins with a get-before-set (benign: proposals race only
    between live hosts that would propose compatible values).
    """

    def __init__(self, client):
        self._client = client

    def set(self, key: str, value: str) -> None:
        self._client.key_value_set(key, value, allow_overwrite=True)

    def get(self, key: str) -> Optional[str]:
        try:
            value = self._client.key_value_try_get(key)
        except Exception:  # graftlint: disable=broad-except — the client maps NOT_FOUND to different exception types across jax versions; absent-key is the expected answer here
            return None
        return value if value else None

    def propose(self, key: str, value: str) -> str:
        try:
            self._client.key_value_set(key, value)  # no-overwrite default
            return value
        except Exception:  # graftlint: disable=broad-except — ALREADY_EXISTS (someone else won) surfaces as version-dependent exception types; the get() below recovers the winning value either way
            won = self.get(key)
            return won if won is not None else value


def jax_kv_client():
    """The live coordination-service client, or None.

    Reaches into ``jax._src.distributed.global_state`` — the only place
    jax exposes the KV client today. Version-gated: any import/attr
    failure means "no client" rather than an exception, so CPU tests and
    future jax refactors degrade to the filesystem store.
    """
    try:
        from jax._src import distributed as _dist  # type: ignore

        return getattr(_dist.global_state, "client", None)
    except Exception:  # graftlint: disable=broad-except — version-gated probe into jax._src internals: any import/attr/layout change means "no client", never a crash
        return None


# ---------------------------------------------------------------------------
# the quorum
# ---------------------------------------------------------------------------

@dataclass
class QuorumOutcome:
    """What a heal round agreed on — folded into the heal event."""

    generation: int
    arrived: List[int]
    excluded: List[int]
    devices: int
    spec: str


class Quorum:
    """Deadline-bounded barriers + propose/agree over a KVStore.

    ``index``/``count`` are the host identity (simulated-host wrappers in
    parallel/distributed.py under test, jax.process_index/count on real
    pods). ``active`` starts as the full host set and shrinks when a heal
    round excludes a host — later barriers only wait for active members,
    so one dead host does not deadline every subsequent save.
    """

    def __init__(self, store: KVStore, index: int, count: int, *,
                 timeout_s: float = 60.0, min_fraction: float = 0.5,
                 poll_s: float = 0.05,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 elog=None):
        """``elog``: optional graftscope EventLog (duck-typed; None or a
        NullEventLog both no-op) — every barrier then emits a typed
        ``barrier`` event from this host's view: name, own wait_s
        (monotonic), arrival order over the shared wall stamps, who
        arrived last, timed_out. The grafttower fleet fold (obs/fleet.py)
        attributes the waiters' time to the last arriver and uses the
        releases as its residual-clock-skew correction signal."""
        self.store = store
        self.index = index
        self.count = count
        self.timeout_s = timeout_s
        self.min_fraction = min_fraction
        self.poll_s = poll_s
        self._clock = clock
        self._sleep = sleep
        self.elog = elog
        self.active: Set[int] = set(range(count))

    # -- identity ----------------------------------------------------------

    @property
    def leader(self) -> int:
        """The host that owns publication duties (lowest active index)."""
        return min(self.active)

    def is_leader(self) -> bool:
        return self.index == self.leader

    # -- waits -------------------------------------------------------------

    def wait(self, key: str, timeout_s: Optional[float] = None
             ) -> Optional[str]:
        """Poll ``key`` until present or deadline; None on timeout."""
        deadline = self._clock() + (timeout_s if timeout_s is not None
                                    else self.timeout_s)
        while True:
            value = self.store.get(key)
            if value is not None:
                return value
            if self._clock() >= deadline:
                return None
            self._sleep(self.poll_s)

    def _arrival_stamps(self, prefix: str, arrived: Set[int]
                        ) -> Dict[int, float]:
        """The wall stamps the arrived hosts published (barrier arrivals
        carry ``time.time()`` so arrival ORDER is recoverable across
        hosts — shared-store stamps, comparable to within NTP skew; a
        pre-grafttower "1" value reads as stampless and is skipped:
        it parses as float, so plausibility — a wall stamp is seconds
        since the epoch — is the discriminator, not parseability)."""
        stamps: Dict[int, float] = {}
        for i in arrived:
            raw = self.store.get(f"{prefix}/{i}")
            if raw is None:
                continue
            try:
                stamp = float(raw)
            except ValueError:
                continue
            if stamp >= 1e9:  # Sep 2001 — anything earlier isn't a stamp
                stamps[i] = stamp
        return stamps

    def _emit_barrier(self, name: str, wait_s: float, arrived: Set[int],
                      stamps: Dict[int, float]):
        """One ``barrier`` event from this host's view (obs/fleet.py
        folds all views; the shared stamps make every view agree on
        the arrival order)."""
        if self.elog is None:
            return
        order = sorted(stamps, key=stamps.get)
        self.elog.emit(
            "barrier",
            name=name,
            wait_s=round(wait_s, 4),
            arrived=sorted(arrived),
            absent=sorted(self.active - arrived),
            order=order,
            last=order[-1] if order else None,
            timed_out=not arrived >= self.active)

    def barrier(self, name: str, timeout_s: Optional[float] = None
                ) -> Set[int]:
        """Arrive at ``name`` and wait for the active set; returns who
        arrived by the deadline (a superset check is the caller's job).
        Arrival publishes this host's wall stamp (value = time.time(),
        read back only for presence by the wait loop — order/attribution
        live in the emitted ``barrier`` event).

        Chaos: ``barrier_timeout_at=<site>`` armed for this process makes
        it NOT arrive (simulating a host hung past the deadline) — the
        others then see a partial set, which is exactly the exclusion
        path under test.
        """
        t0 = self._clock()
        if not chaos.site("quorum_barrier"):
            self.store.set(f"{name}/arrive/{self.index}",
                           repr(time.time()))
        deadline = self._clock() + (timeout_s if timeout_s is not None
                                    else self.timeout_s)
        arrived: Set[int] = set()
        while True:
            arrived = {i for i in self.active
                       if self.store.get(f"{name}/arrive/{i}") is not None}
            if arrived >= self.active or self._clock() >= deadline:
                break
            self._sleep(self.poll_s)
        self._emit_barrier(name, self._clock() - t0, arrived,
                           self._arrival_stamps(f"{name}/arrive", arrived))
        return arrived

    def propose(self, name: str, value: str) -> str:
        return self.store.propose(f"{name}/value", value)

    def agree(self, name: str, timeout_s: Optional[float] = None
              ) -> Optional[str]:
        return self.wait(f"{name}/value", timeout_s)

    # -- heal rounds -------------------------------------------------------

    def heal_round(self, generation: int, n_devices: int,
                   agree_spec: Callable[[int, int], str]) -> QuorumOutcome:
        """One generation of the multi-host heal protocol.

        Every surviving host publishes its re-acquired device count and
        waits for the others under the deadline. The leader of the
        arrived set agrees the post-heal topology by calling
        ``agree_spec(min_devices, n_hosts_arrived)`` and seals the round
        with the participant list; everyone else adopts the seal. A host
        that arrives after the seal (its index absent from the sealed
        participants) raises :class:`QuorumExcludedError`; a round whose
        arrived fraction is below ``min_fraction`` raises
        :class:`QuorumError` on every host.
        """
        ns = f"heal/{generation}"
        t0 = self._clock()
        if not chaos.site("quorum_barrier"):
            # Arrival stamp rides NEXT to the device publication: the
            # dev value is protocol payload (parsed as an int below),
            # so the barrier-event wall stamp gets its own key.
            self.store.set(f"{ns}/stamp/{self.index}", repr(time.time()))
            self.store.set(f"{ns}/dev/{self.index}", str(n_devices))
        deadline = self._clock() + self.timeout_s
        while True:
            arrived = {i for i in self.active
                       if self.store.get(f"{ns}/dev/{i}") is not None}
            if arrived >= self.active:
                break
            sealed = self.store.get(f"{ns}/seal")
            if sealed is not None:
                break  # a quorum formed without the stragglers
            if self._clock() >= deadline:
                break
            self._sleep(self.poll_s)
        self._emit_barrier(ns, self._clock() - t0, arrived,
                           self._arrival_stamps(f"{ns}/stamp", arrived))

        sealed = self.store.get(f"{ns}/seal")
        if sealed is None and self.index == min(arrived | {self.index}):
            # Leader of the arrived set: agree + seal. propose() makes a
            # double-leader race (clock skew) converge on one seal.
            devices = min(int(self.store.get(f"{ns}/dev/{i}") or n_devices)
                          for i in arrived) if arrived else n_devices
            spec = agree_spec(devices, max(len(arrived), 1))
            sealed = self.store.propose(f"{ns}/seal", json.dumps({
                "spec": spec, "devices": devices,
                "participants": sorted(arrived | {self.index}),
            }))
        if sealed is None:
            sealed = self.wait(f"{ns}/seal")
        if sealed is None:
            raise QuorumError(
                f"heal generation {generation}: no seal within "
                f"{self.timeout_s:.0f}s (store unreachable or all "
                f"leaders dead)")

        seal = json.loads(sealed)
        participants = set(seal["participants"])
        if self.index not in participants:
            raise QuorumExcludedError(
                f"host {self.index} missed heal generation {generation} "
                f"(quorum sealed with hosts {sorted(participants)}); "
                f"exiting resumable")
        excluded = sorted(self.active - participants)
        if len(participants) < self.min_fraction * self.count:
            raise QuorumError(
                f"heal generation {generation}: only "
                f"{len(participants)}/{self.count} hosts reached the "
                f"quorum (< min fraction {self.min_fraction})")
        self.active = participants
        if excluded:
            logger.warning(
                "quorum: heal generation %d excluded hosts %s "
                "(survivors %s)", generation, excluded,
                sorted(participants))
        return QuorumOutcome(generation=generation,
                             arrived=sorted(participants),
                             excluded=excluded,
                             devices=int(seal["devices"]),
                             spec=str(seal["spec"]))


# ---------------------------------------------------------------------------
# coordinated preemption
# ---------------------------------------------------------------------------

class CoordinatedStop:
    """Two-phase agreement on the emergency-stop dispatch boundary.

    Phase 1 (request): the SIGTERM'd host proposes ``stop/req`` = its
    next boundary. Phase 2 (floor exchange): each host, on first
    observing the request, publishes ``max(req, own boundary)`` and the
    agreed stop is the max over all published floors — no host is asked
    to stop at a boundary it already passed. Hosts then drain to the
    agreed boundary, barrier, and only then does the leader publish the
    ONE emergency save.
    """

    def __init__(self, quorum: Quorum):
        self.quorum = quorum
        self._agreed: Optional[int] = None
        self._published = False

    def request(self, boundary: int) -> None:
        """Propose stopping at ``boundary`` (the signal handler's side)."""
        self.quorum.propose("stop/req", str(boundary))

    def check(self, boundary: int) -> Optional[int]:
        """Poll at a dispatch boundary; returns the agreed stop boundary
        once known (blocking for one floor-exchange round the first
        time a request is seen), else None."""
        if self._agreed is not None:
            return self._agreed
        q = self.quorum
        req = q.store.get("stop/req/value")
        if req is None:
            return None
        if not self._published:
            q.store.set(f"stop/floor/{q.index}", str(max(int(req), boundary)))
            self._published = True
        deadline = q._clock() + q.timeout_s
        floors: Dict[int, int] = {}
        while True:
            floors = {i: int(v) for i in q.active
                      if (v := q.store.get(f"stop/floor/{i}")) is not None}
            if set(floors) >= q.active or q._clock() >= deadline:
                break
            q._sleep(q.poll_s)
        self._agreed = max(list(floors.values()) + [int(req), boundary])
        return self._agreed
