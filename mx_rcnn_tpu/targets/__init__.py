"""Training-target assignment, traceable and static-shape.

TPU-native replacement for the reference's host-side numpy target builders:
rcnn/io/rpn.py (assign_anchor — run in the AnchorLoader on CPU) and
rcnn/io/rcnn.py + rcnn/symbol/proposal_target.py (sample_rois — run inside
the graph as a Python CustomOp, serializing every training step through the
host). Here both run inside the jitted train step.
"""

from mx_rcnn_tpu.targets.rpn_targets import assign_anchor
from mx_rcnn_tpu.targets.rcnn_targets import sample_rois
