"""From-data bbox-regression target statistics.

Reference: rcnn/processing/bbox_regression.py::add_bbox_regression_targets —
when ``BBOX_NORMALIZATION_PRECOMPUTED`` is false the reference sweeps the
roidb once, collects the (dx, dy, dw, dh) regression targets of every
foreground (proposal, matched-gt) pair, and normalizes training targets by
the measured mean/std instead of the hard-coded (0, 0.1/0.2) constants.

Here normalization happens in-graph (targets/rcnn_targets.py::sample_rois
reads cfg.train.bbox_means/bbox_stds), so the from-data branch computes the
same statistics on the host and returns an UPDATED config — one sweep before
training, zero per-step cost. Class-agnostic 4-vectors, matching the shape
sample_rois consumes (the per-class expansion happens in-graph as with the
precomputed constants).
"""

from __future__ import annotations

from dataclasses import replace
from typing import Dict, List, Tuple

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.logger import logger


def _flip_x(boxes: np.ndarray, width: float) -> np.ndarray:
    """Horizontal mirror, inclusive-pixel convention (x1' = W-1-x2)."""
    out = boxes.copy()
    out[:, 0] = width - 1 - boxes[:, 2]
    out[:, 2] = width - 1 - boxes[:, 0]
    return out


def _transform_np(ex: np.ndarray, gt: np.ndarray) -> np.ndarray:
    """(dx, dy, dw, dh) targets, numpy host path (ops/boxes.py
    bbox_transform semantics, +1 box widths as in the classic lineage)."""
    ew = ex[:, 2] - ex[:, 0] + 1.0
    eh = ex[:, 3] - ex[:, 1] + 1.0
    ecx = ex[:, 0] + 0.5 * (ew - 1.0)
    ecy = ex[:, 1] + 0.5 * (eh - 1.0)
    gw = gt[:, 2] - gt[:, 0] + 1.0
    gh = gt[:, 3] - gt[:, 1] + 1.0
    gcx = gt[:, 0] + 0.5 * (gw - 1.0)
    gcy = gt[:, 1] + 0.5 * (gh - 1.0)
    return np.stack([
        (gcx - ecx) / (ew + 1e-14),
        (gcy - ecy) / (eh + 1e-14),
        np.log(gw / ew),
        np.log(gh / eh),
    ], axis=1)


def compute_bbox_stats(roidb: List[Dict],
                       fg_overlap: float = 0.5) -> Tuple[tuple, tuple]:
    """Sweep a (proposal-attached) roidb and return (means, stds) of the
    foreground regression targets.

    Each entry contributes its proposals (entry['proposals'], the Fast
    R-CNN path; gt boxes stand in when absent — matching the reference,
    whose roidb['boxes'] always includes gt rows) matched to their
    max-IoU gt; pairs with IoU >= fg_overlap are foreground. Degenerate
    sweeps (no fg pairs) fall back to the classic precomputed constants.
    """
    from mx_rcnn_tpu.evaluation.voc_eval import _iou_matrix

    sums = np.zeros(4, np.float64)
    sqs = np.zeros(4, np.float64)
    count = 0
    for entry in roidb:
        gt = np.asarray(entry["boxes"], np.float64).reshape(-1, 4)
        if "gt_classes" in entry:
            gt = gt[np.asarray(entry["gt_classes"]) > 0]
        if not len(gt):
            continue
        props = entry.get("proposals")
        props = (gt if props is None
                 else np.asarray(props, np.float64).reshape(-1, 4))
        if not len(props):
            continue
        if entry.get("flipped"):
            # Flipped roidb copies share the UNFLIPPED arrays (the loader
            # mirrors at load time), but training consumes the mirrored
            # targets (dx negated) for these entries — mirror here so the
            # statistics match the distribution being normalized
            # (reference sweeps post-flip boxes).
            w0 = entry["width"]
            gt = _flip_x(gt, w0)
            props = _flip_x(props, w0)
        iou = _iou_matrix(props, gt)
        argmax = iou.argmax(axis=1)
        fg = iou[np.arange(len(props)), argmax] >= fg_overlap
        if not fg.any():
            continue
        t = _transform_np(props[fg], gt[argmax[fg]])
        sums += t.sum(axis=0)
        sqs += (t ** 2).sum(axis=0)
        count += len(t)
    if count < 2:
        logger.warning(
            "compute_bbox_stats: %d fg pairs — falling back to the classic "
            "precomputed constants", count)
        return (0.0, 0.0, 0.0, 0.0), (0.1, 0.1, 0.2, 0.2)
    means = sums / count
    var = np.maximum(sqs / count - means ** 2, 1e-12)
    stds = np.sqrt(var)
    logger.info("bbox target stats over %d fg pairs: means=%s stds=%s",
                count, np.round(means, 4), np.round(stds, 4))
    return tuple(float(m) for m in means), tuple(float(s) for s in stds)


def resolve_bbox_stats(cfg: Config, roidb: List[Dict]) -> Config:
    """The BBOX_NORMALIZATION_PRECOMPUTED switch: precomputed=True (the
    classic default) keeps cfg's constants; False measures means/stds from
    the roidb and returns an updated config (which also flows into the
    checkpoint's unnormalization contract via train.bbox_means/stds)."""
    if cfg.train.bbox_normalization_precomputed:
        return cfg
    means, stds = compute_bbox_stats(roidb, fg_overlap=cfg.train.fg_thresh)
    return cfg.with_updates(train=replace(
        cfg.train, bbox_means=means, bbox_stds=stds))
