"""Mask target resampling — box-frame gt masks → per-ROI training targets.

Mask R-CNN (He et al.) supervises the mask head with the gt instance mask
cropped to each sampled fg ROI and resized to the head's output resolution
(28x28). The reference lineage does this on the host with polygon
re-rasterization per ROI (Detectron's segm rasterize); that is a data-
dependent host loop — the TPU design instead stores each gt instance's mask
ONCE, rasterized over its own gt box at a fixed `mask_gt_resolution`
(config, default 56), and resamples it onto ROI frames *inside the jitted
step* with the same separable tent-weight matmuls as ops/roi_align.py.

Coordinate mapping: gt_masks[g][u, v] covers the gt box uniformly — mask
cell (u, v) spans gt_y1 + u/M*(gt_h), etc. A target cell (i, j) of an ROI
samples the point at the cell centre, mapped into the gt mask's continuous
coordinates; points outside the gt box read 0 (zero-padded sampling).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _resample_weights(lo, size, out_res: int, in_res: int, in_lo, in_size):
    """(out_res, in_res) bilinear weights sampling an axis of the gt-mask
    grid at the centres of `out_res` cells spanning [lo, lo+size).

    Gt mask cell u has centre in_lo + (u + 0.5)/in_res * in_size. Sample
    points outside [in_lo, in_lo+in_size) get zero weight rows (zero-pad).
    """
    centers = lo + (jnp.arange(out_res, dtype=jnp.float32) + 0.5) * (
        size / out_res)
    # Continuous gt-grid coordinate of each sample (in units of mask cells,
    # relative to cell centres).
    u = (centers - in_lo) / jnp.maximum(in_size, 1e-6) * in_res - 0.5
    grid = jnp.arange(in_res, dtype=jnp.float32)
    tent = jnp.maximum(0.0, 1.0 - jnp.abs(u[:, None] - grid[None, :]))
    # Outside the gt box entirely -> all-zero row (instead of clamping).
    inside = (u > -1.0) & (u < in_res)
    return tent * inside[:, None]


def mask_targets_for_rois(
    rois: jnp.ndarray,
    matched_gt: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_masks: jnp.ndarray,
    *,
    resolution: int = 28,
) -> jnp.ndarray:
    """Per-ROI binary mask targets.

    Args:
      rois: (R, 4) sampled boxes (image coords).
      matched_gt: (R,) int32 gt index per roi.
      gt_boxes: (G, 4); gt_masks: (G, M, M) {0,1} box-frame instance masks.
      resolution: mask head output size (28).

    Returns: (R, resolution, resolution) float32 in {0, 1}.
    """
    m = gt_masks.shape[-1]

    def one_roi(roi, g):
        gb = gt_boxes[g]
        gm = gt_masks[g].astype(jnp.float32)  # (M, M)
        gw = gb[2] - gb[0] + 1.0
        gh = gb[3] - gb[1] + 1.0
        rw = jnp.maximum(roi[2] - roi[0] + 1.0, 1.0)
        rh = jnp.maximum(roi[3] - roi[1] + 1.0, 1.0)
        wy = _resample_weights(roi[1], rh, resolution, m, gb[1], gh)
        wx = _resample_weights(roi[0], rw, resolution, m, gb[0], gw)
        sampled = wy @ gm @ wx.T  # (res, res)
        return (sampled >= 0.5).astype(jnp.float32)

    return jax.vmap(one_roi)(rois, matched_gt)
