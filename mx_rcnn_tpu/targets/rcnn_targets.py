"""RCNN ROI sampling — `sample_rois`, traceable.

Reference: rcnn/io/rcnn.py::sample_rois called from the ProposalTarget custom
op (rcnn/symbol/proposal_target.py) — the reference's single worst TPU
anti-pattern: a numpy sampler in the middle of the graph, forcing a device →
host → device round trip every step. Here it is a pure function under jit.

Reference semantics reproduced:
- gt boxes are appended to the proposal set before sampling (so early
  training always has positives);
- fg rois: IoU ≥ fg_thresh, up to fg_fraction·batch_rois, sampled without
  replacement; bg rois: IoU in [bg_thresh_lo, bg_thresh_hi), filling the
  remainder, sampled *with replacement* when short (modular refill here);
- class label = matched gt class for fg, 0 for bg;
- bbox targets = bbox_transform(roi, matched gt), normalized by
  (means, stds) when bbox_normalization_precomputed, expanded to per-class
  4-blocks with weight (1,1,1,1) on the label block
  (rcnn/processing/bbox_regression.py::expand_bbox_regression_targets).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.boxes import bbox_overlaps, bbox_transform


class RoiSamples(NamedTuple):
    rois: jnp.ndarray          # (R, 4) sampled boxes
    labels: jnp.ndarray        # (R,) int32 class ids (0 = bg)
    bbox_targets: jnp.ndarray  # (R, 4*num_classes)
    bbox_weights: jnp.ndarray  # (R, 4*num_classes)
    valid: jnp.ndarray         # (R,) bool — False only in degenerate cases
    fg_mask: jnp.ndarray       # (R,) bool
    matched_gt: jnp.ndarray    # (R,) int32 index into the gt arrays
    # (meaningful on fg slots only — the mask head resamples
    # gt_masks[matched_gt]; the reference has no analog because its
    # ProposalTarget recomputes matches on the host.)


def _ranked_candidates(mask: jnp.ndarray, key) -> tuple:
    """Random permutation of True indices first, then the rest; plus count."""
    n = mask.shape[0]
    keys = jnp.where(mask, jax.random.uniform(key, (n,)), 2.0)
    order = jnp.argsort(keys).astype(jnp.int32)
    count = jnp.sum(mask.astype(jnp.int32))
    return order, count


def sample_rois(
    rois: jnp.ndarray,
    roi_valid: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_classes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    key: jax.Array,
    *,
    num_classes: int,
    batch_rois: int = 128,
    fg_fraction: float = 0.25,
    fg_thresh: float = 0.5,
    bg_thresh_hi: float = 0.5,
    bg_thresh_lo: float = 0.0,
    bbox_means=(0.0, 0.0, 0.0, 0.0),
    bbox_stds=(0.1, 0.1, 0.2, 0.2),
) -> RoiSamples:
    """Single-image ROI sampling. vmap over batch at the call site.

    Args:
      rois: (P, 4) proposals (image coords).
      roi_valid: (P,) bool.
      gt_boxes: (G, 4) padded gt boxes; gt_classes: (G,) int; gt_valid: (G,).
    """
    k_fg, k_bg = jax.random.split(key)
    # Append gt boxes to the candidate set (reference: proposal_target.py
    # `all_rois = np.vstack((rois, gt_boxes))`).
    cand = jnp.concatenate([rois, gt_boxes], axis=0)
    cand_valid = jnp.concatenate([roi_valid, gt_valid], axis=0)

    iou = bbox_overlaps(cand, gt_boxes)
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    max_iou = jnp.where(cand_valid, jnp.max(iou, axis=1), -1.0)
    argmax_gt = jnp.argmax(iou, axis=1)

    fg_cand = cand_valid & (max_iou >= fg_thresh)
    bg_cand = cand_valid & (max_iou < bg_thresh_hi) & (max_iou >= bg_thresh_lo)

    fg_per_image = int(round(fg_fraction * batch_rois))
    fg_order, fg_count = _ranked_candidates(fg_cand, k_fg)
    bg_order, bg_count = _ranked_candidates(bg_cand, k_bg)
    n_fg = jnp.minimum(fg_count, fg_per_image)

    slots = jnp.arange(batch_rois, dtype=jnp.int32)
    is_fg_slot = slots < n_fg
    # fg slots index the fg candidate list directly (without replacement —
    # n_fg <= fg_count by construction). bg slots refill modularly when short
    # (reference: npr.choice(..., replace=True)).
    fg_idx = fg_order[jnp.minimum(slots, fg_count - 1)]
    bg_slot = slots - n_fg
    bg_idx = bg_order[jnp.where(bg_count > 0, bg_slot % jnp.maximum(bg_count, 1), 0)]
    # Degenerate case: no bg candidates at all -> refill from fg (keeps
    # shapes; weight masking below keeps the loss sane).
    any_bg = bg_count > 0
    take = jnp.where(is_fg_slot, fg_idx, jnp.where(any_bg, bg_idx, fg_idx))
    slot_valid = is_fg_slot | (any_bg & ~is_fg_slot)
    # If there are neither fg nor bg candidates (all-padding image), mark all
    # slots invalid but keep index 0.
    slot_valid = slot_valid & (fg_count + bg_count > 0)
    take = jnp.where(slot_valid, take, 0)

    out_rois = cand[take]
    matched = argmax_gt[take]
    labels = jnp.where(
        is_fg_slot & slot_valid, gt_classes[matched].astype(jnp.int32), 0
    )
    fg_mask = is_fg_slot & slot_valid

    # Regression targets, normalized (reference: sample_rois under
    # BBOX_NORMALIZATION_PRECOMPUTED).
    t = bbox_transform(out_rois, gt_boxes[matched])
    t = (t - jnp.asarray(bbox_means)) / jnp.asarray(bbox_stds)
    # Expand to per-class blocks (expand_bbox_regression_targets).
    class_onehot = jax.nn.one_hot(labels, num_classes, dtype=t.dtype)  # (R, C)
    expanded = class_onehot[:, :, None] * t[:, None, :]  # (R, C, 4)
    weights = class_onehot[:, :, None] * fg_mask[:, None, None].astype(t.dtype)
    r = out_rois.shape[0]
    return RoiSamples(
        rois=out_rois.astype(jnp.float32),
        labels=labels,
        bbox_targets=expanded.reshape(r, num_classes * 4).astype(jnp.float32),
        bbox_weights=jnp.broadcast_to(weights, expanded.shape)
        .reshape(r, num_classes * 4)
        .astype(jnp.float32),
        valid=slot_valid,
        fg_mask=fg_mask,
        matched_gt=matched.astype(jnp.int32),
    )
