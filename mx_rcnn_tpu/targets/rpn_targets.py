"""RPN anchor target assignment — `assign_anchor`, traceable.

Reference: rcnn/io/rpn.py::assign_anchor, which runs on the host inside
AnchorLoader with Cython IoU. Here it is a pure static-shape JAX function that
runs inside the jitted train step, vmapped over the batch.

Reference semantics reproduced:
- only anchors fully inside the (true, unpadded) image ± allowed_border
  participate; the rest stay at label −1 (ignore);
- label 0 where max IoU < negative_overlap;
- label 1 for the best anchor(s) per gt box (ties included) and wherever
  max IoU ≥ positive_overlap (in that order — positives clobber negatives
  unless rpn_clobber_positives);
- subsample to `rpn_batch_size` anchors with at most
  `rpn_fg_fraction·batch` positives, disabling the excess *at random*
  (label → −1);
- bbox targets = bbox_transform(anchor, matched gt), weight 1 on positives.

Static-shape deltas vs the reference: nothing is dropped — all H·W·A anchors
flow through with labels; gt boxes arrive padded to a fixed count with a
validity mask.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.boxes import bbox_overlaps, bbox_transform


class RpnTargets(NamedTuple):
    labels: jnp.ndarray        # (N,) int32 in {-1, 0, 1}
    bbox_targets: jnp.ndarray  # (N, 4) float32
    bbox_weights: jnp.ndarray  # (N, 4) float32 (1 on positives)


def _random_subsample(mask: jnp.ndarray, limit, key) -> jnp.ndarray:
    """Keep at most `limit` True entries of mask, chosen uniformly.

    Matches the reference's `npr.choice(fg_inds, size=excess, replace=False)`
    disabling. `limit` may be a traced scalar.
    """
    n = mask.shape[0]
    keys = jnp.where(mask, jax.random.uniform(key, (n,)), 2.0)
    order = jnp.argsort(keys)
    rank = jnp.zeros((n,), jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
    return mask & (rank < limit)


def assign_anchor(
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    im_info: jnp.ndarray,
    key: jax.Array,
    *,
    rpn_batch_size: int = 256,
    rpn_fg_fraction: float = 0.5,
    positive_overlap: float = 0.7,
    negative_overlap: float = 0.3,
    allowed_border: float = 0.0,
    clobber_positives: bool = False,
) -> RpnTargets:
    """Single-image anchor assignment. vmap over batch at the call site.

    Args:
      anchors: (N, 4) static anchor grid (ops.anchors.anchor_grid).
      gt_boxes: (G, 4) padded gt boxes (x1,y1,x2,y2).
      gt_valid: (G,) bool.
      im_info: (3,) = (height, width, scale) of the true image extent —
        or a PACKED (5,) row [h, w, scale, y0, x0] (graftcanvas), where
        the extent is the image's placement RECT inside the canvas and
        the anchors/gt boxes arrive in canvas coordinates. The inside
        test then bounds against the rect, so only the image's own
        anchors participate; cross-image IoU is structurally zero
        (placements are disjoint).
      key: PRNG key for the subsampling.
    """
    n = anchors.shape[0]
    k_fg, k_bg = jax.random.split(key)

    y0 = im_info[3] if im_info.shape[0] >= 5 else 0.0
    x0 = im_info[4] if im_info.shape[0] >= 5 else 0.0
    inside = (
        (anchors[:, 0] >= x0 - allowed_border)
        & (anchors[:, 1] >= y0 - allowed_border)
        & (anchors[:, 2] < x0 + im_info[1] + allowed_border)
        & (anchors[:, 3] < y0 + im_info[0] + allowed_border)
    )

    iou = bbox_overlaps(anchors, gt_boxes)  # (N, G)
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    any_gt = jnp.any(gt_valid)
    max_iou = jnp.max(iou, axis=1)
    argmax_gt = jnp.argmax(iou, axis=1)

    # Best anchor(s) per gt, with ties — reference recomputes equality against
    # the per-gt max over the full overlap matrix.
    gt_best = jnp.max(jnp.where(inside[:, None], iou, -1.0), axis=0)  # (G,)
    is_gt_best = jnp.any(
        (jnp.abs(iou - gt_best[None, :]) < 1e-9) & gt_valid[None, :] & (gt_best[None, :] > 0),
        axis=1,
    )

    labels = jnp.full((n,), -1, jnp.int32)
    neg = max_iou < negative_overlap
    pos = (max_iou >= positive_overlap) | is_gt_best
    if clobber_positives:
        labels = jnp.where(inside & pos, 1, labels)
        labels = jnp.where(inside & neg, 0, labels)
    else:
        labels = jnp.where(inside & neg, 0, labels)
        labels = jnp.where(inside & pos, 1, labels)
    # No gt boxes at all: everything inside is background (reference branch
    # for empty gt in assign_anchor).
    labels = jnp.where(any_gt, labels, jnp.where(inside, 0, -1))

    # Subsample: cap positives, then fill the rest of the batch with negatives.
    num_fg_cap = int(rpn_batch_size * rpn_fg_fraction)
    fg_mask = _random_subsample(labels == 1, num_fg_cap, k_fg)
    labels = jnp.where((labels == 1) & ~fg_mask, -1, labels)
    n_fg = jnp.sum(fg_mask.astype(jnp.int32))
    bg_mask = _random_subsample(labels == 0, rpn_batch_size - n_fg, k_bg)
    labels = jnp.where((labels == 0) & ~bg_mask, -1, labels)

    matched_gt = gt_boxes[argmax_gt]
    bbox_targets = bbox_transform(anchors, matched_gt)
    bbox_targets = jnp.where((labels == 1)[:, None], bbox_targets, 0.0)
    bbox_weights = jnp.where((labels == 1)[:, None], 1.0, 0.0)
    return RpnTargets(labels, bbox_targets.astype(jnp.float32), bbox_weights.astype(jnp.float32))
