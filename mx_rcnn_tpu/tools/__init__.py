"""Stage drivers (reference: rcnn/tools/ — train_rpn, train_rcnn, test_rpn,
test_rcnn, reeval) plus the shared fit loop used by every entry point."""
