"""Generate an on-disk COCO-format dataset with synthetic content.

The offline launch-readiness rehearsal (SURVEY.md §8: no real COCO in this
container) needs everything a real run touches — thousands of JPEG files,
a real ``instances_*.json`` parse, the pack_dataset CLI, multi-epoch
training, test.py → COCOEval — with only the pixels being synthetic.
This tool writes the exact layout ``script/get_coco.sh`` documents:

    <root>/annotations/instances_<set>.json
    <root>/<set>/*.jpg

Content mirrors data/datasets/synthetic.py: colored axis-aligned
rectangles on textured noise, class = color, so a detector trained on the
generated train set must generalize to the held-out val set (the color→
class mapping is learnable; mAP has a meaningful floor). The category
list is the full 80-entry COCO one so ``generate_config(..., "coco")``'s
num_classes=81 matches; only the first ``--colors`` categories ever
appear in annotations.

Usage:
    python -m mx_rcnn_tpu.tools.gen_synthetic_coco \
        --root data/coco --train 2400 --val 240 [--seed 0]
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List

import numpy as np

# 16 visually distinct colors; class id = index + 1 (COCO category ids
# are arbitrary ints — we use 1..80 for simplicity, which COCOEval and
# the in-repo coco.py handle identically to the real sparse ids).
_COLORS = np.asarray([
    (220, 40, 40), (40, 200, 60), (50, 80, 230), (230, 200, 40),
    (230, 40, 200), (40, 220, 220), (140, 70, 20), (120, 120, 120),
    (250, 150, 50), (90, 40, 130), (170, 220, 120), (60, 120, 90),
    (240, 120, 160), (30, 40, 90), (200, 170, 130), (100, 200, 250),
], np.float32)

_COCO_CATEGORIES = [
    "person", "bicycle", "car", "motorcycle", "airplane", "bus", "train",
    "truck", "boat", "traffic light", "fire hydrant", "stop sign",
    "parking meter", "bench", "bird", "cat", "dog", "horse", "sheep",
    "cow", "elephant", "bear", "zebra", "giraffe", "backpack", "umbrella",
    "handbag", "tie", "suitcase", "frisbee", "skis", "snowboard",
    "sports ball", "kite", "baseball bat", "baseball glove", "skateboard",
    "surfboard", "tennis racket", "bottle", "wine glass", "cup", "fork",
    "knife", "spoon", "bowl", "banana", "apple", "sandwich", "orange",
    "broccoli", "carrot", "hot dog", "pizza", "donut", "cake", "chair",
    "couch", "potted plant", "bed", "dining table", "toilet", "tv",
    "laptop", "mouse", "remote", "keyboard", "cell phone", "microwave",
    "oven", "toaster", "sink", "refrigerator", "book", "clock", "vase",
    "scissors", "teddy bear", "hair drier", "toothbrush",
]


def _gen_image(rs: np.random.RandomState, n_colors: int):
    """One synthetic image + its annotations (bbox xyxy, class ids)."""
    h = int(rs.randint(360, 640))
    w = int(rs.randint(480, 800))
    if rs.rand() < 0.35:  # mixed orientation, like real COCO
        h, w = w, h
    img = rs.uniform(70, 160, (h, w, 3)).astype(np.float32)
    n = int(rs.randint(1, 6))
    boxes, classes = [], []
    for _ in range(n):
        bw = int(rs.randint(min(h, w) // 8, min(h, w) // 2))
        bh = int(rs.randint(min(h, w) // 8, min(h, w) // 2))
        x1 = int(rs.randint(0, w - bw))
        y1 = int(rs.randint(0, h - bh))
        cls = int(rs.randint(1, n_colors + 1))
        color = _COLORS[cls - 1] + rs.uniform(-12, 12, 3)
        img[y1:y1 + bh, x1:x1 + bw] = color
        boxes.append((x1, y1, bw, bh))  # COCO xywh
        classes.append(cls)
    return np.clip(img, 0, 255).astype(np.uint8), boxes, classes


def generate_split(root: str, image_set: str, num_images: int,
                   seed: int, n_colors: int = 8,
                   quality: int = 90) -> Dict:
    import cv2

    img_dir = os.path.join(root, image_set)
    os.makedirs(img_dir, exist_ok=True)
    os.makedirs(os.path.join(root, "annotations"), exist_ok=True)
    rs = np.random.RandomState(seed)
    images: List[Dict] = []
    annotations: List[Dict] = []
    ann_id = 1
    for i in range(num_images):
        img, boxes, classes = _gen_image(rs, n_colors)
        name = f"{i:012d}.jpg"
        cv2.imwrite(os.path.join(img_dir, name), img[:, :, ::-1],
                    [cv2.IMWRITE_JPEG_QUALITY, quality])
        images.append({
            "id": i + 1, "file_name": name,
            "height": int(img.shape[0]), "width": int(img.shape[1]),
        })
        for (x, y, bw, bh), cls in zip(boxes, classes):
            annotations.append({
                "id": ann_id, "image_id": i + 1, "category_id": cls,
                "bbox": [float(x), float(y), float(bw), float(bh)],
                "area": float(bw * bh), "iscrowd": 0,
                # box-outline polygon: exercises the segmentation parse
                "segmentation": [[float(x), float(y), float(x + bw),
                                  float(y), float(x + bw), float(y + bh),
                                  float(x), float(y + bh)]],
            })
            ann_id += 1
    data = {
        "info": {"description": "synthetic COCO-format rehearsal set"},
        "images": images,
        "annotations": annotations,
        "categories": [{"id": c + 1, "name": n, "supercategory": "none"}
                       for c, n in enumerate(_COCO_CATEGORIES)],
    }
    out = os.path.join(root, "annotations", f"instances_{image_set}.json")
    with open(out, "w") as f:
        json.dump(data, f)
    return {"images": len(images), "annotations": len(annotations),
            "json": out}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--root", default="data/coco")
    p.add_argument("--train", type=int, default=2400)
    p.add_argument("--val", type=int, default=240)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--colors", type=int, default=8,
                   help="distinct object classes actually drawn (<=16)")
    args = p.parse_args(argv)
    for image_set, n, seed in (("train2017", args.train, args.seed),
                               ("val2017", args.val, args.seed + 7919)):
        info = generate_split(args.root, image_set, n, seed,
                              n_colors=min(args.colors, len(_COLORS)))
        print(f"{image_set}: {info}")


if __name__ == "__main__":
    main()
