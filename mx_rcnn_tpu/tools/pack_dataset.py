"""Pack a dataset into pre-decoded shards for the fast input pipeline.

Usage (same dataset/config flags as train_end2end.py):

    python -m mx_rcnn_tpu.tools.pack_dataset --network resnet101_fpn \
        --dataset coco --image_set train2017 --out data/packed/train2017

then train with ``train_end2end.py ... --packed-dir data/packed/train2017``.

Decode+resize happen ONCE here (every cfg.image.scales entry gets its own
shard set, so multi-scale recipes work unchanged); train-time loading is
an mmap slice + one fused native normalize/pad pass — measured 553 img/s
vs 72 for the per-epoch JPEG path (PERF.md r4). The reference has no
equivalent (MXNet's im2rec is the closest ancestor).
"""

from __future__ import annotations

import argparse

from mx_rcnn_tpu.config import generate_config, parse_cli_overrides
from mx_rcnn_tpu.data.packed import write_packed_dataset
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.tools.train import load_gt_roidbs


def parse_args():
    p = argparse.ArgumentParser(
        description="Pack a dataset into pre-decoded uint8 shards")
    p.add_argument("--network", default="resnet101",
                   help="network preset (fixes the training scales/pads)")
    p.add_argument("--dataset", default="coco")
    p.add_argument("--image_set", default=None)
    p.add_argument("--root_path", default=None)
    p.add_argument("--dataset_path", default=None)
    p.add_argument("--out", required=True, help="output shard directory")
    p.add_argument("--shard_images", type=int, default=512)
    p.add_argument("--set", dest="set_cfg", action="append", default=[],
                   metavar="KEY=VALUE")
    return p.parse_args()


def main():
    args = parse_args()
    overrides = {}
    if args.image_set:
        overrides["dataset.image_set"] = args.image_set
    if args.root_path:
        overrides["dataset.root_path"] = args.root_path
    if args.dataset_path:
        overrides["dataset.dataset_path"] = args.dataset_path
    overrides.update(parse_cli_overrides(args.set_cfg))
    cfg = generate_config(args.network, args.dataset, **overrides)

    # Same multi-set load (and box-less filtering) as the train side —
    # flip stays off: flipped copies are a load-time view of the pack.
    roidb = load_gt_roidbs(cfg, flip=False)
    logger.info("packing %d images at scales %s -> %s", len(roidb),
                cfg.image.scales, args.out)
    write_packed_dataset(roidb, cfg, args.out,
                         shard_images=args.shard_images)


if __name__ == "__main__":
    main()
