"""Train-step profiler — stage breakdown + optional jax.profiler trace.

The reference's only perf instrumentation is the Speedometer samples/sec log
(rcnn/core/callback.py); MXNet's engine profiler exists below it but is never
wired into the repo (SURVEY.md §6). This tool is the TPU build's replacement:

  python -m mx_rcnn_tpu.tools.profile --network resnet101 --dataset coco
  python -m mx_rcnn_tpu.tools.profile --trace-dir /tmp/trace  # TensorBoard

Stage timings are additive prefixes of the train forward (backbone → +rpn →
+anchor targets → +proposals/NMS → full fwd → train step), each jitted
separately, so the deltas bound each stage's cost. Through a remote-relay
device (axon) the absolute numbers include per-call transfer overhead for
any large outputs — the train-step row (donated state, scalar outputs) is
the honest end-to-end number; bench.py reports the same quantity.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import faster_rcnn as F
from mx_rcnn_tpu.models.faster_rcnn import FasterRCNN, build_model, init_params
from mx_rcnn_tpu.ops.proposal import generate_proposals


def synthetic_batch(cfg, batch_images=None):
    b = batch_images or cfg.train.batch_images
    h, w = cfg.image.pad_shape
    g = cfg.train.max_gt_boxes
    rs = np.random.RandomState(0)
    n = 8
    boxes = np.zeros((b, g, 4), np.float32)
    for i in range(b):
        x1 = rs.uniform(0, w - 200, n)
        y1 = rs.uniform(0, h - 200, n)
        boxes[i, :n] = np.stack(
            [x1, y1, x1 + rs.uniform(50, 199, n), y1 + rs.uniform(50, 199, n)],
            axis=1)
    valid = np.zeros((b, g), bool)
    valid[:, :n] = True
    classes = np.zeros((b, g), np.int32)
    classes[:, :n] = rs.randint(1, cfg.dataset.num_classes, (b, n))
    return {
        "image": rs.randn(b, h, w, 3).astype(np.float32),
        "im_info": np.asarray([[h * 0.94, w * 0.98, 1.0]] * b, np.float32),
        "gt_boxes": boxes,
        "gt_classes": classes,
        "gt_valid": valid,
    }


def _timeit(name, fn, *args, iters=5, elog=None):
    out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters * 1000
    print(f"{name:36s} {dt:9.2f} ms")
    if elog is not None and elog.enabled:
        elog.emit("step", label=name, step_ms=round(dt, 3), iters=iters)
    return dt


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--network", default="resnet101")
    ap.add_argument("--dataset", default="coco")
    ap.add_argument("--pad", type=int, nargs=2, default=(640, 1024),
                    metavar=("H", "W"))
    ap.add_argument("--batch-images", type=int, default=1)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--trace-dir", default=None,
                    help="capture a jax.profiler trace of the train step "
                         "(view with TensorBoard)")
    ap.add_argument("--stages", action="store_true",
                    help="also time the additive stage prefixes (several "
                         "extra compiles)")
    ap.add_argument("--obs-dir", dest="obs_dir", default=None,
                    help="also write a graftscope event stream (one `step` "
                         "event per timed row + every compile) here; fold "
                         "with `python -m mx_rcnn_tpu.obs.report`")
    args = ap.parse_args(argv)

    cfg = generate_config(
        args.network, args.dataset,
        **{"image.pad_shape": tuple(args.pad),
           "train.batch_images": args.batch_images})
    elog = None
    if args.obs_dir:
        from mx_rcnn_tpu.obs import compile_track, open_event_log, \
            run_meta_fields

        elog = open_event_log(args.obs_dir, fresh=True)  # per-run artifact
        elog.emit("run_meta", **run_meta_fields(
            cfg, tool="profile", batch_size=args.batch_images))
        compile_track.activate(elog)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    batch = synthetic_batch(cfg)
    rng = jax.random.PRNGKey(1)

    if args.stages:
        def backbone(p, bt):
            return jnp.sum(model.apply(p, bt["image"],
                                       method=FasterRCNN.extract)
                           .astype(jnp.float32))
        _timeit("backbone fwd", jax.jit(backbone), params, batch,
                iters=args.iters, elog=elog)

        def with_rpn(p, bt):
            _, cl, bx, _ = F._backbone_rpn(model, p, bt["image"], cfg)
            return jnp.sum(cl.astype(jnp.float32)), jnp.sum(
                bx.astype(jnp.float32))
        _timeit("+rpn heads", jax.jit(with_rpn), params, batch,
                iters=args.iters, elog=elog)

        def with_targets(p, bt, r):
            _, cl, bx, anch = F._backbone_rpn(model, p, bt["image"], cfg)
            t = F._assign_anchors_batch(anch, bt["gt_boxes"],
                                        bt["gt_valid"], bt["im_info"],
                                        r, cfg)
            return jnp.sum(t.labels), jnp.sum(cl.astype(jnp.float32))
        _timeit("+anchor targets", jax.jit(with_targets), params, batch, rng,
                iters=args.iters, elog=elog)

        def with_proposals(p, bt, r):
            _, cl, bx, anch = F._backbone_rpn(model, p, bt["image"], cfg)
            prob = F._rpn_softmax(cl, model.num_anchors)
            rois, rv, _ = generate_proposals(
                prob, bx, bt["im_info"], anch,
                pre_nms_top_n=cfg.train.rpn_pre_nms_top_n,
                post_nms_top_n=cfg.train.rpn_post_nms_top_n,
                nms_thresh=cfg.train.rpn_nms_thresh,
                min_size=cfg.train.rpn_min_size,
                topk_impl=cfg.network.proposal_topk)
            return jnp.sum(rois), jnp.sum(rv)
        _timeit("+proposals (topk+nms)", jax.jit(with_proposals), params,
                batch, rng, iters=args.iters, elog=elog)

        def full_fwd(p, bt, r):
            loss, _ = F.forward_train(model, p, bt, r, cfg)
            return loss
        _timeit("full fwd (loss)", jax.jit(full_fwd), params, batch, rng,
                iters=args.iters, elog=elog)

    # The honest end-to-end number: full train step, donated state, scalar
    # metric outputs only (same quantity bench.py reports).
    from mx_rcnn_tpu.train.optimizer import build_optimizer
    from mx_rcnn_tpu.train.step import create_train_state, make_train_step
    tx = build_optimizer(cfg, params, steps_per_epoch=1000)
    state = create_train_state(params, tx)
    step_fn = make_train_step(model, cfg)

    def run_step(s, bt, r):
        return step_fn(s, bt, r)

    # Two warmups: the second sees the donated device-layout state.
    for _ in range(2):
        rng, k = jax.random.split(rng)
        state, metrics = run_step(state, batch, k)
        jax.block_until_ready(metrics["TotalLoss"])
    t0 = time.perf_counter()
    for _ in range(args.iters):
        rng, k = jax.random.split(rng)
        state, metrics = run_step(state, batch, k)
    jax.block_until_ready(metrics["TotalLoss"])
    dt = (time.perf_counter() - t0) / args.iters * 1000
    b = cfg.train.batch_images
    print(f"{'train step (donated)':36s} {dt:9.2f} ms   "
          f"{b / dt * 1000:6.2f} img/s/chip")
    if elog is not None:
        elog.emit("step", label="train step (donated)",
                  step_ms=round(dt, 3), iters=args.iters,
                  samples_per_sec=round(b / dt * 1000, 3))

    if args.trace_dir:
        with jax.profiler.trace(args.trace_dir):
            for _ in range(3):
                rng, k = jax.random.split(rng)
                state, metrics = run_step(state, batch, k)
            jax.block_until_ready(metrics["TotalLoss"])
        print(f"trace written to {args.trace_dir}")
        # graftprof: fold the capture into the coarse phase breakdown
        # (obs/profile.py) so the split is readable without TensorBoard.
        from mx_rcnn_tpu.obs.profile import summarize_trace

        summary = summarize_trace(args.trace_dir)
        if summary:
            print("trace phases (ms): "
                  + ", ".join(f"{k}={v}"
                              for k, v in summary["phases"].items()))
            if elog is not None and elog.enabled:
                elog.emit("trace", dir=args.trace_dir, reason="manual",
                          summary=summary)

    if elog is not None:
        from mx_rcnn_tpu.obs import compile_track

        compile_track.deactivate()
        elog.close()
        print(f"graftscope events written to {elog.path} "
              "(fold with `python -m mx_rcnn_tpu.obs.report`)")


if __name__ == "__main__":
    main()
