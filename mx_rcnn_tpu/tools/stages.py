"""Alternate-optimization stage drivers.

Reference: rcnn/tools/train_rpn.py, test_rpn.py, train_rcnn.py,
test_rcnn.py — the four stage entry points chained by train_alternate.py
(SURVEY.md §4.4). Stages communicate via files: orbax checkpoints + proposal
pickles, exactly like the reference's .params + *_rpn.pkl contract.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict, List, Optional

import jax

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.data.datasets import dataset_from_config
from mx_rcnn_tpu.data.loader import ROIIter, TestLoader
from mx_rcnn_tpu.evaluation.tester import (
    Predictor,
    generate_proposals,
    pred_eval,
)
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models.faster_rcnn import (
    build_model,
    forward_train_rcnn,
    forward_train_rpn,
    init_params,
)
from mx_rcnn_tpu.tools.train import fit_detector, load_gt_roidbs
from mx_rcnn_tpu.train.checkpoint import load_checkpoint

# The conv trunk freeze for stages 4/6 (reference: train_alternate.py passes
# the full backbone prefix list in stage-2 training).
TRUNK_PATTERNS = ("features",)


def train_rpn(cfg: Config, prefix: str, pretrained_params=None,
              end_epoch: Optional[int] = None, frozen_trunk: bool = False,
              mesh_spec: str = "", frequent: int = 20, seed: int = 0):
    """RPN-only fit (reference: tools/train_rpn.py)."""
    roidb = load_gt_roidbs(cfg)
    return fit_detector(
        cfg, roidb, prefix,
        end_epoch=end_epoch,
        frequent=frequent,
        pretrained_params=pretrained_params,
        mesh_spec=mesh_spec,
        seed=seed,
        forward_fn=forward_train_rpn,
        fixed_param_patterns=TRUNK_PATTERNS if frozen_trunk else None,
    )


def test_rpn_generate(cfg: Config, params, rpn_file: str,
                      image_set: Optional[str] = None,
                      report_recall: bool = True):
    """Dump RPN proposals for an image set and grade them by proposal
    recall vs gt (reference: tools/test_rpn.py --gen →
    tester.generate_proposals, then imdb.evaluate_recall — the classic
    check on an alternate stage-1/5 RPN without training the head).

    Returns (files, recalls): one proposal pickle and one recall dict
    (recall@{300,1000,2000} at IoU 0.5) per image set.
    """
    image_set = image_set or cfg.dataset.image_set
    sets = image_set.split("+")
    model = build_model(cfg)
    predictor = Predictor(model, params, cfg)
    files, recalls = [], []
    for s in sets:
        ds = dataset_from_config(cfg.dataset, s)
        roidb = ds.gt_roidb()
        loader = TestLoader(roidb, cfg, batch_size=1)
        f = rpn_file if len(sets) == 1 else f"{rpn_file}.{s}"
        proposals = generate_proposals(predictor, loader, f)
        files.append(f)
        if report_recall:
            recalls.append(ds.evaluate_recall(roidb, proposals))
    return files, recalls


def _attach_proposals(cfg: Config, rpn_file: str) -> List[Dict]:
    """gt roidb + dumped proposals → Fast-RCNN roidb (with flip doubling;
    proposals for flipped copies are mirrored at load time by ROIIter)."""
    image_set = cfg.dataset.image_set
    sets = image_set.split("+")
    out = []
    for s in sets:
        ds = dataset_from_config(cfg.dataset, s)
        gt = ds.gt_roidb()
        f = rpn_file if len(sets) == 1 else f"{rpn_file}.{s}"
        merged = ds.rpn_roidb(gt, f)
        if cfg.train.flip:
            merged = ds.append_flipped_images(merged)
        out.extend([r for r in merged if len(r["boxes"])])
    return out


def apply_fast_rcnn_bg_preset(cfg: Config) -> Config:
    """Fast-RCNN parity: the reference samples bg rois from IoU in
    [0.1, 0.5) on this path (vs [0.0, 0.5) end2end). The preset applies
    only when ``train.bg_thresh_lo`` is still the None sentinel; an
    explicit override — INCLUDING 0.0, which the sentinel makes
    expressible — is respected, and either way the decision is logged."""
    from dataclasses import replace

    if cfg.train.bg_thresh_lo is None:
        logger.info("train.bg_thresh_lo unset: applying the Fast-RCNN "
                    "preset 0.1 (reference rcnn/io/rcnn.py bg sampling)")
        return cfg.with_updates(train=replace(cfg.train, bg_thresh_lo=0.1))
    logger.info("explicit train.bg_thresh_lo=%g kept on the Fast-RCNN path",
                cfg.train.bg_thresh_lo)
    return cfg


def train_rcnn(cfg: Config, prefix: str, rpn_file: str,
               pretrained_params=None, end_epoch: Optional[int] = None,
               frozen_trunk: bool = False, mesh_spec: str = "",
               frequent: int = 20, seed: int = 0, max_proposals: int = 2000):
    """Fast-R-CNN fit over precomputed proposals (reference:
    tools/train_rcnn.py over ROIIter, incl. its add_bbox_regression_targets
    call when bbox normalization is not precomputed)."""
    from mx_rcnn_tpu.targets.bbox_stats import resolve_bbox_stats

    cfg = apply_fast_rcnn_bg_preset(cfg)

    roidb = _attach_proposals(cfg, rpn_file)
    cfg = resolve_bbox_stats(cfg, roidb)
    return fit_detector(
        cfg, roidb, prefix,
        end_epoch=end_epoch,
        frequent=frequent,
        pretrained_params=pretrained_params,
        mesh_spec=mesh_spec,
        seed=seed,
        forward_fn=forward_train_rcnn,
        loader_factory=partial(_roiiter_factory, max_proposals=max_proposals,
                               seed=seed),
        fixed_param_patterns=TRUNK_PATTERNS if frozen_trunk else None,
    )


def _roiiter_factory(roidb, cfg, num_shards, max_proposals=2000, seed=0,
                     process_count=1, process_index=0):
    return ROIIter(roidb, cfg, num_shards, max_proposals=max_proposals,
                   seed=seed, process_count=process_count,
                   process_index=process_index)


def test_rcnn(cfg: Config, prefix: str, epoch: int,
              image_set: Optional[str] = None, thresh: float = 1e-3):
    """Evaluate a checkpoint (reference: tools/test_rcnn.py)."""
    image_set = image_set or cfg.dataset.test_image_set
    ds = dataset_from_config(cfg.dataset, image_set)
    roidb = ds.gt_roidb()
    model = build_model(cfg)
    template = init_params(model, cfg, jax.random.PRNGKey(0))
    params, _ = load_checkpoint(
        prefix, epoch, template={"params": template},
        means=cfg.train.bbox_means, stds=cfg.train.bbox_stds,
        num_classes=cfg.dataset.num_classes)
    predictor = Predictor(model, params, cfg)
    loader = TestLoader(roidb, cfg, batch_size=1)
    return pred_eval(predictor, loader, ds, thresh=thresh)


def reeval(imdb, detections_pkl: str):
    """Re-run evaluation on saved detections (reference: tools/reeval.py)."""
    import pickle

    with open(detections_pkl, "rb") as f:
        all_boxes = pickle.load(f)
    results = imdb.evaluate_detections(all_boxes)
    logger.info("reeval: %s", results)
    return results
